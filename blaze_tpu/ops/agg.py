"""Hash aggregation, TPU-style.

≙ reference AggExec + agg/ (agg_exec.rs:59, agg_table.rs, acc.rs —
~5,600 LoC of hash-table aggregation with radix buckets and spill).
The TPU design replaces the hash table with an **exact sort+segment
reduce**: XLA has no efficient scatter-with-collision-resolution, but
``lax.sort`` over multiple key operands is fast and
collision-free:

1. encode group keys into equality-preserving uint64 words
2. ``lax.sort`` rows lexicographically by those words (row idx payload)
3. segment boundaries where any word changes; seg_id = cumsum
4. per-agg ``segment_sum/min/max`` with ``indices_are_sorted=True``
5. compact boundary rows -> one output row per distinct group

The same kernel shape serves Partial (raw inputs), PartialMerge/Final
(state inputs) — only the reduce ops differ.  Cross-batch state lives
in ONE device-resident accumulator batch, re-reduced with amortized
doubling (pending list merges when pending rows >= accumulated rows),
so per-input-batch cost stays O(batch log batch) amortized.

Modes mirror agg/mod.rs:58-82 (Partial/PartialMerge/Final); partial-agg
skipping mirrors agg_table.rs:147 + BlazeConf partialAggSkipping: when
the observed group/row ratio stays above the threshold past minRows,
Partial stops aggregating and emits row-wise states directly.

Spill: when the memory manager asks, the accumulator is staged to a
Spill and merged back chunk-wise at finish (associative re-reduce).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import conf
from ..batch import Column, RecordBatch, bucket_capacity, concat_batches
from ..exprs.compile import infer_dtype, lower
from ..exprs.ir import Expr
from ..io.batch_serde import deserialize_batch, serialize_batch
from ..runtime import faults
from ..runtime.context import TaskContext
from ..runtime.memmgr import MemConsumer, MemManager, Spill, try_new_spill
from ..schema import (
    DataType,
    Field,
    Schema,
    TypeKind,
    decimal_avg_agg_type,
    decimal_sum_agg_type,
)
from .base import BatchStream, ExecNode
from .filter import compact_columns


class AggMode(enum.Enum):
    PARTIAL = 0
    PARTIAL_MERGE = 1
    FINAL = 2


@dataclass
class GroupingExpr:
    expr: Expr
    name: str


@dataclass
class AggFunction:
    """One aggregate call.  ``fn`` in sum/count/count_star/avg/min/max/
    first/first_ignores_null (≙ agg/mod.rs:84-97 create_agg)."""

    fn: str
    expr: Optional[Expr]
    name: str


# ---------------------------------------------------------------- typing

def sum_result_type(t: DataType) -> DataType:
    if t.is_decimal:
        return decimal_sum_agg_type(t)
    if t.is_float:
        return DataType.float64()
    return DataType.int64()


def agg_result_type(fn: str, in_t: Optional[DataType]) -> DataType:
    if fn in ("count", "count_star"):
        return DataType.int64()
    if fn == "sum":
        return sum_result_type(in_t)
    if fn == "avg":
        if in_t.is_decimal:
            return decimal_avg_agg_type(in_t)
        return DataType.float64()
    if fn in ("stddev_samp", "var_samp"):
        return DataType.float64()
    if fn in ("collect_list", "collect_set"):
        if fn == "collect_set" and in_t.is_nested:
            # set dedup encodes elements into equality-preserving
            # uint64 sort words (_value_words): lists, lists-of-lists,
            # lists-of-structs and lists-of-strings all encode, any
            # width (wide ARRAY levels take extra flag words).  MAP
            # elements are rejected per Spark's own CollectSet rule
            # ("collect_set() cannot have map type data"); a total
            # word-count bound keeps lax.sort operand counts sane.
            if not _collect_set_elem_supported(in_t):
                raise NotImplementedError(
                    f"collect_set over {in_t!r} (MAP elements are "
                    "rejected by Spark semantics; or total sort-word "
                    "count exceeds the 128-word bound)"
                )
        return DataType.array(in_t, int(conf.COLLECT_MAX_ELEMS.get()))
    return in_t  # min/max/first


def sum_is_wide(in_t: Optional[DataType]) -> bool:
    """True when the sum accumulator can exceed int64 (decimal sums
    with result precision > 18): accumulate in TWO radix-2^32 limbs —
    value = hi*2^32 + lo, both int64, summed independently (redundant
    representation: no carry propagation until finalize), exactly the
    int128 accumulation the reference gets from Arrow decimal128."""
    return in_t is not None and in_t.is_decimal and sum_result_type(in_t).precision > 18


def agg_state_fields(fn: str, in_t: Optional[DataType], name: str) -> List[Field]:
    if fn in ("count", "count_star"):
        return [Field(f"{name}#count", DataType.int64())]
    if fn == "sum":
        if sum_is_wide(in_t):
            # hi limb carries the state decimal scale; the LO limb name
            # carries the true input precision (the hi precision
            # saturates at 38 for inputs >= p29, so "-10" recovery
            # alone would be lossy there)
            return [
                Field(f"{name}#sum_hi", sum_result_type(in_t)),
                Field(f"{name}#sum_lo{in_t.precision}", DataType.int64()),
                Field(f"{name}#nonnull", DataType.int64()),
            ]
        return [
            Field(f"{name}#sum", sum_result_type(in_t)),
            Field(f"{name}#nonnull", DataType.int64()),
        ]
    if fn == "avg":
        if sum_is_wide(in_t):
            return [
                Field(f"{name}#sum_hi", sum_result_type(in_t)),
                Field(f"{name}#sum_lo{in_t.precision}", DataType.int64()),
                Field(f"{name}#count", DataType.int64()),
            ]
        return [
            Field(f"{name}#sum", sum_result_type(in_t)),
            Field(f"{name}#count", DataType.int64()),
        ]
    if fn in ("min", "max", "first", "first_ignores_null"):
        return [Field(f"{name}#value", in_t)]
    if fn in ("stddev_samp", "var_samp"):
        # (count, sum, centered M2) in float64 — per-batch deviations
        # + the Chan parallel-variance merge, cancellation-safe like
        # the reference's Welford-merging variance accumulator
        return [
            Field(f"{name}#cnt", DataType.int64()),
            Field(f"{name}#fsum", DataType.float64()),
            Field(f"{name}#m2", DataType.float64()),
        ]
    if fn in ("collect_list", "collect_set"):
        return [Field(f"{name}#list", agg_result_type(fn, in_t))]
    raise NotImplementedError(f"agg fn {fn}")


# ------------------------------------------------------- key word encode

def encode_key_words(cols: Sequence[Column]) -> List[jnp.ndarray]:
    """Equality-preserving uint64 words per group column: a null word,
    then the value words (strings: zero-padded bytes as words +
    length)."""
    words: List[jnp.ndarray] = []
    for c in cols:
        words.append((~c.validity).astype(jnp.uint64))
        if c.dtype.is_string:
            n, w = c.data.shape
            words.append(c.lengths.astype(jnp.uint64))
            nw = (w + 7) // 8
            data = c.data if nw * 8 == w else jnp.pad(c.data, ((0, 0), (0, nw * 8 - w)))
            b = data.reshape(n, nw, 8).astype(jnp.uint64)
            for k in range(nw):
                word = b[:, k, 0] << jnp.uint64(56)
                for j in range(1, 8):
                    word = word | (b[:, k, j] << jnp.uint64(8 * (7 - j)))
                words.append(jnp.where(c.validity, word, jnp.uint64(0)))
        elif c.dtype.is_float:
            from ..exprs.hash import f64_raw_bits

            d = jnp.where(c.data == 0, jnp.zeros((), c.data.dtype), c.data)  # -0.0 -> 0.0
            d = jnp.where(jnp.isnan(d), jnp.full((), jnp.nan, c.data.dtype), d)  # canonical NaN
            bits = d.view(jnp.int32) if c.data.dtype == jnp.float32 else f64_raw_bits(d)
            words.append(jnp.where(c.validity, bits.astype(jnp.int64).view(jnp.uint64), jnp.uint64(0)))
        else:
            words.append(
                jnp.where(c.validity, c.data.astype(jnp.int64).view(jnp.uint64), jnp.uint64(0))
            )
    return words


# ------------------------------------------------------- segment reduces

# ``seg is None`` selects the GLOBAL (single-segment) fast path: a
# plain tree reduction.  segment_* with num_segments=1 lowers to a
# scatter, which XLA:TPU executes orders of magnitude slower than a
# reduce — the no-groupings agg was 70x off the chip's reduce speed.
#
# ``seg`` may also be a :class:`SortedSegs`: rows sorted by group with
# known boundary structure.  Reduces then run as segmented
# associative scans + cumsum-difference + gathers — NO scatter at all
# (jax.ops.segment_* and jnp.nonzero's bincount both lower to scatter,
# the other TPU cliff).


@dataclass
class SortedSegs:
    """Segment structure of a group-sorted row block.

    - ``seg``: (cap,) int32 group id per row (0..n_out-1, clipped)
    - ``boundary``: (cap,) bool, True at each segment's first row
    - ``starts``: (cap,) int32, row index of group g's first row
    - ``ends``: (cap,) int32, row index of group g's last row
    (entries past n_out are garbage; callers mask with out_live)
    """

    seg: jnp.ndarray
    boundary: jnp.ndarray
    starts: jnp.ndarray
    ends: jnp.ndarray


def _segscan(op, vals, flags):
    """Segmented inclusive scan: at row i, reduce of ``vals`` from i's
    segment start through i.  Hillis-Steele log-depth doubling over the
    (value, boundary-flag) monoid, built from CONTIGUOUS pad+slice
    shifts and elementwise ops only.

    Deliberately NOT ``lax.associative_scan``: its recursive even/odd
    decomposition emits strided slices + interleaves whose Mosaic/TPU
    compile is pathological — measured on the real chip, ONE
    associative_scan at the 4M bucket pushed the q1 agg kernel's
    remote compile past 35 minutes and its execution to ~50 s/call
    (.bench_q1diag.log, round 5); the doubling form compiles in
    seconds and runs at HBM speed."""
    n = vals.shape[0]
    v, f = vals, flags
    d = 1
    while d < n:
        # shift right by d: element i combines with i-d
        pv = jnp.concatenate([v[:1].repeat(d, axis=0), v[:-d]])
        pf = jnp.concatenate([jnp.ones(d, dtype=f.dtype), f[:-d]])
        keep = f  # a boundary inside (i-d, i] blocks the carry
        v = jnp.where(keep, v, op(pv, v))
        f = f | pf
        d <<= 1
    return v


def build_sorted_segs(boundary, s_live) -> SortedSegs:
    """Derive SortedSegs from boundary flags over group-sorted rows
    (dead rows sort AFTER live ones).  Uses one single-operand u32 sort
    for end-position compaction instead of jnp.nonzero (whose bincount
    is a scatter)."""
    cap = boundary.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    seg = jnp.clip(jnp.cumsum(boundary.astype(jnp.int32)) - 1, 0, cap - 1)
    nxt_boundary = jnp.roll(boundary, -1).at[-1].set(True)
    nxt_dead = jnp.roll(~s_live, -1).at[-1].set(True)
    ends_mask = s_live & (nxt_boundary | nxt_dead)
    ends_pos = jnp.where(ends_mask, idx, jnp.int32(cap))
    ends = jnp.clip(jax.lax.sort((ends_pos,), num_keys=1)[0], 0, cap - 1)
    # last boundary at-or-before each row == the row's segment start;
    # boundary indices are monotone, so a PLAIN cummax is exact (no
    # segmented scan needed — one native TPU op)
    start_at_row = jax.lax.cummax(jnp.where(boundary, idx, jnp.int32(-1)))
    starts = jnp.clip(jnp.take(start_at_row, ends), 0, cap - 1)
    return SortedSegs(seg=seg, boundary=boundary, starts=starts, ends=ends)


def _seg_min_reduce(values, seg, cap):
    """Raw per-segment min with the global fast path — use THIS (or
    _seg_max_reduce) for any new reduce; never call jax.ops.segment_*
    directly (seg=None must stay a tree reduce, not a scatter)."""
    if seg is None:
        return jnp.min(values, keepdims=True)
    if isinstance(seg, SortedSegs):
        return jnp.take(_segscan(jnp.minimum, values, seg.boundary), seg.ends)
    return jax.ops.segment_min(values, seg, num_segments=cap, indices_are_sorted=True)


def _seg_max_reduce(values, seg, cap):
    if seg is None:
        return jnp.max(values, keepdims=True)
    if isinstance(seg, SortedSegs):
        return jnp.take(_segscan(jnp.maximum, values, seg.boundary), seg.ends)
    return jax.ops.segment_max(values, seg, num_segments=cap, indices_are_sorted=True)


def _seg_sum(values, valid, seg, cap):
    z = jnp.where(valid, values, jnp.zeros((), values.dtype))
    if seg is None:
        return jnp.sum(z, keepdims=True)
    if isinstance(seg, SortedSegs):
        if jnp.issubdtype(z.dtype, jnp.floating):
            # floats: a global-cumsum difference catastrophically
            # cancels when a small group follows a large prefix, so
            # accumulate WITHIN each segment (error scales with the
            # group's own magnitude, matching segment_sum)
            return jnp.take(_segscan(jnp.add, z, seg.boundary), seg.ends)
        # ints/decimals: cumsum difference is exact (wraparound
        # cancels in the subtraction) — gathers only
        incl = jnp.cumsum(z)
        return (
            jnp.take(incl, seg.ends)
            - jnp.take(incl, seg.starts)
            + jnp.take(z, seg.starts)
        )
    return jax.ops.segment_sum(z, seg, num_segments=cap, indices_are_sorted=True)


def _seg_count(valid, seg, cap):
    return _seg_sum(valid.astype(jnp.int64), jnp.ones_like(valid), seg, cap)


def _seg_minmax(values, valid, seg, cap, is_min: bool):
    dt = values.dtype
    if jnp.issubdtype(dt, jnp.floating):
        sentinel = jnp.array(jnp.inf if is_min else -jnp.inf, dt)
    else:
        info = jnp.iinfo(dt)
        sentinel = jnp.array(info.max if is_min else info.min, dt)
    z = jnp.where(valid, values, sentinel)
    return (_seg_min_reduce if is_min else _seg_max_reduce)(z, seg, cap)


def _seg_first(values, valid, seg, cap, ignore_nulls: bool):
    n = values.shape[0]
    pick = valid if ignore_nulls else jnp.ones_like(valid)
    idx = jnp.where(pick, jnp.arange(n), n)
    first_idx = _seg_min_reduce(idx, seg, cap)
    safe = jnp.clip(first_idx, 0, n - 1)
    has = first_idx < n
    return jnp.take(values, safe, axis=0), jnp.take(valid, safe) & has, has


def _seg_gather_first(v: Column, pick, seg, cap: int) -> Column:
    """Gather the first row per segment where ``pick`` holds."""
    n = v.validity.shape[0]
    idx = jnp.where(pick, jnp.arange(n), n)
    first = _seg_min_reduce(idx, seg, cap)
    has = first < n
    out = v.take(jnp.clip(first, 0, n - 1))
    return Column(v.dtype, out.data, out.validity & has,
                  None if out.lengths is None else jnp.where(has, out.lengths, 0))


def _seg_string_minmax(v: Column, seg, cap: int, is_min: bool) -> Column:
    """Lexicographic per-segment min/max over a string column: W/8
    tie-break passes of segment_min over order-preserving words, then a
    first-candidate gather (rows arrive segment-sorted)."""
    from .sort import order_words

    words = order_words(v, ascending=is_min, nulls_first=False)[1:]  # value words
    cand = v.validity
    sentinel = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    for word in words:
        masked = jnp.where(cand, word, sentinel)
        m = _seg_min_reduce(masked, seg, cap)
        if seg is None:
            per_row = m[0]
        elif isinstance(seg, SortedSegs):
            per_row = jnp.take(m, seg.seg)
        else:
            per_row = jnp.take(m, seg)
        cand = cand & (word == per_row)
    return _seg_gather_first(v, cand, seg, cap)


# ------------------------------------------------- collect_list/set

def _seg_first_row(seg, cap, n):
    """Index of each segment's first row, mapped back per row."""
    arange = jnp.arange(n, dtype=jnp.int32)
    first = jax.ops.segment_min(arange, seg, num_segments=cap, indices_are_sorted=True)
    return jnp.clip(jnp.take(first, seg), 0, n - 1)


def _scatter_elem_col(c: Column, tgt, pos, cap: int, m: int, n_lead: int,
                      top_validity=None) -> Column:
    """Scatter a column's rows/elements into a (cap, m)-leading output
    at ``[tgt, pos]`` — recursive over nested children, so any element
    dtype collects (arrays of arrays/maps/structs included).

    ``n_lead``: leading axes of the SOURCE arrays (1 = one entry per
    input row, 2 = per (row, element) in merge mode)."""

    def sc(arr, dtype):
        if arr is None:
            return None
        out = jnp.zeros((cap, m) + arr.shape[n_lead:], dtype)
        return out.at[tgt, pos].set(arr, mode="drop")

    validity = (
        top_validity
        if top_validity is not None
        else sc(c.validity, jnp.bool_)
    )
    return Column(
        c.dtype,
        sc(c.data, c.data.dtype) if c.data is not None else None,
        validity,
        sc(c.lengths, jnp.int32) if c.lengths is not None else None,
        None if c.children is None else tuple(
            _scatter_elem_col(k, tgt, pos, cap, m, n_lead) for k in c.children
        ),
    )


def _collect_reduce(v: Column, arr_t: DataType, seg, cap: int, merging: bool) -> Column:
    """Segment-collect into the fixed max-elements ARRAY layout
    (≙ reference agg/collect.rs collect_list/collect_set accs).  Nulls
    are skipped (Spark semantics); elements past ``max_elems`` are
    DROPPED — the padded layout's documented deviation from the
    reference's unbounded lists.  Element scatter recurses over nested
    children, so nested element types collect too."""
    elem_t = arr_t.elem
    m = arr_t.max_elems
    n = v.validity.shape[0]
    if not merging:
        valid = v.validity
        cv = jnp.cumsum(valid.astype(jnp.int32))
        prefix = cv - valid.astype(jnp.int32)  # exclusive count of valid rows
        base = jnp.take(prefix, _seg_first_row(seg, cap, n))
        pos = prefix - base                    # within-segment rank among valid
        emit = valid & (pos < m)
        tgt = jnp.where(emit, seg, cap)        # cap = dropped (out of bounds)
        counts = jnp.clip(_seg_count(valid, seg, cap), 0, m).astype(jnp.int32)
        ev = jnp.arange(m)[None, :] < counts[:, None]
        elem = _scatter_elem_col(v, tgt, pos, cap, m, 1, top_validity=ev)
        return Column(arr_t, None, jnp.ones(cap, jnp.bool_), counts, (elem,))
    # merging: v is an ARRAY state column (rows sorted by group)
    rc = jnp.where(v.validity, v.lengths, 0).astype(jnp.int32)
    cum = jnp.cumsum(rc)
    excl = cum - rc
    base = jnp.take(excl, _seg_first_row(seg, cap, n))
    start = excl - base                        # offset of this row's elems in its group
    elem = v.children[0]
    within = jnp.arange(m)[None, :] < rc[:, None]
    pos2 = start[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
    seg2 = jnp.broadcast_to(seg[:, None], (n, m))
    tgt = jnp.where(within & (pos2 < m), seg2, cap)
    counts = jnp.clip(
        jax.ops.segment_sum(rc, seg, num_segments=cap, indices_are_sorted=True), 0, m
    ).astype(jnp.int32)
    ev = jnp.arange(m)[None, :] < counts[:, None]
    out_elem = _scatter_elem_col(elem, tgt, pos2, cap, m, 2, top_validity=ev)
    return Column(arr_t, None, jnp.ones(cap, jnp.bool_), counts, (out_elem,))


def _canon_float_bits(data):
    """Equality-canonical float bits: -0.0 -> 0.0, all NaNs -> one
    payload; f32 views as i32, f64 through the raw-bits helper."""
    from ..exprs.hash import f64_raw_bits

    d = jnp.where(data == 0, jnp.zeros((), data.dtype), data)
    d = jnp.where(jnp.isnan(d), jnp.full((), jnp.nan, data.dtype), d)
    return d.view(jnp.int32) if data.dtype == jnp.float32 else f64_raw_bits(d)


def _value_words(dtype: DataType, col: Column, live) -> List[jnp.ndarray]:
    """Recursive equality-preserving uint64 words for values of any
    supported nesting, each word shaped like ``live`` (the liveness
    mask at this level).  A trailing element axis is flattened into
    max_elems separate words, so total key count stays static."""
    if dtype.kind == TypeKind.ARRAY:
        m = dtype.max_elems
        child = col.children[0]
        words = [jnp.where(live, col.lengths, 0).astype(jnp.uint64)]
        inner_live = (
            jnp.arange(m)[(None,) * live.ndim] < col.lengths[..., None]
        ) & live[..., None]
        lv = inner_live & child.validity
        # element-validity flags, 64 bits per word (levels wider than
        # 64 spill into additional flag words)
        for base in range(0, m, 64):
            flags = jnp.zeros(live.shape, jnp.uint64)
            for j in range(base, min(base + 64, m)):
                flags = flags | (
                    lv[..., j].astype(jnp.uint64) << jnp.uint64(j - base))
            words.append(flags)
        for w in _value_words(dtype.elem, child, lv):
            for j in range(m):
                words.append(w[..., j])
        return words
    if dtype.kind == TypeKind.STRUCT:
        words = []
        for f, ch in zip(dtype.struct_fields, col.children):
            lv = live & ch.validity
            words.append(lv.astype(jnp.uint64))  # per-field null flag
            words.extend(_value_words(f.dtype, ch, lv))
        return words
    if dtype.is_string:
        w_ = col.data.shape[-1]
        nw = (w_ + 7) // 8
        d = col.data
        if nw * 8 != w_:
            pad = [(0, 0)] * (d.ndim - 1) + [(0, nw * 8 - w_)]
            d = jnp.pad(d, pad)
        b = d.reshape(live.shape + (nw, 8)).astype(jnp.uint64)
        words = [jnp.where(live, col.lengths, 0).astype(jnp.uint64)]
        for k in range(nw):
            word = b[..., k, 0] << jnp.uint64(56)
            for j in range(1, 8):
                word = word | (b[..., k, j] << jnp.uint64(8 * (7 - j)))
            words.append(jnp.where(live, word, jnp.uint64(0)))
        return words
    bits = _canon_float_bits(col.data) if dtype.is_float else col.data
    bits = bits.astype(jnp.int64).view(jnp.uint64)
    return [jnp.where(live, bits, jnp.uint64(0))]


def _word_count(dtype: DataType) -> int:
    """Sort words _value_words emits per value (the ARRAY levels
    multiply: each child word splits into max_elems words)."""
    if dtype.kind == TypeKind.ARRAY:
        return 1 + (dtype.max_elems + 63) // 64 + (
            dtype.max_elems * _word_count(dtype.elem))
    if dtype.kind == TypeKind.STRUCT:
        return sum(1 + _word_count(f.dtype) for f in dtype.struct_fields)
    if dtype.is_string:
        return 1 + (dtype.string_width + 7) // 8
    return 1


def _collect_set_elem_supported(dtype: DataType) -> bool:
    """Element types the sort-word dedup can encode: primitives,
    strings, and ARRAY/STRUCT nestings thereof (ARRAY levels wider
    than 64 use extra validity-flag words) with a bounded TOTAL word
    count (the levels multiply; lax.sort with thousands of operands
    would blow up compile rather than fail cleanly).  MAP elements are
    rejected because Spark itself rejects them: CollectSet refuses any
    input type containing a MapType ("collect_set() cannot have map
    type data"), so the gate IS the reference semantics."""
    def ok(t: DataType) -> bool:
        if t.kind == TypeKind.ARRAY:
            return ok(t.elem)
        if t.kind == TypeKind.STRUCT:
            return all(ok(f.dtype) for f in t.struct_fields)
        if t.kind in (TypeKind.MAP, TypeKind.OPAQUE):
            return False
        return True

    return ok(dtype) and _word_count(dtype) <= 128


def _elem_sort_words(elem: Column, within) -> List[jnp.ndarray]:
    """Equality-preserving uint64 sort words along the element axis
    (dead slots first key = 1 so they sort last)."""
    words: List[jnp.ndarray] = [(~within).astype(jnp.uint64)]
    if elem.dtype.is_string:
        cap, m, w = elem.data.shape
        words.append(jnp.where(within, elem.lengths, 0).astype(jnp.uint64))
        nw = (w + 7) // 8
        d = elem.data if nw * 8 == w else jnp.pad(elem.data, ((0, 0), (0, 0), (0, nw * 8 - w)))
        b = d.reshape(cap, m, nw, 8).astype(jnp.uint64)
        for k in range(nw):
            word = b[:, :, k, 0] << jnp.uint64(56)
            for j in range(1, 8):
                word = word | (b[:, :, k, j] << jnp.uint64(8 * (7 - j)))
            words.append(jnp.where(within, word, jnp.uint64(0)))
    elif elem.dtype.is_float:
        bits = _canon_float_bits(elem.data)
        words.append(
            jnp.where(within, bits.astype(jnp.int64).view(jnp.uint64), jnp.uint64(0))
        )
    elif elem.dtype.is_nested:
        # nested elements (lists, lists-of-lists, lists-of-structs,
        # lists-of-strings): recursive equality-word encoding
        words.extend(_value_words(elem.dtype, elem, within))
    else:
        words.append(
            jnp.where(within, elem.data.astype(jnp.int64).view(jnp.uint64), jnp.uint64(0))
        )
    return words


def _dedup_array_state(col: Column) -> Column:
    """Per-row element dedup (collect_set): sort elements within each
    row, drop adjacent duplicates, recompact."""
    arr_t = col.dtype
    elem_t = arr_t.elem
    elem = col.children[0]
    m = arr_t.max_elems
    cap = col.validity.shape[0]
    within = jnp.arange(m)[None, :] < col.lengths[:, None]
    words = _elem_sort_words(elem, within)
    payload = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None, :], (cap, m))
    sorted_ = jax.lax.sort(tuple(words) + (payload,), dimension=1, num_keys=len(words))
    s_words, s_idx = sorted_[:-1], sorted_[-1]
    s_within = jnp.take_along_axis(within, s_idx, axis=1)
    changed = jnp.zeros((cap, m), jnp.bool_)
    for wv in s_words:
        changed = changed | (wv != jnp.roll(wv, 1, axis=1))
    changed = changed.at[:, 0].set(True)
    keep = s_within & changed
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    counts = jnp.sum(keep.astype(jnp.int32), axis=1)
    rows2 = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32)[:, None], (cap, m))
    tgt = jnp.where(keep, rows2, cap)
    ev = jnp.arange(m)[None, :] < counts[:, None]
    if elem_t.is_string:
        w = elem.data.shape[-1]
        g_data = jnp.take_along_axis(elem.data, s_idx[:, :, None], axis=1)
        g_len = jnp.take_along_axis(elem.lengths, s_idx, axis=1)
        data = jnp.zeros((cap, m, w), jnp.uint8).at[tgt, new_pos].set(g_data, mode="drop")
        lengths = jnp.zeros((cap, m), jnp.int32).at[tgt, new_pos].set(g_len, mode="drop")
        out_elem = Column(elem_t, data, ev, lengths)
    elif elem_t.is_nested:
        # nested elements: recursive permute (gather by s_idx along the
        # element axis) + compacting scatter of every buffer level
        def reorder(c: Column, valid_override=None) -> Column:
            def move(a):
                if a is None:
                    return None
                ix = s_idx
                for _ in range(a.ndim - 2):
                    ix = ix[..., None]
                g = jnp.take_along_axis(a, ix, axis=1)
                return jnp.zeros(a.shape, a.dtype).at[tgt, new_pos].set(
                    g, mode="drop")

            return Column(
                c.dtype,
                move(c.data),
                move(c.validity) if valid_override is None else valid_override,
                move(c.lengths),
                None if c.children is None else tuple(
                    reorder(ch) for ch in c.children),
            )

        out_elem = reorder(elem, valid_override=ev)
    else:
        g_data = jnp.take_along_axis(elem.data, s_idx, axis=1)
        data = jnp.zeros((cap, m), elem.data.dtype).at[tgt, new_pos].set(g_data, mode="drop")
        out_elem = Column(elem_t, data, ev)
    return Column(arr_t, None, col.validity, counts, (out_elem,))


# ---------------------------------------------------------------- AggExec

class AggExec(ExecNode):
    def __init__(
        self,
        child: ExecNode,
        mode: AggMode,
        groupings: Sequence[GroupingExpr],
        aggs: Sequence[AggFunction],
        initial_input_buffer_offset: int = 0,
        supports_partial_skipping: bool = False,
        pre_filter: Optional[Expr] = None,
        post_sort: Optional[Sequence] = None,
        post_fetch: Optional[int] = None,
    ):
        super().__init__([child])
        self.mode = mode
        # stage fusion may fold a downstream Sort(+Limit) into the
        # finalize program (FINAL mode emits one blocking batch per
        # partition, so an in-program key sort over it is exact):
        # post_sort = SortFields over the OUTPUT schema, post_fetch =
        # host-side row clamp after the sorted finalize
        assert post_sort is None or mode == AggMode.FINAL
        self.post_sort = list(post_sort) if post_sort else None
        self.post_fetch = post_fetch
        self.groupings = list(groupings)
        # brickhouse names are aliases (≙ agg/mod.rs:84-97 create_agg
        # mapping BrickhouseCollect/BrickhouseCombineUnique)
        _ALIAS = {"count0": "count_star", "brickhouse_collect": "collect_list",
                  "brickhouse_combine_unique": "collect_set"}
        self.aggs = [
            AggFunction(_ALIAS.get(a.fn, a.fn), a.expr, a.name) for a in aggs
        ]
        # fused pre-aggregation predicate (stage fusion: a FilterExec
        # collapsed into this kernel; rows failing it never aggregate)
        self.pre_filter = pre_filter
        self.supports_partial_skipping = supports_partial_skipping
        # tier-5 blocking-boundary fusion (shuffle write absorbing this
        # FINAL agg's finalize as its chain bottom): when set, _finish
        # emits the RAW state batch and the writer's fused program
        # applies the finalize — no finalized intermediate batch
        self.emit_state = False

        in_schema = child.schema
        # input value types of each agg (for PARTIAL: from expr; for
        # merge modes: recover from the state columns in in_schema)
        self._in_types: List[Optional[DataType]] = []
        for a in self.aggs:
            if mode == AggMode.PARTIAL:
                self._in_types.append(None if a.expr is None else infer_dtype(a.expr, in_schema))
            else:
                if a.fn in ("count", "count_star"):
                    self._in_types.append(None)
                elif a.fn in ("sum", "avg"):
                    # state sum column carries the sum type; recover in_t
                    # (wide decimal sums split into #sum_hi/#sum_loP limbs,
                    # P = the TRUE input precision: the hi precision
                    # saturates at 38 for inputs >= p29, so the plain
                    # "-10" inversion is lossy there and would skew the
                    # final avg result type vs Spark's).  BOTH sum and avg
                    # states carry decimal(p+10, s), so both subtract 10 —
                    # recovering p+10 as the input precision would flip
                    # sum_is_wide() against the partial stage's layout
                    if f"{a.name}#sum" in in_schema.names:
                        st = in_schema.field(f"{a.name}#sum").dtype
                        true_p = max(1, st.precision - 10)
                    else:
                        st = in_schema.field(f"{a.name}#sum_hi").dtype
                        lo_prefix = f"{a.name}#sum_lo"
                        true_p = next(
                            (
                                int(nm[len(lo_prefix):])
                                for nm in in_schema.names
                                if nm.startswith(lo_prefix)
                                and nm[len(lo_prefix):].isdigit()
                            ),
                            max(1, st.precision - 10),
                        )
                    if st.is_decimal:
                        self._in_types.append(DataType.decimal(true_p, st.scale))
                    else:
                        self._in_types.append(st)
                elif a.fn in ("collect_list", "collect_set"):
                    self._in_types.append(in_schema.field(f"{a.name}#list").dtype.elem)
                elif a.fn in ("stddev_samp", "var_samp"):
                    self._in_types.append(DataType.float64())
                else:
                    self._in_types.append(in_schema.field(f"{a.name}#value").dtype)

        group_fields = [
            Field(g.name, infer_dtype(g.expr, in_schema)) for g in self.groupings
        ]
        state_fields: List[Field] = []
        for a, t in zip(self.aggs, self._in_types):
            fields = agg_state_fields(a.fn, t, a.name)
            if mode != AggMode.PARTIAL and a.fn in ("collect_list", "collect_set"):
                # preserve the incoming state's element budget exactly
                # (conf may differ between stages)
                fields = [Field(f"{a.name}#list", in_schema.field(f"{a.name}#list").dtype)]
            state_fields.extend(fields)
        self._state_schema = Schema(group_fields + state_fields)

        if mode == AggMode.FINAL:
            out_fields = group_fields + [
                Field(
                    a.name,
                    self._state_schema.field(f"{a.name}#list").dtype
                    if a.fn in ("collect_list", "collect_set")
                    else agg_result_type(a.fn, t),
                )
                for a, t in zip(self.aggs, self._in_types)
            ]
            self._schema = Schema(out_fields)
        else:
            self._schema = self._state_schema

        self._merger: Optional["_StateMerger"] = None
        self._update_k = None
        from ..exprs.compile import expr_key
        from ..runtime.kernel_cache import cached_kernel, schema_key
        from .sort import sort_fields_key

        kernel_key = (
            "agg", mode.value, schema_key(in_schema), schema_key(self._state_schema),
            None if self.pre_filter is None else expr_key(self.pre_filter),
            tuple((expr_key(g.expr), g.name) for g in self.groupings),
            tuple((a.fn, None if a.expr is None else expr_key(a.expr), a.name)
                  for a in self.aggs),
            bool(conf.SEG_SCAN_REDUCE.get()),
            bool(conf.AGG_HASH_SORT_PARTIAL.get()),
            None if self.post_sort is None else sort_fields_key(self.post_sort),
        )
        self._kernel_key = kernel_key
        self._grouped_kernel, self._scalar_kernel, self._finalize_kernel = cached_kernel(
            kernel_key, lambda: self._build_kernels(in_schema)
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    # ------------------------------------- static-analysis contract

    def required_child_distribution(self):
        """A grouped FINAL agg needs every row of a group co-located:
        its feeding exchange must hash on (a subset of) the group keys
        (analysis/plan_verify.py rule ``dist.final-agg``); ungrouped
        FINAL needs exactly one partition (``dist.final-scalar``)."""
        if self.mode != AggMode.FINAL or not self.groupings:
            return None
        from ..exprs.compile import expr_key

        return ("hash", frozenset(expr_key(g.expr) for g in self.groupings))

    def provided_ordering(self):
        """A fused ``post_sort`` finalize satisfies downstream
        sort-consumers exactly like the SortExec it absorbed —
        ``(expr_key, ascending)`` entries, direction included."""
        if not self.post_sort:
            return ()
        from ..exprs.compile import expr_key

        return tuple((expr_key(f.expr), bool(f.ascending))
                     for f in self.post_sort)

    # -------------------------------------------------------- kernels

    def _build_kernels(self, in_schema: Schema):
        groupings = self.groupings
        aggs = self.aggs
        mode = self.mode
        pre_filter = self.pre_filter
        post_sort = self.post_sort
        out_schema = self._schema
        n_groups_cols = len(groupings)
        state_schema = self._state_schema
        in_types = list(self._in_types)  # NEVER capture self below: the
        # kernels are cached process-wide and must not pin this exec's
        # child subtree (scanned data) alive
        use_segscan = bool(conf.SEG_SCAN_REDUCE.get())  # in kernel_key
        # exactness: only PARTIAL may emit hash-split duplicate groups
        # (every later stage re-merges); FINAL/PARTIAL_MERGE sort the
        # full key words
        use_hash_sort = (
            bool(conf.AGG_HASH_SORT_PARTIAL.get()) and self.mode == AggMode.PARTIAL
        )

        def eval_inputs(cols: Tuple[Column, ...], schema: Schema):
            env = {f.name: c for f, c in zip(schema.fields, cols)}
            n = cols[0].validity.shape[0] if cols else 0
            key_cols = [lower(g.expr, schema, env, n) for g in groupings]
            return env, key_cols, n

        def partial_inputs(env, schema, n) -> List[List[Column]]:
            """Per-agg list of raw input columns (PARTIAL mode).
            count(*) gets a synthetic all-valid bool column so the
            liveness masking applied to sorted inputs covers it too."""
            out = []
            for a in aggs:
                if a.expr is None:
                    ones = jnp.ones(n, jnp.bool_)
                    out.append([Column(DataType.bool_(), ones, ones)])
                else:
                    out.append([lower(a.expr, schema, env, n)])
            return out

        def state_inputs(env) -> List[List[Column]]:
            out = []
            for a, t in zip(aggs, in_types):
                fields = agg_state_fields(a.fn, t, a.name)
                out.append([env[f.name] for f in fields])
            return out

        def reduce_one(
            a: AggFunction,
            in_t: Optional[DataType],
            inputs: List[Column],
            seg,
            cap: int,
            merging: bool,
        ) -> List[Column]:
            """Produce the state columns (length cap, indexed by seg id)."""
            if a.fn in ("count", "count_star"):
                c = inputs[0]
                if merging:
                    s = _seg_sum(c.data, c.validity, seg, cap)
                else:
                    s = _seg_count(c.validity, seg, cap)
                return [Column(DataType.int64(), s, jnp.ones(cap, jnp.bool_))]
            if a.fn in ("sum", "avg"):
                sum_t = sum_result_type(in_t)
                ones = jnp.ones(cap, jnp.bool_)
                if sum_is_wide(in_t):
                    # radix-2^32 limbs summed independently (redundant
                    # carry-free int128 accumulation; finalize combines)
                    if merging:
                        hc, lc, cc = inputs
                        hi_in, lo_in, hval = hc.data, lc.data, hc.validity
                        cval, cdata = cc.validity, cc.data
                    else:
                        v = inputs[0]
                        hi_in = v.data >> jnp.int64(32)
                        lo_in = v.data & jnp.int64(0xFFFFFFFF)
                        hval = v.validity
                        cval, cdata = v.validity, None
                    s_hi = _seg_sum(hi_in, hval, seg, cap)
                    s_lo = _seg_sum(lo_in, hval, seg, cap)
                    c = (
                        _seg_sum(cdata, cval, seg, cap)
                        if merging else _seg_count(cval, seg, cap)
                    )
                    return [
                        Column(sum_t, s_hi, ones),
                        Column(DataType.int64(), s_lo, ones),
                        Column(DataType.int64(), c, ones),
                    ]
                if merging:
                    sc, cc = inputs
                    s = _seg_sum(sc.data, sc.validity, seg, cap)
                    c = _seg_sum(cc.data, cc.validity, seg, cap)
                else:
                    v = inputs[0]
                    vv = v.data.astype(sum_t.np_dtype)
                    s = _seg_sum(vv, v.validity, seg, cap)
                    c = _seg_count(v.validity, seg, cap)
                return [
                    Column(sum_t, s, ones),
                    Column(DataType.int64(), c, ones),
                ]
            if a.fn in ("min", "max"):
                v = inputs[0]
                if v.dtype.is_string:
                    return [_seg_string_minmax(v, seg, cap, a.fn == "min")]
                vals = _seg_minmax(v.data, v.validity, seg, cap, a.fn == "min")
                has = _seg_max_reduce(v.validity.astype(jnp.int32), seg, cap).astype(jnp.bool_)
                return [Column(v.dtype, jnp.where(has, vals, jnp.zeros((), vals.dtype)), has)]
            if a.fn in ("first", "first_ignores_null"):
                v = inputs[0]
                ignore = a.fn == "first_ignores_null" or mode != AggMode.PARTIAL
                if v.dtype.is_string:
                    pick = v.validity if ignore else jnp.ones_like(v.validity)
                    return [_seg_gather_first(v, pick, seg, cap)]
                vals, valid, has = _seg_first(v.data, v.validity, seg, cap, ignore)
                return [Column(v.dtype, jnp.where(valid, vals, jnp.zeros((), vals.dtype)), valid)]
            if a.fn in ("stddev_samp", "var_samp"):
                ones = jnp.ones(cap, jnp.bool_)
                if merging:
                    # parallel-variance merge in DEVIATION scale:
                    # M2 = sum(M2_i) + sum(n_i * (mean_i - mean)^2) —
                    # no large-square cancellation (mean_i - mean is
                    # deviation-sized), unlike the sum-of-squares form
                    cc, sc, mc = inputs
                    cnt = _seg_sum(cc.data, cc.validity, seg, cap)
                    fs = _seg_sum(sc.data, sc.validity, seg, cap)
                    nf = cnt.astype(jnp.float64)
                    mean_tot = fs / jnp.where(cnt > 0, nf, 1.0)
                    if seg is None:
                        mean_row = mean_tot[0]
                    elif isinstance(seg, SortedSegs):
                        mean_row = jnp.take(mean_tot, seg.seg)
                    else:
                        mean_row = jnp.take(mean_tot, seg)
                    nf_i = cc.data.astype(jnp.float64)
                    mean_i = sc.data / jnp.where(cc.data > 0, nf_i, 1.0)
                    d = mean_i - mean_row
                    term = jnp.where(cc.data > 0, nf_i * d * d, 0.0)
                    m2 = _seg_sum(mc.data + term, mc.validity, seg, cap)
                else:
                    v = inputs[0]
                    f = v.data.astype(jnp.float64)
                    if v.dtype.is_decimal:
                        # decimals carry the UNSCALED int64; rescale or
                        # every moment would be off by 10^scale
                        f = f / float(10 ** v.dtype.scale)
                    cnt = _seg_count(v.validity, seg, cap)
                    fs = _seg_sum(f, v.validity, seg, cap)
                    nf = cnt.astype(jnp.float64)
                    mean = fs / jnp.where(cnt > 0, nf, 1.0)
                    if seg is None:
                        mean_row = mean[0]
                    elif isinstance(seg, SortedSegs):
                        mean_row = jnp.take(mean, seg.seg)
                    else:
                        mean_row = jnp.take(mean, seg)
                    dev = f - mean_row
                    m2 = _seg_sum(dev * dev, v.validity, seg, cap)
                return [
                    Column(DataType.int64(), cnt, ones),
                    Column(DataType.float64(), fs, ones),
                    Column(DataType.float64(), m2, ones),
                ]
            if a.fn in ("collect_list", "collect_set"):
                arr_t = state_schema.field(f"{a.name}#list").dtype
                if seg is None:  # collect keeps the segment machinery
                    seg = jnp.zeros(inputs[0].validity.shape[0], jnp.int32)
                elif isinstance(seg, SortedSegs):
                    seg = seg.seg
                out = _collect_reduce(inputs[0], arr_t, seg, cap, merging)
                if a.fn == "collect_set":
                    out = _dedup_array_state(out)
                return [out]
            raise NotImplementedError(a.fn)

        merging = mode != AggMode.PARTIAL

        @jax.jit
        def grouped_kernel(cols: Tuple[Column, ...], num_rows):
            schema = in_schema
            env, key_cols, _ = eval_inputs(cols, schema)
            cap = cols[0].validity.shape[0]
            live = jnp.arange(cap) < num_rows
            if pre_filter is not None:
                pf = lower(pre_filter, schema, env, cap)
                live = live & pf.validity & pf.data.astype(jnp.bool_)
            key_words = [
                jnp.where(live, w, jnp.uint64(0)) for w in encode_key_words(key_cols)
            ]
            row_idx = jnp.arange(cap, dtype=jnp.int32)
            if use_hash_sort:
                # PARTIAL-mode fast path: sort ONE u32 hash key instead
                # of every 64-bit key word.  Hash collisions between
                # distinct keys may split a group into multiple
                # segments (boundaries compare the FULL words, so
                # distinct keys never merge); duplicate partial states
                # are legal — the merge stage re-reduces them.
                h = jnp.full(cap, 2166136261, jnp.uint32)
                for w in key_words:
                    for half in (w.astype(jnp.uint32), (w >> jnp.uint64(32)).astype(jnp.uint32)):
                        h = (h ^ half) * jnp.uint32(16777619)
                key = jnp.where(live, h & jnp.uint32(0x7FFFFFFF), jnp.uint32(0xFFFFFFFF))
                _, s_idx = jax.lax.sort((key, row_idx), num_keys=1)
                s_live = jnp.take(live, s_idx)
                # full key words join the stacked u64 gather below;
                # boundaries compare sorted words against their roll
                changed = None
            else:
                words = [live.astype(jnp.uint64) ^ jnp.uint64(1)] + key_words
                sorted_ops = jax.lax.sort(tuple(words) + (row_idx,), num_keys=len(words))
                s_words, s_idx = sorted_ops[:-1], sorted_ops[-1]
                s_live = jnp.take(live, s_idx)
                changed = jnp.zeros(cap, jnp.bool_)
                for w in s_words:
                    changed = changed | (w != jnp.roll(w, 1))
                changed = changed.at[0].set(True)

            # sort every flat payload column with ONE stacked row
            # gather per dtype group — TPU gathers cost per ROW, not
            # per element (~131 ms per 1M-row gather on the real chip,
            # .bench_q1diag.log), so 20 per-column takes collapse into
            # ~4 matrix takes
            inputs = partial_inputs(env, schema, cap) if not merging else state_inputs(env)
            flat_cols = [c for ins in inputs for c in ins] + list(key_cols)
            groups: Dict = {}
            if changed is None:  # hash path: key words ride the gather
                for wi, w in enumerate(key_words):
                    groups.setdefault(("d", "uint64"), []).append(
                        (("kw", wi), "kw", w))
            for ci, c in enumerate(flat_cols):
                if c.children is not None or c.data.ndim > 2:
                    continue  # nested: per-column take fallback below
                groups.setdefault(("v", jnp.bool_.__name__), []).append(
                    (ci, "validity", c.validity))
                if c.data.ndim == 1:
                    groups.setdefault(("d", str(c.data.dtype)), []).append(
                        (ci, "data", c.data))
                else:  # (cap, W) u8 string payload: W lanes
                    for lane in range(c.data.shape[1]):
                        groups.setdefault(("d", str(c.data.dtype)), []).append(
                            ((ci, lane), "lane", c.data[:, lane]))
                if c.lengths is not None:
                    groups.setdefault(("l", str(c.lengths.dtype)), []).append(
                        (ci, "lengths", c.lengths))
            sorted_parts: Dict = {}
            for _, entries in groups.items():
                mat = jnp.stack([e[2] for e in entries], axis=1)
                smat = jnp.take(mat, s_idx, axis=0)
                for k2, (tag, kind, _) in enumerate(entries):
                    sorted_parts[(tag, kind)] = smat[:, k2]
            if changed is None:  # hash path boundary from sorted words
                changed = jnp.zeros(cap, jnp.bool_)
                for wi in range(len(key_words)):
                    sw = sorted_parts[(("kw", wi), "kw")]
                    changed = changed | (sw != jnp.roll(sw, 1))
                changed = changed.at[0].set(True)

            sorted_flat: List[Column] = []
            for ci, c in enumerate(flat_cols):
                if c.children is not None or c.data.ndim > 2:
                    g = c.take(s_idx)
                    sorted_flat.append(Column(
                        g.dtype, g.data, g.validity & s_live, g.lengths,
                        g.children))
                    continue
                valid = sorted_parts[(ci, "validity")] & s_live
                if c.data.ndim == 1:
                    data = sorted_parts[(ci, "data")]
                else:
                    data = jnp.stack(
                        [sorted_parts[((ci, lane), "lane")]
                         for lane in range(c.data.shape[1])], axis=1)
                lengths = (sorted_parts[(ci, "lengths")]
                           if c.lengths is not None else None)
                sorted_flat.append(Column(c.dtype, data, valid, lengths))
            n_inputs = sum(len(ins) for ins in inputs)
            sorted_inputs = []
            k = 0
            for ins in inputs:
                sorted_inputs.append(sorted_flat[k : k + len(ins)])
                k += len(ins)
            sorted_keys = sorted_flat[n_inputs:]
            boundary = s_live & (changed | ~jnp.roll(s_live, 1))
            boundary = boundary.at[0].set(s_live[0])
            n_out = jnp.sum(boundary.astype(jnp.int32))
            if use_segscan:
                seg = build_sorted_segs(boundary, s_live)
            else:
                seg = jnp.clip(jnp.cumsum(boundary.astype(jnp.int32)) - 1, 0, cap - 1)

            # agg inputs arrived in sorted order via the stacked
            # gathers (nested children fell back to take(s_idx))
            state_cols: List[Column] = []
            for a, t, ins in zip(aggs, in_types, sorted_inputs):
                state_cols.extend(reduce_one(a, t, ins, seg, cap, merging))

            # group key columns: already sorted; gather at boundaries
            if use_segscan:
                b_idx = seg.starts
            else:
                b_idx = jnp.nonzero(boundary, size=cap, fill_value=0)[0]
            out_live = jnp.arange(cap) < n_out
            group_out: List[Column] = []
            for skc in sorted_keys:
                g = skc.take(b_idx)
                group_out.append(
                    Column(g.dtype, g.data, g.validity & out_live,
                           None if g.lengths is None else jnp.where(out_live, g.lengths, 0))
                )
            # state columns: indexed by seg id == output row already
            state_out = [
                Column(c.dtype, c.data, c.validity & out_live,
                       None if c.lengths is None else jnp.where(out_live, c.lengths, 0),
                       c.children)
                for c in state_cols
            ]
            return tuple(group_out + state_out), n_out


        @jax.jit
        def scalar_kernel(cols: Tuple[Column, ...], num_rows):
            """No-groups fast path: one jitted masked reduction, state
            is a 1-row batch."""
            schema = in_schema
            env, _, _ = eval_inputs(cols, schema)
            cap = cols[0].validity.shape[0]
            live = jnp.arange(cap) < num_rows
            if pre_filter is not None:
                pf = lower(pre_filter, schema, env, cap)
                live = live & pf.validity & pf.data.astype(jnp.bool_)
            seg = None  # global reduce fast path (no scatter)
            inputs = partial_inputs(env, schema, cap) if not merging else state_inputs(env)
            masked = [
                [Column(c.dtype, c.data, c.validity & live, c.lengths, c.children) for c in ins]
                for ins in inputs
            ]
            state_cols: List[Column] = []
            for a, t, ins in zip(aggs, in_types, masked):
                state_cols.extend(reduce_one(a, t, ins, seg, 1, merging))
            return tuple(state_cols)


        # finalization: state batch -> output batch (FINAL mode)

        def combine_limbs(hi, lo):
            """(hi*2^32 + lo) limbs -> int128 (hi64, lo64)."""
            from ..exprs import int128 as I

            h128 = (hi >> jnp.int64(32), (hi << jnp.int64(32)).view(jnp.uint64))
            return I.add(*h128, *I.from_i64(lo))

        @jax.jit
        def finalize_kernel(cols: Tuple[Column, ...], num_rows):
            from ..exprs import int128 as I

            env = {f.name: c for f, c in zip(state_schema.fields, cols)}
            out: List[Column] = [env[g.name] for g in groupings]
            for a, t in zip(aggs, in_types):
                if a.fn in ("count", "count_star"):
                    out.append(env[f"{a.name}#count"])
                elif a.fn == "sum":
                    if sum_is_wide(t):
                        hc = env[f"{a.name}#sum_hi"]
                        lc = env[f"{a.name}#sum_lo{t.precision}"]
                        nn = env[f"{a.name}#nonnull"]
                        vh, vl = combine_limbs(hc.data, lc.data)
                        data, fits = I.to_i64(vh, vl)
                        # values beyond int64 overflow to NULL (Spark
                        # nulls beyond precision 38; our representable
                        # domain ends at 2^63-1 ≈ 19 digits)
                        out.append(Column(hc.dtype, data, hc.validity & fits & (nn.data > 0)))
                    else:
                        s = env[f"{a.name}#sum"]
                        nn = env[f"{a.name}#nonnull"]
                        out.append(Column(s.dtype, s.data, s.validity & (nn.data > 0)))
                elif a.fn == "avg":
                    res_t = agg_result_type("avg", t)
                    if sum_is_wide(t):
                        hc = env[f"{a.name}#sum_hi"]
                        lc = env[f"{a.name}#sum_lo{t.precision}"]
                        c = env[f"{a.name}#count"]
                        valid = hc.validity & (c.data > 0)
                        den = jnp.where(c.data == 0, jnp.int64(1), c.data)
                        vh, vl = combine_limbs(hc.data, lc.data)
                        vh, vl = I.mul_pow10(vh, vl, res_t.scale - hc.dtype.scale)
                        q, fits = I.div_round_half_up(vh, vl, den)
                        out.append(Column(res_t, q, valid & fits))
                        continue
                    s = env[f"{a.name}#sum"]
                    c = env[f"{a.name}#count"]
                    valid = s.validity & (c.data > 0)
                    den = jnp.where(c.data == 0, jnp.int64(1), c.data)
                    if res_t.is_decimal:
                        shift = res_t.scale - s.dtype.scale
                        if s.dtype.precision + shift <= 18:
                            num = s.data * jnp.int64(10**shift)
                            half = den // 2
                            adj = jnp.where(num >= 0, num + half, num - half)
                            q = jnp.where(adj >= 0, adj // den, -((-adj) // den))
                        else:
                            # shifted sum may exceed int64: exact int128
                            vh, vl = I.mul_pow10(*I.from_i64(s.data), shift)
                            q, fits = I.div_round_half_up(vh, vl, den)
                            valid = valid & fits
                        out.append(Column(res_t, q, valid))
                    else:
                        out.append(
                            Column(res_t, s.data.astype(jnp.float64) / den.astype(jnp.float64), valid)
                        )
                elif a.fn in ("stddev_samp", "var_samp"):
                    cnt = env[f"{a.name}#cnt"].data
                    m2 = env[f"{a.name}#m2"].data
                    nf = cnt.astype(jnp.float64)
                    den = jnp.where(cnt > 1, nf - 1.0, 1.0)
                    var = jnp.maximum(m2, 0.0) / den
                    val = jnp.sqrt(var) if a.fn == "stddev_samp" else var
                    out.append(Column(DataType.float64(), val, cnt > 1))
                elif a.fn in ("collect_list", "collect_set"):
                    out.append(env[f"{a.name}#list"])
                else:
                    out.append(env[f"{a.name}#value"])
            if post_sort is not None:
                # the fused downstream sort: FINAL emits one blocking
                # batch, so the key sort runs INSIDE this program —
                # no extra dispatch, no host round trip between the
                # final merge and the ordered result
                from .sort import apply_sort

                out = list(apply_sort(tuple(out), out_schema, post_sort, num_rows))
            return tuple(out)

        return grouped_kernel, scalar_kernel, finalize_kernel

    # ------------------------------------------------------ execution

    def _reduce_batch(self, batch: RecordBatch, in_schema: Schema) -> RecordBatch:
        """One device reduce of a batch against schema -> state batch."""
        if self.groupings:
            cols, n_out = self._grouped_kernel(tuple(batch.columns), batch.num_rows)
            return RecordBatch(self._state_schema, list(cols), int(n_out))
        cols = self._scalar_kernel(tuple(batch.columns), batch.num_rows)
        return RecordBatch(self._state_schema, list(cols), 1)

    def _update_kernels(self):
        """(grouped_update, scalar_update): the whole-stage update
        programs — per input batch, ONE jitted program reduces the
        batch AND folds it into the stacked accumulator state (the
        reduce and merge kernels inline into a single XLA executable;
        the concat between them is traced, not dispatched).  This is
        the q01 dispatch collapse: the eager path cost one program per
        reduce plus ~#state-buffers programs per concat+merge cascade.

        grouped_update(acc_cols, acc_n, in_cols, in_n, out_cap) ->
        (state cols sliced to the STATIC ``out_cap``, true merged group
        count); when the count exceeds out_cap the caller redoes the
        batch through the eager reduce+merge, which re-buckets the
        grown accumulator to a power-of-two capacity.
        scalar_update(acc_cols, in_cols, in_n) -> 1-row state cols."""
        if self._update_k is None:
            from functools import partial

            from ..batch import _concat_device_cols, head_rows
            from ..runtime import dispatch
            from ..runtime.kernel_cache import cached_kernel

            twin = _StateMerger.for_agg(self)._twin
            # raw (uninstrumented) kernels: inlined sub-programs are
            # not dispatches
            reduce_g = dispatch.raw(self._grouped_kernel)
            reduce_s = dispatch.raw(self._scalar_kernel)
            merge_g = dispatch.raw(twin._grouped_kernel)
            merge_s = dispatch.raw(twin._scalar_kernel)
            state_schema = self._state_schema

            def build():
                @partial(jax.jit, static_argnums=(4,))
                def grouped_update(acc_cols, acc_n, in_cols, in_n, out_cap):
                    part_cols, part_n = reduce_g(in_cols, in_n)
                    cap_a = acc_cols[0].validity.shape[0]
                    cap_i = part_cols[0].validity.shape[0]
                    comb = tuple(
                        _concat_device_cols(
                            f.dtype, [a, p], [acc_n, part_n], cap_a + cap_i
                        )
                        for f, a, p in zip(state_schema.fields, acc_cols, part_cols)
                    )
                    merged, m_n = merge_g(comb, acc_n + part_n)
                    return tuple(head_rows(c, out_cap) for c in merged), m_n

                @jax.jit
                def scalar_update(acc_cols, in_cols, in_n):
                    part_cols = reduce_s(in_cols, in_n)
                    comb = tuple(
                        _concat_device_cols(f.dtype, [a, p], [1, 1], 2)
                        for f, a, p in zip(state_schema.fields, acc_cols, part_cols)
                    )
                    return merge_s(comb, 2)

                return grouped_update, scalar_update

            self._update_k = cached_kernel(
                ("agg_update",) + self._kernel_key, build
            )
        return self._update_k

    def _fused_scalar_update(self, batch: RecordBatch, in_schema: Schema,
                             consumer: "_AggConsumer") -> None:
        """No-groupings fused update: the 1-row state never syncs."""
        acc = consumer.take_state()
        if acc is None:
            consumer.set_state(self._reduce_batch(batch, in_schema))
            return
        _, scalar_update = self._update_kernels()
        cols = scalar_update(
            tuple(acc.columns), tuple(batch.columns), batch.num_rows
        )
        consumer.set_state(RecordBatch(self._state_schema, list(cols), 1))

    def _merge_states(self, states: List[RecordBatch]) -> Optional[RecordBatch]:
        """Associative re-reduce of state batches (merge mode kernel on
        the state schema)."""
        if not states:
            return None
        if len(states) == 1:
            return states[0]
        merged_input = concat_batches(states)
        merger = _StateMerger.for_agg(self)
        return merger.reduce(merged_input)

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        child_stream = self.children[0].execute(partition, ctx)
        in_schema = self.children[0].schema
        # batch autotuning: the agg update is the dispatch-floor hot
        # loop (q01 grouped / q06 scalar both land here after tier-1
        # filter/project absorption), so the controller's coalescing
        # bucket applies to ITS input stream — one update program per
        # bucket instead of one per scan batch
        from ..runtime import dispatch as _dispatch

        if _dispatch.autotune_enabled():
            from ..batch import coalesce_stream

            child_stream = coalesce_stream(
                child_stream, _dispatch.autotune_target_rows)

        def stream():
            merger = _StateMerger.for_agg(self)
            pending: List[RecordBatch] = []
            pending_rows = 0
            consumer = _AggConsumer(self, ctx)
            ctx.mem.register_consumer(consumer)
            in_rows = 0
            skipping = False
            fused_update = bool(conf.FUSED_AGG_UPDATE.get())
            fctx = (
                _FusedGroupedUpdate(self, consumer, in_schema)
                if fused_update and self.groupings else None
            )
            try:
                for batch in child_stream:
                    if not ctx.is_task_running():
                        return
                    in_rows += batch.num_rows
                    part: Optional[RecordBatch] = None
                    # the consumer OWNS the accumulator: a spill() from
                    # the memory manager atomically moves it out, and a
                    # take_state() here starts a fresh accumulation
                    # (re-merging a spilled state would double-count it)
                    if fused_update and not skipping:
                        with self.metrics.timer("elapsed_compute"):
                            if fctx is not None:
                                updated = fctx.update(batch)
                            else:
                                self._fused_scalar_update(batch, in_schema, consumer)
                                updated = True
                    else:
                        updated = False
                    if not updated:
                        with self.metrics.timer("elapsed_compute"):
                            part = self._reduce_batch(batch, in_schema)
                    acc_rows_hint = consumer.state_rows
                    if (
                        self.mode == AggMode.PARTIAL
                        and self.supports_partial_skipping
                        and self.groupings
                        and not skipping
                        and bool(conf.ENABLE_PARTIAL_AGG_SKIPPING.get())
                        and in_rows >= int(conf.PARTIAL_AGG_SKIPPING_MIN_ROWS.get())
                    ):
                        acc_rows = acc_rows_hint + pending_rows + (
                            0 if part is None else part.num_rows
                        )
                        if acc_rows / max(1, in_rows) > float(conf.PARTIAL_AGG_SKIPPING_RATIO.get()):
                            skipping = True
                            self.metrics.add("partial_skipped", 1)
                    if updated:
                        continue  # batch already folded into the accumulator
                    if skipping:
                        # stream states through; downstream merge finishes
                        self._record_batch(part)
                        yield part
                        continue
                    pending.append(part)
                    pending_rows += part.num_rows
                    if acc_rows_hint == 0 or pending_rows >= max(acc_rows_hint, 4096):
                        acc = consumer.take_state()
                        group = ([acc] if acc else []) + pending
                        with self.metrics.timer("elapsed_compute"):
                            acc = self._merge_states(group) if len(group) > 1 else group[0]
                        pending, pending_rows = [], 0
                        consumer.set_state(acc)
                # finish: merge residue + spills
                if fctx is not None:
                    fctx.finish()  # resolve the deferred overflow check
                final_acc = consumer.take_state()
                tail = ([final_acc] if final_acc else []) + pending
                tail += consumer.drain_spills()
                final_state = self._merge_states(tail) if tail else None
                if final_state is not None and final_state.num_rows > 0:
                    out = self._finish(final_state)
                    self._record_batch(out)
                    yield out
                elif not self.groupings:
                    # empty input, global agg still emits one row
                    empty = RecordBatch(
                        in_schema,
                        list(_empty_batch(in_schema).columns),
                        0,
                    )
                    part = self._reduce_batch(empty.to_device(), in_schema)
                    out = self._finish(part)
                    self._record_batch(out)
                    yield out
            finally:
                ctx.mem.unregister_consumer(consumer)

        out_stream = stream()
        # per-group-key NDV sketching (runtime/stats.py, behind
        # spark.blaze.stats.sketches): the output layout puts the
        # grouping keys first, so the sketch hashes exactly those
        # columns.  Disarmed cost is the one sketches_enabled() read.
        if self.groupings:
            from ..runtime import stats as _stats

            if _stats.sketches_enabled():
                out_stream = _stats.sketch_stream(
                    self, len(self.groupings), out_stream)
        return out_stream

    def _finish(self, state: RecordBatch) -> RecordBatch:
        if self.mode == AggMode.FINAL:
            if self.emit_state:
                # boundary fusion: the downstream fused shuffle write
                # owns the finalize (absorb_traceable_chain) — hand it
                # the raw state, single-consumer so donation-eligible
                state.consumable = True
                return state
            cols = self._finalize_kernel(tuple(state.columns), state.num_rows)
            n = state.num_rows
            if self.post_fetch is not None:
                # fused Limit/fetch: rows past n are padding after the
                # in-program post_sort, so a host-side clamp suffices
                n = min(n, self.post_fetch)
            out = RecordBatch(self._schema, list(cols), n)
            out.consumable = True  # fresh finalize output, single consumer
            return out
        return state


def _empty_batch(schema: Schema) -> RecordBatch:
    from ..batch import batch_from_pydict

    return batch_from_pydict({f.name: [] for f in schema.fields}, schema, capacity=int(conf.MIN_CAPACITY.get()))


class _StateMerger:
    """Merge-mode reducer over the state schema (sum of sums etc.).
    Built lazily per AggExec INSTANCE (never keyed by id(): ids recycle
    after GC and a stale twin silently merges with the wrong schema);
    the merge kernels live in a PARTIAL_MERGE-mode twin on the state
    schema."""

    def __init__(self, agg: "AggExec"):
        class _Src(ExecNode):
            def __init__(self, schema):
                super().__init__([])
                self._s = schema

            @property
            def schema(self):
                return self._s

        self._twin = AggExec(
            _Src(agg._state_schema),
            AggMode.PARTIAL_MERGE,
            [GroupingExpr(_col(g.name), g.name) for g in agg.groupings],
            agg.aggs,
        )

    @classmethod
    def for_agg(cls, agg: "AggExec") -> "_StateMerger":
        if agg._merger is None:
            agg._merger = cls(agg)
        return agg._merger

    def reduce(self, state_batch: RecordBatch) -> RecordBatch:
        return self._twin._reduce_batch(state_batch.to_device(), state_batch.schema)


def _col(name):
    from ..exprs.ir import Col

    return Col(name)


class _LazyAccState:
    """Accumulator columns with a DEVICE-RESIDENT occupancy count
    (``n_dev``: the int32 scalar the update program returned, never
    fetched on the per-batch path).  ``hint`` is the last host-known
    count — exact once the deferred overflow check resolved
    (``pending_check`` False), a stale-by-one heuristic before that
    (partial-skipping ratio, merge thresholds).  ``materialize()``
    produces a plain RecordBatch, syncing the scalar only when the
    check is still outstanding."""

    __slots__ = ("schema", "cols", "n_dev", "hint", "pending_check")

    def __init__(self, schema: Schema, cols, n_dev, hint: int):
        self.schema = schema
        self.cols = list(cols)
        self.n_dev = n_dev
        self.hint = int(hint)
        self.pending_check = True

    @property
    def capacity(self) -> int:
        return int(self.cols[0].validity.shape[0])

    @property
    def num_rows(self) -> int:
        return self.hint

    def memory_size(self) -> int:
        return RecordBatch(self.schema, self.cols, self.hint).memory_size()

    def materialize(self) -> RecordBatch:
        n = self.hint if not self.pending_check else int(self.n_dev)
        return RecordBatch(self.schema, list(self.cols), n)


class _FusedGroupedUpdate:
    """Drives the grouped single-program update with the accumulator
    count kept device-resident: batch N+1's program is dispatched
    against batch N's DEVICE count scalar, and N's overflow check
    (``merged groups > bucket capacity``) syncs only AFTER that
    dispatch — so the fused path never stalls the dispatch pipeline on
    a per-batch scalar fetch (over a remote chip the old ``int(m_n)``
    cost a full RTT between every two update programs).

    Rollback: a detected overflow means the checked state AND the
    just-dispatched update consuming it are both invalid.  The driver
    retains the last PROVEN state and the one input batch in flight,
    and rebuilds both steps through the eager reduce+merge (which
    re-buckets the grown accumulator) — the pre-existing overflow
    semantics, paid only when cardinality actually outgrows the bucket.

    Observability (runtime.dispatch counters):
    ``fused_agg_deferred_syncs`` — post-dispatch count fetches (the
    happy path), ``fused_agg_stall_syncs`` — fetches that DID gate a
    dispatch (mode switches; zero on the steady-state path, pinned by
    tests), ``fused_agg_rollbacks`` — overflow rebuilds."""

    def __init__(self, agg: "AggExec", consumer: "_AggConsumer",
                 in_schema: Schema):
        self._agg = agg
        self._consumer = consumer
        self._in_schema = in_schema
        self._good: Optional[Tuple[tuple, int]] = None  # (cols, n) proven
        # (input state, input batch, produced state, bucket capacity)
        self._pending = None

    def update(self, batch: RecordBatch) -> bool:
        """Fold one input batch into the accumulator; False = this
        batch must take the eager pending/doubling path (accumulator
        outgrew one batch bucket)."""
        from ..batch import slice_rows_device

        agg = self._agg
        consumer = self._consumer
        st = consumer.take_state_any()
        if st is None:
            # seed (or post-spill restart): reduce, shrink to its own
            # bucket so steady-state updates sort acc_cap + batch_cap
            # rows, not 2x batch_cap (q01: 4 groups -> min capacity)
            self._pending = None
            part = agg._reduce_batch(batch, self._in_schema)
            cap = bucket_capacity(max(part.num_rows, 1))
            if cap < part.capacity:
                part = slice_rows_device(part, 0, part.num_rows)
            consumer.set_state(part)
            self._good = (tuple(part.columns), part.num_rows)
            return True
        if st.capacity > batch.capacity:
            resolved = self._resolve_to_batch(st, counter="fused_agg_stall_syncs")
            if resolved is not None:
                consumer.set_state(resolved)
            return False
        out_cap = st.capacity
        grouped_update, _ = agg._update_kernels()
        if isinstance(st, _LazyAccState):
            acc_cols, acc_n = tuple(st.cols), st.n_dev
        else:
            # a plain RecordBatch entering the fused path (the eager
            # pending-merge interleave, a post-rollback resume) is
            # proven by construction: it MUST become the rollback base,
            # or an overflow after the resume would rebuild from a
            # stale accumulator and silently drop its merged groups
            self._good = (tuple(st.columns), st.num_rows)
            acc_cols, acc_n = tuple(st.columns), jnp.int32(st.num_rows)
        cols, m_n = grouped_update(
            acc_cols, acc_n, tuple(batch.columns), batch.num_rows, out_cap
        )
        good_n = self._good[1] if self._good is not None else out_cap
        new = _LazyAccState(
            agg._state_schema, cols, m_n,
            hint=min(good_n + batch.num_rows, out_cap),
        )
        consumer.set_state(new)
        prev, self._pending = self._pending, (st, batch, new, out_cap)
        if prev is not None:
            # deferred: the fetched program precedes the one just
            # dispatched in device queue order — no pipeline stall
            self._resolve(prev, counter="fused_agg_deferred_syncs")
        return True

    def finish(self) -> None:
        """Resolve the outstanding check before the stream's finish
        path materializes the state (once per stream, not per batch)."""
        st = self._consumer.take_state_any()
        if st is None:
            self._pending = None
            return
        resolved = self._resolve_to_batch(st, counter="fused_agg_finish_syncs")
        if resolved is not None:
            self._consumer.set_state(resolved)

    # ----------------------------------------------------- internals

    def _resolve(self, pending, counter: str) -> None:
        from ..runtime import dispatch

        in_st, in_batch, out_st, out_cap = pending
        n = int(out_st.n_dev)
        dispatch.record(counter)
        if n <= out_cap:
            out_st.hint = n
            out_st.pending_check = False
            self._good = (tuple(out_st.cols), n)
            return
        # overflow: rebuild from the last proven state through the
        # eager reduce+merge (re-buckets to a power-of-two capacity,
        # preserving the shape-bucketing invariant), replaying the
        # overflowed input batch AND — when a later update already
        # consumed the invalid state — the in-flight batch after it
        dispatch.record("fused_agg_rollbacks")
        agg = self._agg
        good_cols, good_n = self._good
        acc = RecordBatch(agg._state_schema, list(good_cols), good_n)
        part = agg._reduce_batch(in_batch, self._in_schema)
        acc = agg._merge_states([acc, part])
        cur = self._pending
        if cur is not None and cur[2] is not out_st:
            part2 = agg._reduce_batch(cur[1], self._in_schema)
            acc = agg._merge_states([acc, part2])
        self._pending = None
        self._good = (tuple(acc.columns), acc.num_rows)
        self._consumer.set_state(acc)

    def _resolve_to_batch(self, st, counter: str) -> Optional[RecordBatch]:
        """Resolve ``st`` (the consumer's newest state) into a plain
        RecordBatch, running the outstanding overflow check first.
        None = the state ended up in a spill (a rollback re-seats the
        rebuilt accumulator in the consumer, where a concurrent memmgr
        spill may legitimately claim it — the final merge then reads
        it back through drain_spills)."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._resolve(pending, counter=counter)
            if pending[2] is st and pending[2].pending_check:
                # the check rolled the state back: the consumer holds
                # the rebuilt accumulator (unless a spill just took it)
                replaced = self._consumer.take_state_any()
                assert replaced is None or isinstance(replaced, RecordBatch)
                return replaced
        if isinstance(st, _LazyAccState):
            return st.materialize()
        return st


class _AggConsumer(MemConsumer):
    """OWNS the in-flight accumulator state; on pressure, serializes it
    to a Spill (host-RAM or disk tier) and clears it, so the exec
    restarts accumulation — never re-merging a spilled state
    (≙ agg spill path agg_table.rs:343-375, flattened: whole-state
    chunks re-reduced at finish)."""

    name = "agg"

    def __init__(self, agg: AggExec, ctx: TaskContext):
        super().__init__()
        self._agg = agg
        self._state: Optional[RecordBatch] = None
        self._spills: List[Spill] = []
        self._lock = threading.Lock()
        self._quiesced = threading.Condition(self._lock)
        self._inflight = 0      # spills serializing outside the lock
        self._closed = False    # drain started: no further spills

    @property
    def state_rows(self) -> int:
        s = self._state
        return s.num_rows if s is not None else 0

    def take_state(self) -> Optional[RecordBatch]:
        """Atomically claim the accumulator for merging.  A concurrent
        spill() (MemManager serving another thread's pressure) either
        runs before (state already spilled, returns None here) or after
        set_state() — never both paths on the same state, which would
        double-count it.  Device-count states resolve to plain batches
        here (callers on this path need the host row count)."""
        with self._lock:
            s, self._state = self._state, None
        if isinstance(s, _LazyAccState):
            assert not s.pending_check, (
                "fused-update state taken with its overflow check "
                "unresolved (resolve via _FusedGroupedUpdate first)"
            )
            s = s.materialize()
        return s

    def take_state_any(self):
        """Claim the accumulator WITHOUT materializing: the fused
        update path keeps the occupancy count device-resident."""
        with self._lock:
            s, self._state = self._state, None
            return s

    def set_state(self, state) -> None:
        # state handoff and accounting are atomic w.r.t. spill(): a
        # spill landing between them would otherwise leave mem_used
        # reporting phantom memory after the state was already cleared
        with self._lock:
            self._state = state
            self.set_mem_used_no_trigger(state.memory_size())
        self.trigger_spill_check()

    def spill(self) -> int:
        # fault probe at the spill entry, outside the state lock (see
        # _SortState.spill)
        faults.hit("spill.write")
        with self._lock:
            if self._closed:
                # finish() is draining: a spill landing now would
                # append AFTER the drain cleared the list and the
                # state would be silently LOST (observed as missing
                # distinct rows at SF0.1 under a capped budget)
                return 0
            state = self._state
            if state is None:
                return 0
            if isinstance(state, _LazyAccState) and state.pending_check:
                # the deferred overflow check hasn't resolved: this
                # state may be invalid, and spilling it would bake the
                # corruption into the final merge.  It is at most one
                # batch bucket anyway — let pressure fall on the big
                # consumers for this one batch.
                return 0
            self._state = None
            freed = state.memory_size()
            self.set_mem_used_no_trigger(0)
            self._inflight += 1
        # serialize outside the lock: this thread owns `state` now
        if isinstance(state, _LazyAccState):
            state = state.materialize()
        try:
            sp = try_new_spill()
            try:
                sp.write_frame(serialize_batch(state))
                sp.complete()
            except BaseException:
                # never leak the spill's temp file on a failed write
                # (the task retry rebuilds the accumulator state, but
                # the blaze_spill_* file would survive to process exit)
                sp.release()
                raise
            with self._quiesced:
                self._spills.append(sp)
        finally:
            # ALWAYS release the in-flight slot, or a spill error
            # would leave drain_spills() waiting forever
            with self._quiesced:
                self._inflight -= 1
                self._quiesced.notify_all()
        self._agg.metrics.add("spill_count", 1)
        self._agg.metrics.add("spilled_bytes", sp.size)
        return freed

    def drain_spills(self) -> List[RecordBatch]:
        # close the consumer to new spills, then wait out any spill
        # already past the state-claim (it still owns an accumulator
        # chunk that MUST reach the final merge)
        with self._quiesced:
            self._closed = True
            self._quiesced.wait_for(lambda: self._inflight == 0)
            spills, self._spills = self._spills, []
        out: List[RecordBatch] = []
        for sp in spills:
            while True:
                payload = sp.read_frame()
                if payload is None:
                    break
                out.append(deserialize_batch(payload, self._agg._state_schema).to_device())
            sp.release()
        return out
