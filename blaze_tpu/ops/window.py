"""Window functions.

≙ reference WindowExec (window_exec.rs:44-370, window/processors/:
RankLike row_number/rank/dense_rank + Agg processors over
partition-by/order-by).  TPU design: buffer the partition's input
(planner pre-sorts by partition+order keys, like Spark's
EnsureRequirements), then ONE device kernel computes every window
column via segmented prefix ops:

- partition segments from key-word boundaries (as in agg)
- row_number = position - segment start
- rank/dense_rank from order-key-change boundaries inside segments
- running aggregates with Spark's default frame (RANGE UNBOUNDED
  PRECEDING .. CURRENT ROW: peers share the value at their last row)
  via global cumsum minus segment-start offset, gathered at peer-group
  end; whole-partition aggregates via segment reduce + gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import Column, RecordBatch, concat_batches
from ..exprs.compile import infer_dtype, lower
from ..exprs.ir import Expr
from ..runtime.context import TaskContext
from ..schema import DataType, Field, Schema
from .agg import encode_key_words, sum_result_type
from .base import BatchStream, ExecNode
from .sort import SortField, order_words


@dataclass
class WindowFunction:
    """kind: row_number | rank | dense_rank | sum | count | avg |
    min | max (agg kinds use ``expr``).

    Frames: default = RANGE unbounded-preceding..current-peer;
    ``whole_partition`` = unbounded..unbounded; ``rows_frame`` =
    ROWS BETWEEN p PRECEDING AND f FOLLOWING (None bound = unbounded
    on that side) — sum/count/avg only, computed as prefix-sum
    differences clamped to the partition (≙ the reference's sliding
    window processors, window/processors/)."""

    kind: str
    name: str
    expr: Optional[Expr] = None
    whole_partition: bool = False  # True: unbounded..unbounded frame
    rows_frame: Optional[Tuple[Optional[int], Optional[int]]] = None
    offset: int = 1  # lead/lag row offset; ntile bucket count; nth_value n
    ignore_nulls: bool = False  # lead/lag: skip nulls when offsetting
    # RANGE BETWEEN x PRECEDING AND y FOLLOWING on a single numeric
    # ORDER BY key: logical value offsets (None bound = unbounded).
    # Frame rows found by per-partition binary search on the sorted key.
    range_frame: Optional[Tuple[Optional[int], Optional[int]]] = None


def _minmax_sentinel(dt, kind: str):
    """Identity element for a min/max reduce of dtype ``dt``."""
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(jnp.inf if kind == "min" else -jnp.inf, dt)
    info = jnp.iinfo(dt)
    return jnp.array(info.max if kind == "min" else info.min, dt)


def _window_body(in_schema, functions_, part_by, ord_by):
    """The whole-partition window transform as a plain traceable
    function over one buffered batch — jitted standalone by
    :func:`_build_window_kernel`, or inlined into a fused program
    above a partition-buffering node (trace contract)."""

    def kernel(cols: Tuple[Column, ...], num_rows):
        cap = cols[0].validity.shape[0]
        env = {f.name: c for f, c in zip(in_schema.fields, cols)}
        live = jnp.arange(cap) < num_rows

        def boundaries(words):
            ch = jnp.zeros(cap, jnp.bool_)
            for w in words:
                w = jnp.where(live, w, jnp.uint64(0))
                ch = ch | (w != jnp.roll(w, 1))
            return ch.at[0].set(True)

        pwords = encode_key_words([lower(e, in_schema, env, cap) for e in part_by]) if part_by else []
        part_b = boundaries(pwords) if part_by else jnp.zeros(cap, jnp.bool_).at[0].set(True)
        owords: List = []
        for f in ord_by:
            owords.extend(order_words(lower(f.expr, in_schema, env, cap), f.ascending, f.nulls_first))
        peer_b = boundaries(pwords + owords) if ord_by else part_b

        pos = jnp.arange(cap, dtype=jnp.int64)
        seg = jnp.cumsum(part_b.astype(jnp.int64)) - 1
        n_segs = cap  # upper bound
        seg_start = jax.ops.segment_min(pos, seg, num_segments=n_segs, indices_are_sorted=True)
        start_of_row = jnp.take(seg_start, seg)

        # last live row index of each row's partition (frame clamp)
        part_end = jnp.take(
            jax.ops.segment_max(pos * live, seg, num_segments=n_segs, indices_are_sorted=True),
            seg,
        )
        # peer-group end index per row (last row of equal order keys
        # within the partition): next peer boundary - 1
        nxt = jnp.where(peer_b, pos, jnp.int64(cap))
        # for each row, the smallest boundary position > pos:
        rev_min = jax.lax.associative_scan(jnp.minimum, nxt[::-1])[::-1]
        shifted = jnp.concatenate([rev_min[1:], jnp.array([cap], jnp.int64)])
        peer_end = jnp.minimum(shifted - 1, part_end)

        out_cols: List[Column] = list(cols)
        ones = jnp.ones(cap, jnp.bool_) & live

        def range_bounds(f):
            """[lo, hi] row indices of a RANGE offset frame: binary
            search over the partition's sorted single ORDER BY key
            (static log2(cap) steps, vectorized).

            NULL order keys follow Spark's semantics: a null row's
            frame is its null PEER GROUP (all nulls sort together), and
            non-null rows search only the non-null region — nulls would
            otherwise break the sorted-key invariant with garbage data
            lanes."""
            assert len(ord_by) == 1, "RANGE offset frame needs ONE order key"
            kc = lower(ord_by[0].expr, in_schema, env, cap)
            key = kc.data.astype(jnp.int64)
            kvalid = kc.validity & live
            x, y = f.range_frame
            if kc.dtype.is_decimal:
                # frame offsets are LOGICAL values; the key column is
                # unscaled ints
                sc = 10 ** kc.dtype.scale
                x = None if x is None else x * sc
                y = None if y is None else y * sc
            if not ord_by[0].ascending:
                # descending order: negate so the partition region is
                # ascending and the offsets swap roles
                key = -key
            # nulls are contiguous at the partition's head or tail (the
            # upstream sort honours nulls_first); exclude them from the
            # searched region
            cnulls = jnp.cumsum((~kvalid & live).astype(jnp.int64))
            base_n = jnp.where(
                start_of_row > 0,
                jnp.take(cnulls, jnp.maximum(start_of_row - 1, 0)), 0,
            )
            n_nulls = jnp.take(cnulls, jnp.clip(part_end, 0, cap - 1)) - base_n
            if ord_by[0].nulls_first:
                region_lo = start_of_row + n_nulls
                region_hi = part_end
            else:
                region_lo = start_of_row
                region_hi = part_end - n_nulls
            steps = max(1, int(np.ceil(np.log2(max(cap, 2)))) + 1)

            def bsearch(target, side_left: bool):
                # first index in [region_lo, region_hi] with
                # key >= target (left) / key > target (right edge+1)
                lo_b = region_lo
                hi_b = region_hi + 1
                for _ in range(steps):
                    mid = (lo_b + hi_b) // 2
                    kv = jnp.take(key, jnp.clip(mid, 0, cap - 1))
                    go_right = (kv < target) if side_left else (kv <= target)
                    lo_b = jnp.where((mid < hi_b) & go_right, mid + 1, lo_b)
                    hi_b = jnp.where((mid < hi_b) & go_right, hi_b, mid)
                return lo_b

            v = jnp.take(key, jnp.clip(pos, 0, cap - 1))
            lo = region_lo if x is None else bsearch(v - x, True)
            hi = region_hi if y is None else bsearch(v + y, False) - 1
            # null rows: the frame is the null peer group itself
            null_lo = jnp.where(
                ord_by[0].nulls_first, start_of_row, region_hi + 1
            )
            null_hi = jnp.where(
                ord_by[0].nulls_first, region_lo - 1, part_end
            )
            row_is_null = ~jnp.take(kvalid, jnp.clip(pos, 0, cap - 1))
            lo = jnp.where(row_is_null, null_lo, lo)
            hi = jnp.where(row_is_null, null_hi, hi)
            return lo, hi

        for f in functions_:
            if f.kind == "row_number":
                v = pos - start_of_row + 1
                out_cols.append(Column(DataType.int64(), v, ones))
            elif f.kind == "rank":
                last_peer_start = jax.lax.associative_scan(
                    jnp.maximum, jnp.where(peer_b, pos, jnp.int64(0))
                )
                v = last_peer_start - start_of_row + 1
                out_cols.append(Column(DataType.int64(), v, ones))
            elif f.kind == "dense_rank":
                peers_seen = jnp.cumsum(peer_b.astype(jnp.int64))
                peers_at_start = jnp.take(peers_seen, start_of_row)
                v = peers_seen - peers_at_start + 1
                out_cols.append(Column(DataType.int64(), v, ones))
            elif f.kind == "ntile":
                n_buckets = f.offset
                rn0 = pos - start_of_row
                count = part_end - start_of_row + 1
                base = count // n_buckets
                rem = count % n_buckets
                # first `rem` buckets take base+1 rows (Spark NTile)
                fat = rem * (base + 1)
                in_fat = rn0 < fat
                v = jnp.where(
                    in_fat,
                    rn0 // jnp.maximum(base + 1, 1),
                    rem + (rn0 - fat) // jnp.maximum(base, 1),
                ) + 1
                out_cols.append(Column(DataType.int64(), v, ones))
            elif f.kind == "nth_value":
                # value of the frame's n-th row; NULL until the default
                # running frame has grown to n rows (Spark NthValue)
                c = lower(f.expr, in_schema, env, cap)
                src = start_of_row + (f.offset - 1)
                frame_end = part_end if f.whole_partition else peer_end
                in_frame = src <= frame_end
                idx = jnp.clip(src, 0, cap - 1).astype(jnp.int32)
                g = c.take(idx)
                out_cols.append(
                    Column(c.dtype, g.data, g.validity & in_frame & ones,
                           g.lengths, g.children)
                )
            elif f.kind in ("lead", "lag"):
                c = lower(f.expr, in_schema, env, cap)
                if f.ignore_nulls:
                    # k-th NON-NULL neighbour: map valid-ranks to row
                    # indexes once, then gather each row's target rank
                    valid = c.validity & live
                    cv = jnp.cumsum(valid.astype(jnp.int64))  # inclusive
                    rank_slot = jnp.where(valid, cv, jnp.int64(0))
                    idx_of_rank = (
                        jnp.zeros(cap + 1, jnp.int64)
                        .at[rank_slot].set(jnp.where(valid, pos, jnp.int64(0)))
                    )
                    base = jnp.where(
                        start_of_row > 0,
                        jnp.take(cv, jnp.maximum(start_of_row - 1, 0)),
                        jnp.int64(0),
                    )
                    if f.kind == "lag":
                        # k-th valid strictly BEFORE pos, within part
                        before = cv - valid.astype(jnp.int64)
                        target = before - (f.offset - 1)
                        in_part = target > base
                    else:
                        # k-th valid strictly AFTER pos
                        target = cv + f.offset
                        end_cv = jnp.take(cv, jnp.clip(part_end, 0, cap - 1))
                        in_part = target <= end_cv
                    src = jnp.take(
                        idx_of_rank, jnp.clip(target, 0, cap).astype(jnp.int32)
                    )
                    g = c.take(jnp.clip(src, 0, cap - 1).astype(jnp.int32))
                    out_cols.append(
                        Column(c.dtype, g.data, g.validity & in_part & ones,
                               g.lengths, g.children)
                    )
                else:
                    # offset row within the partition; NULL past the edge
                    k = f.offset if f.kind == "lead" else -f.offset
                    src = pos + k
                    in_part = (src >= start_of_row) & (src <= part_end)
                    idx = jnp.clip(src, 0, cap - 1).astype(jnp.int32)
                    g = c.take(idx)
                    out_cols.append(
                        Column(c.dtype, g.data, g.validity & in_part & ones,
                               g.lengths, g.children)
                    )
            elif f.kind in ("first_value", "last_value"):
                # default frame: first over the partition start..peer
                # end window == value at partition start; last == value
                # at peer end (Spark's default RANGE frame semantics);
                # whole_partition: last over the full partition
                c = lower(f.expr, in_schema, env, cap)
                if f.kind == "first_value":
                    src = start_of_row
                else:
                    src = part_end if f.whole_partition else peer_end
                idx = jnp.clip(src, 0, cap - 1).astype(jnp.int32)
                g = c.take(idx)
                out_cols.append(
                    Column(c.dtype, g.data, g.validity & ones, g.lengths, g.children)
                )
            else:
                c = lower(f.expr, in_schema, env, cap)
                valid = c.validity & live
                if f.kind in ("sum", "avg", "count"):
                    st = sum_result_type(c.dtype) if f.kind != "count" else DataType.int64()
                    vals = (
                        jnp.where(valid, c.data, jnp.zeros((), c.data.dtype)).astype(st.np_dtype)
                        if f.kind != "count"
                        else valid.astype(jnp.int64)
                    )
                    csum = jnp.cumsum(vals)
                    cnt = jnp.cumsum(valid.astype(jnp.int64))
                    if f.rows_frame is not None or f.range_frame is not None:
                        # ROWS BETWEEN p..q / RANGE BETWEEN x..y:
                        # prefix-sum difference over [lo, hi] clamped
                        # to the partition
                        if f.rows_frame is not None:
                            p_, q_ = f.rows_frame
                            lo = start_of_row if p_ is None else jnp.maximum(pos - p_, start_of_row)
                            hi = part_end if q_ is None else jnp.minimum(pos + q_, part_end)
                        else:
                            lo, hi = range_bounds(f)
                        base_sum = jnp.where(lo > 0, jnp.take(csum, jnp.maximum(lo - 1, 0)), 0)
                        base_cnt = jnp.where(lo > 0, jnp.take(cnt, jnp.maximum(lo - 1, 0)), 0)
                        hi_c = jnp.clip(hi, 0, cap - 1)
                        run_sum = jnp.take(csum, hi_c) - base_sum
                        run_cnt = jnp.take(cnt, hi_c) - base_cnt
                        empty = hi < lo  # e.g. 0 PRECEDING..0 FOLLOWING off-range
                        run_sum = jnp.where(empty, 0, run_sum)
                        run_cnt = jnp.where(empty, 0, run_cnt)
                    elif f.whole_partition:
                        seg_sum = jax.ops.segment_sum(vals, seg, num_segments=n_segs, indices_are_sorted=True)
                        seg_cnt = jax.ops.segment_sum(valid.astype(jnp.int64), seg, num_segments=n_segs, indices_are_sorted=True)
                        run_sum = jnp.take(seg_sum, seg)
                        run_cnt = jnp.take(seg_cnt, seg)
                    else:
                        base_sum = jnp.where(start_of_row > 0, jnp.take(csum, jnp.maximum(start_of_row - 1, 0)), 0)
                        base_cnt = jnp.where(start_of_row > 0, jnp.take(cnt, jnp.maximum(start_of_row - 1, 0)), 0)
                        run_sum = jnp.take(csum, peer_end) - base_sum
                        run_cnt = jnp.take(cnt, peer_end) - base_cnt
                    if f.kind == "count":
                        out_cols.append(Column(DataType.int64(), run_cnt, ones))
                    elif f.kind == "sum":
                        out_cols.append(Column(st, run_sum, ones & (run_cnt > 0)))
                    else:
                        den = jnp.maximum(run_cnt, 1)
                        from ..schema import decimal_avg_agg_type

                        if c.dtype.is_decimal:
                            rt = decimal_avg_agg_type(c.dtype)
                            shift = rt.scale - c.dtype.scale
                            num = run_sum * jnp.int64(10**shift)
                            half = den // 2
                            adj = jnp.where(num >= 0, num + half, num - half)
                            q = jnp.where(adj >= 0, adj // den, -((-adj) // den))
                            out_cols.append(Column(rt, q, ones & (run_cnt > 0)))
                        else:
                            out_cols.append(
                                Column(
                                    DataType.float64(),
                                    run_sum.astype(jnp.float64) / den.astype(jnp.float64),
                                    ones & (run_cnt > 0),
                                )
                            )
                elif f.kind in ("min", "max"):
                    from .agg import _seg_minmax

                    if f.range_frame is not None:
                        # sparse table over the full column (window
                        # width is value-dependent), bounds from the
                        # per-partition binary search
                        dt = c.data.dtype
                        sentinel = _minmax_sentinel(dt, f.kind)
                        op = jnp.minimum if f.kind == "min" else jnp.maximum
                        levels = max(1, int(np.ceil(np.log2(max(cap, 2)))) + 1)
                        t = jnp.where(valid, c.data, sentinel)
                        table = [t]
                        for j in range(1, levels):
                            half = 1 << (j - 1)
                            prev = table[-1]
                            shifted = jnp.concatenate(
                                [prev[half:], jnp.full(half, sentinel, dt)]
                            )
                            table.append(op(prev, shifted))
                        tbl = jnp.stack(table)
                        l, r = range_bounds(f)
                        ln = jnp.maximum(r - l + 1, 1)
                        jlev = jnp.zeros(cap, jnp.int32)
                        for k in range(1, levels):
                            jlev = jlev + (ln >= (1 << k)).astype(jnp.int32)
                        a = tbl[jlev, jnp.clip(l, 0, cap - 1)]
                        b_end = jnp.clip(r - (1 << jlev.astype(jnp.int64)) + 1, 0, cap - 1)
                        run = op(a, tbl[jlev, b_end])
                        cv = jnp.cumsum(valid.astype(jnp.int64))
                        base_cnt = jnp.where(l > 0, jnp.take(cv, jnp.maximum(l - 1, 0)), 0)
                        run_cnt = jnp.take(cv, jnp.clip(r, 0, cap - 1)) - base_cnt
                        has = ones & (run_cnt > 0) & (r >= l)
                        out_cols.append(
                            Column(c.dtype, jnp.where(has, run, jnp.zeros((), dt)), has)
                        )
                    elif f.rows_frame is not None:
                        # sliding min/max over ROWS BETWEEN p..q via a
                        # SPARSE TABLE: L = ceil(log2(maxW)) doubling
                        # levels T_j[i] = op(T_{j-1}[i], T_{j-1}[i+2^(j-1)])
                        # (static L from the frame spec), then each
                        # row's clamped window [l, r] is op of two
                        # overlapping power-of-2 spans — gathers only,
                        # no data-dependent loop
                        p_, q_ = f.rows_frame
                        if p_ is None or q_ is None:
                            raise NotImplementedError(
                                "unbounded ROWS min/max frame (use the "
                                "running/whole-partition frames)"
                            )
                        dt = c.data.dtype
                        sentinel = _minmax_sentinel(dt, f.kind)
                        op = jnp.minimum if f.kind == "min" else jnp.maximum
                        max_w = p_ + q_ + 1
                        levels = max(1, int(np.ceil(np.log2(max_w))) + 1)
                        # window spans never exceed the batch, so the
                        # table never needs spans beyond cap
                        levels = min(levels, max(1, int(np.ceil(np.log2(cap))) + 1))
                        t = jnp.where(valid, c.data, sentinel)
                        table = [t]
                        for j in range(1, levels):
                            half = 1 << (j - 1)
                            prev = table[-1]
                            shifted = jnp.concatenate(
                                [prev[half:], jnp.full(half, sentinel, dt)]
                            )
                            table.append(op(prev, shifted))
                        tbl = jnp.stack(table)  # (L, cap)
                        part_end_i = part_end.astype(jnp.int64)
                        l = jnp.maximum(pos - p_, start_of_row)
                        r = jnp.minimum(pos + q_, part_end_i)
                        ln = jnp.maximum(r - l + 1, 1)
                        # floor(log2(ln)) with static level count
                        jlev = jnp.zeros(cap, jnp.int32)
                        for k in range(1, levels):
                            jlev = jlev + (ln >= (1 << k)).astype(jnp.int32)
                        a = tbl[jlev, jnp.clip(l, 0, cap - 1)]
                        b_end = jnp.clip(r - (1 << jlev.astype(jnp.int64)) + 1, 0, cap - 1)
                        b_val = tbl[jlev, b_end]
                        run = op(a, b_val)
                        cv = jnp.cumsum(valid.astype(jnp.int64))
                        base_cnt = jnp.where(l > 0, jnp.take(cv, jnp.maximum(l - 1, 0)), 0)
                        run_cnt = jnp.take(cv, jnp.clip(r, 0, cap - 1)) - base_cnt
                        has = ones & (run_cnt > 0) & (r >= l)
                        out_cols.append(
                            Column(c.dtype, jnp.where(has, run, jnp.zeros((), dt)), has)
                        )
                    elif f.whole_partition:
                        red = _seg_minmax(c.data, valid, seg, n_segs, f.kind == "min")
                        has = jax.ops.segment_max(valid.astype(jnp.int32), seg, num_segments=n_segs, indices_are_sorted=True).astype(jnp.bool_)
                        out_cols.append(
                            Column(c.dtype, jnp.take(red, seg), jnp.take(has, seg) & ones)
                        )
                    else:
                        # running frame (unbounded preceding .. current
                        # peer): SEGMENTED prefix min/max — an
                        # associative scan carrying partition-boundary
                        # flags, then gathered at each row's peer end
                        dt = c.data.dtype
                        sentinel = _minmax_sentinel(dt, f.kind)
                        vals = jnp.where(valid, c.data, sentinel)
                        pick = jnp.minimum if f.kind == "min" else jnp.maximum

                        def seg_scan_op(a, b, _pick=pick):
                            m = jnp.where(b[1], b[0], _pick(a[0], b[0]))
                            return m, a[1] | b[1]

                        m, _ = jax.lax.associative_scan(seg_scan_op, (vals, part_b))
                        run = jnp.take(m, peer_end)
                        cv = jnp.cumsum(valid.astype(jnp.int64))
                        base_cnt = jnp.where(
                            start_of_row > 0,
                            jnp.take(cv, jnp.maximum(start_of_row - 1, 0)), 0,
                        )
                        run_cnt = jnp.take(cv, peer_end) - base_cnt
                        has = ones & (run_cnt > 0)
                        out_cols.append(
                            Column(c.dtype, jnp.where(has, run, jnp.zeros((), dt)), has)
                        )
                else:
                    raise NotImplementedError(f.kind)
        return tuple(out_cols)

    return kernel


def _build_window_kernel(in_schema, functions_, part_by, ord_by):
    return jax.jit(_window_body(in_schema, functions_, part_by, ord_by))


class WindowExec(ExecNode):
    def __init__(
        self,
        child: ExecNode,
        functions: Sequence[WindowFunction],
        partition_by: Sequence[Expr],
        order_by: Sequence[SortField],
    ):
        super().__init__([child])
        self.functions = list(functions)
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        for f in self.functions:
            if f.range_frame is not None:
                if f.kind not in ("sum", "count", "avg", "min", "max"):
                    raise NotImplementedError(
                        f"RANGE frame for window kind {f.kind!r}"
                    )
                if len(self.order_by) != 1:
                    raise NotImplementedError(
                        "RANGE offset frame requires exactly one ORDER BY key"
                    )
                kt = infer_dtype(self.order_by[0].expr, child.schema)
                if not (kt.is_integer or kt.is_decimal or kt.kind.name == "DATE32"):
                    raise NotImplementedError(
                        "RANGE offset frame requires an integral order key"
                    )
                continue
            if f.rows_frame is None:
                continue
            if f.kind in ("sum", "count", "avg"):
                continue
            if f.kind in ("min", "max"):
                p_, q_ = f.rows_frame
                if p_ is None or q_ is None:
                    raise NotImplementedError(
                        "unbounded ROWS min/max frame (running and "
                        "whole-partition frames cover those bounds)"
                    )
                continue
            raise NotImplementedError(f"ROWS frame for window kind {f.kind!r}")
        in_schema = child.schema
        out_fields = list(in_schema.fields)
        for f in self.functions:
            if f.kind in ("row_number", "rank", "dense_rank", "count", "ntile"):
                out_fields.append(Field(f.name, DataType.int64()))
            elif f.kind == "nth_value":
                out_fields.append(Field(f.name, infer_dtype(f.expr, in_schema)))
            elif f.kind in ("lead", "lag", "first_value", "last_value"):
                out_fields.append(Field(f.name, infer_dtype(f.expr, in_schema)))
            elif f.kind == "sum":
                out_fields.append(Field(f.name, sum_result_type(infer_dtype(f.expr, in_schema))))
            elif f.kind == "avg":
                t = infer_dtype(f.expr, in_schema)
                from ..schema import decimal_avg_agg_type

                out_fields.append(
                    Field(f.name, decimal_avg_agg_type(t) if t.is_decimal else DataType.float64())
                )
            else:
                out_fields.append(Field(f.name, infer_dtype(f.expr, in_schema)))
        self._schema = Schema(out_fields)

        functions_ = self.functions
        part_by = self.partition_by
        ord_by = self.order_by

        def build():
            return _build_window_kernel(in_schema, functions_, part_by, ord_by)

        from ..exprs.compile import expr_key
        from ..runtime.kernel_cache import cached_kernel, schema_key

        self._key = (
            "window", schema_key(in_schema),
            tuple((f.kind, f.name, None if f.expr is None else expr_key(f.expr),
                   f.whole_partition, f.rows_frame, f.offset,
                   f.ignore_nulls, f.range_frame) for f in functions_),
            tuple(expr_key(e) for e in part_by),
            tuple((expr_key(f.expr), f.ascending, f.nulls_first) for f in ord_by),
        )
        self._kernel = cached_kernel(self._key, build)

    @property
    def schema(self) -> Schema:
        return self._schema

    # ---------------------------------------------- tracing contract
    #
    # The window kernel is exact only over the WHOLE partition in one
    # batch (partition/peer segments span batch boundaries), so the
    # contract advertises trace_requires_buffer: fusion plants a
    # partition-buffering node below the fused program — the same
    # buffer-then-concat this operator's own execute performs — and the
    # kernel composes with downstream traceable ops (e.g. the
    # partition-id computation of a fused shuffle write) in ONE program.

    def trace_fn(self):
        body = _window_body(
            self.children[0].schema, self.functions, self.partition_by,
            self.order_by,
        )

        def fn(cols, num_rows):
            return body(cols, num_rows), num_rows

        return fn

    def trace_key(self):
        return self._key

    @property
    def trace_requires_buffer(self) -> bool:
        return True

    def required_child_orderings(self):
        """Static-analysis contract: the segment kernels assume the
        partition/order layout an upstream sort established.  Relaxed
        form (empty tuple) — the builders sort by varying prefixes of
        (partition_by, order_by), so the verifier only requires that
        SOME sort is downstream (rule ``order.window``)."""
        return [()]

    @property
    def preserves_ordering(self) -> bool:
        # window APPENDS value columns over the buffered partition;
        # row order is untouched, so a stacked window (tpcds q47/q57)
        # still sees the sort below its sibling
        return True

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        child_stream = self.children[0].execute(partition, ctx)

        def stream():
            buffered = [b.to_host() for b in child_stream]
            if not buffered:
                return
            merged = concat_batches(buffered).to_device()
            with self.metrics.timer("elapsed_compute"):
                cols = self._kernel(tuple(merged.columns), merged.num_rows)
            out = RecordBatch(self._schema, list(cols), merged.num_rows)
            self._record_batch(out)
            yield out

        return stream()
