"""CoalesceBatches: re-bucket small batches up to batch_size.

≙ reference coalesce stream (streams/coalesce_stream.rs), which wraps
every operator output.  Here operators keep their natural output size
and the planner inserts this node where small fragments hurt (shuffle
read, filter-heavy chains): fewer, larger device launches.
"""

from __future__ import annotations

from typing import List

from .. import conf
from ..batch import RecordBatch, concat_batches
from ..runtime.context import TaskContext
from ..schema import Schema
from .base import BatchStream, ExecNode


class CoalesceBatchesExec(ExecNode):
    def __init__(self, child: ExecNode, target_rows: int = 0):
        super().__init__([child])
        self.target_rows = target_rows or int(conf.BATCH_SIZE.get())

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        child_stream = self.children[0].execute(partition, ctx)

        def stream():
            buf: List[RecordBatch] = []
            buffered = 0
            for b in child_stream:
                if b.num_rows >= self.target_rows and not buf:
                    self._record_batch(b)
                    yield b
                    continue
                buf.append(b)
                buffered += b.num_rows
                if buffered >= self.target_rows:
                    out = concat_batches(buf)
                    buf, buffered = [], 0
                    self._record_batch(out)
                    yield out
            if buf:
                out = concat_batches(buf) if len(buf) > 1 else buf[0]
                self._record_batch(out)
                yield out

        return stream()
