"""Stage fusion: collapse Filter/Project chains into the partial-agg
kernel so a map stage runs as ONE XLA program.

≙ SURVEY.md §7 "hard parts": "ours depends on keeping a stage's
operator chain fused on-device".  The reference gets per-operator
streams fused by its CPU pipeline; on TPU every operator boundary is a
dispatch + a materialized intermediate, so q06's
scan->filter->project->partial-agg collapses to scan->partial-agg with
the predicate applied as the kernel's liveness mask (AggExec
pre_filter) and the projection substituted into the aggregate
expressions.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..exprs.ir import (
    Alias,
    BinOp,
    Case,
    Cast,
    Col,
    Expr,
    GetIndexedField,
    GetMapValue,
    GetStructField,
    InList,
    IsNotNull,
    IsNull,
    Like,
    NamedStruct,
    Not,
    ScalarFunc,
)


def substitute(e: Expr, mapping: Dict[str, Expr]) -> Expr:
    """Replace column references per ``mapping``, rebuilding the tree."""
    if isinstance(e, Col):
        return mapping.get(e.name, e)
    if isinstance(e, Alias):
        return Alias(substitute(e.child, mapping), e.name)
    if isinstance(e, BinOp):
        return BinOp(e.op, substitute(e.left, mapping), substitute(e.right, mapping))
    if isinstance(e, Not):
        return Not(substitute(e.child, mapping))
    if isinstance(e, IsNull):
        return IsNull(substitute(e.child, mapping))
    if isinstance(e, IsNotNull):
        return IsNotNull(substitute(e.child, mapping))
    if isinstance(e, Cast):
        return Cast(substitute(e.child, mapping), e.to)
    if isinstance(e, Case):
        return Case(
            [(substitute(c, mapping), substitute(v, mapping)) for c, v in e.branches],
            None if e.else_ is None else substitute(e.else_, mapping),
        )
    if isinstance(e, InList):
        return InList(
            substitute(e.child, mapping), [substitute(v, mapping) for v in e.values],
            e.negated,
        )
    if isinstance(e, Like):
        return Like(substitute(e.child, mapping), e.pattern, e.negated)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.name, [substitute(a, mapping) for a in e.args])
    if isinstance(e, GetIndexedField):
        return GetIndexedField(substitute(e.child, mapping), e.index)
    if isinstance(e, GetMapValue):
        return GetMapValue(substitute(e.child, mapping), e.key)
    if isinstance(e, GetStructField):
        return GetStructField(substitute(e.child, mapping), e.name)
    if isinstance(e, NamedStruct):
        return NamedStruct(list(e.names), [substitute(x, mapping) for x in e.exprs])
    return e  # literals, opaque nodes


def projection_mapping(names, exprs) -> Dict[str, Expr]:
    """name -> (Alias-stripped) expr for inlining a projection."""
    return {n: (e.child if isinstance(e, Alias) else e) for n, e in zip(names, exprs)}


def _apply_mapping(groupings, aggs, pre, mapping):
    from .agg import AggFunction, GroupingExpr

    groupings = [GroupingExpr(substitute(g.expr, mapping), g.name) for g in groupings]
    aggs = [
        AggFunction(a.fn, None if a.expr is None else substitute(a.expr, mapping), a.name)
        for a in aggs
    ]
    if pre is not None:
        pre = substitute(pre, mapping)
    return groupings, aggs, pre


def fuse_stages(plan):
    """Rewrite (in place below the root): PARTIAL AggExec over pure
    device Filter/Project chains absorbs them.  Returns the root."""
    from .agg import AggExec, AggFunction, AggMode, GroupingExpr
    from .filter import FilterExec
    from .project import ProjectExec

    def try_fuse(agg: "AggExec"):
        if agg.mode != AggMode.PARTIAL:
            return agg
        groupings = list(agg.groupings)
        aggs = list(agg.aggs)
        pre = agg.pre_filter
        child = agg.children[0]
        changed = False
        while True:
            if isinstance(child, ProjectExec) and not child._host_parts:
                mapping = projection_mapping(child.names, child.exprs)
                groupings, aggs, pre = _apply_mapping(groupings, aggs, pre, mapping)
                child = child.children[0]
                changed = True
                continue
            if isinstance(child, FilterExec) and not child._host_parts:
                if child.project is not None:
                    # a filter already fused with a projection: inline
                    # the projection first (pre/groupings/aggs reference
                    # its OUTPUT names), then AND the predicate (which
                    # references the filter's INPUT schema)
                    proj_exprs, proj_names = child.project
                    mapping = projection_mapping(proj_names, proj_exprs)
                    groupings, aggs, pre = _apply_mapping(groupings, aggs, pre, mapping)
                pred = child.predicate
                pre = pred if pre is None else BinOp("and", pred, pre)
                child = child.children[0]
                changed = True
                continue
            break
        if not changed:
            return agg
        return AggExec(
            child, AggMode.PARTIAL, groupings, aggs,
            supports_partial_skipping=agg.supports_partial_skipping,
            pre_filter=pre,
        )

    def try_fuse_fp(node):
        """Project(Filter(x)) / Filter(Project(x)) -> one FilterExec
        with a fused projection (single kernel, compacts only the
        projected columns)."""
        if (
            isinstance(node, ProjectExec)
            and not node._host_parts
            and node._select_names is None
            and isinstance(node.children[0], FilterExec)
            and not node.children[0]._host_parts
            and node.children[0].project is None
        ):
            f = node.children[0]
            return FilterExec(f.children[0], f.predicate,
                              project=(list(node.exprs), list(node.names)))
        if (
            isinstance(node, FilterExec)
            and node.project is None
            and not node._host_parts
            and isinstance(node.children[0], ProjectExec)
            and not node.children[0]._host_parts
        ):
            proj = node.children[0]
            mapping = projection_mapping(proj.names, proj.exprs)
            return FilterExec(
                proj.children[0], substitute(node.predicate, mapping),
                project=(list(proj.exprs), list(proj.names)),
            )
        return node

    def walk(node):
        for i, c in enumerate(list(node.children)):
            walk(c)
            if isinstance(c, AggExec):
                node.children[i] = try_fuse(c)
            else:
                node.children[i] = try_fuse_fp(node.children[i])

    from .agg import AggExec

    walk(plan)
    if isinstance(plan, AggExec):
        return try_fuse(plan)
    return try_fuse_fp(plan)
