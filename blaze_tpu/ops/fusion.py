"""Whole-stage program fusion: collapse a stage's operator chain into
single XLA programs.

≙ SURVEY.md §7 "hard parts": "ours depends on keeping a stage's
operator chain fused on-device".  The reference gets per-operator
streams fused by its CPU pipeline; on TPU every operator boundary is a
dispatch + a materialized intermediate, and over a remote/tunneled
chip each dispatch costs ~70-80 ms of per-program turnaround — q01's
hash-agg -> final-merge -> sort chain issued on the order of a hundred
programs per batch (VERDICT r5).  Four tiers, all gated on
``spark.blaze.fusion.enabled``:

1. **Agg absorption** (:func:`fuse_stages`): a PARTIAL AggExec over
   pure device Filter/Project chains absorbs them — the predicate
   becomes the kernel's liveness mask (``pre_filter``) and projections
   substitute into the aggregate expressions, so q06 collapses to
   scan->partial-agg.
2. **Trivial-exchange elimination** (:func:`fuse_stages`): a shuffle
   into ONE partition whose child already has one partition is a
   pass-through; dropping it removes the partition/concat programs
   between the final agg and its consumer in single-chip plans.
3. **Final-sort folding** (:func:`fuse_stages`): ``Limit?(Sort(FINAL
   agg))`` folds the key sort (+ fetch clamp) into the agg's finalize
   program — FINAL emits one blocking batch per partition, so the
   in-program sort is exact (``AggExec.post_sort``/``post_fetch``).
4. **Traceable-chain collapse** (:func:`fuse_traceable_chains`, run
   AFTER column pruning so scan narrowing still sees the original
   operators): consecutive unary operators exposing the
   ``ExecNode.trace_fn`` contract compose into one
   :class:`FusedStageExec` program per batch.  Operators whose traced
   transform needs the whole partition in one batch
   (``trace_requires_buffer`` — WindowExec) get a
   :class:`BufferPartitionExec` planted below the fused program.
5. **Fused shuffle write** (:func:`fuse_shuffle_write`, run last): when
   a traceable chain (or nothing) feeds a ``ShuffleWriterExec`` with
   hash or round-robin partitioning, the chain's transform, the
   partition-id computation, the pid sort, and the per-partition
   bincount compose into ONE program per batch
   (``ShuffleWriterExec.absorb_traceable_chain``) — a shuffle map
   stage costs ~1 dispatch/batch instead of chain+hash+sort, mirroring
   the reference's native shuffle writer where map-side compute and
   partitioning live in one pipeline.

The per-batch agg-update program (reduce + accumulator merge in one
dispatch) lives in ``ops/agg.py`` (``AggExec._update_kernels``); the
``fused_stage_len`` observability counter feeds the scheduler's
MetricNode through ``runtime.dispatch``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import conf
from ..batch import RecordBatch
from .base import BatchStream, ExecNode

from ..exprs.ir import (
    Alias,
    BinOp,
    Case,
    Cast,
    Col,
    Expr,
    GetIndexedField,
    GetMapValue,
    GetStructField,
    InList,
    IsNotNull,
    IsNull,
    Like,
    NamedStruct,
    Not,
    ScalarFunc,
)


def substitute(e: Expr, mapping: Dict[str, Expr]) -> Expr:
    """Replace column references per ``mapping``, rebuilding the tree."""
    if isinstance(e, Col):
        return mapping.get(e.name, e)
    if isinstance(e, Alias):
        return Alias(substitute(e.child, mapping), e.name)
    if isinstance(e, BinOp):
        return BinOp(e.op, substitute(e.left, mapping), substitute(e.right, mapping))
    if isinstance(e, Not):
        return Not(substitute(e.child, mapping))
    if isinstance(e, IsNull):
        return IsNull(substitute(e.child, mapping))
    if isinstance(e, IsNotNull):
        return IsNotNull(substitute(e.child, mapping))
    if isinstance(e, Cast):
        return Cast(substitute(e.child, mapping), e.to)
    if isinstance(e, Case):
        return Case(
            [(substitute(c, mapping), substitute(v, mapping)) for c, v in e.branches],
            None if e.else_ is None else substitute(e.else_, mapping),
        )
    if isinstance(e, InList):
        return InList(
            substitute(e.child, mapping), [substitute(v, mapping) for v in e.values],
            e.negated,
        )
    if isinstance(e, Like):
        return Like(substitute(e.child, mapping), e.pattern, e.negated)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.name, [substitute(a, mapping) for a in e.args])
    if isinstance(e, GetIndexedField):
        return GetIndexedField(substitute(e.child, mapping), e.index)
    if isinstance(e, GetMapValue):
        return GetMapValue(substitute(e.child, mapping), e.key)
    if isinstance(e, GetStructField):
        return GetStructField(substitute(e.child, mapping), e.name)
    if isinstance(e, NamedStruct):
        return NamedStruct(list(e.names), [substitute(x, mapping) for x in e.exprs])
    return e  # literals, opaque nodes


def projection_mapping(names, exprs) -> Dict[str, Expr]:
    """name -> (Alias-stripped) expr for inlining a projection."""
    return {n: (e.child if isinstance(e, Alias) else e) for n, e in zip(names, exprs)}


def _apply_mapping(groupings, aggs, pre, mapping):
    from .agg import AggFunction, GroupingExpr

    groupings = [GroupingExpr(substitute(g.expr, mapping), g.name) for g in groupings]
    aggs = [
        AggFunction(a.fn, None if a.expr is None else substitute(a.expr, mapping), a.name)
        for a in aggs
    ]
    if pre is not None:
        pre = substitute(pre, mapping)
    return groupings, aggs, pre


def fuse_stages(plan):
    """Rewrite (in place below the root): agg absorption, trivial
    single-partition exchange elimination, and final-sort folding (see
    module docstring tiers 1-3).  Returns the root.  A no-op under
    ``spark.blaze.fusion.enabled=false`` — the per-operator fallback
    the fused-vs-unfused differential tests pin."""
    from .agg import AggExec, AggFunction, AggMode, GroupingExpr
    from .filter import FilterExec
    from .project import ProjectExec

    if not bool(conf.FUSION_ENABLE.get()):
        return plan

    plan = _drop_noop_exchanges(plan)

    def try_fuse(agg: "AggExec"):
        if agg.mode != AggMode.PARTIAL:
            return agg
        groupings = list(agg.groupings)
        aggs = list(agg.aggs)
        pre = agg.pre_filter
        child = agg.children[0]
        changed = False
        absorbed = 0
        while True:
            if isinstance(child, ProjectExec) and not child._host_parts:
                mapping = projection_mapping(child.names, child.exprs)
                groupings, aggs, pre = _apply_mapping(groupings, aggs, pre, mapping)
                child = child.children[0]
                changed = True
                absorbed += 1
                continue
            if isinstance(child, FilterExec) and not child._host_parts:
                if child.project is not None:
                    # a filter already fused with a projection: inline
                    # the projection first (pre/groupings/aggs reference
                    # its OUTPUT names), then AND the predicate (which
                    # references the filter's INPUT schema)
                    proj_exprs, proj_names = child.project
                    mapping = projection_mapping(proj_names, proj_exprs)
                    groupings, aggs, pre = _apply_mapping(groupings, aggs, pre, mapping)
                pred = child.predicate
                pre = pred if pre is None else BinOp("and", pred, pre)
                child = child.children[0]
                changed = True
                absorbed += 1
                continue
            break
        if not changed:
            return agg
        from ..runtime import dispatch

        dispatch.record_max("fused_stage_len", absorbed + 1)
        return AggExec(
            child, AggMode.PARTIAL, groupings, aggs,
            supports_partial_skipping=agg.supports_partial_skipping,
            pre_filter=pre,
        )

    def try_fuse_fp(node):
        """Project(Filter(x)) / Filter(Project(x)) -> one FilterExec
        with a fused projection (single kernel, compacts only the
        projected columns)."""
        if (
            isinstance(node, ProjectExec)
            and not node._host_parts
            and node._select_names is None
            and isinstance(node.children[0], FilterExec)
            and not node.children[0]._host_parts
            and node.children[0].project is None
        ):
            f = node.children[0]
            return FilterExec(f.children[0], f.predicate,
                              project=(list(node.exprs), list(node.names)))
        if (
            isinstance(node, FilterExec)
            and node.project is None
            and not node._host_parts
            and isinstance(node.children[0], ProjectExec)
            and not node.children[0]._host_parts
        ):
            proj = node.children[0]
            mapping = projection_mapping(proj.names, proj.exprs)
            return FilterExec(
                proj.children[0], substitute(node.predicate, mapping),
                project=(list(proj.exprs), list(proj.names)),
            )
        return node

    def walk(node):
        for i, c in enumerate(list(node.children)):
            walk(c)
            if isinstance(c, AggExec):
                node.children[i] = try_fuse(c)
            else:
                node.children[i] = try_fuse_fp(node.children[i])

    from .agg import AggExec

    walk(plan)
    if isinstance(plan, AggExec):
        plan = try_fuse(plan)
    else:
        plan = try_fuse_fp(plan)
    return _fuse_final_sort(plan)


# ------------------------------------------------- tier 2: exchanges

def _drop_noop_exchanges(plan):
    """Remove shuffle exchanges that provably move nothing: ONE output
    partition fed by ONE input partition is a pass-through (any
    partitioning function maps every row to partition 0).  In
    single-chip plans this deletes the partition-kernel + concat
    programs between the two agg stages and before the result sort —
    the adjacency tiers 3/4 then fuse across."""
    from ..parallel.exchange import NativeShuffleExchangeExec

    def rewrite(node):
        while (
            isinstance(node, NativeShuffleExchangeExec)
            and node.partitioning.num_partitions == 1
            and node.children[0].num_partitions() == 1
        ):
            node = node.children[0]
        return node

    def walk(node):
        for i, c in enumerate(list(node.children)):
            node.children[i] = rewrite(c)
            walk(node.children[i])

    plan = rewrite(plan)
    walk(plan)
    return plan


# ------------------------------------------- tier 3: final-agg sort

def _fuse_final_sort(plan):
    """Fold ``Limit?(Sort(FINAL agg))`` into the agg's finalize
    program (``post_sort``/``post_fetch``): the FINAL agg emits one
    blocking batch per partition, so sorting inside finalize is exact
    and saves the sort's own dispatch + host round trip."""
    from ..exprs.compile import device_only, infer_dtype
    from .agg import AggExec, AggMode
    from .limit import LimitExec
    from .pruning import expr_columns
    from .sort import SortExec

    def rewrite(node):
        limit = None
        sort = node
        if isinstance(node, LimitExec) and isinstance(node.children[0], SortExec):
            limit = node.limit
            sort = node.children[0]
        if not isinstance(sort, SortExec):
            return node
        agg = sort.children[0]
        if not (
            isinstance(agg, AggExec)
            and agg.mode == AggMode.FINAL
            and agg.post_sort is None
            and device_only([f.expr for f in sort.fields])
        ):
            return node
        out_names = set(agg.schema.names)
        for f in sort.fields:
            if not expr_columns(f.expr) <= out_names:
                return node
            if infer_dtype(f.expr, agg.schema).is_nested:
                return node  # no order words for nested keys
        fetch = sort.fetch
        if limit is not None:
            fetch = limit if fetch is None else min(fetch, limit)
        from ..runtime import dispatch

        dispatch.record_max("fused_stage_len", 2 if limit is None else 3)
        return AggExec(
            agg.children[0], agg.mode, agg.groupings, agg.aggs,
            supports_partial_skipping=agg.supports_partial_skipping,
            pre_filter=agg.pre_filter,
            post_sort=list(sort.fields), post_fetch=fetch,
        )

    def walk(node):
        for i, c in enumerate(list(node.children)):
            node.children[i] = rewrite(c)
            walk(node.children[i])

    plan = rewrite(plan)
    walk(plan)
    return plan


# -------------------------------------- tier 4: traceable chains

class BufferPartitionExec(ExecNode):
    """Buffer the child partition's batches and emit them as ONE
    concatenated batch — the blocking prelude a ``trace_requires_buffer``
    operator (WindowExec) needs before its traced transform can join a
    fused program.  Identical semantics to WindowExec's own
    buffer-then-concat execute, just factored below the fused kernel."""

    def __init__(self, child: ExecNode):
        super().__init__([child])

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def preserves_ordering(self) -> bool:
        return True  # concat of the ordered stream, in order

    def execute(self, partition: int, ctx) -> BatchStream:
        child_stream = self.children[0].execute(partition, ctx)

        def stream():
            from ..batch import concat_batches

            buffered = [b.to_host() for b in child_stream]
            if not buffered:
                return
            merged = concat_batches(buffered).to_device()
            self._record_batch(merged)
            yield merged

        return stream()


class FusedStageExec(ExecNode):
    """One jitted program per batch for a chain of traceable unary
    operators (``ExecNode.trace_fn`` contract), bottom-up.  All
    intermediates stay on device; the single count scalar syncs only
    when some fused operator compacts rows.

    Itself implements the trace contract (the composition of its ops'
    transforms), so tier 5 can absorb an already-collapsed chain into
    a fused shuffle-write program without re-walking the originals."""

    def __init__(self, child, ops: List):
        super().__init__([child])
        self.ops = list(ops)  # bottom -> top
        self._schema = self.ops[-1].schema
        self._changes_count = any(op.trace_changes_count for op in self.ops)
        fns = [op.trace_fn() for op in self.ops]
        assert all(fn is not None for fn in fns)
        self._fns = fns
        self._keys = tuple(op.trace_key() for op in self.ops)
        keys = self._keys
        # slots-as-cols-tail contract (ops/base.py trace_slots): the
        # fused program takes the CONCATENATION of every op's slot
        # values appended after the input columns and deals each op its
        # own group; the per-op counts are static (part of the chain's
        # structure), only the VALUES are traced, so parameter-shifted
        # chains reuse this one compiled program.
        self._slot_counts = tuple(len(op.trace_slots()) for op in self.ops)
        self._slot_args = tuple(
            v for op in self.ops for v in op.trace_slots())
        slot_counts = self._slot_counts
        n_slots = len(self._slot_args)

        def build():
            import jax

            @jax.jit
            def kernel(cols, num_rows):
                cols = tuple(cols)
                slots = cols[len(cols) - n_slots:] if n_slots else ()
                cols = cols[:len(cols) - n_slots] if n_slots else cols
                n = num_rows
                i = 0
                for fn, cnt in zip(fns, slot_counts):
                    cols, n = fn(tuple(cols) + slots[i:i + cnt], n)
                    i += cnt
                return cols, n

            return kernel

        from ..runtime.kernel_cache import cached_kernel

        self._kernel = cached_kernel(("fused_stage", keys), build)
        self.metrics.set("fused_stage_len", len(self.ops))
        #: OOM degradation (runtime/oom.py): halving a batch is only
        #: sound for per-row streaming transforms — a whole-partition
        #: op (trace_requires_buffer, e.g. window) must see its batch
        #: intact, so such chains skip rung 2 and go straight to eager
        self._downshift_ok = not any(
            getattr(op, "trace_requires_buffer", False) for op in self.ops)
        self._eager_kernels = None  # built lazily, only if rung 3 fires

    @property
    def schema(self):
        return self._schema

    # ------------------------------------------- tracing contract

    def trace_fn(self):
        fns = self._fns
        slot_counts = self._slot_counts
        n_slots = len(self._slot_args)

        def fn(cols, num_rows):
            cols = tuple(cols)
            slots = cols[len(cols) - n_slots:] if n_slots else ()
            cols = cols[:len(cols) - n_slots] if n_slots else cols
            n = num_rows
            i = 0
            for f, cnt in zip(fns, slot_counts):
                cols, n = f(tuple(cols) + slots[i:i + cnt], n)
                i += cnt
            return cols, n

        return fn

    def trace_key(self):
        return ("fused_stage", self._keys)

    def trace_slots(self) -> tuple:
        # the chain's flattened slot vector, in op order — an enclosing
        # consumer (the fused shuffle write) appends these exactly like
        # any single op's slots
        return self._slot_args

    @property
    def trace_changes_count(self) -> bool:
        return self._changes_count

    @property
    def preserves_ordering(self) -> bool:
        # every traceable op is a per-row/in-order transform; columns
        # may be renamed by fused projections, so the verifier
        # downgrades key matching past a fused chain
        return True

    def name(self) -> str:
        inner = "+".join(type(op).__name__ for op in self.ops)
        return f"FusedStageExec[{inner}]"

    def _eager_run(self, batch):
        """Rung 3 of the OOM ladder: the chain's per-operator programs,
        one dispatch each (the pre-fusion path) — every intermediate is
        materialized separately, so peak program memory drops to the
        single-op footprint.  Kernels are cached under the op's own
        trace key and built only the first time the rung fires."""
        if self._eager_kernels is None:
            from ..runtime.oom import build_eager_kernels

            self._eager_kernels = build_eager_kernels(
                [(op.trace_key(), fn)
                 for op, fn in zip(self.ops, self._fns)])
        cols, n = tuple(batch.columns), batch.num_rows
        for kernel, op in zip(self._eager_kernels, self.ops):
            cols, n = kernel(tuple(cols) + op.trace_slots(), n)
        return cols, n

    def _degradable_results(self, batch, depth: int):
        """Run one batch through the fused program, walking rungs 2-3
        of the OOM degradation ladder (rung 1 — force-spill + one
        retry — already ran inside the instrumented kernel,
        runtime/dispatch._oom_call).  Yields ``(cols, n)`` per
        surviving piece with the live count already RESOLVED: the
        one-scalar sync (when a fused op compacts) happens inside the
        try, so a RESOURCE_EXHAUSTED that async dispatch only surfaces
        at the first consumption point is still caught by the ladder —
        and inside the caller's ``elapsed_compute`` timer, so the
        device bill stays attributed.  A non-compacting chain's OOM
        can still surface further downstream (the next host transfer);
        that path fails the attempt and retries, the pre-ladder
        behavior."""
        from ..runtime import oom as _oom

        try:
            cols, n_dev = self._kernel(
                tuple(batch.columns) + self._slot_args, batch.num_rows)
            n = int(n_dev) if self._changes_count else batch.num_rows
        except Exception as exc:  # noqa: BLE001 — classified below
            if not _oom.is_resource_exhausted(exc):
                raise
            if (self._downshift_ok and depth < _oom.max_downshifts()
                    and batch.num_rows > 1):
                _oom.record_downshift("fused_stage", batch.num_rows,
                                      depth + 1)
                for piece in _oom.split_batch(batch):
                    yield from self._degradable_results(piece, depth + 1)
                return
            _oom.record_eager_fallback("fused_stage")
            try:
                cols, n_dev = self._eager_run(batch)
                n = int(n_dev) if self._changes_count else batch.num_rows
            except Exception as exc2:  # noqa: BLE001
                if _oom.is_resource_exhausted(exc2):
                    # ladder exhausted: genuine pressure, retryable
                    raise _oom.DeviceOomError(self.name(), exc2) from exc2
                raise
        yield cols, n

    def execute(self, partition: int, ctx) -> BatchStream:
        child_stream = self.children[0].execute(partition, ctx)

        def stream():
            from ..batch import bucket_capacity

            for batch in child_stream:
                with self.metrics.timer("elapsed_compute"):
                    pieces = list(self._degradable_results(batch, 0))
                for cols, n in pieces:
                    if n == 0:
                        continue
                    out = RecordBatch(self._schema, list(cols), n)
                    # expanding ops (generate cap*M, expand cap*P)
                    # leave a non-power-of-two capacity: renormalize so
                    # downstream kernels keep the shape-bucketing
                    # invariant (mirrors GenerateExec's unfused stream)
                    cap = out.capacity
                    if cap != bucket_capacity(cap):
                        out = out.with_capacity(bucket_capacity(n))
                    self._record_batch(out)
                    yield out

        return stream()


def optimize_plan(plan):
    """THE canonical task-plan optimizer composition:
    ``fuse_stages -> prune_columns -> fuse_traceable_chains ->
    fuse_shuffle_write`` (order matters: pruning rebuilds known
    operator types and treats FusedStageExec conservatively, so chain
    collapse must come after it, and the shuffle-write absorption eats
    the collapsed chain, so it must come last).  Every entry point —
    run_task, bench.py, ``--warmup``, the budget tests — MUST go
    through this helper: the persistent compile cache pre-warm is only
    worth anything if warmup compiles exactly the programs production
    tasks execute.

    With conf ``spark.blaze.verify.plan`` armed (forced on in tests
    and ``--chaos``), the OPTIMIZED plan runs through the structural
    plan verifier (analysis/plan_verify.py) before execution — this is
    THE choke point every execution path crosses, so a rewrite tier
    that breaks a schema/distribution/ordering/fusion invariant fails
    loudly here instead of producing wrong answers downstream."""
    from .pruning import prune_columns

    plan = fuse_shuffle_write(
        fuse_traceable_chains(prune_columns(fuse_stages(plan)))
    )
    if bool(conf.VERIFY_PLAN.get()):
        from ..analysis.plan_verify import verify_or_raise

        verify_or_raise(plan)
    # Level-1 plan-cache bookkeeping (runtime/querycache.py): every
    # execution path crosses this choke point, so the fingerprint tally
    # here is THE ground truth for compiled-program reuse — a hit means
    # this plan structure's programs (parameter shifts included, via
    # literal slots) are already in the kernel cache
    from ..runtime.querycache import record_plan

    fp = record_plan(plan)
    # Runtime-stats estimator (runtime/stats.py): stamp est_rows /
    # est_bytes onto the optimized plan (persisted actuals for this
    # fingerprint replace the cold estimates) and register the
    # instance for actuals collection at query-span flush.  Disarmed
    # cost is the one enabled() bool read.
    from ..runtime import stats as _stats

    if _stats.enabled():
        _stats.annotate(plan, fp)
    return plan


def traceable_chain_from(node):
    """THE chain-discovery rule every fusion consumer shares (tier 4's
    collapse and tier 5's shuffle-write absorption must agree on what a
    chain is): walk down through consecutive unary operators exposing
    ``trace_fn``, stopping after a ``trace_requires_buffer`` op (a
    whole-partition transform like window becomes the chain's BOTTOM,
    fed by a partition-buffering node; anything below it streams per
    batch and is collapsed separately by the recursive walks).
    Returns (ops top-down, the node below the chain, buffered?)."""
    ops_top_down = []
    cur = node
    buffered = False
    while len(cur.children) == 1 and cur.trace_fn() is not None:
        ops_top_down.append(cur)
        if cur.trace_requires_buffer:
            buffered = True
            cur = cur.children[0]
            break
        cur = cur.children[0]
    return ops_top_down, cur, buffered


def fuse_traceable_chains(plan):
    """Collapse maximal runs (length >= 2, with >= 2 real kernels) of
    consecutive traceable unary operators into FusedStageExec nodes.
    Run AFTER ``prune_columns`` — pruning rebuilds known operator
    types and treats FusedStageExec conservatively, so fusing first
    would block scan narrowing."""
    if not bool(conf.FUSION_ENABLE.get()):
        return plan

    chain_from = traceable_chain_from

    def rewrite(node):
        ops, bottom, buffered = chain_from(node)
        kernels = sum(1 for o in ops if o.has_kernel)
        if len(ops) >= 2 and kernels >= 2:
            from ..runtime import dispatch

            dispatch.record_max("fused_stage_len", len(ops))
            if buffered:
                bottom = BufferPartitionExec(bottom)
            return FusedStageExec(bottom, list(reversed(ops)))
        return node

    def walk(node):
        for i, c in enumerate(list(node.children)):
            node.children[i] = rewrite(c)
            walk(node.children[i])

    plan = rewrite(plan)
    walk(plan)
    return plan


# -------------------------------------- tier 5: fused shuffle write

def fuse_shuffle_write(plan):
    """Absorb the traceable chain feeding each hash/round-robin
    ``ShuffleWriterExec`` into the writer's per-batch program: chain
    transform + partition-id computation + pid sort + per-partition
    counts compile into ONE dispatch (see
    ``ShuffleWriterExec.absorb_traceable_chain``).  Applies after
    :func:`fuse_traceable_chains`, so the common shape is absorbing a
    single FusedStageExec (whose trace contract composes its ops)."""
    if not bool(conf.FUSION_ENABLE.get()):
        return plan
    from ..parallel.shuffle import ShuffleWriterExec

    def rewrite(node):
        if isinstance(node, ShuffleWriterExec):
            node.absorb_traceable_chain()
        return node

    def walk(node):
        for i, c in enumerate(list(node.children)):
            walk(rewrite(c))

    walk(rewrite(plan))
    return plan
