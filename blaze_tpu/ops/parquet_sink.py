"""Parquet sink.

≙ reference ParquetSinkExec (parquet_sink_exec.rs:55-573): drains the
child stream into parquet files, one per partition, with hive-style
``col=value`` subdirectories when partition columns are set (dynamic
partitioning).  Output paths/committing belong to the caller (the JVM
side's NativeParquetSinkUtils / committer in Spark mode; the standalone
scheduler here).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..batch import RecordBatch, strings_to_list
from ..io import parquet as pq
from ..runtime.context import TaskContext
from ..schema import Field, Schema
from .base import BatchStream, ExecNode


class ParquetSinkExec(ExecNode):
    def __init__(
        self,
        child: ExecNode,
        output_path: str,
        partition_columns: Sequence[str] = (),
    ):
        super().__init__([child])
        self.output_path = output_path
        self.partition_columns = list(partition_columns)
        self.written_files: List[str] = []

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _accumulate(self, target: Dict[str, list], batch: RecordBatch):
        b = batch.to_host()
        for f, c in zip(b.schema.fields, b.columns):
            data = np.asarray(c.data)[: b.num_rows]
            validity = np.asarray(c.validity)[: b.num_rows]
            entry = target.setdefault(f.name, [[], [], []])
            entry[0].append(data)
            entry[1].append(validity)
            if c.lengths is not None:
                entry[2].append(np.asarray(c.lengths)[: b.num_rows])

    def _write(self, path: str, cols: Dict[str, list], schema: Schema):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        arrays = {}
        for f in schema.fields:
            data_parts, valid_parts, len_parts = cols[f.name]
            if f.dtype.is_string:
                w = f.dtype.string_width
                n = sum(p.shape[0] for p in data_parts)
                data = np.zeros((n, w), np.uint8)
                off = 0
                for p in data_parts:
                    data[off : off + p.shape[0], : p.shape[1]] = p[:, :w]
                    off += p.shape[0]
                arrays[f.name] = (
                    data,
                    np.concatenate(valid_parts) if valid_parts else np.zeros(0, bool),
                    np.concatenate(len_parts) if len_parts else np.zeros(0, np.int32),
                )
            else:
                arrays[f.name] = (
                    np.concatenate(data_parts) if data_parts else np.zeros(0, f.dtype.np_dtype),
                    np.concatenate(valid_parts) if valid_parts else np.zeros(0, bool),
                    None,
                )
        pq.write_parquet(path, schema, arrays)
        self.written_files.append(path)

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        def stream():
            out_schema = Schema(
                [f for f in self.schema.fields if f.name not in self.partition_columns]
            )
            if not self.partition_columns:
                acc: Dict[str, list] = {}
                rows = 0
                for batch in self.children[0].execute(partition, ctx):
                    self._accumulate(acc, batch)
                    rows += batch.num_rows
                if rows or partition == 0:
                    path = os.path.join(self.output_path, f"part-{partition:05d}.parquet")
                    with self.metrics.timer("output_io_time"):
                        if not acc:
                            acc = {f.name: [[], [], []] for f in self.schema.fields}
                        self._write(path, acc, self.schema)
                    self.metrics.add("output_rows", rows)
                return
            # dynamic hive partitioning: group rows by partition values
            buckets: Dict[Tuple, Dict[str, list]] = {}
            for batch in self.children[0].execute(partition, ctx):
                b = batch.to_host()
                keys_per_row = []
                for pc in self.partition_columns:
                    c = b.column(pc)
                    if c.dtype.is_string:
                        keys_per_row.append(strings_to_list(c, b.num_rows))
                    else:
                        keys_per_row.append(
                            [
                                None if not np.asarray(c.validity)[i] else np.asarray(c.data)[i]
                                for i in range(b.num_rows)
                            ]
                        )
                row_keys = list(zip(*keys_per_row)) if keys_per_row else []
                distinct = sorted(set(row_keys), key=lambda t: tuple(str(x) for x in t))
                for key in distinct:
                    mask = np.array([rk == key for rk in row_keys], bool)
                    idx = np.nonzero(mask)[0]
                    sub_cols = []
                    for f in out_schema.fields:
                        c = b.column(f.name)
                        sub_cols.append(
                            type(c)(
                                c.dtype,
                                np.asarray(c.data)[idx],
                                np.asarray(c.validity)[idx],
                                None if c.lengths is None else np.asarray(c.lengths)[idx],
                            )
                        )
                    sub = RecordBatch(out_schema, sub_cols, len(idx))
                    self._accumulate(buckets.setdefault(key, {}), sub)
            with self.metrics.timer("output_io_time"):
                for key, acc in buckets.items():
                    parts = "/".join(
                        f"{pc}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
                        for pc, v in zip(self.partition_columns, key)
                    )
                    path = os.path.join(
                        self.output_path, parts, f"part-{partition:05d}.parquet"
                    )
                    self._write(path, acc, out_schema)
            return
            yield  # pragma: no cover

        return stream()
