"""EmptyPartitions — ≙ empty_partitions_exec.rs:39."""

from __future__ import annotations

from ..runtime.context import TaskContext
from ..schema import Schema
from .base import BatchStream, ExecNode


class EmptyPartitionsExec(ExecNode):
    def __init__(self, schema: Schema, num_partitions: int):
        super().__init__([])
        self._schema = schema
        self._num_partitions = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        return iter(())
