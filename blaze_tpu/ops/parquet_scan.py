"""Parquet scan.

≙ reference ParquetExec (parquet_exec.rs:65-418): per-partition file
groups, projected read schema, and statistics-based pruning driven by
pushed-down predicates (the row-group granularity of the reference's
page filtering, conf spark.blaze.parquet.enable.pageFiltering).
Missing columns materialize as nulls and matching is by name —
Spark-compatible schema adaption (scan/mod.rs:28-187).
"""

from __future__ import annotations

import datetime
import struct
from typing import List, Optional, Sequence

import numpy as np

from .. import conf
from ..batch import Column, RecordBatch, bucket_capacity
from ..exprs.compile import infer_lit_dtype
from ..exprs.ir import BinOp, Col, Expr, Lit
from ..io import parquet as pq
from ..runtime.context import TaskContext
from ..runtime.errors import reraise_control
from ..schema import DataType, Schema, TypeKind
from .base import BatchStream, ExecNode


def _lit_physical(value, dtype: DataType):
    """Literal -> comparable physical value (matching chunk stats)."""
    if dtype.is_decimal:
        if isinstance(value, float):
            return int(round(value * 10**dtype.scale))
        if isinstance(value, str):
            from decimal import Decimal

            return int(Decimal(value).scaleb(dtype.scale).to_integral_value())
        return int(value) * 10**dtype.scale
    if dtype.kind == TypeKind.DATE32:
        if isinstance(value, str):
            value = datetime.date.fromisoformat(value)
        if isinstance(value, datetime.date):
            return (value - datetime.date(1970, 1, 1)).days
        return int(value)
    if dtype.is_string:
        return value.encode("utf-8") if isinstance(value, str) else bytes(value)
    return value


def _prune_conjuncts(predicate: Optional[Expr]) -> List:
    """Extract (col, op, physical literal) conjuncts usable against
    row-group min/max stats."""
    out = []

    def walk(e: Optional[Expr]):
        if e is None:
            return
        if isinstance(e, BinOp):
            if e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if e.op in ("<", "<=", ">", ">=", "=="):
                l, r = e.left, e.right
                if isinstance(l, Col) and isinstance(r, Lit) and r.value is not None:
                    t = infer_lit_dtype(r.value, r.dtype)
                    out.append((l.name, e.op, _lit_physical(r.value, t)))
                elif isinstance(r, Col) and isinstance(l, Lit) and l.value is not None:
                    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}
                    t = infer_lit_dtype(l.value, l.dtype)
                    out.append((r.name, flip[e.op], _lit_physical(l.value, t)))

    walk(predicate)
    return out


def _maybe_match(chunk: pq.ChunkMeta, dtype: DataType, op: str, lit_v) -> bool:
    if chunk.min_value is None or chunk.max_value is None:
        return True
    try:
        if chunk.phys == pq.T_FLBA:
            # FLBA stats (decimal): big-endian signed
            lo = int.from_bytes(chunk.min_value, "big", signed=True)
            hi = int.from_bytes(chunk.max_value, "big", signed=True)
        else:
            lo = pq._stat_value(dtype, chunk.min_value)
            hi = pq._stat_value(dtype, chunk.max_value)
    except (struct.error, ValueError) as e:
        reraise_control(e)
        return True
    try:
        if op == "<":
            return lo < lit_v
        if op == "<=":
            return lo <= lit_v
        if op == ">":
            return hi > lit_v
        if op == ">=":
            return hi >= lit_v
        if op == "==":
            return lo <= lit_v <= hi
    except TypeError:
        return True
    return True


class ParquetScanExec(ExecNode):
    def __init__(
        self,
        file_groups: Sequence[Sequence[str]],
        schema: Schema,
        predicate: Optional[Expr] = None,
        batch_rows: int = 0,
    ):
        super().__init__([])
        self.file_groups = [list(g) for g in file_groups]
        self._schema = schema
        self.predicate = predicate
        self.batch_rows = batch_rows or int(conf.BATCH_SIZE.get())
        self._conjuncts = _prune_conjuncts(predicate) if bool(
            conf.PARQUET_FILTER_PUSHDOWN.get()
        ) else []

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return max(1, len(self.file_groups))

    def _null_column(self, dtype: DataType, cap: int) -> Column:
        if dtype.is_string:
            return Column(
                dtype,
                np.zeros((cap, dtype.string_width), np.uint8),
                np.zeros(cap, np.bool_),
                np.zeros(cap, np.int32),
            )
        return Column(dtype, np.zeros(cap, dtype.np_dtype), np.zeros(cap, np.bool_))

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        files = self.file_groups[partition] if partition < len(self.file_groups) else []

        def stream():
            for path in files:
                try:
                    meta = pq.read_metadata(path)
                except Exception:
                    if bool(conf.IGNORE_CORRUPT_FILES.get()):
                        self.metrics.add("skipped_corrupt_files", 1)
                        continue
                    raise
                for rg in meta.row_groups:
                    if rg.rows == 0:
                        continue
                    pruned = False
                    for name, op, lit_v in self._conjuncts:
                        ch = rg.chunks.get(name)
                        if ch is None:
                            continue
                        fld = next((f for f in self._schema.fields if f.name == name), None)
                        if fld is None:
                            # predicate column pruned from the read
                            # schema: stats pruning just skips it
                            continue
                        if not _maybe_match(ch, fld.dtype, op, lit_v):
                            pruned = True
                            break
                    if pruned:
                        self.metrics.add("pruned_row_groups", 1)
                        self.metrics.add("pruned_rows", rg.rows)
                        continue
                    with self.metrics.timer("input_io_time"):
                        cap = bucket_capacity(rg.rows)
                        cols: List[Column] = []
                        for f in self._schema.fields:
                            ch = rg.chunks.get(f.name)
                            if ch is None:
                                # schema adaption: missing column -> null
                                cols.append(self._null_column(f.dtype, cap))
                                continue
                            data, validity, lengths = pq.read_column_chunk(path, ch, f.dtype)
                            from ..batch import _pad_1d

                            if f.dtype.is_string:
                                d = np.zeros((cap, f.dtype.string_width), np.uint8)
                                d[: rg.rows, : data.shape[1]] = data[:, : f.dtype.string_width]
                                cols.append(
                                    Column(f.dtype, d, _pad_1d(validity, cap), _pad_1d(lengths, cap))
                                )
                            else:
                                cols.append(
                                    Column(
                                        f.dtype,
                                        _pad_1d(data.astype(f.dtype.np_dtype, copy=False), cap),
                                        _pad_1d(validity, cap),
                                    )
                                )
                    # emit in batch_rows slices to bound device batches
                    full = RecordBatch(self._schema, cols, rg.rows)
                    if rg.rows <= self.batch_rows:
                        self.metrics.add("output_rows", rg.rows)
                        yield full.to_device()
                    else:
                        host = full
                        for s in range(0, rg.rows, self.batch_rows):
                            e = min(s + self.batch_rows, rg.rows)
                            scap = bucket_capacity(e - s)
                            sl: List[Column] = []
                            for c in host.columns:
                                d = np.asarray(c.data)[s:e]
                                sl.append(
                                    Column(
                                        c.dtype,
                                        _pad_1d(np.ascontiguousarray(d), scap),
                                        _pad_1d(np.asarray(c.validity)[s:e], scap),
                                        None
                                        if c.lengths is None
                                        else _pad_1d(np.asarray(c.lengths)[s:e], scap),
                                    )
                                )
                            b = RecordBatch(self._schema, sl, e - s)
                            self._record_batch(b)
                            yield b.to_device()

        from ..runtime.pipeline import maybe_pipelined

        # file decode overlaps downstream device compute (≙ rt.rs:100-133)
        return maybe_pipelined(stream(), ctx, "parquet_scan")


from ..batch import _pad_1d  # noqa: E402  (used in stream closures)
