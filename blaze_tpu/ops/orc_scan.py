"""ORC scan.

≙ reference OrcExec (orc_exec.rs:53-285): per-partition file groups,
projected read schema with by-name adaption (missing columns -> null),
and stripe pruning from the file's stripe-level column statistics —
the ORC analogue of ParquetScanExec's row-group pruning.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .. import conf
from ..batch import Column, RecordBatch, _pad_1d, bucket_capacity
from ..exprs.ir import Expr
from ..io import orc
from ..runtime.context import TaskContext
from ..schema import DataType, Schema, TypeKind
from .base import BatchStream, ExecNode
from .parquet_scan import _prune_conjuncts


def _stat_comparable(dtype: DataType, v):
    if v is None:
        return None
    if dtype.is_string and isinstance(v, (bytes, bytearray)):
        # predicate literals are python str: decode so comparisons in
        # _stripe_maybe_match actually fire instead of raising TypeError
        return bytes(v).decode("utf-8", "surrogateescape")
    return v


def _stripe_maybe_match(stats, dtype: DataType, op: str, lit_v) -> bool:
    mn, mx, _ = stats
    lo = _stat_comparable(dtype, mn)
    hi = _stat_comparable(dtype, mx)
    if lo is None or hi is None:
        return True
    try:
        if op == "<":
            return lo < lit_v
        if op == "<=":
            return lo <= lit_v
        if op == ">":
            return hi > lit_v
        if op == ">=":
            return hi >= lit_v
        if op == "==":
            return lo <= lit_v <= hi
    except TypeError:
        return True
    return True


class OrcScanExec(ExecNode):
    def __init__(
        self,
        file_groups: Sequence[Sequence[str]],
        schema: Schema,
        predicate: Optional[Expr] = None,
        batch_rows: int = 0,
    ):
        super().__init__([])
        self.file_groups = [list(g) for g in file_groups]
        self._schema = schema
        self.predicate = predicate
        self.batch_rows = batch_rows or int(conf.BATCH_SIZE.get())
        self._conjuncts = _prune_conjuncts(predicate)

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return max(1, len(self.file_groups))

    def _null_column(self, dtype: DataType, cap: int) -> Column:
        if dtype.is_string:
            return Column(
                dtype,
                np.zeros((cap, dtype.string_width), np.uint8),
                np.zeros(cap, np.bool_),
                np.zeros(cap, np.int32),
            )
        if dtype.kind.name == "ARRAY":
            elem = self._null_column(dtype.elem, cap * dtype.max_elems)
            elem = Column(
                dtype.elem,
                None if elem.data is None else elem.data.reshape(
                    (cap, dtype.max_elems) + elem.data.shape[1:]),
                elem.validity.reshape(cap, dtype.max_elems),
                None if elem.lengths is None else elem.lengths.reshape(
                    cap, dtype.max_elems),
            )
            return Column(dtype, None, np.zeros(cap, np.bool_),
                          np.zeros(cap, np.int32), (elem,))
        return Column(dtype, np.zeros(cap, dtype.np_dtype), np.zeros(cap, np.bool_))

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        files = self.file_groups[partition] if partition < len(self.file_groups) else []

        def stream():
            max_w = max(
                [f.dtype.string_width for f in self._schema.fields if f.dtype.is_string],
                default=64,
            )
            max_elems = max(
                [f.dtype.max_elems for f in self._schema.fields
                 if f.dtype.kind.name == "ARRAY"], default=16,
            )
            for path in files:
                try:
                    meta = orc.read_metadata(path, list_elems=max_elems,
                                             string_width=max_w)
                except Exception:
                    if bool(conf.IGNORE_CORRUPT_FILES.get()):
                        self.metrics.add("skipped_corrupt_files", 1)
                        continue
                    raise
                file_fields = {f.name: f for f in meta.schema.fields}
                for stripe in meta.stripes:
                    if stripe.rows == 0:
                        continue
                    pruned = False
                    for name, op, lit_v in self._conjuncts:
                        st = stripe.stats.get(name)
                        if st is None or name not in file_fields:
                            continue
                        fld = next((f for f in self._schema.fields if f.name == name), None)
                        if fld is None:
                            continue  # predicate column pruned from read schema
                        if not _stripe_maybe_match(st, fld.dtype, op, lit_v):
                            pruned = True
                            break
                    if pruned:
                        self.metrics.add("pruned_stripes", 1)
                        self.metrics.add("pruned_rows", stripe.rows)
                        continue
                    with self.metrics.timer("input_io_time"):
                        raw = orc.read_stripe(path, meta, stripe)
                    rows = stripe.rows
                    for s in range(0, rows, self.batch_rows):
                        e = min(s + self.batch_rows, rows)
                        cap = bucket_capacity(e - s)
                        cols: List[Column] = []
                        for f in self._schema.fields:
                            if f.name not in raw:
                                cols.append(self._null_column(f.dtype, cap))
                                continue
                            if (len(raw[f.name]) == 2
                                    and raw[f.name][0] == "py"):
                                # compound column decoded to python
                                # values; build the padded nested
                                # Column through the canonical path
                                from ..batch import column_from_pylist

                                _, vals = raw[f.name]
                                cols.append(column_from_pylist(
                                    f.dtype, list(vals[s:e]), capacity=cap))
                                continue
                            if len(raw[f.name]) == 4:
                                # LIST column: (None, validity, lengths,
                                # (elem_data, elem_valid)) from the reader
                                _, validity, lengths, (ed, ev) = raw[f.name]
                                m = f.dtype.max_elems
                                if int(np.max(lengths[s:e], initial=0)) > m:
                                    # read_metadata decodes with ONE
                                    # uniform cap (the widest field);
                                    # a narrower declared field must
                                    # gate, not silently truncate
                                    raise NotImplementedError(
                                        f"ORC subset: list length "
                                        f"{int(np.max(lengths[s:e]))} exceeds "
                                        f"max_elems {m} for {f.name!r}")
                                ed2 = np.zeros((cap, m), f.dtype.elem.np_dtype)
                                ev2 = np.zeros((cap, m), np.bool_)
                                k = min(m, ed.shape[1])
                                ed2[: e - s, :k] = ed[s:e, :k].astype(
                                    f.dtype.elem.np_dtype, copy=False)
                                ev2[: e - s, :k] = ev[s:e, :k]
                                elem = Column(f.dtype.elem, ed2, ev2)
                                cols.append(Column(
                                    f.dtype, None,
                                    _pad_1d(validity[s:e], cap),
                                    _pad_1d(np.minimum(lengths[s:e], m), cap),
                                    (elem,),
                                ))
                                continue
                            data, validity, lengths = raw[f.name]
                            if f.dtype.is_string:
                                d = np.zeros((cap, f.dtype.string_width), np.uint8)
                                seg = data[s:e]
                                d[: e - s, : min(seg.shape[1], f.dtype.string_width)] = seg[
                                    :, : f.dtype.string_width
                                ]
                                cols.append(
                                    Column(
                                        f.dtype,
                                        d,
                                        _pad_1d(validity[s:e], cap),
                                        _pad_1d(
                                            np.minimum(lengths[s:e], f.dtype.string_width), cap
                                        ),
                                    )
                                )
                            else:
                                cols.append(
                                    Column(
                                        f.dtype,
                                        _pad_1d(
                                            data[s:e].astype(f.dtype.np_dtype, copy=False), cap
                                        ),
                                        _pad_1d(validity[s:e], cap),
                                    )
                                )
                        b = RecordBatch(self._schema, cols, e - s)
                        self._record_batch(b)
                        yield b.to_device()

        from ..runtime.pipeline import maybe_pipelined

        # file decode overlaps downstream device compute (≙ rt.rs:100-133)
        return maybe_pipelined(stream(), ctx, "orc_scan")
