"""ExecNode: the operator interface.

≙ DataFusion's ``ExecutionPlan`` as used by the reference
(from_proto.rs builds ``Arc<dyn ExecutionPlan>`` trees;
datafusion-ext-plans implements them).  Differences, TPU-first:

- ``execute`` returns a plain python iterator of RecordBatches; the
  task runtime (runtime/task.py) drives it through a bounded channel
  on a worker thread (≙ tokio + sync_channel(1), rt.rs:100-133).
- the hot math lives in jitted per-batch kernels; the iterator layer
  only sequences device calls and host IO.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from ..batch import RecordBatch
from ..runtime.context import TaskContext
from ..runtime.metrics import MetricsSet
from ..schema import Schema

BatchStream = Iterator[RecordBatch]


class ExecNode:
    """Base physical operator."""

    def __init__(self, children: Sequence["ExecNode"]):
        self.children: List[ExecNode] = list(children)
        self.metrics = MetricsSet()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        raise NotImplementedError

    def num_partitions(self) -> int:
        """Output partitioning degree (propagates from children by
        default)."""
        if self.children:
            return self.children[0].num_partitions()
        return 1

    def _count_output(self, stream: BatchStream) -> BatchStream:
        for b in stream:
            self.metrics.add("output_rows", b.num_rows)
            yield b

    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.name() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def collect(self, ctx: Optional[TaskContext] = None) -> List[RecordBatch]:
        """Run all partitions serially and collect (test helper)."""
        out: List[RecordBatch] = []
        n = self.num_partitions()
        for p in range(n):
            c = ctx or TaskContext(p, n)
            out.extend(self.execute(p, c))
        return out
