"""ExecNode: the operator interface.

≙ DataFusion's ``ExecutionPlan`` as used by the reference
(from_proto.rs builds ``Arc<dyn ExecutionPlan>`` trees;
datafusion-ext-plans implements them).  Differences, TPU-first:

- ``execute`` returns a plain python iterator of RecordBatches; the
  task runtime (runtime/task.py) drives it through a bounded channel
  on a worker thread (≙ tokio + sync_channel(1), rt.rs:100-133).
- the hot math lives in jitted per-batch kernels; the iterator layer
  only sequences device calls and host IO.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from ..batch import RecordBatch
from ..runtime import monitor
from ..runtime.context import TaskContext
from ..runtime.metrics import MetricsSet
from ..schema import Schema

BatchStream = Iterator[RecordBatch]


class ExecNode:
    """Base physical operator."""

    def __init__(self, children: Sequence["ExecNode"]):
        self.children: List[ExecNode] = list(children)
        self.metrics = MetricsSet()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        raise NotImplementedError

    # ------------------------------------------------- tracing contract
    #
    # Whole-stage program fusion (ops/fusion.py) composes consecutive
    # unary operators into ONE jitted per-batch program: every operator
    # boundary otherwise costs an XLA dispatch + a materialized
    # intermediate, and over a remote/tunneled chip per-program
    # turnaround (~70-80 ms) dominates the actual math.

    def trace_fn(self):
        """Pure per-batch transform ``(cols, num_rows) -> (cols,
        num_rows)`` safe to inline inside an enclosing ``jax.jit``
        (``num_rows`` may be a traced scalar; all intermediates stay on
        device), or ``None`` when this operator cannot be traced
        (blocking, stateful across batches, multi-child, or
        host-dependent).  The returned closure must capture only
        schemas / expression IR — never the child subtree (fused
        programs are cached process-wide, kernel_cache rules apply)."""
        return None

    def trace_key(self):
        """Structural cache key for :meth:`trace_fn` (kernel_cache
        conventions: schema signature + expression keys).  Required
        non-None whenever trace_fn returns a function."""
        return None

    def trace_slots(self) -> tuple:
        """Slot values (numpy scalars) for this operator's slotified
        literals (exprs.compile.slotify_literals) — the parameters that
        let `WHERE price > 5` and `WHERE price > 9` share one compiled
        program.  CONTRACT: when non-empty, the transform returned by
        :meth:`trace_fn` expects exactly ``len(trace_slots())`` traced
        scalars appended at the TAIL of its ``cols`` tuple (after the
        schema columns) and slices them off itself; callers — the
        standalone execute, FusedStageExec, the fused shuffle write,
        and the eager OOM rung — append the values per call.  The
        values are DATA, never part of :meth:`trace_key`."""
        return ()

    @property
    def trace_changes_count(self) -> bool:
        """True when the traced transform can change ``num_rows`` (a
        filter compacts); the fused stage then syncs the one count
        scalar per batch, exactly like the standalone operator."""
        return False

    @property
    def trace_requires_buffer(self) -> bool:
        """True when the traced transform is only exact over the WHOLE
        partition in one batch (WindowExec: partition segments span
        batch boundaries).  Fusion then plants a buffering node below
        the fused program — the same concat-the-partition semantics the
        operator's own execute uses — instead of applying it per
        streamed batch."""
        return False

    @property
    def has_kernel(self) -> bool:
        """False when this operator issues no device program of its own
        (pure column selects); fusion only builds a combined program
        when it replaces at least two real kernels."""
        return True

    # ------------------------------------- static-analysis contract
    #
    # Declarations the plan verifier (analysis/plan_verify.py, conf
    # spark.blaze.verify.plan) checks over every optimized plan: the
    # rewrite tiers rely on these prerequisites holding, and a rewrite
    # that breaks one produces wrong ANSWERS, not errors.

    def required_child_distribution(self):
        """None, or ``("hash", frozenset(expr_keys))``: the child
        subtree must deliver co-partitioning on these keys (a FINAL
        grouped agg needs every row of a group in one partition) —
        rule ``dist.final-agg``."""
        return None

    def required_child_orderings(self):
        """Per-child ordering prerequisite: None (no requirement) or a
        tuple of expr_keys the child stream must be key-sorted on
        (prefix match; the EMPTY tuple means 'must be downstream of
        some sort', the relaxed form) — rules ``order.*``."""
        return [None] * len(self.children)

    def provided_ordering(self):
        """expr_keys this node's OUTPUT is sorted on (() = none):
        SortExec declares its fields, a FINAL agg its fused
        ``post_sort``."""
        return ()

    @property
    def preserves_ordering(self) -> bool:
        """True when this unary op passes its child's sort order
        through (filters compact in order; sorts/aggs/exchanges
        destroy or replace it)."""
        return False

    def num_partitions(self) -> int:
        """Output partitioning degree (propagates from children by
        default)."""
        if self.children:
            return self.children[0].num_partitions()
        return 1

    def _record_batch(self, b) -> None:
        """Land one output batch's rows/bytes/batches on this node's
        MetricsSet — the per-node annotation EXPLAIN ANALYZE
        (runtime/perf.py) renders.  ``nbytes`` is an attribute read
        per column buffer, never a device sync."""
        self.metrics.add("output_rows", b.num_rows)
        self.metrics.add("output_batches")
        self.metrics.add(
            "output_bytes",
            sum(getattr(c.data, "nbytes", 0) for c in b.columns))

    def _count_output(self, stream: BatchStream) -> BatchStream:
        for b in stream:
            self._record_batch(b)
            # heartbeat hookpoint: a task whose plan never yields to
            # the driver (map stages feed the shuffle writer) still
            # beats from inside the operator drive; one thread-local
            # read when no instrumented task is active
            monitor.tick()
            yield b

    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.name() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def collect(self, ctx: Optional[TaskContext] = None) -> List[RecordBatch]:
        """Run all partitions serially and collect (test helper)."""
        out: List[RecordBatch] = []
        n = self.num_partitions()
        for p in range(n):
            c = ctx or TaskContext(p, n)
            out.extend(self.execute(p, c))
        return out
