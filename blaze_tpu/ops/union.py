"""Union / EmptyPartitions / Rename / Debug / CoalesceBatches plumbing
operators — ≙ reference union, empty_partitions_exec.rs:39,
rename_columns_exec.rs:44, debug_exec.rs:39, coalesce stream."""

from __future__ import annotations

from typing import List, Sequence

from ..batch import RecordBatch
from ..runtime.context import TaskContext
from ..schema import Schema
from .base import BatchStream, ExecNode


class UnionExec(ExecNode):
    """Concatenation of children streams (same schema, same partition
    count)."""

    def __init__(self, children: Sequence[ExecNode]):
        super().__init__(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def num_partitions(self) -> int:
        return max(c.num_partitions() for c in self.children)

    def execute(self, partition: int, ctx: TaskContext) -> BatchStream:
        def stream():
            for child in self.children:
                if partition < child.num_partitions():
                    for b in child.execute(partition, ctx):
                        self._record_batch(b)
                        yield b

        return stream()
