"""Pallas TPU kernels for the engine's hot loops.

The XLA operator kernels (ops/, exprs/) are the portable path; this
package holds hand-written Pallas kernels for the few loops where
hand-scheduling beats the XLA default on TPU:

- murmur3_pids — shuffle partition-id computation (murmur3 seed-42 +
  pmod) fused over key columns, one HBM pass, no intermediate hash
  array (≙ reference shuffle/mod.rs evaluate_hashes/
  evaluate_partition_ids).  Wired into ShuffleWriterExec as the TPU
  fast path for fixed-width keys.
- pid_histogram — per-partition row counts; XLA lowers the equivalent
  scatter as sort+segsum, the kernel accumulates one-hot counts in
  VMEM instead.  Building block for repartitioner layouts.
- fused_group_sums — small-cardinality grouped aggregation (one-hot ×
  values, the TPC-H q01 shape): predicate mask, projection and
  segment-sum in a single pass (≙ agg_table.rs update path).
  float32 accumulation; the exact int64 (decimal) variant that AggExec
  can adopt wholesale is the planned follow-up.

Everything degrades gracefully: `available()` is False off-TPU unless
interpret mode is forced, and callers keep their pure-XLA fallback.
"""

from .pallas_ops import (
    available,
    fused_group_sums,
    murmur3_pids,
    pid_histogram,
)

__all__ = [
    "available",
    "fused_group_sums",
    "murmur3_pids",
    "pid_histogram",
]
