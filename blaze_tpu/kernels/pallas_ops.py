"""Pallas kernels (see package docstring for the inventory).

Layout convention: a logical row vector of length N is padded to a
multiple of ``TILE_ROWS*LANES`` (=1024) and viewed as an (M, 128)
array; the grid walks blocks of ``TILE_ROWS`` sublane-rows.  All
arithmetic inside kernels is 32-bit (TPU-native); 64-bit key columns
enter as separate low/high uint32 word planes.

Kernels use the output-revisit accumulation pattern (every grid step
maps to the same output block, initialized at step 0) instead of
scratch+copy so the same code runs under ``interpret=True`` on CPU for
tests (tests/test_pallas.py).
"""

from __future__ import annotations

import contextlib
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.errors import reraise_control


def _x32():
    """Trace pallas calls with x64 OFF.

    The engine enables jax_enable_x64 globally (decimals/sums are
    int64/float64), but under x64 Mosaic's grid path emits 64-bit index
    arithmetic it cannot legalize ("failed to legalize func.return").
    Every kernel here is 32-bit end to end, so tracing them in an
    x64-off scope is value-preserving.  (jax 0.9 removed the public
    disable_x64 context manager; fall back to a no-op if the internal
    one moves.)
    """
    try:
        from jax._src.config import enable_x64

        return enable_x64(False)
    except Exception as e:  # noqa: BLE001 — version probe
        reraise_control(e)
        return contextlib.nullcontext()

LANES = 128
TILE_ROWS = 8
TILE = TILE_ROWS * LANES


def _pl():
    from jax.experimental import pallas as pl

    return pl


_FORCE_INTERPRET = False  # tests: exercise kernels off-TPU via interpret mode


def force_interpret(flag: bool) -> None:
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = flag


def available() -> bool:
    """True when the kernels can run (real TPU, or forced interpret)."""
    if _FORCE_INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception as e:  # noqa: BLE001 — backend probe
        reraise_control(e)
        return False


def _interpret() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception as e:  # noqa: BLE001 — backend probe
        reraise_control(e)
        return True


# ---------------------------------------------------------------- helpers


def _pad_plane(a: jnp.ndarray, fill) -> jnp.ndarray:
    """(N,) -> (M, 128) with M*128 a multiple of TILE, padded with fill."""
    n = a.shape[0]
    padded = ((n + TILE - 1) // TILE) * TILE
    if padded != n:
        a = jnp.pad(a, (0, padded - n), constant_values=fill)
    return a.reshape(-1, LANES)


# ---------------------------------------------------------------- murmur3

# Spark's Murmur3_x86_32 (seed 42): the bit-exactness-critical mix
# primitives are imported from exprs/hash.py (single source of truth;
# they are pure jnp and trace fine inside a pallas kernel).  The
# kernel's contribution is fusion: hashing K key columns is one HBM
# read of each plane and one HBM write of the pids.
from ..exprs.hash import _fmix, _mix_h1, _mix_k1, _normalize_float  # noqa: E402


def _murmur3_pids_kernel(n_parts: int, widths: Tuple[int, ...], *refs):
    """refs = [plane0, plane1, ..., valid0, valid1, ..., out].

    widths[i] in (1, 2): number of uint32 word planes of key column i.
    valids are uint32 (1 = valid); one per key column.
    """
    n_cols = len(widths)
    n_planes = sum(widths)
    planes = refs[:n_planes]
    valids = refs[n_planes : n_planes + n_cols]
    out = refs[-1]

    h = jnp.full(planes[0].shape, np.uint32(42), jnp.uint32)
    pi = 0
    for ci, w in enumerate(widths):
        if w == 1:
            hv = _fmix(_mix_h1(h, _mix_k1(planes[pi][...])), np.uint32(4))
        else:
            h1 = _mix_h1(h, _mix_k1(planes[pi][...]))
            h1 = _mix_h1(h1, _mix_k1(planes[pi + 1][...]))
            hv = _fmix(h1, np.uint32(8))
        pi += w
        h = jnp.where(valids[ci][...] != 0, hv, h)

    signed = jax.lax.bitcast_convert_type(h, jnp.int32)
    m = signed % np.int32(n_parts)
    out[...] = jnp.where(m < 0, m + np.int32(n_parts), m)


def murmur3_pids(
    planes: Sequence[jnp.ndarray],
    widths: Sequence[int],
    valids: Sequence[jnp.ndarray],
    n_parts: int,
) -> jnp.ndarray:
    """Fused Spark murmur3(seed 42) + pmod partition ids.

    planes: flat list of (N,) uint32 word planes (LE words; int32-like
    columns contribute 1 plane, int64-like 2 planes low-then-high).
    valids: one (N,) uint32/bool plane per key column.
    Returns (N,) int32 pids.
    """
    n = planes[0].shape[0]
    in_planes = [_pad_plane(p.astype(jnp.uint32), 0) for p in planes]
    in_valids = [_pad_plane(v.astype(jnp.uint32), 0) for v in valids]
    m = in_planes[0].shape[0]
    call = _build_murmur3_pids(n_parts, tuple(widths), m, _interpret())
    with _x32():
        out = call(*in_planes, *in_valids)
    return out.reshape(-1)[:n]


@functools.lru_cache(maxsize=256)
def _build_murmur3_pids(n_parts: int, widths: Tuple[int, ...], m: int, interpret: bool):
    """Cached pallas_call construction — jit caches by callable
    identity, so rebuilding per batch would re-trace every call."""
    pl = _pl()
    spec = pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0))
    n_in = sum(widths) + len(widths)
    return pl.pallas_call(
        functools.partial(_murmur3_pids_kernel, n_parts, widths),
        out_shape=jax.ShapeDtypeStruct((m, LANES), jnp.int32),
        grid=(m // TILE_ROWS,),
        in_specs=[spec] * n_in,
        out_specs=spec,
        interpret=interpret,
    )


def column_word_planes(col) -> Tuple[List[jnp.ndarray], int]:
    """Split a Column's data into uint32 word planes for murmur3_pids.

    Returns (planes, width).  Only fixed-width non-string types; the
    caller falls back to the XLA hash path otherwise.
    """
    from ..schema import TypeKind

    k = col.dtype.kind
    d = col.data
    if col.dtype.is_string:
        raise NotImplementedError("string keys use the XLA hash path")
    if col.dtype.is_float:
        d, k = _normalize_float(col)  # -0.0 normalize + bit view (hash.py)
    if k in (TypeKind.BOOL, TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.DATE32):
        return [d.astype(jnp.int32).view(jnp.uint32)], 1
    if k in (TypeKind.INT64, TypeKind.TIMESTAMP, TypeKind.DECIMAL):
        v = d.astype(jnp.int64)
        low = (v & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        high = ((v >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        return [low, high], 2
    raise NotImplementedError(f"murmur3 pallas path over {col.dtype!r}")


# ---------------------------------------------------------------- histogram


def _histogram_kernel(p_pad: int, pids_ref, out_ref):
    pl = _pl()
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    p_iota = jax.lax.broadcasted_iota(jnp.int32, (p_pad, LANES), 0)
    acc = out_ref[...]
    for r in range(TILE_ROWS):
        row = pids_ref[r : r + 1, :]  # (1, 128): keep 2-D for mosaic
        acc = acc + (p_iota == row).astype(jnp.int32)
    out_ref[...] = acc


def pid_histogram(pids: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Per-partition row counts in one pass (padding rows enter as -1
    and match no partition).  Returns (n_parts,) int32.

    ≙ the per-partition counts SortShuffleRepartitioner derives when
    laying out partition runs (sort_repartitioner.rs); XLA would lower
    the equivalent scatter-add as sort + segment-sum.
    """
    p_pad = max(8, ((n_parts + 7) // 8) * 8)
    planes = _pad_plane(pids.astype(jnp.int32), -1)
    m = planes.shape[0]
    call = _build_histogram(p_pad, m, _interpret())
    with _x32():
        out = call(planes)
    return jnp.sum(out, axis=1)[:n_parts]


@functools.lru_cache(maxsize=256)
def _build_histogram(p_pad: int, m: int, interpret: bool):
    pl = _pl()
    return pl.pallas_call(
        functools.partial(_histogram_kernel, p_pad),
        out_shape=jax.ShapeDtypeStruct((p_pad, LANES), jnp.int32),
        grid=(m // TILE_ROWS,),
        in_specs=[pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((p_pad, LANES), lambda i: (0, 0)),
        interpret=interpret,
    )


# ------------------------------------------------------ grouped aggregation


def _group_sums_kernel(g_pad: int, n_vals: int, *refs):
    """refs = [gids, v0..v{K-1}, out(K, g_pad, LANES)]."""
    pl = _pl()
    gids_ref = refs[0]
    val_refs = refs[1 : 1 + n_vals]
    out_ref = refs[-1]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    g_iota = jax.lax.broadcasted_iota(jnp.int32, (g_pad, LANES), 0)
    # per-k running sums (static-indexed loads/stores; .at[k].add would
    # lower as an unsupported scatter-add)
    accs = [out_ref[k] for k in range(n_vals)]
    for r in range(TILE_ROWS):
        onehot = (g_iota == gids_ref[r : r + 1, :]).astype(jnp.float32)
        for k in range(n_vals):
            accs[k] = accs[k] + onehot * val_refs[k][r : r + 1, :]
    for k in range(n_vals):
        out_ref[k] = accs[k]


def fused_group_sums(
    gids: jnp.ndarray,
    values: Sequence[jnp.ndarray],
    n_groups: int,
) -> jnp.ndarray:
    """Small-cardinality grouped sums in one fused pass.

    gids: (N,) int32 group ids; rows failing the predicate (or padding)
    carry gid -1 and contribute nothing — the caller folds its filter
    into the gid assignment, so scan->filter->agg is ONE kernel.
    values: K arrays (N,) float32.  Returns (K, n_groups) float32.
    """
    g_pad = max(8, ((n_groups + 7) // 8) * 8)
    gid_planes = _pad_plane(gids.astype(jnp.int32), -1)
    val_planes = [_pad_plane(v.astype(jnp.float32), 0) for v in values]
    m = gid_planes.shape[0]
    k = len(values)
    call = _build_group_sums(g_pad, k, m, _interpret())
    with _x32():
        out = call(gid_planes, *val_planes)
    return jnp.sum(out, axis=2)[:, :n_groups]


@functools.lru_cache(maxsize=256)
def _build_group_sums(g_pad: int, k: int, m: int, interpret: bool):
    pl = _pl()
    spec = pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_group_sums_kernel, g_pad, k),
        out_shape=jax.ShapeDtypeStruct((k, g_pad, LANES), jnp.float32),
        grid=(m // TILE_ROWS,),
        in_specs=[spec] * (1 + k),
        out_specs=pl.BlockSpec((k, g_pad, LANES), lambda i: (0, 0, 0)),
        interpret=interpret,
    )


# ------------------------------------------------- hash-join probe lookup

#: largest build-side key table the pallas probe path accepts: the
#: kernel counts ALL (probe, table) pairs per tile, so work is N*T —
#: a win only for the small sorted tables of broadcast-style builds
#: where XLA's per-probe searchsorted dispatch dominates
SORTED_LOOKUP_MAX_TABLE = 8192


def _sorted_lookup_kernel(q_hi_ref, q_lo_ref, t_hi_ref, t_lo_ref,
                          lo_ref, hi_ref):
    """Counting searchsorted over uint64 keys as hi/lo uint32 planes:
    lo = #{t < q} (XLA side="left"), hi = #{t <= q} (side="right").
    Unsigned 32-bit order via the sign-bias flip (x ^ 0x8000_0000
    viewed int32 preserves uint32 order); uint64 order is the (hi, lo)
    lexicographic combination.  The table enters as ONE full block per
    grid step (it is the sorted build side, bounded by
    SORTED_LOOKUP_MAX_TABLE); the grid walks probe tiles."""
    bias = np.uint32(0x80000000)

    def signed(ref):
        return jax.lax.bitcast_convert_type(ref[...] ^ bias, jnp.int32)

    q_hi, q_lo = signed(q_hi_ref), signed(q_lo_ref)
    t_hi, t_lo = signed(t_hi_ref), signed(t_lo_ref)
    th = t_hi.reshape(-1)[None, None, :]
    tl = t_lo.reshape(-1)[None, None, :]
    qh, ql = q_hi[:, :, None], q_lo[:, :, None]
    lt = (th < qh) | ((th == qh) & (tl < ql))
    le = lt | ((th == qh) & (tl == ql))
    lo_ref[...] = jnp.sum(lt.astype(jnp.int32), axis=-1)
    hi_ref[...] = jnp.sum(le.astype(jnp.int32), axis=-1)


def sorted_lookup(table_keys: jnp.ndarray, probe_keys: jnp.ndarray):
    """(lo, hi) candidate-range bounds per probe key — the hash-join
    probe inner loop (ops/joins/core.py ``probe_counts``) as one fused
    pallas program instead of two XLA searchsorted dispatches.

    ``table_keys``: sorted (T,) uint64 hashes (the JoinMap key table);
    ``probe_keys``: (N,) uint64 probe hashes.  Table padding fills with
    the all-ones sentinel, which sorts after every real key and is
    never ``< q`` nor (for non-sentinel q) ``<= q`` — so lo matches
    XLA's searchsorted exactly and hi matches for every probe the
    caller doesn't already zero (sentinel probes carry count 0).
    Returns ((N,) int32 lo, (N,) int32 hi).
    """
    def planes(a, fill):
        lo32 = (a & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi32 = (a >> jnp.uint64(32)).astype(jnp.uint32)
        return _pad_plane(hi32, fill), _pad_plane(lo32, fill)

    n = probe_keys.shape[0]
    q_hi, q_lo = planes(probe_keys, 0)
    t_hi, t_lo = planes(table_keys, np.uint32(0xFFFFFFFF))
    m, tm = q_hi.shape[0], t_hi.shape[0]
    call = _build_sorted_lookup(m, tm, _interpret())
    with _x32():
        lo, hi = call(q_hi, q_lo, t_hi, t_lo)
    return lo.reshape(-1)[:n], hi.reshape(-1)[:n]


@functools.lru_cache(maxsize=256)
def _build_sorted_lookup(m: int, tm: int, interpret: bool):
    pl = _pl()
    probe_spec = pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0))
    table_spec = pl.BlockSpec((tm, LANES), lambda i: (0, 0))
    out = jax.ShapeDtypeStruct((m, LANES), jnp.int32)
    return pl.pallas_call(
        _sorted_lookup_kernel,
        out_shape=[out, out],
        grid=(m // TILE_ROWS,),
        in_specs=[probe_spec, probe_spec, table_spec, table_spec],
        out_specs=[probe_spec, probe_spec],
        interpret=interpret,
    )
