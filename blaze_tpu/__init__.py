"""blaze-tpu: a TPU-native Spark SQL acceleration framework.

A brand-new implementation of the capability surface of Blaze (the
Spark + DataFusion native engine; see SURVEY.md): physical-plan
interception behind a protobuf plan contract, columnar operators,
Spark-compatible native shuffle, memory management with spill, and
metrics — with the operator kernels running on TPU via JAX/XLA instead
of Rust/DataFusion on CPU.

Layering (mirrors SURVEY.md §1, TPU-first rather than a port):

- ``blaze_tpu.schema`` / ``blaze_tpu.batch``: the columnar data model —
  fixed-capacity padded device batches (shape-bucketed so XLA compiles a
  bounded number of programs), validity masks, fixed-width string
  columns that hash/compare on the VPU.
- ``blaze_tpu.exprs``: Spark-semantics expression IR compiled to pure
  JAX functions (3-valued null logic, decimals as scaled int64,
  spark-exact murmur3/xxhash64).
- ``blaze_tpu.ops``: operators (scan/filter/project/agg/sort/joins/
  window/generate/expand/limit/union/ipc) as streams of device batches,
  ≙ reference crate ``datafusion-ext-plans``.
- ``blaze_tpu.parallel``: hash-partition shuffle (murmur3 pmod on
  device, sort-by-pid writer, ``.data``/``.index`` files) plus the ICI
  fast path: ``shard_map`` all-to-all over a ``jax.sharding.Mesh``.
- ``blaze_tpu.runtime``: memory manager (HBM budget → host RAM → disk
  spill tiers), per-task runtime, metrics tree, conf mirror.
- ``blaze_tpu.serde``: the protobuf plan contract (≙ blaze.proto) and
  ``from_proto`` plan builder.

JAX int64/float64 support is required for decimal and timestamp math;
we enable x64 at import (all internal dtypes are explicit).
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
