"""Self-contained ORC subset writer/reader.

≙ the file-format half of the reference's OrcExec (orc_exec.rs:53-285,
which scans ORC through a forked orc-rust) — implemented from the
public ORC v1 spec (no pyorc/pyarrow in the image):

- file layout: "ORC" header, stripes (data streams + protobuf
  StripeFooter), protobuf Metadata (stripe-level column statistics),
  protobuf Footer (types/stripes/counts), PostScript, 1-byte
  postscript length.
- encodings (all DIRECT, compression NONE): PRESENT = bit-packed
  bool + byte-RLE; ints/dates = signed RLEv1 (zigzag varints);
  int8 = byte-RLE; bool = bit-packed byte-RLE; float/double = raw
  IEEE LE; string = LENGTH (unsigned RLEv1) + concatenated DATA;
  decimal(<=18) = unbounded zigzag varint DATA + signed RLEv1 scale
  SECONDARY.
- reader: REAL-WORLD files too (round-2): compressed streams
  (zlib/snappy/lz4/zstd chunked framing), RLEv2 integers (short
  repeat / direct / patched base / delta), DIRECT_V2 and
  DICTIONARY(_V2) string encodings — what ORC C++ (pyarrow/Spark)
  writers actually emit — plus the subset our writer produces.
  Stripe statistics drive predicate pruning (the stripe granularity
  of the reference's ORC scan pushdown).

Compound types: LIST of primitive reads keep a vectorized fast path
(LENGTH stream + child PRESENT/DATA, rectangularized to the declared
max_elems); MAP/STRUCT/nested LIST read through a recursive
python-value decoder.  The writer mirrors the full set: flat columns
and LIST-of-primitive via numpy tuples, and MAP/STRUCT/nested LIST
fields as plain python value lists (the same shape the reader's
compound path returns) through a recursive encoder.  TIMESTAMP is
covered at both levels (top-level vectorized + compound py-value,
int64 unix-µs lane).  Remaining gate (not silently wrong): BINARY.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType, Field, Schema, TypeKind

MAGIC = b"ORC"

# Type.kind enum
K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG, K_FLOAT, K_DOUBLE, K_STRING = range(8)
K_BINARY = 8
K_TIMESTAMP = 9
K_LIST = 10
K_MAP = 11
K_STRUCT = 12
K_DECIMAL = 14
K_DATE = 15

# ORC timestamps are seconds relative to 2015-01-01 00:00:00 UTC plus
# a nanosecond stream with decimal-trailing-zero packing
ORC_TS_EPOCH = 1420070400

# Stream.kind enum
S_PRESENT, S_DATA, S_LENGTH = 0, 1, 2
S_DICTIONARY_DATA = 3
S_SECONDARY = 5

# ColumnEncoding.kind enum
E_DIRECT, E_DICTIONARY, E_DIRECT_V2, E_DICTIONARY_V2 = 0, 1, 2, 3

# CompressionKind
C_NONE, C_ZLIB, C_SNAPPY, C_LZO, C_LZ4, C_ZSTD = range(6)


def orc_decompress(buf: bytes, kind: int) -> bytes:
    """ORC chunked stream framing: repeated [u24le (len<<1 | original)]
    [chunk]; `original` chunks are stored verbatim."""
    if kind == C_NONE or not buf:
        return buf
    out = bytearray()
    pos = 0
    n = len(buf)
    while pos + 3 <= n:
        h = buf[pos] | (buf[pos + 1] << 8) | (buf[pos + 2] << 16)
        pos += 3
        orig = h & 1
        ln = h >> 1
        chunk = buf[pos : pos + ln]
        pos += ln
        if orig:
            out += chunk
        elif kind == C_ZLIB:
            out += zlib.decompress(chunk, -15)  # raw deflate
        elif kind == C_SNAPPY:
            from .parquet import _snappy_decompress

            out += _snappy_decompress(chunk)
        elif kind == C_LZ4:
            from .parquet import _lz4_block_decompress

            out += _lz4_block_decompress(chunk)
        elif kind == C_ZSTD:
            import zstandard

            out += zstandard.ZstdDecompressor().decompress(
                chunk, max_output_size=1 << 26
            )
        else:
            raise NotImplementedError(f"ORC compression kind {kind}")
    return bytes(out)


def orc_compress(data: bytes, kind: int, block: int = 65536) -> bytes:
    """Writer half of the chunked framing: split into <= ``block``-byte
    chunks, compress each (zlib raw-deflate, zstd, snappy, or lz4
    raw-block), store verbatim (original bit) when compression does not
    shrink the chunk — the exact format orc_decompress consumes and ORC
    C++ readers expect."""
    if kind == C_NONE or not data:
        return data
    if kind not in (C_ZLIB, C_ZSTD, C_SNAPPY, C_LZ4):
        raise NotImplementedError(f"ORC writer compression kind {kind}")
    if kind == C_ZSTD:
        import zstandard

        zc = zstandard.ZstdCompressor()
    out = bytearray()
    for pos in range(0, len(data), block):
        chunk = data[pos : pos + block]
        if kind == C_ZSTD:
            comp = zc.compress(chunk)
        elif kind == C_SNAPPY:
            from .parquet import _snappy_compress

            comp = _snappy_compress(chunk)
        elif kind == C_LZ4:
            from .ipc_compression import lz4_block_compress

            comp = lz4_block_compress(chunk)
        else:
            co = zlib.compressobj(6, zlib.DEFLATED, -15)
            comp = co.compress(chunk) + co.flush()
        if len(comp) < len(chunk):
            h = len(comp) << 1
            out += bytes([h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF])
            out += comp
        else:
            h = (len(chunk) << 1) | 1
            out += bytes([h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF])
            out += chunk
    return bytes(out)


# ------------------------------------------------------------- RLE v2

_RLEV2_WIDTHS = [
    1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
    17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48, 56, 64,
]


def _w_decode(code: int, delta: bool = False) -> int:
    if delta and code == 0:
        return 0
    return _RLEV2_WIDTHS[code]


def _unpack_be(data, pos: int, width: int, count: int) -> Tuple[np.ndarray, int]:
    """MSB-first bit-unpack `count` unsigned values of `width` bits."""
    if width == 0 or count == 0:
        return np.zeros(count, np.int64), pos
    nbytes = (width * count + 7) // 8
    bits = np.unpackbits(np.frombuffer(data, np.uint8, nbytes, pos))
    vals = np.zeros(count, np.uint64)
    b = bits[: width * count].reshape(count, width).astype(np.uint64)
    for j in range(width):
        vals = (vals << np.uint64(1)) | b[:, j]
    return vals.view(np.int64), pos + nbytes


def _wrap_u64(v):
    """Unsigned->signed int64 wrap for "unsigned" RLE streams.

    ORC C++ packs signed values (e.g. pre-epoch packed nanos) into
    unsigned streams as their two's-complement uint64 image; a python
    varint/big-endian decode hands back the raw >= 2**63 integer, which
    overflows an int64 slice-assign.  Every unsigned decode path wraps
    through here — RLEv1 literal + run base and RLEv2 SHORT_REPEAT +
    DELTA base as scalars, RLEv2 DIRECT vectorized (a uint64 ndarray
    image reinterpreted as its two's-complement int64 view)."""
    if isinstance(v, np.ndarray):
        return v.astype(np.uint64, copy=False).view(np.int64)
    return v - (1 << 64) if v >= 1 << 63 else v


def _rlev2_decode(data: bytes, count: int, signed: bool) -> np.ndarray:
    """ORC RLEv2: short-repeat / direct / patched-base / delta runs."""
    out = np.zeros(count, np.int64)
    n = 0
    pos = 0

    def uv():
        nonlocal pos
        v = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7

    def sv():  # signed varint (zigzag)
        u = uv()
        return (u >> 1) ^ -(u & 1)

    while n < count:
        b0 = data[pos]
        pos += 1
        enc = b0 >> 6
        if enc == 0:  # SHORT_REPEAT
            width = ((b0 >> 3) & 7) + 1
            run = (b0 & 7) + 3
            v = int.from_bytes(data[pos : pos + width], "big")
            pos += width
            v = (v >> 1) ^ -(v & 1) if signed else _wrap_u64(v)
            out[n : n + run] = v
            n += run
        elif enc == 1:  # DIRECT
            width = _w_decode((b0 >> 1) & 0x1F)
            run = ((b0 & 1) << 8 | data[pos]) + 1
            pos += 1
            vals, pos = _unpack_be(data, pos, width, run)
            if signed:
                u = vals.view(np.uint64)
                vals = ((u >> np.uint64(1)).astype(np.int64)) ^ -(
                    (u & np.uint64(1)).astype(np.int64)
                )
            else:
                # explicit uint64->int64 wrap through the shared helper
                # (ADVICE r5: no more relying on numpy's reinterpret
                # happening implicitly in the slice-assign below)
                vals = _wrap_u64(vals.view(np.uint64))
            out[n : n + run] = vals
            n += run
        elif enc == 2:  # PATCHED_BASE
            width = _w_decode((b0 >> 1) & 0x1F)
            run = ((b0 & 1) << 8 | data[pos]) + 1
            pos += 1
            b2 = data[pos]
            b3 = data[pos + 1]
            pos += 2
            bw = ((b2 >> 5) & 7) + 1           # base width bytes
            pw = _w_decode(b2 & 0x1F)          # patch width
            pgw = ((b3 >> 5) & 7) + 1          # patch gap width
            pll = b3 & 0x1F                    # patch list length
            base = int.from_bytes(data[pos : pos + bw], "big")
            pos += bw
            sign_mask = 1 << (bw * 8 - 1)
            if base & sign_mask:               # sign-magnitude
                base = -(base & (sign_mask - 1))
            vals, pos = _unpack_be(data, pos, width, run)
            vals = vals.copy()
            if pll:
                # patch entries are (gap,patch) pairs packed at the
                # CLOSEST FIXED width >= pgw+pw (ORC getClosestFixedBits)
                raw_bits = pgw + pw
                patch_bits = next(w for w in _RLEV2_WIDTHS if w >= raw_bits)
                entries, pos = _unpack_be(data, pos, patch_bits, pll)
                idx = 0
                for e in entries.view(np.uint64):
                    gap = int(e >> np.uint64(pw))
                    patch = int(e & ((np.uint64(1) << np.uint64(pw)) - np.uint64(1)))
                    idx += gap
                    vals[idx] |= patch << width
            out[n : n + run] = vals + base
            n += run
        else:  # DELTA
            width = _w_decode((b0 >> 1) & 0x1F, delta=True)
            run = ((b0 & 1) << 8 | data[pos]) + 1
            pos += 1
            base = sv() if signed else _wrap_u64(uv())
            if run == 1:
                out[n] = base
                n += 1
                continue
            delta0 = sv()
            inc = np.zeros(run, np.int64)
            inc[0] = base
            inc[1] = delta0
            if run > 2:
                if width:
                    mags, pos = _unpack_be(data, pos, width, run - 2)
                else:
                    mags = np.full(run - 2, abs(delta0), np.int64)
                inc[2:] = mags if delta0 >= 0 else -mags
            out[n : n + run] = np.cumsum(inc)
            n += run
    return out


def _orc_kind(dtype: DataType) -> int:
    k = dtype.kind
    if k == TypeKind.BOOL:
        return K_BOOLEAN
    if k == TypeKind.INT8:
        return K_BYTE
    if k == TypeKind.INT16:
        return K_SHORT
    if k == TypeKind.INT32:
        return K_INT
    if k == TypeKind.INT64:
        return K_LONG
    if k == TypeKind.FLOAT32:
        return K_FLOAT
    if k == TypeKind.FLOAT64:
        return K_DOUBLE
    if k == TypeKind.DATE32:
        return K_DATE
    if k == TypeKind.DECIMAL:
        return K_DECIMAL
    if k == TypeKind.TIMESTAMP:
        return K_TIMESTAMP
    if dtype.is_string:
        return K_STRING
    raise NotImplementedError(f"ORC subset: unsupported type {dtype!r}")


# ------------------------------------------------------------- protobuf

def _uvarint(v: int) -> bytes:
    out = bytearray()
    v = int(v)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zz(v: int) -> int:
    v = int(v)
    return (v << 1) ^ (v >> 63)


def _unzz(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


class PbWriter:
    def __init__(self):
        self.buf = bytearray()

    def varint(self, fid: int, v: int):
        self.buf += _uvarint(fid << 3 | 0)
        self.buf += _uvarint(v)

    def bytes_(self, fid: int, b: bytes):
        self.buf += _uvarint(fid << 3 | 2)
        self.buf += _uvarint(len(b))
        self.buf += b

    def string(self, fid: int, s: str):
        self.bytes_(fid, s.encode("utf-8"))

    def msg(self, fid: int, w: "PbWriter"):
        self.bytes_(fid, bytes(w.buf))

    def double(self, fid: int, v: float):
        self.buf += _uvarint(fid << 3 | 1)
        self.buf += struct.pack("<d", v)

    def getvalue(self) -> bytes:
        return bytes(self.buf)


class PbReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _uv(self) -> int:
        v = 0
        shift = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7

    def fields(self):
        """Yields (field_id, wire_type, value)."""
        while self.pos < len(self.data):
            tag = self._uv()
            fid, wt = tag >> 3, tag & 7
            if wt == 0:
                yield fid, wt, self._uv()
            elif wt == 1:
                v = struct.unpack_from("<d", self.data, self.pos)[0]
                self.pos += 8
                yield fid, wt, v
            elif wt == 2:
                ln = self._uv()
                yield fid, wt, self.data[self.pos : self.pos + ln]
                self.pos += ln
            elif wt == 5:
                v = struct.unpack_from("<f", self.data, self.pos)[0]
                self.pos += 4
                yield fid, wt, v
            else:
                raise ValueError(f"orc: unsupported protobuf wire type {wt}")


# ----------------------------------------------------------- encodings

def _byte_rle_encode(data: bytes) -> bytes:
    """ORC byte RLE: runs [n-3, byte] for 3..130 repeats, literal
    groups [-(n), n bytes]."""
    out = bytearray()
    i = 0
    n = len(data)
    while i < n:
        run = 1
        while i + run < n and run < 130 and data[i + run] == data[i]:
            run += 1
        if run >= 3:
            out.append(run - 3)
            out.append(data[i])
            i += run
            continue
        # literal group: scan ahead until a >=3 run starts
        j = i
        while j < n and j - i < 128:
            r = 1
            while j + r < n and r < 3 and data[j + r] == data[j]:
                r += 1
            if r >= 3:
                break
            j += 1
        out.append(256 - (j - i))
        out += data[i:j]
        i = j
    return bytes(out)


def _byte_rle_decode(data: bytes, count: int) -> bytes:
    out = bytearray()
    i = 0
    while len(out) < count:
        h = data[i]
        i += 1
        if h < 128:
            out += bytes([data[i]]) * (h + 3)
            i += 1
        else:
            ln = 256 - h
            out += data[i : i + ln]
            i += ln
    return bytes(out[:count])


def _bool_encode(bits: np.ndarray) -> bytes:
    packed = np.packbits(bits.astype(np.uint8))  # MSB-first, ORC order
    return _byte_rle_encode(packed.tobytes())


def _bool_decode(data: bytes, count: int) -> np.ndarray:
    nbytes = (count + 7) // 8
    raw = _byte_rle_decode(data, nbytes)
    return np.unpackbits(np.frombuffer(raw, np.uint8))[:count].astype(bool)


def _rlev1_encode(values: np.ndarray, signed: bool) -> bytes:
    """Literal groups only (spec-valid; the reader handles runs too)."""
    out = bytearray()
    vals = [int(v) for v in values]
    for i in range(0, len(vals), 128):
        group = vals[i : i + 128]
        out.append(256 - len(group))
        for v in group:
            out += _uvarint(_zz(v) if signed else v)
    return bytes(out)


def _rlev1_decode(data: bytes, count: int, signed: bool) -> np.ndarray:
    out = np.empty(count, np.int64)
    n = 0
    pos = 0

    def uv():
        nonlocal pos
        v = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7

    while n < count:
        h = data[pos]
        pos += 1
        if h < 128:  # run: h+3 values, delta int8, base varint
            ln = h + 3
            delta = struct.unpack_from("<b", data, pos)[0]
            pos += 1
            base = uv()
            base = _unzz(base) if signed else _wrap_u64(base)
            for k in range(ln):
                out[n] = base + k * delta
                n += 1
        else:
            ln = 256 - h
            for _ in range(ln):
                v = uv()
                out[n] = _unzz(v) if signed else _wrap_u64(v)
                n += 1
    return out


# --------------------------------------------------------------- writer

@dataclass
class _Stream:
    kind: int
    column: int
    data: bytes


def _pack_nanos(nanos: np.ndarray) -> np.ndarray:
    """ORC nanosecond packing (java formatNanos): values divisible by
    100 are divided down and the low 3 bits store zeros-1 (so c=1 means
    100 removed, c=7 means 10^8); c=0 means nothing removed."""
    out = np.zeros(nanos.shape[0], np.int64)
    for i, n in enumerate(np.asarray(nanos, np.int64)):
        n = int(n)
        if n == 0:
            continue
        if n % 100 != 0:
            out[i] = n << 3
            continue
        n //= 100
        c = 1
        while n % 10 == 0 and c < 7:
            n //= 10
            c += 1
        out[i] = (n << 3) | c
    return out


def _unpack_nanos(packed: np.ndarray) -> np.ndarray:
    """Inverse (java parseNanos): multiply by 10^(c+1) when c != 0."""
    c = packed & 7
    base = packed >> 3
    mult = np.where(c == 0, 1, 10 ** (c + 1)).astype(np.int64)
    return (base * mult).astype(np.int64)


def _encode_ts_streams(micros: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int64 unix-µs -> (DATA rel-seconds, SECONDARY packed nanos) —
    the single writer-side split, shared by every TIMESTAMP site.

    Uses the ORC C++ convention (verified against pyarrow's writer):
    seconds are TRUNC-TOWARD-ZERO unix seconds shifted to the 2015
    epoch, and nanos carry the SIGNED sub-second remainder (negative
    for pre-epoch fractions: -1µs -> secs 0, nanos -1000), wrapped to
    uint64 for the unsigned SECONDARY stream.  The Java writers' form
    (floor seconds, nanos in [0, 1e9)) is ambiguous in the second
    before the unix epoch — trunc secs 0 there is indistinguishable
    from a genuine +0.x value — so the C++ form is the one that
    roundtrips every value; the reader handles both."""
    micros = np.asarray(micros, np.int64)
    secs = np.where(micros < 0, -((-micros) // 1_000_000),
                    micros // 1_000_000)
    nanos = (micros - secs * 1_000_000) * 1000
    return secs - ORC_TS_EPOCH, _pack_nanos(nanos).view(np.uint64)


def _decode_ts_micros(rel: np.ndarray, packed: np.ndarray) -> np.ndarray:
    """(DATA rel-seconds, SECONDARY packed nanos) -> int64 unix-µs —
    the single reader-side join, shared by every TIMESTAMP site.
    Handles both writer conventions: signed-remainder nanos (ORC C++)
    fall through untouched; Java floor-second files carry positive
    nanos and need the seconds re-floored below zero."""
    nanos = _unpack_nanos(np.asarray(packed, np.int64))
    secs = np.asarray(rel, np.int64) + ORC_TS_EPOCH
    secs = np.where((secs < 0) & (nanos > 999_999), secs - 1, secs)
    return secs * 1_000_000 + nanos // 1000


def _encode_column(
    col_id: int, dtype: DataType, data: np.ndarray, validity: np.ndarray,
    lengths: Optional[np.ndarray],
) -> List[_Stream]:
    streams: List[_Stream] = []
    has_nulls = not bool(validity.all())
    if has_nulls:
        streams.append(_Stream(S_PRESENT, col_id, _bool_encode(validity)))
    live = validity.astype(bool)
    k = dtype.kind
    if k == TypeKind.BOOL:
        streams.append(_Stream(S_DATA, col_id, _bool_encode(data[live].astype(bool))))
    elif k == TypeKind.INT8:
        streams.append(_Stream(S_DATA, col_id, _byte_rle_encode(
            data[live].astype(np.int8).tobytes())))
    elif k in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.DATE32):
        streams.append(_Stream(S_DATA, col_id, _rlev1_encode(data[live], signed=True)))
    elif k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
        streams.append(_Stream(S_DATA, col_id, np.ascontiguousarray(data[live]).tobytes()))
    elif k == TypeKind.DECIMAL:
        body = bytearray()
        for v in data[live]:
            body += _uvarint(_zz(int(v)))
        streams.append(_Stream(S_DATA, col_id, bytes(body)))
        streams.append(_Stream(S_SECONDARY, col_id, _rlev1_encode(
            np.full(int(live.sum()), dtype.scale, np.int64), signed=True)))
    elif k == TypeKind.TIMESTAMP:
        rel, packed = _encode_ts_streams(data[live])
        streams.append(_Stream(S_DATA, col_id, _rlev1_encode(rel, signed=True)))
        streams.append(_Stream(S_SECONDARY, col_id, _rlev1_encode(
            packed, signed=False)))
    elif dtype.is_string:
        ln = lengths[live]
        streams.append(_Stream(S_LENGTH, col_id, _rlev1_encode(ln, signed=False)))
        body = bytearray()
        d = data[live]
        for i in range(d.shape[0]):
            body += bytes(d[i, : ln[i]])
        streams.append(_Stream(S_DATA, col_id, bytes(body)))
    else:
        raise NotImplementedError(f"ORC subset: {dtype!r}")
    return streams


def _encode_list_column(
    col_id: int, dtype: DataType, validity: np.ndarray,
    lengths: np.ndarray, edata: np.ndarray, evalid: np.ndarray,
) -> List[_Stream]:
    """LIST of primitive: LENGTH at the list column, flattened child
    PRESENT/DATA at col_id+1 (the writer's preorder child id)."""
    if dtype.elem.is_nested or dtype.elem.is_string:
        raise NotImplementedError(f"ORC subset writer: {dtype!r}")
    streams: List[_Stream] = []
    live = validity.astype(bool)
    if not bool(live.all()):
        streams.append(_Stream(S_PRESENT, col_id, _bool_encode(validity)))
    ln = lengths[live].astype(np.int64)
    streams.append(_Stream(S_LENGTH, col_id, _rlev1_encode(ln, signed=False)))
    flat_v: List[np.ndarray] = []
    flat_d: List[np.ndarray] = []
    for i in np.flatnonzero(live):
        L = int(lengths[i])
        flat_v.append(evalid[i, :L])
        flat_d.append(edata[i, :L])
    ev = np.concatenate(flat_v) if flat_v else np.zeros(0, bool)
    ed = np.concatenate(flat_d) if flat_d else np.zeros(0, dtype.elem.np_dtype)
    streams.extend(_encode_column(col_id + 1, dtype.elem, ed, ev, None))
    return streams


def _type_size(dt: DataType) -> int:
    """Number of preorder type-tree slots this type consumes."""
    if dt.kind == TypeKind.ARRAY:
        return 1 + _type_size(dt.elem)
    if dt.kind == TypeKind.MAP:
        return 1 + _type_size(dt.key) + _type_size(dt.value)
    if dt.kind == TypeKind.STRUCT:
        return 1 + sum(_type_size(f.dtype) for f in dt.struct_fields)
    return 1


def _is_compound(dt: DataType) -> bool:
    """Columns that take the recursive python-value path, on BOTH the
    writer and reader sides (one predicate so they can never
    disagree on dispatch): maps, structs, and lists whose elements
    are nested or strings (flat lists keep the vectorized path)."""
    return dt.kind in (TypeKind.MAP, TypeKind.STRUCT) or (
        dt.kind == TypeKind.ARRAY and (dt.elem.is_nested or dt.elem.is_string)
    )


def _encode_pyvalues(
    col_id: int, dtype: DataType, vals: list,
    counts: Dict[int, Tuple[int, bool]],
) -> List[_Stream]:
    """Recursive encoder for compound columns fed as python values —
    the exact shape the reader's compound path (`decode_nested`)
    produces: None for null, list per ARRAY slot, dict per MAP/STRUCT
    slot.  Mirrors the reader's conventions: PRESENT per nesting
    level, children carry one entry per non-null parent slot (per
    element for LIST/MAP)."""
    streams: List[_Stream] = []
    validity = np.array([v is not None for v in vals], bool)
    live = [v for v in vals if v is not None]
    counts[col_id] = (len(live), len(live) < len(vals))
    if not bool(validity.all()):
        streams.append(_Stream(S_PRESENT, col_id, _bool_encode(validity)))
    k = dtype.kind
    if k == TypeKind.ARRAY:
        ln = np.array([len(v) for v in live], np.int64)
        streams.append(_Stream(S_LENGTH, col_id, _rlev1_encode(ln, signed=False)))
        streams.extend(_encode_pyvalues(
            col_id + 1, dtype.elem, [e for v in live for e in v], counts))
        return streams
    if k == TypeKind.MAP:
        ln = np.array([len(v) for v in live], np.int64)
        streams.append(_Stream(S_LENGTH, col_id, _rlev1_encode(ln, signed=False)))
        streams.extend(_encode_pyvalues(
            col_id + 1, dtype.key, [e for v in live for e in v.keys()], counts))
        streams.extend(_encode_pyvalues(
            col_id + 1 + _type_size(dtype.key), dtype.value,
            [e for v in live for e in v.values()], counts))
        return streams
    if k == TypeKind.STRUCT:
        sub = col_id + 1
        for f in dtype.struct_fields:
            streams.extend(_encode_pyvalues(
                sub, f.dtype, [v[f.name] for v in live], counts))
            sub += _type_size(f.dtype)
        return streams
    if dtype.is_string:
        bodies = [s.encode() if isinstance(s, str) else bytes(s) for s in live]
        streams.append(_Stream(S_LENGTH, col_id, _rlev1_encode(
            np.array([len(b) for b in bodies], np.int64), signed=False)))
        streams.append(_Stream(S_DATA, col_id, b"".join(bodies)))
        return streams
    if k == TypeKind.BOOL:
        streams.append(_Stream(S_DATA, col_id, _bool_encode(
            np.array([bool(v) for v in live], bool))))
        return streams
    if k == TypeKind.DECIMAL:
        import decimal as _dec

        body = bytearray()
        for v in live:
            scaled = _dec.Decimal(v).scaleb(dtype.scale)
            if scaled != scaled.to_integral_value():
                # same gate as the reader's _rescale_decimals: a value
                # with more fractional digits than the declared scale
                # cannot be represented exactly — never truncate
                raise NotImplementedError(
                    f"ORC subset: decimal value {v} exceeds the "
                    f"declared scale {dtype.scale}")
            body += _uvarint(_zz(int(scaled)))
        streams.append(_Stream(S_DATA, col_id, bytes(body)))
        streams.append(_Stream(S_SECONDARY, col_id, _rlev1_encode(
            np.full(len(live), dtype.scale, np.int64), signed=True)))
        return streams
    if k == TypeKind.INT8:
        streams.append(_Stream(S_DATA, col_id, _byte_rle_encode(
            np.array(live, np.int8).tobytes())))
        return streams
    if k in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.DATE32):
        streams.append(_Stream(S_DATA, col_id, _rlev1_encode(
            np.array([int(v) for v in live], np.int64), signed=True)))
        return streams
    if k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
        streams.append(_Stream(S_DATA, col_id, np.ascontiguousarray(
            np.array(live, dtype.np_dtype)).tobytes()))
        return streams
    if k == TypeKind.TIMESTAMP:
        # values are int64 unix microseconds (the engine's physical
        # timestamp lane)
        rel, packed = _encode_ts_streams(
            np.array([int(v) for v in live], np.int64))
        streams.append(_Stream(S_DATA, col_id, _rlev1_encode(rel, signed=True)))
        streams.append(_Stream(S_SECONDARY, col_id, _rlev1_encode(
            packed, signed=False)))
        return streams
    raise NotImplementedError(f"ORC subset writer: compound element {dtype!r}")


def _col_stats(dtype: DataType, data, validity, lengths) -> "PbWriter":
    w = PbWriter()
    live = validity.astype(bool)
    nvals = int(live.sum())
    w.varint(1, nvals)
    if nvals:
        k = dtype.kind
        if k in (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                 TypeKind.DECIMAL):
            s = PbWriter()
            s.varint(1, _zz(int(data[live].min())) )
            s.varint(2, _zz(int(data[live].max())))
            # sint64 via zigzag: IntegerStatistics min/max are sint64
            w.msg(2, s)
        elif k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
            s = PbWriter()
            s.double(1, float(data[live].min()))
            s.double(2, float(data[live].max()))
            w.msg(3, s)
        elif dtype.is_string:
            vals = [bytes(data[i, : lengths[i]]) for i in np.flatnonzero(live)]
            s = PbWriter()
            s.bytes_(1, min(vals))
            s.bytes_(2, max(vals))
            w.msg(4, s)
        elif k == TypeKind.DATE32:
            s = PbWriter()
            s.varint(1, _zz(int(data[live].min())))
            s.varint(2, _zz(int(data[live].max())))
            w.msg(7, s)
    w.varint(10, 0 if bool(live.all()) else 1)  # hasNull
    return w


def write_orc(
    path: str,
    schema: Schema,
    columns: Dict[str, Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]],
    stripe_rows: int = 65536,
    compression: str = "none",
) -> None:
    """columns: name -> (data, validity|None, lengths|None for strings).
    ARRAY-of-primitive fields instead take the reader's 4-tuple shape:
    (None, validity|None, lengths, (elem_data_2d, elem_valid_2d)).
    MAP/STRUCT/nested-LIST fields take a plain python value list
    (None/list/dict per row — the reader's compound-path shape).
    ``compression``: "none", "zlib" (Spark's ORC default), "zstd",
    "snappy", or "lz4" — every stream, stripe footer, Metadata and
    Footer region gets the chunked [u24 header][block] framing; the
    PostScript stays raw."""
    comp_kind = {"none": C_NONE, "zlib": C_ZLIB, "zstd": C_ZSTD,
                 "snappy": C_SNAPPY, "lz4": C_LZ4}[compression]
    any_name = next(iter(columns))
    any_col = columns[any_name]
    any_dt = schema.field(any_name).dtype
    if _is_compound(any_dt):
        n = len(any_col)
    elif any_dt.kind == TypeKind.ARRAY:
        n = any_col[2].shape[0]  # 4-tuple shape: lengths carries rows
    else:
        n = any_col[0].shape[0]
    from .fs import get_fs

    # preorder type ids: root = 0; compound fields consume one slot per
    # nested type-tree node
    field_type_ids: List[int] = []
    _next = 1
    for _fld in schema.fields:
        field_type_ids.append(_next)
        _next += _type_size(_fld.dtype)
    total_type_ids = _next

    with get_fs(path).create(path) as f:
        f.write(MAGIC)
        stripe_infos: List[Tuple[int, int, int, int]] = []  # offset, dataLen, footLen, rows
        stripe_stats: List[List[bytes]] = []
        for start in range(0, max(n, 1), stripe_rows):
            rows = min(stripe_rows, n - start)
            if rows <= 0 and n > 0:
                break
            offset = f.tell()
            streams: List[_Stream] = []
            stats_msgs: List[bytes] = []
            # root struct stats
            root = PbWriter()
            root.varint(1, rows)
            root.varint(10, 0)
            stats_msgs.append(root.getvalue())
            for ci, fld in zip(field_type_ids, schema.fields):
                if _is_compound(fld.dtype):
                    vals = columns[fld.name][start : start + rows]
                    counts: Dict[int, Tuple[int, bool]] = {}
                    streams.extend(_encode_pyvalues(ci, fld.dtype, vals, counts))
                    for slot in range(ci, ci + _type_size(fld.dtype)):
                        nvals, had_null = counts.get(slot, (0, False))
                        cw = PbWriter()
                        cw.varint(1, nvals)
                        cw.varint(10, 1 if had_null else 0)
                        stats_msgs.append(cw.getvalue())
                    continue
                if fld.dtype.kind == TypeKind.ARRAY:
                    _, validity, lengths, (edata, evalid) = columns[fld.name]
                    if validity is None:
                        validity = np.ones(lengths.shape[0], bool)
                    sl = slice(start, start + rows)
                    streams.extend(_encode_list_column(
                        ci, fld.dtype, validity[sl], lengths[sl],
                        edata[sl], evalid[sl]))
                    # truthful per-slot stats (SARG readers prune
                    # `IS NULL` stripes on hasNull): parent slot =
                    # live rows; child slot = live elements within
                    # live rows' lengths
                    v_sl, ln_sl, ev_sl = validity[sl], lengths[sl], evalid[sl]
                    within = (np.arange(ev_sl.shape[1])[None, :]
                              < ln_sl[:, None]) & v_sl[:, None]
                    live_elems = within & ev_sl
                    for nvals, had_null in (
                        (int(v_sl.sum()), not bool(v_sl.all())),
                        (int(live_elems.sum()),
                         bool((within & ~ev_sl).any())),
                    ):
                        cw = PbWriter()
                        cw.varint(1, nvals)
                        cw.varint(10, 1 if had_null else 0)
                        stats_msgs.append(cw.getvalue())
                    continue
                data, validity, lengths = columns[fld.name]
                if validity is None:
                    validity = np.ones(data.shape[0], bool)
                sl = slice(start, start + rows)
                d, v = data[sl], validity[sl]
                ln = None if lengths is None else lengths[sl]
                streams.extend(_encode_column(ci, fld.dtype, d, v, ln))
                stats_msgs.append(_col_stats(fld.dtype, d, v, ln).getvalue())
            # stream lengths in the stripe footer are the COMPRESSED
            # on-disk lengths (readers slice the data region by them,
            # then undo the chunked framing per stream)
            wire = [orc_compress(s.data, comp_kind) for s in streams]
            data_len = 0
            for w in wire:
                f.write(w)
                data_len += len(w)
            sf = PbWriter()
            for s, w in zip(streams, wire):
                m = PbWriter()
                m.varint(1, s.kind)
                m.varint(2, s.column)
                m.varint(3, len(w))
                sf.msg(1, m)
            for _ in range(total_type_ids):
                enc = PbWriter()
                enc.varint(1, 0)  # DIRECT
                sf.msg(2, enc)
            foot = orc_compress(sf.getvalue(), comp_kind)
            f.write(foot)
            stripe_infos.append((offset, data_len, len(foot), rows))
            stripe_stats.append(stats_msgs)
            if n == 0:
                break

        # Metadata: per-stripe column statistics
        md = PbWriter()
        for msgs in stripe_stats:
            ss = PbWriter()
            for m in msgs:
                ss.bytes_(1, m)
            md.msg(1, ss)
        md_bytes = orc_compress(md.getvalue(), comp_kind)
        f.write(md_bytes)

        # Footer
        ft = PbWriter()
        ft.varint(1, 3)  # headerLength ("ORC")
        content_len = stripe_infos[-1][0] + stripe_infos[-1][1] + stripe_infos[-1][2] if stripe_infos else 3
        ft.varint(2, content_len)
        for off, dl, fl, rows in stripe_infos:
            si = PbWriter()
            si.varint(1, off)
            si.varint(2, 0)   # indexLength (no row index in subset)
            si.varint(3, dl)
            si.varint(4, fl)
            si.varint(5, rows)
            ft.msg(3, si)
        root_t = PbWriter()
        root_t.varint(1, K_STRUCT)
        for tid in field_type_ids:
            root_t.varint(2, tid)
        for fld in schema.fields:
            root_t.string(3, fld.name)
        ft.msg(4, root_t)

        def emit_type(dt: DataType, tid: int) -> None:
            t = PbWriter()
            if dt.kind == TypeKind.ARRAY:
                t.varint(1, K_LIST)
                t.varint(2, tid + 1)
                ft.msg(4, t)
                emit_type(dt.elem, tid + 1)
                return
            if dt.kind == TypeKind.MAP:
                t.varint(1, K_MAP)
                kid, vid = tid + 1, tid + 1 + _type_size(dt.key)
                t.varint(2, kid)
                t.varint(2, vid)
                ft.msg(4, t)
                emit_type(dt.key, kid)
                emit_type(dt.value, vid)
                return
            if dt.kind == TypeKind.STRUCT:
                t.varint(1, K_STRUCT)
                sub = tid + 1
                for f2 in dt.struct_fields:
                    t.varint(2, sub)
                    sub += _type_size(f2.dtype)
                for f2 in dt.struct_fields:
                    t.string(3, f2.name)
                ft.msg(4, t)
                sub = tid + 1
                for f2 in dt.struct_fields:
                    emit_type(f2.dtype, sub)
                    sub += _type_size(f2.dtype)
                return
            t.varint(1, _orc_kind(dt))
            if dt.is_decimal:
                t.varint(5, dt.precision)
                t.varint(6, dt.scale)
            ft.msg(4, t)

        for tid, fld in zip(field_type_ids, schema.fields):
            emit_type(fld.dtype, tid)
        ft.varint(6, n)  # numberOfRows
        ft_bytes = orc_compress(ft.getvalue(), comp_kind)
        f.write(ft_bytes)

        ps = PbWriter()
        ps.varint(1, len(ft_bytes))
        ps.varint(2, comp_kind)
        ps.varint(3, 65536)
        ps.bytes_(4, _uvarint(0) + _uvarint(12))  # version [0, 12] packed
        ps.varint(5, len(md_bytes))
        ps.varint(6, 1)
        ps.string(8000, "ORC")
        ps_bytes = ps.getvalue()
        f.write(ps_bytes)
        assert len(ps_bytes) < 256
        f.write(bytes([len(ps_bytes)]))


# --------------------------------------------------------------- reader

@dataclass
class StripeInfo:
    offset: int
    data_length: int
    footer_length: int
    rows: int
    # per-column stats: name -> (min, max, has_null) python values
    stats: Dict[str, Tuple] = field(default_factory=dict)


@dataclass
class OrcFileMeta:
    schema: Schema
    stripes: List[StripeInfo]
    num_rows: int
    compression: int = C_NONE
    # per top-level field: its column id in the flattened type tree
    # (flat files: 1..n; a LIST field consumes its child's id too)
    field_ids: List[int] = None
    # field name -> element column id (LIST fields only)
    child_ids: dict = None
    # type id -> (kind, subtype ids): the full flattened type tree,
    # needed to walk MAP/STRUCT/nested-LIST columns
    type_tree: dict = None


def _decode_type(b: bytes) -> Tuple[int, List[int], List[str], int, int]:
    kind = 0
    subtypes: List[int] = []
    names: List[str] = []
    precision = scale = 0
    for fid, wt, v in PbReader(b).fields():
        if fid == 1:
            kind = v
        elif fid == 2:
            if isinstance(v, (bytes, bytearray)):
                # packed repeated uint32 (ORC C++ writers)
                pos = 0
                while pos < len(v):
                    u = 0
                    shift = 0
                    while True:
                        byte = v[pos]
                        pos += 1
                        u |= (byte & 0x7F) << shift
                        if not byte & 0x80:
                            break
                        shift += 7
                    subtypes.append(u)
            else:
                subtypes.append(v)
        elif fid == 3:
            names.append(v.decode("utf-8"))
        elif fid == 5:
            precision = v
        elif fid == 6:
            scale = v
    return kind, subtypes, names, precision, scale


_KIND_TO_DTYPE = {
    K_BOOLEAN: DataType.bool_(),
    K_BYTE: DataType.int8(),
    K_SHORT: DataType.int16(),
    K_INT: DataType.int32(),
    K_LONG: DataType.int64(),
    K_FLOAT: DataType.float32(),
    K_DOUBLE: DataType.float64(),
    K_DATE: DataType.date32(),
    K_TIMESTAMP: DataType.timestamp(),
}


def _decode_col_stats(b: bytes):
    mn = mx = None
    has_null = False
    for fid, wt, v in PbReader(b).fields():
        if fid == 10:
            has_null = bool(v)
        elif fid in (2, 7):  # IntegerStatistics / DateStatistics
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    mn = _unzz(v2)
                elif f2 == 2:
                    mx = _unzz(v2)
        elif fid == 3:  # DoubleStatistics
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    mn = v2
                elif f2 == 2:
                    mx = v2
        elif fid == 4:  # StringStatistics
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    mn = v2
                elif f2 == 2:
                    mx = v2
    return mn, mx, has_null


def read_metadata(path: str, list_elems: int = 16, string_width: int = 64) -> OrcFileMeta:
    from .fs import get_fs

    with get_fs(path).open(path) as f:
        f.seek(0, 2)
        size = f.tell()
        f.seek(size - 1)
        ps_len = f.read(1)[0]
        f.seek(size - 1 - ps_len)
        ps = f.read(ps_len)
        footer_len = md_len = 0
        magic = None
        compression = 0
        for fid, wt, v in PbReader(ps).fields():
            if fid == 1:
                footer_len = v
            elif fid == 2:
                compression = v
            elif fid == 5:
                md_len = v
            elif fid == 8000:
                magic = v
        if magic != b"ORC":
            raise ValueError(f"{path}: not an ORC file")
        f.seek(size - 1 - ps_len - footer_len)
        footer = orc_decompress(f.read(footer_len), compression)
        f.seek(size - 1 - ps_len - footer_len - md_len)
        md = orc_decompress(f.read(md_len), compression)

    stripes: List[StripeInfo] = []
    types: List[bytes] = []
    num_rows = 0
    for fid, wt, v in PbReader(footer).fields():
        if fid == 3:
            off = il = dl = fl = rows = 0
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    off = v2
                elif f2 == 2:
                    il = v2
                elif f2 == 3:
                    dl = v2
                elif f2 == 4:
                    fl = v2
                elif f2 == 5:
                    rows = v2
            stripes.append(StripeInfo(off + il, dl, fl, rows))
        elif fid == 4:
            types.append(v)
        elif fid == 6:
            num_rows = v

    kind0, subtypes, names, _, _ = _decode_type(types[0])
    if kind0 != K_STRUCT:
        raise NotImplementedError("ORC subset: root must be a struct")
    fields = []
    field_ids: List[int] = []
    child_ids: dict = {}

    def prim_dtype(kind, precision, scale):
        if kind == K_DECIMAL:
            return DataType.decimal(precision or 18, scale)
        if kind == K_STRING:
            return DataType.string(string_width)
        if kind in _KIND_TO_DTYPE:
            return _KIND_TO_DTYPE[kind]
        raise NotImplementedError(f"ORC subset: type kind {kind}")

    type_tree: dict = {}

    def full_dtype(tid: int) -> DataType:
        kind, subs, cnames, precision, scale = _decode_type(types[tid])
        type_tree[tid] = (kind, list(subs))
        if kind == K_LIST:
            return DataType.array(full_dtype(subs[0]), list_elems)
        if kind == K_MAP:
            return DataType.map(full_dtype(subs[0]), full_dtype(subs[1]),
                                list_elems)
        if kind == K_STRUCT:
            return DataType.struct(
                [Field(n, full_dtype(s2)) for n, s2 in zip(cnames, subs)])
        return prim_dtype(kind, precision, scale)

    for name, st in zip(names, subtypes):
        kind, subs, _, precision, scale = _decode_type(types[st])
        field_ids.append(st)
        dt = full_dtype(st)
        if kind == K_LIST and not (dt.elem.is_nested or dt.elem.is_string):
            # flat LIST keeps the vectorized fast path in read_stripe
            child_ids[name] = subs[0]
        fields.append(Field(name, dt))
    schema = Schema(fields)

    # stripe statistics from the Metadata section
    stripe_stats: List[List[bytes]] = []
    for fid, wt, v in PbReader(md).fields():
        if fid == 1:
            cols = [v2 for f2, _, v2 in PbReader(v).fields() if f2 == 1]
            stripe_stats.append(cols)
    for si, st in enumerate(stripes):
        if si < len(stripe_stats):
            cols = stripe_stats[si]
            for ci, fld in zip(field_ids, schema.fields):
                if ci < len(cols):
                    st.stats[fld.name] = _decode_col_stats(cols[ci])
    return OrcFileMeta(schema, stripes, num_rows, compression,
                       field_ids=field_ids, child_ids=child_ids,
                       type_tree=type_tree)


S_ROW_INDEX, S_BLOOM_FILTER, S_BLOOM_FILTER_UTF8 = 6, 7, 8


def _rescale_decimals(vals: np.ndarray, scales: np.ndarray,
                      declared: int) -> np.ndarray:
    """Align per-value decimal scales (the SECONDARY stream) to the
    declared type scale.  Writers normally emit the declared scale for
    every value, but the spec allows differing ones; a value with MORE
    fractional digits than the declared type cannot be represented
    exactly and is gated."""
    scales = np.asarray(scales[: vals.size], np.int64)
    if np.all(scales == declared):
        return vals
    if int(scales.max(initial=declared)) > declared:
        raise NotImplementedError(
            f"ORC subset: decimal value scale {int(scales.max())} exceeds "
            f"the declared scale {declared}"
        )
    return vals * (10 ** (declared - scales)).astype(np.int64)


def _varint_stream_decode(raw: bytes, nvals: int) -> np.ndarray:
    """Unbounded zigzag varints (decimal DATA stream)."""
    vals = np.empty(nvals, np.int64)
    pos = 0
    for i in range(nvals):
        v = 0
        shift = 0
        while True:
            b = raw[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        vals[i] = _unzz(v)
    return vals


def read_stripe(
    path: str, meta: OrcFileMeta, stripe: StripeInfo
) -> Dict[str, Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
    """name -> (data, validity, lengths|None); strings return (rows, W)
    uint8 data at the column's declared width.

    Handles DIRECT (RLEv1) and DIRECT_V2 (RLEv2) integer encodings,
    DICTIONARY(_V2) strings, and per-stream compressed framing."""
    from .fs import get_fs

    comp = meta.compression
    with get_fs(path).open(path) as f:
        f.seek(stripe.offset)
        blob = f.read(stripe.data_length)
        foot = orc_decompress(f.read(stripe.footer_length), comp)
    streams: List[Tuple[int, int, int]] = []  # kind, column, length
    encodings: List[Tuple[int, int]] = []     # (encoding kind, dict size)
    for fid, wt, v in PbReader(foot).fields():
        if fid == 1:
            kind = column = length = 0
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    kind = v2
                elif f2 == 2:
                    column = v2
                elif f2 == 3:
                    length = v2
            streams.append((kind, column, length))
        elif fid == 2:
            ek = ds = 0
            for f2, _, v2 in PbReader(v).fields():
                if f2 == 1:
                    ek = v2
                elif f2 == 2:
                    ds = v2
            encodings.append((ek, ds))

    # data-region streams appear in file order; index-region streams
    # (ROW_INDEX/BLOOM) precede them and are NOT in our blob
    per_col: Dict[int, Dict[int, bytes]] = {}
    off = 0
    for kind, column, length in streams:
        if kind in (S_ROW_INDEX, S_BLOOM_FILTER, S_BLOOM_FILTER_UTF8):
            continue
        per_col.setdefault(column, {})[kind] = blob[off : off + length]
        off += length

    def dec(ci: int, kind: int) -> bytes:
        return orc_decompress(per_col.get(ci, {}).get(kind, b""), comp)

    def int_decode(raw: bytes, nvals: int, signed: bool, enc: int) -> np.ndarray:
        if enc in (E_DIRECT_V2, E_DICTIONARY_V2):
            return _rlev2_decode(raw, nvals, signed)
        return _rlev1_decode(raw, nvals, signed)

    tree = meta.type_tree or {}

    def decode_nested(tid: int, dtype: DataType, count: int) -> list:
        """Recursive python-value decode for compound columns
        (MAP/STRUCT/nested LIST/list-of-string) — each nesting level
        carries its own PRESENT stream; children hold one entry per
        non-null parent slot (LIST/MAP: per element)."""
        stt = per_col.get(tid, {})
        encn = encodings[tid][0] if tid < len(encodings) else E_DIRECT
        dsz = encodings[tid][1] if tid < len(encodings) else 0
        validity = (
            _bool_decode(dec(tid, S_PRESENT), count)
            if S_PRESENT in stt
            else np.ones(count, bool)
        )
        nv = int(validity.sum())
        k = dtype.kind

        def scatter(vals: list) -> list:
            it = iter(vals)
            return [next(it) if ok else None for ok in validity]

        if k == TypeKind.ARRAY:
            ln = int_decode(dec(tid, S_LENGTH), nv, False, encn)
            elems = decode_nested(tree[tid][1][0], dtype.elem, int(ln.sum()))
            vals, pos = [], 0
            for L in ln:
                vals.append(elems[pos : pos + int(L)])
                pos += int(L)
            return scatter(vals)
        if k == TypeKind.MAP:
            ln = int_decode(dec(tid, S_LENGTH), nv, False, encn)
            total = int(ln.sum())
            keys = decode_nested(tree[tid][1][0], dtype.key, total)
            mvals = decode_nested(tree[tid][1][1], dtype.value, total)
            vals, pos = [], 0
            for L in ln:
                vals.append(dict(zip(keys[pos : pos + int(L)],
                                     mvals[pos : pos + int(L)])))
                pos += int(L)
            return scatter(vals)
        if k == TypeKind.STRUCT:
            kids = [
                decode_nested(s2, f2.dtype, nv)
                for s2, f2 in zip(tree[tid][1], dtype.struct_fields)
            ]
            vals = [
                {f2.name: kid[j] for f2, kid in zip(dtype.struct_fields, kids)}
                for j in range(nv)
            ]
            return scatter(vals)
        if dtype.is_string:
            if encn in (E_DICTIONARY, E_DICTIONARY_V2):
                dlen = int_decode(dec(tid, S_LENGTH), dsz, False, encn)
                dbody = dec(tid, S_DICTIONARY_DATA)
                offs = np.concatenate([[0], np.cumsum(dlen)])
                words = [
                    bytes(dbody[int(offs[i]) : int(offs[i + 1])]).decode()
                    for i in range(dsz)
                ]
                indices = int_decode(dec(tid, S_DATA), nv, False, encn)
                return scatter([words[int(i)] for i in indices])
            ln = int_decode(dec(tid, S_LENGTH), nv, False, encn)
            body = dec(tid, S_DATA)
            vals, pos = [], 0
            for L in ln:
                vals.append(bytes(body[pos : pos + int(L)]).decode())
                pos += int(L)
            return scatter(vals)
        if k == TypeKind.BOOL:
            return scatter([bool(v) for v in _bool_decode(dec(tid, S_DATA), nv)])
        if k == TypeKind.DECIMAL:
            import decimal as _dec

            unscaled = _varint_stream_decode(dec(tid, S_DATA), nv)
            unscaled = _rescale_decimals(
                unscaled, int_decode(dec(tid, S_SECONDARY), nv, True, encn),
                dtype.scale)
            q = _dec.Decimal(1).scaleb(-dtype.scale)
            return scatter([_dec.Decimal(int(v)).scaleb(-dtype.scale)
                            .quantize(q) for v in unscaled])
        if k in (TypeKind.INT8,):
            return scatter([int(v) for v in np.frombuffer(
                _byte_rle_decode(dec(tid, S_DATA), nv), np.int8)])
        if k in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                 TypeKind.DATE32):
            return scatter([int(v) for v in
                            int_decode(dec(tid, S_DATA), nv, True, encn)])
        if k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
            return scatter([float(v) for v in np.frombuffer(
                dec(tid, S_DATA), dtype.np_dtype, nv)])
        if k == TypeKind.TIMESTAMP:
            return scatter([int(v) for v in _decode_ts_micros(
                int_decode(dec(tid, S_DATA), nv, True, encn),
                int_decode(dec(tid, S_SECONDARY), nv, False, encn))])
        raise NotImplementedError(f"ORC subset: nested element {dtype!r}")

    rows = stripe.rows
    out = {}
    ids = meta.field_ids or list(range(1, len(meta.schema.fields) + 1))
    for ci, fld in zip(ids, meta.schema.fields):
        st = per_col.get(ci, {})
        enc = encodings[ci][0] if ci < len(encodings) else E_DIRECT
        dict_size = encodings[ci][1] if ci < len(encodings) else 0
        if _is_compound(fld.dtype):
            # compound columns (maps, structs, nested/str lists):
            # recursive python-value decode (incl. its own PRESENT);
            # the scan layer builds the padded nested Column via
            # column_from_pylist
            out[fld.name] = ("py", decode_nested(ci, fld.dtype, rows))
            continue
        validity = (
            _bool_decode(dec(ci, S_PRESENT), rows)
            if S_PRESENT in st
            else np.ones(rows, bool)
        )
        nvals = int(validity.sum())
        k = fld.dtype.kind
        lengths = None
        if k == TypeKind.BOOL:
            vals = _bool_decode(dec(ci, S_DATA), nvals)
            data = np.zeros(rows, bool)
            data[validity] = vals
        elif k == TypeKind.INT8:
            vals = np.frombuffer(_byte_rle_decode(dec(ci, S_DATA), nvals), np.int8)
            data = np.zeros(rows, np.int8)
            data[validity] = vals
        elif k in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.DATE32,
                   TypeKind.DECIMAL):
            if k == TypeKind.DECIMAL:
                vals = _varint_stream_decode(dec(ci, S_DATA), nvals)
                vals = _rescale_decimals(
                    vals, int_decode(dec(ci, S_SECONDARY), nvals, True, enc),
                    fld.dtype.scale)
            else:
                vals = int_decode(dec(ci, S_DATA), nvals, True, enc)
            data = np.zeros(rows, fld.dtype.np_dtype)
            data[validity] = vals.astype(fld.dtype.np_dtype)
        elif k == TypeKind.TIMESTAMP:
            vals = _decode_ts_micros(
                int_decode(dec(ci, S_DATA), nvals, True, enc),
                int_decode(dec(ci, S_SECONDARY), nvals, False, enc))
            data = np.zeros(rows, np.int64)
            data[validity] = vals
        elif k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
            vals = np.frombuffer(dec(ci, S_DATA), fld.dtype.np_dtype, nvals)
            data = np.zeros(rows, fld.dtype.np_dtype)
            data[validity] = vals
        elif fld.dtype.is_string:
            w = fld.dtype.string_width
            data = np.zeros((rows, w), np.uint8)
            lengths = np.zeros(rows, np.int32)
            idxs = np.flatnonzero(validity)
            if enc in (E_DICTIONARY, E_DICTIONARY_V2):
                dlen = int_decode(dec(ci, S_LENGTH), dict_size, False, enc)
                dbody = dec(ci, S_DICTIONARY_DATA)
                offs = np.concatenate([[0], np.cumsum(dlen)])
                indices = int_decode(dec(ci, S_DATA), nvals, False, enc)
                for j, i in enumerate(idxs):
                    di = int(indices[j])
                    L = int(dlen[di])
                    data[i, : min(L, w)] = np.frombuffer(
                        dbody, np.uint8, min(L, w), int(offs[di])
                    )
                    lengths[i] = min(L, w)
            else:
                ln = int_decode(dec(ci, S_LENGTH), nvals, False, enc)
                body = dec(ci, S_DATA)
                pos = 0
                for j, i in enumerate(idxs):
                    L = int(ln[j])
                    data[i, : min(L, w)] = np.frombuffer(body, np.uint8, min(L, w), pos)
                    lengths[i] = min(L, w)
                    pos += L
        elif fld.dtype.kind == TypeKind.ARRAY:
            # LIST of primitive: LENGTH stream at the list column,
            # PRESENT+DATA at the child column id; rectangularized to
            # the declared max_elems
            et = fld.dtype.elem
            m = fld.dtype.max_elems
            cid = (meta.child_ids or {}).get(fld.name, ci + 1)
            ln = int_decode(dec(ci, S_LENGTH), nvals, False, enc)
            if ln.size and int(ln.max()) > m:
                # gated, not silently wrong: a list longer than the
                # padded layout's declared cap cannot be represented
                raise NotImplementedError(
                    f"ORC subset: list length {int(ln.max())} exceeds the "
                    f"declared max_elems {m} for {fld.name!r}; re-read with "
                    f"a wider ARRAY type"
                )
            lengths = np.zeros(rows, np.int32)
            lengths[validity] = ln.astype(np.int32)
            total = int(ln.sum())
            cst = per_col.get(cid, {})
            cenc = encodings[cid][0] if cid < len(encodings) else E_DIRECT
            evalid = (
                _bool_decode(dec(cid, S_PRESENT), total)
                if S_PRESENT in cst
                else np.ones(total, bool)
            )
            cn = int(evalid.sum())
            ek = et.kind
            if ek in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                      TypeKind.DATE32, TypeKind.DECIMAL):
                if ek == TypeKind.DECIMAL:
                    cvals = _varint_stream_decode(dec(cid, S_DATA), cn)
                    cvals = _rescale_decimals(
                        cvals,
                        int_decode(dec(cid, S_SECONDARY), cn, True, cenc),
                        et.scale)
                else:
                    cvals = int_decode(dec(cid, S_DATA), cn, True, cenc)
            elif ek in (TypeKind.FLOAT32, TypeKind.FLOAT64):
                cvals = np.frombuffer(dec(cid, S_DATA), et.np_dtype, cn)
            elif ek == TypeKind.TIMESTAMP:
                cvals = _decode_ts_micros(
                    int_decode(dec(cid, S_DATA), cn, True, cenc),
                    int_decode(dec(cid, S_SECONDARY), cn, False, cenc))
            else:
                raise NotImplementedError(f"ORC subset: list element {et!r}")
            flat = np.zeros(total, et.np_dtype)
            flat[evalid] = cvals.astype(et.np_dtype, copy=False)
            edata = np.zeros((rows, m), et.np_dtype)
            evalid2 = np.zeros((rows, m), bool)
            pos = 0
            for j, r in enumerate(np.flatnonzero(validity)):
                L = int(ln[j])
                k = min(L, m)
                edata[r, :k] = flat[pos : pos + k]
                evalid2[r, :k] = evalid[pos : pos + k]
                pos += L
            out[fld.name] = (None, validity, np.minimum(lengths, m),
                             (edata, evalid2))
            continue
        else:
            raise NotImplementedError(f"ORC subset: {fld.dtype!r}")
        out[fld.name] = (data, validity, lengths)
    return out
