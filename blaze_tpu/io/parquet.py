"""Self-contained Parquet subset writer/reader.

≙ the file-format half of the reference's ParquetExec/ParquetSinkExec
(parquet_exec.rs:65-418, parquet_sink_exec.rs) — implemented from the
public parquet-format spec (no pyarrow in the image):

- written files: PAR1 magic, one DATA_PAGE v1 per column chunk per row
  group, PLAIN encoding, RLE/bit-packed definition levels for OPTIONAL
  columns, UNCOMPRESSED or GZIP pages, thrift-compact FileMetaData with
  min/max statistics per chunk.
- reader: decodes that subset (plus dictionary-free files other writers
  produce with the same encodings) and prunes row groups with the
  pushed-down predicate over chunk statistics — the row-group
  granularity of the reference's page filtering
  (spark.blaze.parquet.enable.pageFiltering).

Physical mapping: BOOLEAN (bit-packed) <- bool; INT32 <- int8/16/32 +
DATE; INT64 <- int64/timestamp/decimal(<=18) [ConvertedType DECIMAL];
FLOAT/DOUBLE; BYTE_ARRAY(UTF8) <- string.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType, Field, Schema, TypeKind
from .thrift_compact import (
    CT_BINARY, CT_I32, CT_I64, CT_STRUCT, CompactReader, CompactWriter,
)

MAGIC = b"PAR1"

# parquet physical types
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, T_FLBA = range(8)
# converted types
CONV_UTF8, CONV_DECIMAL, CONV_DATE, CONV_TS_MICROS = 0, 5, 6, 10
# codecs
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2


def _physical(dtype: DataType) -> int:
    k = dtype.kind
    if k == TypeKind.BOOL:
        return T_BOOLEAN
    if k in (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.DATE32):
        return T_INT32
    if k in (TypeKind.INT64, TypeKind.TIMESTAMP, TypeKind.DECIMAL):
        return T_INT64
    if k == TypeKind.FLOAT32:
        return T_FLOAT
    if k == TypeKind.FLOAT64:
        return T_DOUBLE
    if dtype.is_string:
        return T_BYTE_ARRAY
    raise NotImplementedError(f"parquet type for {dtype!r}")


def _rle_encode_defs(validity: np.ndarray) -> bytes:
    """RLE runs of the 1-bit definition levels (bit width 1)."""
    out = bytearray()
    n = len(validity)
    i = 0
    while i < n:
        v = validity[i]
        j = i
        while j < n and validity[j] == v:
            j += 1
        run = j - i
        # RLE run: varint(count << 1), then the value in 1 byte (bit width 1)
        hdr = run << 1
        while True:
            byte = hdr & 0x7F
            hdr >>= 7
            if hdr:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
        out.append(1 if v else 0)
        i = j
    return bytes(out)


def _rle_decode_defs(data: bytes, num_values: int) -> Tuple[np.ndarray, int]:
    """Decode 1-bit RLE/bit-packed hybrid definition levels."""
    out = np.zeros(num_values, np.bool_)
    pos = 0
    filled = 0
    while filled < num_values:
        hdr = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            hdr |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if hdr & 1:
            # bit-packed: groups of 8 values, 1 bit each
            groups = hdr >> 1
            nvals = groups * 8
            for g in range(groups):
                byte = data[pos]
                pos += 1
                for bit in range(8):
                    if filled < num_values:
                        out[filled] = (byte >> bit) & 1
                        filled += 1
        else:
            run = hdr >> 1
            v = data[pos]
            pos += 1
            out[filled : filled + run] = bool(v)
            filled += run
    return out, pos


def _plain_encode(dtype: DataType, data: np.ndarray, validity: np.ndarray,
                  lengths: Optional[np.ndarray]) -> bytes:
    """PLAIN values for non-null rows only."""
    phys = _physical(dtype)
    nn = validity.astype(bool)
    if phys == T_BOOLEAN:
        vals = data[nn].astype(np.bool_)
        return np.packbits(vals, bitorder="little").tobytes()
    if phys == T_INT32:
        return data[nn].astype("<i4").tobytes()
    if phys == T_INT64:
        return data[nn].astype("<i8").tobytes()
    if phys == T_FLOAT:
        return data[nn].astype("<f4").tobytes()
    if phys == T_DOUBLE:
        return data[nn].astype("<f8").tobytes()
    # byte array: u32 length + bytes per value
    out = bytearray()
    idx = np.nonzero(nn)[0]
    for i in idx:
        ln = int(lengths[i])
        out += struct.pack("<I", ln)
        out += data[i, :ln].tobytes()
    return bytes(out)


def _plain_decode(dtype: DataType, raw: bytes, validity: np.ndarray, width: int):
    phys = _physical(dtype)
    n = len(validity)
    nn = int(validity.sum())
    if phys == T_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(raw, np.uint8), bitorder="little")[:nn].astype(np.bool_)
        out = np.zeros(n, np.bool_)
        out[validity] = bits
        return out, None
    np_map = {T_INT32: "<i4", T_INT64: "<i8", T_FLOAT: "<f4", T_DOUBLE: "<f8"}
    if phys in np_map:
        vals = np.frombuffer(raw, np_map[phys], count=nn)
        out = np.zeros(n, dtype=dtype.np_dtype)
        out[validity] = vals.astype(dtype.np_dtype)
        return out, None
    # byte array
    data = np.zeros((n, width), np.uint8)
    lengths = np.zeros(n, np.int32)
    pos = 0
    for i in np.nonzero(validity)[0]:
        (ln,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        lengths[i] = min(ln, width)
        data[i, : lengths[i]] = np.frombuffer(raw, np.uint8, count=lengths[i], offset=pos)
        pos += ln
    return data, lengths


def _stat_bytes(dtype: DataType, v) -> bytes:
    phys = _physical(dtype)
    if phys == T_INT32:
        return struct.pack("<i", int(v))
    if phys == T_INT64:
        return struct.pack("<q", int(v))
    if phys == T_FLOAT:
        return struct.pack("<f", float(v))
    if phys == T_DOUBLE:
        return struct.pack("<d", float(v))
    if phys == T_BOOLEAN:
        return struct.pack("<?", bool(v))
    return bytes(v)  # byte array: raw bytes


def _stat_value(dtype: DataType, b: bytes):
    phys = _physical(dtype)
    if phys == T_INT32:
        return struct.unpack("<i", b)[0]
    if phys == T_INT64:
        return struct.unpack("<q", b)[0]
    if phys == T_FLOAT:
        return struct.unpack("<f", b)[0]
    if phys == T_DOUBLE:
        return struct.unpack("<d", b)[0]
    if phys == T_BOOLEAN:
        return b[0] != 0
    return bytes(b)


# ------------------------------------------------------------------ writer

def write_parquet(
    path: str,
    schema: Schema,
    columns: Dict[str, Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]],
    row_group_rows: int = 1 << 20,
    codec: int = CODEC_GZIP,
):
    """columns: name -> (data, validity|None, lengths|None) host arrays."""
    n = next(iter(columns.values()))[0].shape[0]
    f = open(path, "wb")
    f.write(MAGIC)
    row_groups: List[dict] = []
    for rg_start in range(0, max(n, 1), row_group_rows):
        rg_end = min(rg_start + row_group_rows, n)
        rg_rows = rg_end - rg_start
        chunks = []
        total_bytes = 0
        for fld in schema.fields:
            data, validity, lengths = columns[fld.name]
            v = (
                validity[rg_start:rg_end].astype(bool)
                if validity is not None
                else np.ones(rg_rows, bool)
            )
            d = data[rg_start:rg_end]
            l = lengths[rg_start:rg_end] if lengths is not None else None
            defs = _rle_encode_defs(v)
            values = _plain_encode(fld.dtype, d, v, l)
            payload = struct.pack("<I", len(defs)) + defs + values
            comp = gzip.compress(payload, 1) if codec == CODEC_GZIP else payload
            # min/max over non-null rows
            stats = None
            if v.any():
                if fld.dtype.is_string:
                    vals = [d[i, : l[i]].tobytes() for i in np.nonzero(v)[0]]
                    stats = (min(vals), max(vals))
                else:
                    nn = d[v]
                    stats = (nn.min(), nn.max())
            ph = CompactWriter()
            ph.write_i(1, 0)                        # type = DATA_PAGE
            ph.write_i(2, len(payload))             # uncompressed size
            ph.write_i(3, len(comp))                # compressed size
            ph.begin_struct(5)                      # data_page_header
            ph.write_i(1, rg_rows)                  # num_values
            ph.write_i(2, 0)                        # encoding PLAIN
            ph.write_i(3, 3)                        # def levels RLE
            ph.write_i(4, 3)                        # rep levels RLE
            ph.end_struct()
            ph.buf.append(0)                        # end PageHeader struct
            header = ph.getvalue()
            offset = f.tell()
            f.write(header)
            f.write(comp)
            chunk_bytes = len(header) + len(comp)
            total_bytes += chunk_bytes
            chunks.append(
                dict(
                    field=fld, offset=offset, num_values=rg_rows,
                    total_comp=chunk_bytes, total_uncomp=len(header) + len(payload),
                    stats=stats, null_count=int((~v).sum()), codec=codec,
                )
            )
        row_groups.append(dict(chunks=chunks, rows=rg_rows, bytes=total_bytes))
        if n == 0:
            break

    # FileMetaData
    w = CompactWriter()
    w.write_i(1, 1)  # version
    # schema: root element + one per field
    w.begin_list(2, CT_STRUCT, len(schema.fields) + 1)
    w.list_elem_struct_begin()
    _w_string(w, 4, "schema")
    w.write_i(5, len(schema.fields))  # num_children
    w.list_elem_struct_end()
    for fld in schema.fields:
        w.list_elem_struct_begin()
        w.write_i(1, _physical(fld.dtype))
        w.write_i(3, 1)  # always OPTIONAL: def levels are always written
        _w_string(w, 4, fld.name)
        conv = None
        if fld.dtype.kind == TypeKind.STRING:
            conv = CONV_UTF8
        elif fld.dtype.is_decimal:
            conv = CONV_DECIMAL
        elif fld.dtype.kind == TypeKind.DATE32:
            conv = CONV_DATE
        elif fld.dtype.kind == TypeKind.TIMESTAMP:
            conv = CONV_TS_MICROS
        if conv is not None:
            w.write_i(6, conv)
        if fld.dtype.is_decimal:
            w.write_i(7, fld.dtype.scale)
            w.write_i(8, fld.dtype.precision)
        w.list_elem_struct_end()
    w.write_i64(3, n)  # num_rows
    w.begin_list(4, CT_STRUCT, len(row_groups))
    for rg in row_groups:
        w.list_elem_struct_begin()
        w.begin_list(1, CT_STRUCT, len(rg["chunks"]))
        for ch in rg["chunks"]:
            w.list_elem_struct_begin()
            w.write_i64(2, ch["offset"])  # file_offset
            w.begin_struct(3)             # ColumnMetaData
            w.write_i(1, _physical(ch["field"].dtype))
            w.begin_list(2, CT_I32, 2)
            w.list_elem_varint(0)  # PLAIN
            w.list_elem_varint(3)  # RLE
            w.begin_list(3, CT_BINARY, 1)
            w.list_elem_binary(ch["field"].name.encode())
            w.write_i(4, ch["codec"])
            w.write_i64(5, ch["num_values"])
            w.write_i64(6, ch["total_uncomp"])
            w.write_i64(7, ch["total_comp"])
            w.write_i64(9, ch["offset"])  # data_page_offset
            if ch["stats"] is not None:
                w.begin_struct(12)
                w.write_binary(3, struct.pack("<q", ch["null_count"]))
                # use modern min_value/max_value fields
                w.write_binary(5, _stat_bytes(ch["field"].dtype, ch["stats"][1]))
                w.write_binary(6, _stat_bytes(ch["field"].dtype, ch["stats"][0]))
                w.end_struct()
            w.end_struct()
            w.list_elem_struct_end()
        w.write_i64(2, rg["bytes"])
        w.write_i64(3, rg["rows"])
        w.list_elem_struct_end()
    _w_string(w, 6, "blaze-tpu parquet 0.1")
    w.buf.append(0)  # FileMetaData stop

    meta = w.getvalue()
    f.write(meta)
    f.write(struct.pack("<I", len(meta)))
    f.write(MAGIC)
    f.close()


def _w_string(w: CompactWriter, fid: int, s: str):
    w.write_binary(fid, s.encode("utf-8"))


# ------------------------------------------------------------------ reader

@dataclass
class ChunkMeta:
    name: str
    phys: int
    codec: int
    num_values: int
    offset: int
    total_comp: int
    min_value: Optional[bytes] = None
    max_value: Optional[bytes] = None
    null_count: Optional[int] = None


@dataclass
class RowGroupMeta:
    rows: int
    chunks: Dict[str, ChunkMeta]


@dataclass
class ParquetFileMeta:
    num_rows: int
    schema_elements: List[dict]
    row_groups: List[RowGroupMeta]


def read_metadata(path: str) -> ParquetFileMeta:
    with open(path, "rb") as f:
        f.seek(-8, os.SEEK_END)
        tail = f.read(8)
        assert tail[4:] == MAGIC, "not a parquet file"
        meta_len = struct.unpack("<I", tail[:4])[0]
        f.seek(-8 - meta_len, os.SEEK_END)
        meta = f.read(meta_len)
    r = CompactReader(meta)
    fm = r.read_struct()
    schema_elems = [dict(e) for e in fm.get(2, [])]
    rgs: List[RowGroupMeta] = []
    for rg in fm.get(4, []):
        chunks: Dict[str, ChunkMeta] = {}
        for ch in rg.get(1, []):
            md = ch.get(3, {})
            name = b"/".join(md.get(3, [b"?"])).decode()
            stats = md.get(12, {})
            chunks[name] = ChunkMeta(
                name=name,
                phys=md.get(1, 0),
                codec=md.get(4, 0),
                num_values=md.get(5, 0),
                offset=md.get(9, md.get(2, ch.get(2, 0))),
                total_comp=md.get(7, 0),
                min_value=bytes(stats[6]) if 6 in stats else None,
                max_value=bytes(stats[5]) if 5 in stats else None,
                null_count=struct.unpack("<q", bytes(stats[3]))[0]
                if 3 in stats and len(stats.get(3, b"")) == 8
                else None,
            )
        rgs.append(RowGroupMeta(rows=rg.get(3, 0), chunks=chunks))
    return ParquetFileMeta(num_rows=fm.get(3, 0), schema_elements=schema_elems, row_groups=rgs)


def read_column_chunk(path: str, chunk: ChunkMeta, dtype: DataType, nullable: bool = True):
    """Returns (data, validity, lengths|None) numpy arrays."""
    with open(path, "rb") as f:
        f.seek(chunk.offset)
        blob = f.read(chunk.total_comp if chunk.total_comp else None)
    r = CompactReader(blob)
    ph = r.read_struct()
    uncomp_size = ph.get(2, 0)
    comp_size = ph.get(3, 0)
    dph = ph.get(5, {})
    num_values = dph.get(1, chunk.num_values)
    payload = blob[r.pos : r.pos + comp_size]
    if chunk.codec == CODEC_GZIP:
        payload = gzip.decompress(payload)
    elif chunk.codec != CODEC_UNCOMPRESSED:
        raise NotImplementedError(f"codec {chunk.codec}")
    if nullable:
        (def_len,) = struct.unpack_from("<I", payload, 0)
        defs = payload[4 : 4 + def_len]
        validity, _ = _rle_decode_defs(defs, num_values)
        values = payload[4 + def_len :]
    else:
        validity = np.ones(num_values, bool)
        values = payload
    width = dtype.string_width if dtype.is_string else 0
    data, lengths = _plain_decode(dtype, values, validity, width)
    return data, validity, lengths
