"""Self-contained Parquet subset writer/reader.

≙ the file-format half of the reference's ParquetExec/ParquetSinkExec
(parquet_exec.rs:65-418, parquet_sink_exec.rs) — implemented from the
public parquet-format spec (no pyarrow in the image):

- written files: PAR1 magic, one DATA_PAGE v1 per column chunk per row
  group, PLAIN encoding, RLE/bit-packed definition levels for OPTIONAL
  columns, UNCOMPRESSED / GZIP / SNAPPY (Spark's default, pure-python
  LZ77) / ZSTD / LZ4_RAW pages, thrift-compact FileMetaData with
  min/max statistics per chunk.
- reader: decodes that subset (plus dictionary-free files other writers
  produce with the same encodings) and prunes row groups with the
  pushed-down predicate over chunk statistics — the row-group
  granularity of the reference's page filtering
  (spark.blaze.parquet.enable.pageFiltering).

Physical mapping: BOOLEAN (bit-packed) <- bool; INT32 <- int8/16/32 +
DATE; INT64 <- int64/timestamp/decimal(<=18) [ConvertedType DECIMAL];
FLOAT/DOUBLE; BYTE_ARRAY(UTF8) <- string.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType, Field, Schema, TypeKind
from .thrift_compact import (
    CT_BINARY, CT_I32, CT_I64, CT_STRUCT, CompactReader, CompactWriter,
)

MAGIC = b"PAR1"

# parquet physical types
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, T_FLBA = range(8)
# converted types
CONV_UTF8, CONV_DECIMAL, CONV_DATE, CONV_TS_MICROS = 0, 5, 6, 10
# codecs (parquet CompressionCodec enum)
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
CODEC_LZO, CODEC_BROTLI, CODEC_LZ4, CODEC_ZSTD, CODEC_LZ4_RAW = 3, 4, 5, 6, 7
# page types
PAGE_DATA, PAGE_INDEX, PAGE_DICT, PAGE_DATA_V2 = 0, 1, 2, 3
# encodings
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE, ENC_RLE_DICT = 0, 2, 3, 8


def _snappy_decompress(src: bytes) -> bytes:
    """Pure-python snappy raw-block decode (no external lib in image)."""
    # uvarint: uncompressed length
    pos = 0
    total = 0
    shift = 0
    while True:
        b = src[pos]
        pos += 1
        total |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    out = bytearray()
    n = len(src)
    while pos < n:
        tag = src[pos]
        pos += 1
        t = tag & 3
        if t == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:
                extra = ln - 60
                ln = int.from_bytes(src[pos : pos + extra], "little") + 1
                pos += extra
            out += src[pos : pos + ln]
            pos += ln
            continue
        if t == 1:
            ln = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8) | src[pos]
            pos += 1
        elif t == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(src[pos : pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(src[pos : pos + 4], "little")
            pos += 4
        start = len(out) - off
        if off >= ln:
            out += out[start : start + ln]
        else:  # overlapping copy
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != total:
        raise ValueError(f"snappy: decoded {len(out)} bytes, expected {total}")
    return bytes(out)


def _snappy_compress(src: bytes) -> bytes:
    """Pure-python snappy raw-block encode: greedy LZ77 over a 4-byte
    hash table, the inverse of _snappy_decompress (differential-tested
    against it and against the ORC C++ reader via pyarrow).  Callers
    pass bounded chunks (ORC framing: 64 KiB, parquet pages ~1 MiB), so
    2-byte literal lengths and 2-byte copy offsets always suffice; the
    4-byte copy form is still emitted for completeness when an offset
    exceeds 64 KiB."""
    n = len(src)
    out = bytearray()
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)

    def emit_literal(lo: int, hi: int) -> None:
        ln = hi - lo
        while ln > 0:
            take = min(ln, 1 << 16)
            if take <= 60:
                out.append((take - 1) << 2)
            elif take <= 0x100:
                out.append(60 << 2)
                out.append(take - 1)
            else:
                out.append(61 << 2)
                out.extend((take - 1).to_bytes(2, "little"))
            out.extend(src[lo : lo + take])
            lo += take
            ln -= take

    def emit_copy(off: int, ln: int) -> None:
        while ln > 0:
            take = min(ln, 64)
            if 4 <= take <= 11 and off < 2048:
                out.append(1 | ((take - 4) << 2) | ((off >> 8) << 5))
                out.append(off & 0xFF)
            elif off <= 0xFFFF:
                out.append(2 | ((take - 1) << 2))
                out.extend(off.to_bytes(2, "little"))
            else:
                out.append(3 | ((take - 1) << 2))
                out.extend(off.to_bytes(4, "little"))
            ln -= take

    table: dict = {}
    i = 0
    lit = 0
    limit = n - 3
    while i < limit:
        key = src[i : i + 4]
        j = table.get(key)
        table[key] = i
        if j is None:
            i += 1
            continue
        # extend the match (source-vs-source compare is exact: emitted
        # output always equals the src prefix, overlap included)
        L = 4
        max_l = n - i
        while L < max_l:
            step = min(512, max_l - L)
            if src[i + L : i + L + step] == src[j + L : j + L + step]:
                L += step
                continue
            while L < max_l and src[i + L] == src[j + L]:
                L += 1
            break
        emit_literal(lit, i)
        emit_copy(i - j, L)
        # index the match tail so immediately-following repeats hit
        if i + L < limit:
            table[src[i + L - 1 : i + L + 3]] = i + L - 1
        i += L
        lit = i
    emit_literal(lit, n)
    return bytes(out)


def _lz4_block_decompress(src: bytes) -> bytes:
    """LZ4 raw-block decode (canonical impl in io.ipc_compression)."""
    from .ipc_compression import lz4_block_decompress

    return lz4_block_decompress(src)


def _decompress(payload: bytes, codec: int, uncompressed_size: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return payload
    if codec == CODEC_GZIP:
        return gzip.decompress(payload)
    if codec == CODEC_SNAPPY:
        return _snappy_decompress(payload)
    if codec == CODEC_ZSTD:
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            payload, max_output_size=max(uncompressed_size, 1)
        )
    if codec == CODEC_LZ4_RAW:
        return _lz4_block_decompress(payload)
    if codec == CODEC_LZ4:
        # hadoop framing: [u32be total][u32be block_len][block]...
        out = bytearray()
        pos = 0
        while pos < len(payload):
            total = int.from_bytes(payload[pos : pos + 4], "big")
            pos += 4
            got = 0
            while got < total:
                blen = int.from_bytes(payload[pos : pos + 4], "big")
                pos += 4
                piece = _lz4_block_decompress(payload[pos : pos + blen])
                pos += blen
                got += len(piece)
                out += piece
        return bytes(out)
    raise NotImplementedError(f"parquet codec {codec}")


def _rle_bp_decode(data: bytes, bit_width: int, num_values: int) -> np.ndarray:
    """General RLE / bit-packed hybrid decode -> int32 values."""
    out = np.zeros(num_values, np.int32)
    if bit_width == 0:
        return out
    pos = 0
    filled = 0
    mask = (1 << bit_width) - 1
    byte_w = (bit_width + 7) // 8
    n = len(data)
    while filled < num_values and pos < n:
        hdr = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            hdr |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if hdr & 1:  # bit-packed groups of 8
            groups = hdr >> 1
            nbytes = groups * bit_width
            chunk = data[pos : pos + nbytes]
            pos += nbytes
            bits = np.unpackbits(
                np.frombuffer(chunk, np.uint8), bitorder="little"
            ).reshape(-1, bit_width)
            vals = (bits.astype(np.int64) << np.arange(bit_width)).sum(axis=1)
            take = min(len(vals), num_values - filled)
            out[filled : filled + take] = vals[:take]
            filled += take
        else:
            run = hdr >> 1
            v = int.from_bytes(data[pos : pos + byte_w], "little") & mask
            pos += byte_w
            take = min(run, num_values - filled)
            out[filled : filled + take] = v
            filled += take
    return out


def _physical(dtype: DataType) -> int:
    k = dtype.kind
    if k == TypeKind.BOOL:
        return T_BOOLEAN
    if k in (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.DATE32):
        return T_INT32
    if k in (TypeKind.INT64, TypeKind.TIMESTAMP, TypeKind.DECIMAL):
        return T_INT64
    if k == TypeKind.FLOAT32:
        return T_FLOAT
    if k == TypeKind.FLOAT64:
        return T_DOUBLE
    if dtype.is_string:
        return T_BYTE_ARRAY
    raise NotImplementedError(f"parquet type for {dtype!r}")


def _rle_encode_defs(validity: np.ndarray) -> bytes:
    """RLE runs of the 1-bit definition levels (bit width 1)."""
    out = bytearray()
    n = len(validity)
    i = 0
    while i < n:
        v = validity[i]
        j = i
        while j < n and validity[j] == v:
            j += 1
        run = j - i
        # RLE run: varint(count << 1), then the value in 1 byte (bit width 1)
        hdr = run << 1
        while True:
            byte = hdr & 0x7F
            hdr >>= 7
            if hdr:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
        out.append(1 if v else 0)
        i = j
    return bytes(out)


def _rle_decode_defs(data: bytes, num_values: int) -> Tuple[np.ndarray, int]:
    """Decode 1-bit RLE/bit-packed hybrid definition levels."""
    out = np.zeros(num_values, np.bool_)
    pos = 0
    filled = 0
    while filled < num_values:
        hdr = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            hdr |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if hdr & 1:
            # bit-packed: groups of 8 values, 1 bit each
            groups = hdr >> 1
            nvals = groups * 8
            for g in range(groups):
                byte = data[pos]
                pos += 1
                for bit in range(8):
                    if filled < num_values:
                        out[filled] = (byte >> bit) & 1
                        filled += 1
        else:
            run = hdr >> 1
            v = data[pos]
            pos += 1
            out[filled : filled + run] = bool(v)
            filled += run
    return out, pos


def _plain_encode(dtype: DataType, data: np.ndarray, validity: np.ndarray,
                  lengths: Optional[np.ndarray]) -> bytes:
    """PLAIN values for non-null rows only."""
    phys = _physical(dtype)
    nn = validity.astype(bool)
    if phys == T_BOOLEAN:
        vals = data[nn].astype(np.bool_)
        return np.packbits(vals, bitorder="little").tobytes()
    if phys == T_INT32:
        return data[nn].astype("<i4").tobytes()
    if phys == T_INT64:
        return data[nn].astype("<i8").tobytes()
    if phys == T_FLOAT:
        return data[nn].astype("<f4").tobytes()
    if phys == T_DOUBLE:
        return data[nn].astype("<f8").tobytes()
    # byte array: u32 length + bytes per value
    out = bytearray()
    idx = np.nonzero(nn)[0]
    for i in idx:
        ln = int(lengths[i])
        out += struct.pack("<I", ln)
        out += data[i, :ln].tobytes()
    return bytes(out)


def _flba_to_int64(raw: bytes, count: int, type_length: int) -> np.ndarray:
    """FIXED_LEN_BYTE_ARRAY big-endian two's-complement -> int64 (the
    Spark/pyarrow decimal physical encoding)."""
    out = np.zeros(count, np.int64)
    for i in range(count):
        b = raw[i * type_length : (i + 1) * type_length]
        out[i] = int.from_bytes(b, "big", signed=True)
    return out


def _plain_decode_phys(phys: int, raw: bytes, validity: np.ndarray, width: int,
                       type_length: int = 0):
    """PLAIN decode by the FILE's physical type; caller converts to the
    requested logical dtype (schema adaption)."""
    n = len(validity)
    nn = int(validity.sum())
    if phys == T_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(raw, np.uint8), bitorder="little")[:nn].astype(np.bool_)
        out = np.zeros(n, np.bool_)
        out[validity] = bits
        return out, None
    np_map = {T_INT32: "<i4", T_INT64: "<i8", T_FLOAT: "<f4", T_DOUBLE: "<f8"}
    if phys in np_map:
        vals = np.frombuffer(raw, np_map[phys], count=nn)
        out = np.zeros(n, vals.dtype)
        out[validity] = vals
        return out, None
    if phys == T_FLBA:
        vals = _flba_to_int64(raw, nn, type_length)
        out = np.zeros(n, np.int64)
        out[validity] = vals
        return out, None
    if phys == T_INT96:
        # legacy Spark timestamps: 8B nanos-of-day LE + 4B julian day
        out = np.zeros(n, np.int64)
        idx = np.nonzero(validity)[0]
        for j, i in enumerate(idx):
            nanos = int.from_bytes(raw[j * 12 : j * 12 + 8], "little")
            julian = int.from_bytes(raw[j * 12 + 8 : j * 12 + 12], "little")
            out[i] = (julian - 2440588) * 86_400_000_000 + nanos // 1000
        return out, None
    # byte array
    data = np.zeros((n, width), np.uint8)
    lengths = np.zeros(n, np.int32)
    pos = 0
    for i in np.nonzero(validity)[0]:
        (ln,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        lengths[i] = min(ln, width)
        data[i, : lengths[i]] = np.frombuffer(raw, np.uint8, count=lengths[i], offset=pos)
        pos += ln
    return data, lengths


def _stat_bytes(dtype: DataType, v) -> bytes:
    phys = _physical(dtype)
    if phys == T_INT32:
        return struct.pack("<i", int(v))
    if phys == T_INT64:
        return struct.pack("<q", int(v))
    if phys == T_FLOAT:
        return struct.pack("<f", float(v))
    if phys == T_DOUBLE:
        return struct.pack("<d", float(v))
    if phys == T_BOOLEAN:
        return struct.pack("<?", bool(v))
    return bytes(v)  # byte array: raw bytes


def _stat_value(dtype: DataType, b: bytes):
    phys = _physical(dtype)
    if phys == T_INT32:
        return struct.unpack("<i", b)[0]
    if phys == T_INT64:
        return struct.unpack("<q", b)[0]
    if phys == T_FLOAT:
        return struct.unpack("<f", b)[0]
    if phys == T_DOUBLE:
        return struct.unpack("<d", b)[0]
    if phys == T_BOOLEAN:
        return b[0] != 0
    return bytes(b)


# ------------------------------------------------------------------ writer

def write_parquet(
    path: str,
    schema: Schema,
    columns: Dict[str, Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]],
    row_group_rows: int = 1 << 20,
    codec: int = CODEC_GZIP,
):
    """columns: name -> (data, validity|None, lengths|None) host arrays."""
    from .fs import get_fs

    n = next(iter(columns.values()))[0].shape[0]
    f = get_fs(path).create(path)
    f.write(MAGIC)
    row_groups: List[dict] = []
    for rg_start in range(0, max(n, 1), row_group_rows):
        rg_end = min(rg_start + row_group_rows, n)
        rg_rows = rg_end - rg_start
        chunks = []
        total_bytes = 0
        for fld in schema.fields:
            data, validity, lengths = columns[fld.name]
            v = (
                validity[rg_start:rg_end].astype(bool)
                if validity is not None
                else np.ones(rg_rows, bool)
            )
            d = data[rg_start:rg_end]
            l = lengths[rg_start:rg_end] if lengths is not None else None
            defs = _rle_encode_defs(v)
            values = _plain_encode(fld.dtype, d, v, l)
            payload = struct.pack("<I", len(defs)) + defs + values
            if codec == CODEC_GZIP:
                comp = gzip.compress(payload, 1)
            elif codec == CODEC_SNAPPY:  # Spark's parquet default codec
                comp = _snappy_compress(payload)
            elif codec == CODEC_ZSTD:
                import zstandard

                comp = zstandard.ZstdCompressor().compress(payload)
            elif codec == CODEC_LZ4_RAW:
                from .ipc_compression import lz4_block_compress

                comp = lz4_block_compress(payload)
            elif codec == CODEC_UNCOMPRESSED:
                comp = payload
            else:
                raise NotImplementedError(f"parquet writer codec {codec}")
            # min/max over non-null rows
            stats = None
            if v.any():
                if fld.dtype.is_string:
                    vals = [d[i, : l[i]].tobytes() for i in np.nonzero(v)[0]]
                    stats = (min(vals), max(vals))
                else:
                    nn = d[v]
                    stats = (nn.min(), nn.max())
            ph = CompactWriter()
            ph.write_i(1, 0)                        # type = DATA_PAGE
            ph.write_i(2, len(payload))             # uncompressed size
            ph.write_i(3, len(comp))                # compressed size
            ph.begin_struct(5)                      # data_page_header
            ph.write_i(1, rg_rows)                  # num_values
            ph.write_i(2, 0)                        # encoding PLAIN
            ph.write_i(3, 3)                        # def levels RLE
            ph.write_i(4, 3)                        # rep levels RLE
            ph.end_struct()
            ph.buf.append(0)                        # end PageHeader struct
            header = ph.getvalue()
            offset = f.tell()
            f.write(header)
            f.write(comp)
            chunk_bytes = len(header) + len(comp)
            total_bytes += chunk_bytes
            chunks.append(
                dict(
                    field=fld, offset=offset, num_values=rg_rows,
                    total_comp=chunk_bytes, total_uncomp=len(header) + len(payload),
                    stats=stats, null_count=int((~v).sum()), codec=codec,
                )
            )
        row_groups.append(dict(chunks=chunks, rows=rg_rows, bytes=total_bytes))
        if n == 0:
            break

    # FileMetaData
    w = CompactWriter()
    w.write_i(1, 1)  # version
    # schema: root element + one per field
    w.begin_list(2, CT_STRUCT, len(schema.fields) + 1)
    w.list_elem_struct_begin()
    _w_string(w, 4, "schema")
    w.write_i(5, len(schema.fields))  # num_children
    w.list_elem_struct_end()
    for fld in schema.fields:
        w.list_elem_struct_begin()
        w.write_i(1, _physical(fld.dtype))
        w.write_i(3, 1)  # always OPTIONAL: def levels are always written
        _w_string(w, 4, fld.name)
        conv = None
        if fld.dtype.kind == TypeKind.STRING:
            conv = CONV_UTF8
        elif fld.dtype.is_decimal:
            conv = CONV_DECIMAL
        elif fld.dtype.kind == TypeKind.DATE32:
            conv = CONV_DATE
        elif fld.dtype.kind == TypeKind.TIMESTAMP:
            conv = CONV_TS_MICROS
        if conv is not None:
            w.write_i(6, conv)
        if fld.dtype.is_decimal:
            w.write_i(7, fld.dtype.scale)
            w.write_i(8, fld.dtype.precision)
        w.list_elem_struct_end()
    w.write_i64(3, n)  # num_rows
    w.begin_list(4, CT_STRUCT, len(row_groups))
    for rg in row_groups:
        w.list_elem_struct_begin()
        w.begin_list(1, CT_STRUCT, len(rg["chunks"]))
        for ch in rg["chunks"]:
            w.list_elem_struct_begin()
            w.write_i64(2, ch["offset"])  # file_offset
            w.begin_struct(3)             # ColumnMetaData
            w.write_i(1, _physical(ch["field"].dtype))
            w.begin_list(2, CT_I32, 2)
            w.list_elem_varint(0)  # PLAIN
            w.list_elem_varint(3)  # RLE
            w.begin_list(3, CT_BINARY, 1)
            w.list_elem_binary(ch["field"].name.encode())
            w.write_i(4, ch["codec"])
            w.write_i64(5, ch["num_values"])
            w.write_i64(6, ch["total_uncomp"])
            w.write_i64(7, ch["total_comp"])
            w.write_i64(9, ch["offset"])  # data_page_offset
            if ch["stats"] is not None:
                w.begin_struct(12)
                w.write_i(3, ch["null_count"], CT_I64)  # null_count: i64 per spec
                # use modern min_value/max_value fields
                w.write_binary(5, _stat_bytes(ch["field"].dtype, ch["stats"][1]))
                w.write_binary(6, _stat_bytes(ch["field"].dtype, ch["stats"][0]))
                w.end_struct()
            w.end_struct()
            w.list_elem_struct_end()
        w.write_i64(2, rg["bytes"])
        w.write_i64(3, rg["rows"])
        w.list_elem_struct_end()
    _w_string(w, 6, "blaze-tpu parquet 0.1")
    w.buf.append(0)  # FileMetaData stop

    meta = w.getvalue()
    f.write(meta)
    f.write(struct.pack("<I", len(meta)))
    f.write(MAGIC)
    f.close()


def _w_string(w: CompactWriter, fid: int, s: str):
    w.write_binary(fid, s.encode("utf-8"))


# ------------------------------------------------------------------ reader

@dataclass
class ChunkMeta:
    name: str
    phys: int
    codec: int
    num_values: int
    offset: int                      # first page (dict page if present)
    total_comp: int
    min_value: Optional[bytes] = None
    max_value: Optional[bytes] = None
    null_count: Optional[int] = None
    max_def: int = 1                 # 0 = REQUIRED column (no def levels)
    type_length: int = 0             # FLBA byte width


@dataclass
class RowGroupMeta:
    rows: int
    chunks: Dict[str, ChunkMeta]


@dataclass
class ParquetFileMeta:
    num_rows: int
    schema_elements: List[dict]
    row_groups: List[RowGroupMeta]


def read_metadata(path: str) -> ParquetFileMeta:
    from .fs import get_fs

    with get_fs(path).open(path) as f:
        f.seek(-8, os.SEEK_END)
        tail = f.read(8)
        assert tail[4:] == MAGIC, "not a parquet file"
        meta_len = struct.unpack("<I", tail[:4])[0]
        f.seek(-8 - meta_len, os.SEEK_END)
        meta = f.read(meta_len)
    r = CompactReader(meta)
    fm = r.read_struct()
    schema_elems = [dict(e) for e in fm.get(2, [])]
    # leaf nullability + FLBA width by name
    repetition: Dict[str, int] = {}
    type_lengths: Dict[str, int] = {}
    for e in schema_elems:
        if e.get(5):  # has children -> group node (root)
            continue
        nm = e.get(4, b"?")
        nm = nm.decode() if isinstance(nm, (bytes, bytearray)) else str(nm)
        repetition[nm] = e.get(3, 1)
        type_lengths[nm] = e.get(2, 0)
    rgs: List[RowGroupMeta] = []
    for rg in fm.get(4, []):
        chunks: Dict[str, ChunkMeta] = {}
        for ch in rg.get(1, []):
            md = ch.get(3, {})
            name = b"/".join(md.get(3, [b"?"])).decode()
            stats = md.get(12, {})
            data_off = md.get(9, md.get(2, ch.get(2, 0)))
            dict_off = md.get(11)  # dictionary_page_offset
            first = min(data_off, dict_off) if dict_off else data_off
            nc = stats.get(3)  # null_count: i64 (spec); old subset files: 8B binary
            if isinstance(nc, (bytes, bytearray)) and len(nc) == 8:
                nc = struct.unpack("<q", bytes(nc))[0]
            elif not isinstance(nc, int):
                nc = None
            # min/max: prefer modern min_value/max_value (5/6), fall
            # back to deprecated max/min (1/2)
            mx = stats.get(5, stats.get(1))
            mn = stats.get(6, stats.get(2))
            chunks[name] = ChunkMeta(
                name=name,
                phys=md.get(1, 0),
                codec=md.get(4, 0),
                num_values=md.get(5, 0),
                offset=first,
                total_comp=md.get(7, 0),
                min_value=bytes(mn) if mn is not None else None,
                max_value=bytes(mx) if mx is not None else None,
                null_count=nc,
                max_def=0 if repetition.get(name) == 0 else 1,
                type_length=type_lengths.get(name, 0),
            )
        rgs.append(RowGroupMeta(rows=rg.get(3, 0), chunks=chunks))
    return ParquetFileMeta(num_rows=fm.get(3, 0), schema_elements=schema_elems, row_groups=rgs)


def _plain_decode_dict_values(phys: int, raw: bytes, count: int, width: int,
                              type_length: int = 0):
    """Decode a PLAIN dictionary page into a lookup table."""
    if phys == T_FLBA:
        return _flba_to_int64(raw, count, type_length)
    if phys == T_INT32:
        return np.frombuffer(raw, "<i4", count=count)
    if phys == T_INT64:
        return np.frombuffer(raw, "<i8", count=count)
    if phys == T_FLOAT:
        return np.frombuffer(raw, "<f4", count=count)
    if phys == T_DOUBLE:
        return np.frombuffer(raw, "<f8", count=count)
    if phys == T_BOOLEAN:
        return np.unpackbits(np.frombuffer(raw, np.uint8), bitorder="little")[:count].astype(bool)
    # byte arrays: (data (count, width), lengths)
    data = np.zeros((count, width), np.uint8)
    lengths = np.zeros(count, np.int32)
    pos = 0
    for i in range(count):
        (ln,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        lengths[i] = min(ln, width)
        data[i, : lengths[i]] = np.frombuffer(raw, np.uint8, count=lengths[i], offset=pos)
        pos += ln
    return data, lengths


def read_column_chunk(path: str, chunk: ChunkMeta, dtype: DataType):
    """Decode a full column chunk: every page (v1/v2), PLAIN or
    dictionary encodings, all supported codecs.  Returns
    (data, validity, lengths|None) numpy arrays of chunk.num_values
    rows.  ≙ the arrow-rs page machinery behind parquet_exec.rs:65-418."""
    from .fs import get_fs

    with get_fs(path).open(path) as f:
        f.seek(chunk.offset)
        blob = f.read(chunk.total_comp if chunk.total_comp else None)

    n_total = chunk.num_values
    width = dtype.string_width if dtype.is_string else 0
    validity = np.zeros(n_total, np.bool_)
    if dtype.is_string:
        data = np.zeros((n_total, width), np.uint8)
        lengths = np.zeros(n_total, np.int32)
    else:
        data = np.zeros(n_total, dtype.np_dtype)
        lengths = None
    dict_table = None  # (values[, lengths]) from the dictionary page

    def emit_values(encoding: int, values: bytes, page_valid: np.ndarray, row0: int):
        nv = page_valid.shape[0]
        nn = int(page_valid.sum())
        sl = slice(row0, row0 + nv)
        validity[sl] = page_valid
        if nn == 0:
            return
        if encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            bit_width = values[0]
            idx = _rle_bp_decode(values[1:], bit_width, nn)
            if dtype.is_string:
                dvals, dlens = dict_table
                rows = row0 + np.nonzero(page_valid)[0]
                data[rows] = dvals[idx]
                lengths[rows] = dlens[idx]
            else:
                out = np.zeros(nv, dtype.np_dtype)
                out[page_valid] = dict_table[idx].astype(dtype.np_dtype, copy=False)
                data[sl] = out
        elif encoding == ENC_RLE and chunk.phys == T_BOOLEAN:
            # v2 booleans: u32 length + RLE/bit-packed hybrid, width 1
            (rl,) = struct.unpack_from("<I", values, 0)
            bits = _rle_bp_decode(values[4 : 4 + rl], 1, nn).astype(bool)
            out = np.zeros(nv, np.bool_)
            out[page_valid] = bits
            data[sl] = out
        elif encoding != ENC_PLAIN:
            # gated, not silently wrong: DELTA_* / BYTE_STREAM_SPLIT
            raise NotImplementedError(f"parquet page encoding {encoding}")
        else:  # PLAIN — decode by the file's physical type, then adapt
            d, l = _plain_decode_phys(chunk.phys, values, page_valid, width,
                                      chunk.type_length)
            if dtype.is_string:
                data[sl, : d.shape[1]] = d[:, :width]
                lengths[sl] = l
            else:
                data[sl] = d.astype(dtype.np_dtype, copy=False)

    pos = 0
    decoded = 0
    blob_len = len(blob)
    view = memoryview(blob)
    while decoded < n_total and pos < blob_len:
        r = CompactReader(view[pos:])
        ph = r.read_struct()
        header_len = r.pos
        ptype = ph.get(1, PAGE_DATA)
        uncomp_size = ph.get(2, 0)
        comp_size = ph.get(3, uncomp_size)
        page_raw = blob[pos + header_len : pos + header_len + comp_size]
        pos += header_len + comp_size
        if ptype == PAGE_DICT:
            dh = ph.get(7, {})
            count = dh.get(1, 0)
            payload = _decompress(page_raw, chunk.codec, uncomp_size)
            dict_table = _plain_decode_dict_values(
                chunk.phys, payload, count, width or 64, chunk.type_length
            )
            continue
        if ptype == PAGE_DATA:
            dph = ph.get(5, {})
            nv = dph.get(1, 0)
            encoding = dph.get(2, ENC_PLAIN)
            payload = _decompress(page_raw, chunk.codec, uncomp_size)
            if chunk.max_def > 0:
                (def_len,) = struct.unpack_from("<I", payload, 0)
                page_valid, _ = _rle_decode_defs(payload[4 : 4 + def_len], nv)
                values = payload[4 + def_len :]
            else:
                page_valid = np.ones(nv, np.bool_)
                values = payload
            emit_values(encoding, values, page_valid, decoded)
            decoded += nv
            continue
        if ptype == PAGE_DATA_V2:
            dph = ph.get(8, {})
            nv = dph.get(1, 0)
            num_nulls = dph.get(2, 0)
            encoding = dph.get(4, ENC_PLAIN)
            def_len = dph.get(5, 0)
            rep_len = dph.get(6, 0)
            is_compressed = dph.get(7, True)
            levels = page_raw[: rep_len + def_len]  # NEVER compressed
            rest = page_raw[rep_len + def_len :]
            if is_compressed:
                rest = _decompress(rest, chunk.codec, max(uncomp_size - rep_len - def_len, 1))
            if chunk.max_def > 0 and def_len:
                # v2 def levels: RLE hybrid WITHOUT the u32 length prefix
                page_valid = _rle_bp_decode(levels[rep_len:], 1, nv).astype(bool)
            else:
                page_valid = np.ones(nv, np.bool_)
            emit_values(encoding, rest, page_valid, decoded)
            decoded += nv
            continue
        # index or unknown page: skip
    return data, validity, lengths
