"""Minimal Thrift Compact Protocol reader/writer.

Parquet metadata (FileMetaData, PageHeader, ...) is thrift-compact
encoded; this is the self-contained codec for blaze_tpu.io.parquet
(the image carries no pyarrow/thrift).  Implements the subset the
parquet structures use: structs, i16/i32/i64 (zigzag varints), binary,
bool, double, lists.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

# compact type ids
CT_STOP = 0x00
CT_BOOL_TRUE = 0x01
CT_BOOL_FALSE = 0x02
CT_BYTE = 0x03
CT_I16 = 0x04
CT_I32 = 0x05
CT_I64 = 0x06
CT_DOUBLE = 0x07
CT_BINARY = 0x08
CT_LIST = 0x09
CT_STRUCT = 0x0C


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class CompactWriter:
    def __init__(self):
        self.buf = bytearray()
        self._last_fid = [0]

    def _varint(self, n: int):
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                self.buf.append(b | 0x80)
            else:
                self.buf.append(b)
                return

    def field_header(self, fid: int, ctype: int):
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ctype)
        else:
            self.buf.append(ctype)
            self._varint(_zigzag(fid) & 0xFFFFFFFF)
        self._last_fid[-1] = fid

    def write_i(self, fid: int, v: int, ctype: int = CT_I32):
        self.field_header(fid, ctype)
        self._varint(_zigzag(v))

    def write_i64(self, fid: int, v: int):
        self.write_i(fid, v, CT_I64)

    def write_binary(self, fid: int, v: bytes):
        self.field_header(fid, CT_BINARY)
        self._varint(len(v))
        self.buf.extend(v)

    def write_string(self, fid: int, v: str):
        self.write_binary(fid, v.encode("utf-8"))

    def write_bool(self, fid: int, v: bool):
        self.field_header(fid, CT_BOOL_TRUE if v else CT_BOOL_FALSE)

    def begin_struct(self, fid: int):
        self.field_header(fid, CT_STRUCT)
        self._last_fid.append(0)

    def end_struct(self):
        self.buf.append(CT_STOP)
        self._last_fid.pop()

    def begin_list(self, fid: int, elem_ctype: int, size: int):
        self.field_header(fid, CT_LIST)
        if size < 15:
            self.buf.append((size << 4) | elem_ctype)
        else:
            self.buf.append(0xF0 | elem_ctype)
            self._varint(size)
        # list elements are written raw by the caller

    def list_elem_varint(self, v: int):
        self._varint(_zigzag(v))

    def list_elem_binary(self, v: bytes):
        self._varint(len(v))
        self.buf.extend(v)

    def list_elem_struct_begin(self):
        self._last_fid.append(0)

    def list_elem_struct_end(self):
        self.buf.append(CT_STOP)
        self._last_fid.pop()

    def getvalue(self) -> bytes:
        return bytes(self.buf)


class CompactReader:
    """Parses a struct into {fid: value}; nested structs become dicts,
    lists become python lists.  Untyped-schema generic decode — the
    caller interprets fids."""

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not (b & 0x80):
                return out
            shift += 7

    def _zig(self) -> int:
        return _unzigzag(self._varint())

    def read_struct(self) -> Dict[int, Any]:
        out: Dict[int, Any] = {}
        last_fid = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            if b == CT_STOP:
                return out
            ctype = b & 0x0F
            delta = b >> 4
            fid = last_fid + delta if delta else _unzigzag(self._varint())
            last_fid = fid
            out[fid] = self._read_value(ctype)

    def _read_value(self, ctype: int):
        if ctype == CT_BOOL_TRUE:
            return True
        if ctype == CT_BOOL_FALSE:
            return False
        if ctype == CT_BYTE:
            v = self.data[self.pos]
            self.pos += 1
            return v
        if ctype in (CT_I16, CT_I32, CT_I64):
            return self._zig()
        if ctype == CT_DOUBLE:
            v = struct.unpack_from("<d", self.data, self.pos)[0]
            self.pos += 8
            return v
        if ctype == CT_BINARY:
            n = self._varint()
            v = self.data[self.pos : self.pos + n]
            self.pos += n
            return v
        if ctype == CT_LIST:
            hdr = self.data[self.pos]
            self.pos += 1
            size = hdr >> 4
            elem = hdr & 0x0F
            if size == 15:
                size = self._varint()
            return [self._read_value(elem if elem != CT_BOOL_TRUE else CT_BOOL_TRUE) for _ in range(size)]
        if ctype == CT_STRUCT:
            return self.read_struct()
        raise ValueError(f"unsupported compact type {ctype}")
