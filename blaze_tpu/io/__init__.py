"""IO: columnar batch wire format + framed compression.

≙ reference ``datafusion-ext-commons``: io/batch_serde.rs (the shuffle/
spill wire format) and common/ipc_compression.rs (framed compressed
blocks)."""

from .batch_serde import deserialize_batch, serialize_batch
from .ipc_compression import IpcFrameReader, IpcFrameWriter, compress_frame, decompress_frame

__all__ = [
    "serialize_batch", "deserialize_batch",
    "IpcFrameWriter", "IpcFrameReader", "compress_frame", "decompress_frame",
]
