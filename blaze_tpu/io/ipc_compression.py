"""Framed compressed IPC blocks: ``[u32 len][u8 codec][payload]``.

≙ reference common/ipc_compression.rs:30-335: the reference frames
``[u32 block_len][codec stream]`` where the stream is a ZSTD frame
(level 1) or an LZ4 FRAME, per ``spark.io.compression.codec``.  Here
the same codecs are spoken — zstd via the zstandard package (standard
zstd frames, byte-interoperable), lz4 via a self-contained LZ4 Frame
codec (reader handles compressed + linked blocks; writer emits
store-mode blocks, spec-valid and readable by any lz4 tool) — plus
zlib and raw for internal spill frames.  One codec byte after the
length keeps frames self-describing (the reference relies on both
sides reading the same conf instead).

Integrity (runtime/integrity.py, conf ``spark.blaze.io.checksum``):
a frame written with ``checksum=<algo id>`` sets the codec byte's high
bit and appends a 5-byte trailer ``[u8 algo][u32 sum]`` over the
STORED bytes —

    plain:       [u32 len][u8 codec][stored]
    checksummed: [u32 len][u8 codec|0x80][stored][u8 algo][u32 sum]

``len`` stays the stored-byte length either way, so offset arithmetic
is uniform (:func:`frame_span`).  Frame STREAMS written as one unit
(worker result files, broadcast blobs) may end with a BLOCK TRAILER
frame (codec ``0x7E``) carrying the frame count and the XOR of the
frame checksums, so truncation of whole frames is detectable too.
Every reader here verifies flagged frames and raises typed
``BlockCorruptionError`` on mismatch; unstamped streams read exactly
as before.
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Dict, Iterator, Optional, Tuple

from .. import conf
from ..runtime.integrity import (
    CHECKSUM_FLAG, TRAILER_LEN, BlockCorruptionError,
    frame_algo, frame_trailer, verify_bytes,
)

TARGET_BLOCK = 4 << 20

CODEC_RAW = 0
CODEC_ZLIB = 1
CODEC_ZSTD = 2
CODEC_LZ4 = 3

#: codec byte of a BLOCK-TRAILER frame: its 9-byte payload is
#: [u32 frame_count][u8 algo][u32 xor-of-frame-checksums]
CODEC_BLOCK_TRAILER = 0x7E

ZSTD_LEVEL = 1  # ≙ reference ZSTD_LEVEL

_LZ4_MAGIC = 0x184D2204


def lz4_block_compress(src: bytes) -> bytes:
    """Greedy hash-match LZ4 block compressor (spec-compliant output:
    any LZ4 decoder reads it)."""
    n = len(src)
    out = bytearray()

    def emit(lit: bytes, off: int = 0, mlen: int = 0):
        ll = len(lit)
        ml = mlen - 4 if mlen else 0
        out.append((min(ll, 15) << 4) | (min(ml, 15) if mlen else 0))
        if ll >= 15:
            rest = ll - 15
            while rest >= 255:
                out.append(255)
                rest -= 255
            out.append(rest)
        out.extend(lit)
        if mlen:
            out.append(off & 0xFF)
            out.append(off >> 8)
            if ml >= 15:
                rest = ml - 15
                while rest >= 255:
                    out.append(255)
                    rest -= 255
                out.append(rest)

    if n < 13:  # too short for any match (spec end constraints)
        emit(src)
        return bytes(out)
    table: Dict[bytes, int] = {}
    anchor = 0
    i = 0
    limit = n - 12  # last match must start >= 12 bytes before end
    while i <= limit:
        key = src[i : i + 4]
        j = table.get(key, -1)
        table[key] = i
        if j >= 0 and i - j <= 0xFFFF and src[j : j + 4] == key:
            mlen = 4
            end = n - 5  # last 5 bytes must be literals
            while i + mlen < end and src[j + mlen] == src[i + mlen]:
                mlen += 1
            emit(src[anchor:i], i - j, mlen)
            i += mlen
            anchor = i
        else:
            i += 1
    emit(src[anchor:])
    return bytes(out)


def lz4_frame_compress(payload: bytes, checksums: bool = False) -> bytes:
    """LZ4 Frame writer: independent blocks, greedy-compressed (stored
    verbatim when compression does not help).  With ``checksums`` the
    frame carries the spec's xxh32 BLOCK checksums (one per block, over
    the stored block bytes) and the CONTENT checksum after the EndMark
    — what the reference's lz4_flex encoder emits.  Readable by any
    LZ4 frame reader (lz4_flex, pyarrow, lz4 CLI)."""
    out = bytearray()
    out += struct.pack("<I", _LZ4_MAGIC)
    # FLG: version=01, block independence=1; +block checksum (bit 4)
    # and content checksum (bit 2) when requested
    flg = 0b0110_0000 | (0b0001_0100 if checksums else 0)
    out.append(flg)
    # BD: block max size 4MB (code 7)
    out.append(7 << 4)
    # HC byte: (xxh32(FLG..BD) >> 8) & 0xFF
    out.append((_xxh32(bytes(out[4:6])) >> 8) & 0xFF)
    block_max = 4 << 20
    for off in range(0, max(len(payload), 1), block_max):
        chunk = payload[off : off + block_max]
        if not chunk:
            break
        comp = lz4_block_compress(chunk)
        if len(comp) < len(chunk):
            out += struct.pack("<I", len(comp))
            block = comp
        else:
            out += struct.pack("<I", len(chunk) | 0x80000000)  # stored
            block = chunk
        out += block
        if checksums:
            out += struct.pack("<I", _xxh32(block))
    out += struct.pack("<I", 0)  # EndMark
    if checksums:
        out += struct.pack("<I", _xxh32(payload))
    return bytes(out)


def _xxh32(data: bytes, seed: int = 0) -> int:
    """xxHash32 (LZ4 frame header checksum)."""
    P1, P2, P3, P4, P5 = 2654435761, 2246822519, 3266489917, 668265263, 374761393
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    pos = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed
        v4 = (seed - P1) & M
        while pos + 16 <= n:
            k1, k2, k3, k4 = struct.unpack_from("<IIII", data, pos)
            v1 = (rotl((v1 + k1 * P2) & M, 13) * P1) & M
            v2 = (rotl((v2 + k2 * P2) & M, 13) * P1) & M
            v3 = (rotl((v3 + k3 * P2) & M, 13) * P1) & M
            v4 = (rotl((v4 + k4 * P2) & M, 13) * P1) & M
            pos += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while pos + 4 <= n:
        (k,) = struct.unpack_from("<I", data, pos)
        h = (rotl((h + k * P3) & M, 17) * P4) & M
        pos += 4
    while pos < n:
        h = (rotl((h + data[pos] * P5) & M, 11) * P1) & M
        pos += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    h ^= h >> 16
    return h


def lz4_block_decompress(src: bytes, history: Optional[bytearray] = None) -> bytes:
    """Canonical LZ4 block decode.  With ``history``, matches may reach
    back into it (linked-block frames) and output is appended IN PLACE
    (returns b"" then); without, returns the decoded bytes.  The single
    implementation shared by parquet/orc codecs and the LZ4 frame
    reader."""
    out = history if history is not None else bytearray()
    pos = 0
    n = len(src)
    while pos < n:
        token = src[pos]
        pos += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = src[pos]
                pos += 1
                lit += b
                if b != 255:
                    break
        out += src[pos : pos + lit]
        pos += lit
        if pos >= n:
            break  # final literal run has no match part
        off = src[pos] | (src[pos + 1] << 8)
        pos += 2
        mlen = token & 15
        if mlen == 15:
            while True:
                b = src[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        start = len(out) - off
        if off >= mlen:
            out += out[start : start + mlen]
        else:
            for i in range(mlen):
                out.append(out[start + i])
    return b"" if history is not None else bytes(out)


def lz4_frame_decompress(src: bytes) -> bytes:
    """LZ4 Frame reader: compressed + uncompressed blocks, linked or
    independent, dictionary-ID header skipped.  Header, block, and
    content checksums ARE verified when the frame carries them (the
    reader previously documented "checksums not verified" — silently
    trusting exactly the bytes the checksums exist to protect); a
    mismatch raises typed :class:`BlockCorruptionError`."""
    (magic,) = struct.unpack_from("<I", src, 0)
    if magic != _LZ4_MAGIC:
        raise ValueError("not an LZ4 frame")
    flg = src[4]
    pos = 6  # magic + FLG + BD
    block_checksum = (flg >> 4) & 1
    content_size = (flg >> 3) & 1
    content_checksum = (flg >> 2) & 1
    dict_id = flg & 1
    if content_size:
        pos += 8
    if dict_id:
        pos += 4
    # HC byte: second byte of xxh32 over the descriptor (FLG..dictID)
    want_hc = (_xxh32(src[4:pos]) >> 8) & 0xFF
    if src[pos] != want_hc:
        raise BlockCorruptionError(
            "lz4.frame", "header checksum (HC byte) mismatch",
            expected=want_hc, got=src[pos])
    pos += 1  # HC byte
    out = bytearray()
    while True:
        (bsize,) = struct.unpack_from("<I", src, pos)
        pos += 4
        if bsize == 0:  # EndMark
            break
        uncompressed = bool(bsize & 0x80000000)
        bsize &= 0x7FFFFFFF
        block = src[pos : pos + bsize]
        pos += bsize
        if block_checksum:
            (want,) = struct.unpack_from("<I", src, pos)
            pos += 4
            got = _xxh32(block)
            if got != want:
                raise BlockCorruptionError(
                    "lz4.frame", "block checksum mismatch",
                    expected=want, got=got)
        if uncompressed:
            out += block
        else:
            # linked blocks reference previous output: decode with the
            # running buffer as history (appended in place)
            lz4_block_decompress(block, history=out)
    if content_checksum:
        (want,) = struct.unpack_from("<I", src, pos)
        got = _xxh32(bytes(out))
        if got != want:
            raise BlockCorruptionError(
                "lz4.frame", "content checksum mismatch",
                expected=want, got=got)
    return bytes(out)


def _codec_id(name: str) -> int:
    return {
        "zlib": CODEC_ZLIB,
        "zstd": CODEC_ZSTD,
        "lz4": CODEC_LZ4,
        "raw": CODEC_RAW,
        "none": CODEC_RAW,
    }.get(name, CODEC_ZLIB)


def compress_frame(payload: bytes, codec: Optional[str] = None,
                   checksum_algo: Optional[int] = None) -> bytes:
    """One framed block.  ``checksum_algo`` (an ``integrity`` algo id;
    None = unstamped — the pre-integrity wire format, still what bare
    callers and the native codec speak) sets the codec byte's checksum
    flag and appends the per-frame trailer over the stored bytes."""
    cid = _codec_id(codec or str(conf.IO_COMPRESSION_CODEC.get()))
    stored = payload
    out_cid = CODEC_RAW
    if cid == CODEC_ZSTD:
        import zstandard

        comp = zstandard.ZstdCompressor(level=ZSTD_LEVEL).compress(payload)
        if len(comp) < len(payload):
            stored, out_cid = comp, CODEC_ZSTD
    elif cid == CODEC_LZ4:
        comp = lz4_frame_compress(payload)
        if len(comp) < len(payload):
            stored, out_cid = comp, CODEC_LZ4
    elif cid == CODEC_ZLIB:
        comp = zlib.compress(payload, 1)
        if len(comp) < len(payload):
            stored, out_cid = comp, CODEC_ZLIB
    if checksum_algo is None:
        return struct.pack("<IB", len(stored), out_cid) + stored
    return (struct.pack("<IB", len(stored), out_cid | CHECKSUM_FLAG)
            + stored + frame_trailer(stored, checksum_algo))


def block_trailer(frame_count: int, checksum_xor: int,
                  algo: int) -> bytes:
    """The end-of-block trailer FRAME for a stream written as one unit
    (worker result files, broadcast blobs): frame count + the XOR of
    the member frames' checksums, so truncation of WHOLE frames —
    which per-frame trailers cannot see — is detectable."""
    payload = struct.pack("<IBI", frame_count, algo,
                          checksum_xor & 0xFFFFFFFF)
    return struct.pack("<IB", len(payload), CODEC_BLOCK_TRAILER) + payload


def frame_span(buf: bytes, off: int) -> Tuple[int, int, int, int]:
    """Parse one frame header at ``off``: returns ``(cid, stored_start,
    stored_len, next_off)``; ``cid`` keeps the checksum flag bit, and
    ``next_off`` includes the trailer when flagged — the ONE
    offset-arithmetic definition every blob walker shares."""
    ln, cid = struct.unpack_from("<IB", buf, off)
    start = off + 5
    nxt = start + ln
    if cid & CHECKSUM_FLAG:
        nxt += TRAILER_LEN
    return cid, start, ln, nxt


def _decode(cid: int, payload: bytes) -> bytes:
    if cid == CODEC_ZLIB:
        return zlib.decompress(payload)
    if cid == CODEC_ZSTD:
        import zstandard

        # decompressobj handles frames with AND without embedded
        # content size (streaming writers like the reference's
        # zstd::Encoder omit it) — no exception-driven fallback
        return zstandard.ZstdDecompressor().decompressobj().decompress(payload)
    if cid == CODEC_LZ4:
        return lz4_frame_decompress(payload)
    return payload


def decompress_frame(frame: bytes, site: str = "frame",
                     path: Optional[str] = None) -> bytes:
    """Decode ONE frame (with or without a checksum trailer); flagged
    frames verify their stored bytes first and raise typed
    :class:`BlockCorruptionError` on mismatch."""
    ln, cid = struct.unpack_from("<IB", frame, 0)
    stored = frame[5 : 5 + ln]
    if cid & CHECKSUM_FLAG:
        verify_bytes(stored, frame[5 + ln : 5 + ln + TRAILER_LEN],
                     site, path=path)
        cid &= ~CHECKSUM_FLAG
    return _decode(cid, stored)


def _verify_block_trailer(stored: bytes, count: int, xor: int,
                          site: str, path: Optional[str]) -> None:
    """Check a BLOCK-TRAILER frame's payload against the frames seen
    so far (count + checksum XOR, algo-tagged)."""
    if len(stored) != 9:
        raise BlockCorruptionError(site, "torn block trailer", path=path)
    want_count, algo, want_xor = struct.unpack("<IBI", stored)
    if want_count != count:
        raise BlockCorruptionError(
            site, f"block trailer frame count {want_count} != {count} read",
            path=path)
    # the XOR check only binds when the frames were checksummed with
    # the same algorithm (xor of their trailers' sums)
    if algo and want_xor != (xor & 0xFFFFFFFF):
        raise BlockCorruptionError(site, "block trailer checksum mismatch",
                                   path=path, expected=want_xor,
                                   got=xor & 0xFFFFFFFF, algo=algo)


def iter_blob_frames(blob: bytes, site: str = "block",
                     path: Optional[str] = None) -> Iterator[bytes]:
    """Decode every frame of an in-memory blob (a shuffle bytes block,
    an RSS fetch, a broadcast payload): verifies flagged frames,
    consumes and checks a block trailer when present, and raises typed
    :class:`BlockCorruptionError` on any mismatch.  The shared walker
    behind every ``while off < len(blob)`` loop that used to hand-roll
    the 5-byte header arithmetic (and would torn-read a checksummed
    frame)."""
    from ..runtime.integrity import enabled

    armed = enabled()  # resolved ONCE per blob, not per frame
    off = 0
    count = 0
    xor = 0
    saw_trailer = False
    while off < len(blob):
        cid, start, ln, nxt = frame_span(blob, off)
        stored = blob[start : start + ln]
        if len(stored) < ln:
            raise BlockCorruptionError(site, "torn frame", path=path)
        if (cid & ~CHECKSUM_FLAG) == CODEC_BLOCK_TRAILER:
            _verify_block_trailer(stored, count, xor, site, path)
            saw_trailer = True
            off = nxt
            continue
        if saw_trailer:
            raise BlockCorruptionError(
                site, "frames after the block trailer", path=path)
        if cid & CHECKSUM_FLAG:
            trailer = blob[start + ln : start + ln + TRAILER_LEN]
            verify_bytes(stored, trailer, site, path=path, armed=armed)
            if len(trailer) == TRAILER_LEN:
                xor ^= struct.unpack("<BI", trailer)[1]
        count += 1
        off = nxt
        yield _decode(cid & ~CHECKSUM_FLAG, stored)


class IpcFrameWriter:
    """Accumulates payloads into frames on a binary stream.  With the
    integrity layer armed (conf ``spark.blaze.io.checksum``) every
    frame carries the per-frame checksum trailer; pass
    ``checksum_algo`` explicitly to override (None in the conf-off
    case keeps the pre-integrity format)."""

    def __init__(self, f: BinaryIO, codec: Optional[str] = None,
                 checksum_algo: Optional[int] = ...):
        self._f = f
        self._codec = codec
        self._algo = frame_algo() if checksum_algo is ... else checksum_algo
        self.bytes_written = 0

    def write(self, payload: bytes) -> int:
        frame = compress_frame(payload, self._codec,
                               checksum_algo=self._algo)
        self._f.write(frame)
        self.bytes_written += len(frame)
        return len(frame)


class IpcFrameReader:
    """Iterates frames from a binary stream (bounded by ``limit`` bytes
    when reading a file segment).  Flagged frames verify their stored
    bytes (typed :class:`BlockCorruptionError` on mismatch); a block
    trailer, when the stream carries one, is checked and consumed."""

    def __init__(self, f: BinaryIO, limit: Optional[int] = None,
                 site: str = "frame", path: Optional[str] = None):
        self._f = f
        self._remaining = limit
        self._site = site
        self._path = path
        # resolved ONCE per stream: frame verification must not pay a
        # conf-store read per frame on the hot shuffle-read path
        self._armed = None  # lazy: streams may be built before reads

    def __iter__(self) -> Iterator[bytes]:
        from ..runtime.integrity import enabled

        if self._armed is None:
            self._armed = enabled()
        count = 0
        xor = 0
        saw_trailer = False
        while True:
            if self._remaining is not None and self._remaining <= 0:
                return
            hdr = self._f.read(5)
            if len(hdr) < 5:
                return
            ln, cid = struct.unpack("<IB", hdr)
            stored = self._f.read(ln)
            consumed = 5 + ln
            trailer = b""
            if cid & CHECKSUM_FLAG:
                trailer = self._f.read(TRAILER_LEN)
                consumed += TRAILER_LEN
            if self._remaining is not None:
                self._remaining -= consumed
            if (cid & ~CHECKSUM_FLAG) == CODEC_BLOCK_TRAILER:
                _verify_block_trailer(stored, count, xor, self._site,
                                      self._path)
                saw_trailer = True
                continue
            if saw_trailer:
                raise BlockCorruptionError(
                    self._site, "frames after the block trailer",
                    path=self._path)
            if cid & CHECKSUM_FLAG:
                verify_bytes(stored, trailer, self._site, path=self._path,
                             armed=self._armed)
                if len(trailer) == TRAILER_LEN:
                    xor ^= struct.unpack("<BI", trailer)[1]
            count += 1
            yield _decode(cid & ~CHECKSUM_FLAG, stored)
