"""Framed compressed IPC blocks: ``[u32 len][u8 codec][payload]``.

≙ reference common/ipc_compression.rs:30-335: the reference frames
``[u32 block_len][codec stream]`` where the stream is a ZSTD frame
(level 1) or an LZ4 FRAME, per ``spark.io.compression.codec``.  Here
the same codecs are spoken — zstd via the zstandard package (standard
zstd frames, byte-interoperable), lz4 via a self-contained LZ4 Frame
codec (reader handles compressed + linked blocks; writer emits
store-mode blocks, spec-valid and readable by any lz4 tool) — plus
zlib and raw for internal spill frames.  One codec byte after the
length keeps frames self-describing (the reference relies on both
sides reading the same conf instead).
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Dict, Iterator, Optional

from .. import conf

TARGET_BLOCK = 4 << 20

CODEC_RAW = 0
CODEC_ZLIB = 1
CODEC_ZSTD = 2
CODEC_LZ4 = 3

ZSTD_LEVEL = 1  # ≙ reference ZSTD_LEVEL

_LZ4_MAGIC = 0x184D2204


def lz4_block_compress(src: bytes) -> bytes:
    """Greedy hash-match LZ4 block compressor (spec-compliant output:
    any LZ4 decoder reads it)."""
    n = len(src)
    out = bytearray()

    def emit(lit: bytes, off: int = 0, mlen: int = 0):
        ll = len(lit)
        ml = mlen - 4 if mlen else 0
        out.append((min(ll, 15) << 4) | (min(ml, 15) if mlen else 0))
        if ll >= 15:
            rest = ll - 15
            while rest >= 255:
                out.append(255)
                rest -= 255
            out.append(rest)
        out.extend(lit)
        if mlen:
            out.append(off & 0xFF)
            out.append(off >> 8)
            if ml >= 15:
                rest = ml - 15
                while rest >= 255:
                    out.append(255)
                    rest -= 255
                out.append(rest)

    if n < 13:  # too short for any match (spec end constraints)
        emit(src)
        return bytes(out)
    table: Dict[bytes, int] = {}
    anchor = 0
    i = 0
    limit = n - 12  # last match must start >= 12 bytes before end
    while i <= limit:
        key = src[i : i + 4]
        j = table.get(key, -1)
        table[key] = i
        if j >= 0 and i - j <= 0xFFFF and src[j : j + 4] == key:
            mlen = 4
            end = n - 5  # last 5 bytes must be literals
            while i + mlen < end and src[j + mlen] == src[i + mlen]:
                mlen += 1
            emit(src[anchor:i], i - j, mlen)
            i += mlen
            anchor = i
        else:
            i += 1
    emit(src[anchor:])
    return bytes(out)


def lz4_frame_compress(payload: bytes) -> bytes:
    """LZ4 Frame writer: independent blocks, greedy-compressed (stored
    verbatim when compression does not help), no checksums.  Readable
    by any LZ4 frame reader (lz4_flex, pyarrow, lz4 CLI)."""
    out = bytearray()
    out += struct.pack("<I", _LZ4_MAGIC)
    # FLG: version=01, block independence=1, no checksums/content size
    out.append(0b0110_0000)
    # BD: block max size 4MB (code 7)
    out.append(7 << 4)
    # HC byte: (xxh32(FLG..BD) >> 8) & 0xFF
    out.append((_xxh32(bytes(out[4:6])) >> 8) & 0xFF)
    block_max = 4 << 20
    for off in range(0, max(len(payload), 1), block_max):
        chunk = payload[off : off + block_max]
        if not chunk:
            break
        comp = lz4_block_compress(chunk)
        if len(comp) < len(chunk):
            out += struct.pack("<I", len(comp))
            out += comp
        else:
            out += struct.pack("<I", len(chunk) | 0x80000000)  # stored
            out += chunk
    out += struct.pack("<I", 0)  # EndMark
    return bytes(out)


def _xxh32(data: bytes, seed: int = 0) -> int:
    """xxHash32 (LZ4 frame header checksum)."""
    P1, P2, P3, P4, P5 = 2654435761, 2246822519, 3266489917, 668265263, 374761393
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    pos = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed
        v4 = (seed - P1) & M
        while pos + 16 <= n:
            k1, k2, k3, k4 = struct.unpack_from("<IIII", data, pos)
            v1 = (rotl((v1 + k1 * P2) & M, 13) * P1) & M
            v2 = (rotl((v2 + k2 * P2) & M, 13) * P1) & M
            v3 = (rotl((v3 + k3 * P2) & M, 13) * P1) & M
            v4 = (rotl((v4 + k4 * P2) & M, 13) * P1) & M
            pos += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while pos + 4 <= n:
        (k,) = struct.unpack_from("<I", data, pos)
        h = (rotl((h + k * P3) & M, 17) * P4) & M
        pos += 4
    while pos < n:
        h = (rotl((h + data[pos] * P5) & M, 11) * P1) & M
        pos += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    h ^= h >> 16
    return h


def lz4_block_decompress(src: bytes, history: Optional[bytearray] = None) -> bytes:
    """Canonical LZ4 block decode.  With ``history``, matches may reach
    back into it (linked-block frames) and output is appended IN PLACE
    (returns b"" then); without, returns the decoded bytes.  The single
    implementation shared by parquet/orc codecs and the LZ4 frame
    reader."""
    out = history if history is not None else bytearray()
    pos = 0
    n = len(src)
    while pos < n:
        token = src[pos]
        pos += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = src[pos]
                pos += 1
                lit += b
                if b != 255:
                    break
        out += src[pos : pos + lit]
        pos += lit
        if pos >= n:
            break  # final literal run has no match part
        off = src[pos] | (src[pos + 1] << 8)
        pos += 2
        mlen = token & 15
        if mlen == 15:
            while True:
                b = src[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        start = len(out) - off
        if off >= mlen:
            out += out[start : start + mlen]
        else:
            for i in range(mlen):
                out.append(out[start + i])
    return b"" if history is not None else bytes(out)


def lz4_frame_decompress(src: bytes) -> bytes:
    """LZ4 Frame reader: compressed + uncompressed blocks, linked or
    independent, dictionary-ID header skipped, checksums not verified."""
    (magic,) = struct.unpack_from("<I", src, 0)
    if magic != _LZ4_MAGIC:
        raise ValueError("not an LZ4 frame")
    flg = src[4]
    pos = 6  # magic + FLG + BD
    block_checksum = (flg >> 4) & 1
    content_size = (flg >> 3) & 1
    dict_id = flg & 1
    if content_size:
        pos += 8
    if dict_id:
        pos += 4
    pos += 1  # HC byte
    out = bytearray()
    while True:
        (bsize,) = struct.unpack_from("<I", src, pos)
        pos += 4
        if bsize == 0:  # EndMark
            break
        uncompressed = bool(bsize & 0x80000000)
        bsize &= 0x7FFFFFFF
        block = src[pos : pos + bsize]
        pos += bsize
        if block_checksum:
            pos += 4
        if uncompressed:
            out += block
        else:
            # linked blocks reference previous output: decode with the
            # running buffer as history (appended in place)
            lz4_block_decompress(block, history=out)
    return bytes(out)


def _codec_id(name: str) -> int:
    return {
        "zlib": CODEC_ZLIB,
        "zstd": CODEC_ZSTD,
        "lz4": CODEC_LZ4,
        "raw": CODEC_RAW,
        "none": CODEC_RAW,
    }.get(name, CODEC_ZLIB)


def compress_frame(payload: bytes, codec: Optional[str] = None) -> bytes:
    cid = _codec_id(codec or str(conf.IO_COMPRESSION_CODEC.get()))
    if cid == CODEC_ZSTD:
        import zstandard

        comp = zstandard.ZstdCompressor(level=ZSTD_LEVEL).compress(payload)
        if len(comp) < len(payload):
            return struct.pack("<IB", len(comp), CODEC_ZSTD) + comp
    elif cid == CODEC_LZ4:
        comp = lz4_frame_compress(payload)
        if len(comp) < len(payload):
            return struct.pack("<IB", len(comp), CODEC_LZ4) + comp
    elif cid == CODEC_ZLIB:
        comp = zlib.compress(payload, 1)
        if len(comp) < len(payload):
            return struct.pack("<IB", len(comp), CODEC_ZLIB) + comp
    return struct.pack("<IB", len(payload), CODEC_RAW) + payload


def _decode(cid: int, payload: bytes) -> bytes:
    if cid == CODEC_ZLIB:
        return zlib.decompress(payload)
    if cid == CODEC_ZSTD:
        import zstandard

        # decompressobj handles frames with AND without embedded
        # content size (streaming writers like the reference's
        # zstd::Encoder omit it) — no exception-driven fallback
        return zstandard.ZstdDecompressor().decompressobj().decompress(payload)
    if cid == CODEC_LZ4:
        return lz4_frame_decompress(payload)
    return payload


def decompress_frame(frame: bytes) -> bytes:
    ln, cid = struct.unpack_from("<IB", frame, 0)
    return _decode(cid, frame[5 : 5 + ln])


class IpcFrameWriter:
    """Accumulates payloads into frames on a binary stream."""

    def __init__(self, f: BinaryIO, codec: Optional[str] = None):
        self._f = f
        self._codec = codec
        self.bytes_written = 0

    def write(self, payload: bytes) -> int:
        frame = compress_frame(payload, self._codec)
        self._f.write(frame)
        self.bytes_written += len(frame)
        return len(frame)


class IpcFrameReader:
    """Iterates frames from a binary stream (bounded by ``limit`` bytes
    when reading a file segment)."""

    def __init__(self, f: BinaryIO, limit: Optional[int] = None):
        self._f = f
        self._remaining = limit

    def __iter__(self) -> Iterator[bytes]:
        while True:
            if self._remaining is not None and self._remaining <= 0:
                return
            hdr = self._f.read(5)
            if len(hdr) < 5:
                return
            ln, cid = struct.unpack("<IB", hdr)
            payload = self._f.read(ln)
            if self._remaining is not None:
                self._remaining -= 5 + ln
            yield _decode(cid, payload)
