"""Framed compressed IPC blocks: ``[u32 len][u8 codec][payload]``.

≙ reference common/ipc_compression.rs:30-335 (same framing idea; the
reference speaks zstd(1)/lz4 per spark.io.compression.codec with 4 MiB
target blocks).  Codecs here: 0=raw, 1=zlib(1) (zstd/lz4 libs are not
in the image; the codec byte keeps the format extensible and the C++
runtime can add them).
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Iterator, Optional

from .. import conf

TARGET_BLOCK = 4 << 20

CODEC_RAW = 0
CODEC_ZLIB = 1


def _codec_id(name: str) -> int:
    return CODEC_ZLIB if name in ("zlib", "zstd", "lz4") else CODEC_RAW


def compress_frame(payload: bytes, codec: Optional[str] = None) -> bytes:
    cid = _codec_id(codec or str(conf.IO_COMPRESSION_CODEC.get()))
    if cid == CODEC_ZLIB:
        comp = zlib.compress(payload, 1)
        if len(comp) < len(payload):
            return struct.pack("<IB", len(comp), CODEC_ZLIB) + comp
    return struct.pack("<IB", len(payload), CODEC_RAW) + payload


def decompress_frame(frame: bytes) -> bytes:
    ln, cid = struct.unpack_from("<IB", frame, 0)
    payload = frame[5 : 5 + ln]
    if cid == CODEC_ZLIB:
        return zlib.decompress(payload)
    return payload


class IpcFrameWriter:
    """Accumulates payloads into frames on a binary stream."""

    def __init__(self, f: BinaryIO, codec: Optional[str] = None):
        self._f = f
        self._codec = codec
        self.bytes_written = 0

    def write(self, payload: bytes) -> int:
        frame = compress_frame(payload, self._codec)
        self._f.write(frame)
        self.bytes_written += len(frame)
        return len(frame)


class IpcFrameReader:
    """Iterates frames from a binary stream (bounded by ``limit`` bytes
    when reading a file segment)."""

    def __init__(self, f: BinaryIO, limit: Optional[int] = None):
        self._f = f
        self._remaining = limit

    def __iter__(self) -> Iterator[bytes]:
        while True:
            if self._remaining is not None and self._remaining <= 0:
                return
            hdr = self._f.read(5)
            if len(hdr) < 5:
                return
            ln, cid = struct.unpack("<IB", hdr)
            payload = self._f.read(ln)
            if self._remaining is not None:
                self._remaining -= 5 + ln
            if cid == CODEC_ZLIB:
                payload = zlib.decompress(payload)
            yield payload
