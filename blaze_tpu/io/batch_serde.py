"""Columnar batch (de)serialization — the shuffle and spill wire format.

≙ reference io/batch_serde.rs:34-97 (schemaless length-prefixed
columnar serde; the reader recovers the schema from plan context).
Layout per batch (little-endian):

    u32 num_rows
    per column (schema-driven, recursive):
      flat:   u8 tag (0=fixed, 1=string)
              u32 data_nbytes | raw data buffer (trimmed to num_rows)
              [u32 width]     | strings only: padded byte width
              bitmap          | validity, ceil(rows/8) bytes
              [lengths]       | strings only: rows * i32
      nested: u8 tag (2)
              bitmap          | row validity
              [counts]        | ARRAY/MAP: rows * i32 element counts
              children        | recursively; ARRAY/MAP element children
                              | are serialized flattened to rows*M rows

Buffers are trimmed to ``num_rows`` (padding never crosses the wire)
and re-bucketed on read.  The native (C++) fast path covers flat-only
batches; nested columns take the python path.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from ..batch import (
    Column,
    RecordBatch,
    _flatten_leading,
    _pad_1d,
    _reshape_leading,
    bucket_capacity,
)
from ..schema import DataType, Schema, TypeKind


def _kid_types(dtype: DataType) -> List[DataType]:
    if dtype.kind == TypeKind.ARRAY:
        return [dtype.elem]
    if dtype.kind == TypeKind.MAP:
        return [dtype.key, dtype.value]
    return [f.dtype for f in dtype.struct_fields]


def _slice_rows(c: Column, n: int) -> Column:
    s = lambda a: None if a is None else np.asarray(a)[:n]
    return Column(
        c.dtype, s(c.data), s(c.validity), s(c.lengths),
        None if c.children is None else tuple(_slice_rows(k, n) for k in c.children),
    )


def _ser_col(out: List[bytes], c: Column, n: int) -> None:
    dtype = c.dtype
    validity = np.packbits(
        np.asarray(c.validity)[:n].astype(np.bool_), bitorder="little"
    ).tobytes()
    if dtype.kind == TypeKind.OPAQUE:
        # opaque UDAF states ride as pickle (≙ UserDefinedArray's
        # kryo-serialized JVM objects crossing the shuffle, uda.rs)
        import pickle

        payload = pickle.dumps([c.data[i] if c.validity[i] else None for i in range(n)])
        out.append(struct.pack("<BI", 3, len(payload)))
        out.append(payload)
        out.append(validity)
        return
    if dtype.is_nested:
        out.append(struct.pack("<B", 2))
        out.append(validity)
        if dtype.kind in (TypeKind.ARRAY, TypeKind.MAP):
            out.append(np.asarray(c.lengths)[:n].astype(np.int32).tobytes())
            m = dtype.max_elems
            for kid in c.children:
                _ser_col(out, _flatten_leading(_slice_rows(kid, n)), n * m)
        else:
            for kid in c.children:
                _ser_col(out, kid, n)
        return
    data = np.asarray(c.data)[:n]
    raw = np.ascontiguousarray(data).tobytes()
    if c.lengths is not None:
        out.append(struct.pack("<BI", 1, len(raw)))
        out.append(struct.pack("<I", data.shape[-1] if data.ndim >= 2 else 0))
        out.append(raw)
        out.append(validity)
        out.append(np.asarray(c.lengths)[:n].astype(np.int32).tobytes())
    else:
        out.append(struct.pack("<BI", 0, len(raw)))
        out.append(raw)
        out.append(validity)


def serialize_batch(batch: RecordBatch) -> bytes:
    from .. import native

    if native.available() and not any(
        f.dtype.is_nested or f.dtype.kind == TypeKind.OPAQUE
        for f in batch.schema.fields
    ):
        out = native.serialize_batch_native(batch)
        if out is not None:
            return out
    b = batch.to_host()
    n = b.num_rows
    parts: List[bytes] = [struct.pack("<I", n)]
    for c in b.columns:
        _ser_col(parts, c, n)
    return b"".join(parts)


def _read_bitmap(data: bytes, off: int, n: int) -> Tuple[np.ndarray, int]:
    vbytes = (n + 7) // 8
    bits = np.unpackbits(
        np.frombuffer(data, np.uint8, count=vbytes, offset=off), bitorder="little"
    )[:n].astype(np.bool_)
    return bits, off + vbytes


def _de_col(dtype: DataType, data: bytes, off: int, n: int) -> Tuple[Column, int]:
    """Deserialize one column at EXACT n rows (caller pads)."""
    (tag,) = struct.unpack_from("<B", data, off)
    off += 1
    if tag == 3:
        assert dtype.kind == TypeKind.OPAQUE, f"wire tag 3 for {dtype!r}"
        from .. import conf

        (nbytes,) = struct.unpack_from("<I", data, off)
        off += 4
        if not bool(conf.ALLOW_PICKLED_UDFS.get()):
            raise PermissionError(
                "opaque column deserialization requires spark.blaze.udf.allowPickled"
            )
        import pickle

        objs_list = pickle.loads(data[off : off + nbytes])
        off += nbytes
        validity, off = _read_bitmap(data, off, n)
        objs = np.empty(n, dtype=object)
        for i, v in enumerate(objs_list):
            objs[i] = v
        return Column(dtype, objs, validity), off
    if tag == 2:
        assert dtype.is_nested, f"wire tag 2 for non-nested {dtype!r}"
        validity, off = _read_bitmap(data, off, n)
        if dtype.kind in (TypeKind.ARRAY, TypeKind.MAP):
            lengths = np.frombuffer(data, np.int32, count=n, offset=off).copy()
            off += 4 * n
            m = dtype.max_elems
            kids = []
            for kt in _kid_types(dtype):
                flat, off = _de_col(kt, data, off, n * m)
                kids.append(_reshape_leading(flat, n, m))
            return Column(dtype, None, validity, lengths, tuple(kids)), off
        kids = []
        for kt in _kid_types(dtype):
            kid, off = _de_col(kt, data, off, n)
            kids.append(kid)
        return Column(dtype, None, validity, None, tuple(kids)), off
    (nbytes,) = struct.unpack_from("<I", data, off)
    off += 4
    if tag == 1:
        (width,) = struct.unpack_from("<I", data, off)
        off += 4
        raw = (
            np.frombuffer(data, np.uint8, count=nbytes, offset=off).reshape(n, width)
            if n
            else np.zeros((0, width), np.uint8)
        )
        off += nbytes
        validity, off = _read_bitmap(data, off, n)
        lengths = np.frombuffer(data, np.int32, count=n, offset=off).copy()
        off += 4 * n
        return Column(dtype, raw.copy(), validity, lengths), off
    dt = dtype.np_dtype
    count = nbytes // dt.itemsize
    raw = np.frombuffer(data, dt, count=count, offset=off).copy()
    off += nbytes
    validity, off = _read_bitmap(data, off, n)
    return Column(dtype, raw, validity), off


def _pad_col(c: Column, cap: int) -> Column:
    p = lambda a: None if a is None else _pad_1d(np.ascontiguousarray(a), cap)
    return Column(
        c.dtype, p(c.data), p(c.validity), p(c.lengths),
        None if c.children is None else tuple(_pad_col(k, cap) for k in c.children),
    )


def deserialize_batch(data: bytes, schema: Schema) -> RecordBatch:
    off = 0
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    cap = bucket_capacity(max(n, 1))
    cols: List[Column] = []
    for f in schema.fields:
        c, off = _de_col(f.dtype, data, off, n)
        cols.append(_pad_col(c, cap))
    return RecordBatch(schema, cols, n)
