"""Columnar batch (de)serialization — the shuffle and spill wire format.

≙ reference io/batch_serde.rs:34-97 (schemaless length-prefixed
columnar serde; the reader recovers the schema from plan context).
Layout per batch (little-endian):

    u32 num_rows
    per column:
        u8  has_lengths (string column)
        u32 data_nbytes      | raw data buffer (trimmed to num_rows)
        [u32 width]          | strings only: padded byte width
        bitmap               | validity, ceil(num_rows/8) bytes
        [lengths]            | strings only: num_rows * i32

Buffers are trimmed to ``num_rows`` (padding never crosses the wire)
and re-bucketed on read.
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

from ..batch import Column, RecordBatch, bucket_capacity, _pad_1d
from ..schema import Schema


def serialize_batch(batch: RecordBatch) -> bytes:
    from .. import native

    if native.available():
        out = native.serialize_batch_native(batch)
        if out is not None:
            return out
    b = batch.to_host()
    n = b.num_rows
    out: List[bytes] = [struct.pack("<I", n)]
    for c in b.columns:
        data = np.asarray(c.data)[:n]
        validity = np.packbits(np.asarray(c.validity)[:n], bitorder="little").tobytes()
        if c.lengths is not None:
            raw = np.ascontiguousarray(data).tobytes()
            out.append(struct.pack("<BI", 1, len(raw)))
            out.append(struct.pack("<I", data.shape[1] if data.ndim == 2 else 0))
            out.append(raw)
            out.append(validity)
            out.append(np.asarray(c.lengths)[:n].astype(np.int32).tobytes())
        else:
            raw = np.ascontiguousarray(data).tobytes()
            out.append(struct.pack("<BI", 0, len(raw)))
            out.append(raw)
            out.append(validity)
    return b"".join(out)


def deserialize_batch(data: bytes, schema: Schema) -> RecordBatch:
    off = 0
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    cap = bucket_capacity(max(n, 1))
    cols: List[Column] = []
    vbytes = (n + 7) // 8
    for f in schema.fields:
        has_len, nbytes = struct.unpack_from("<BI", data, off)
        off += 5
        if has_len:
            (width,) = struct.unpack_from("<I", data, off)
            off += 4
            raw = np.frombuffer(data, np.uint8, count=nbytes, offset=off).reshape(n, width) if n else np.zeros((0, width), np.uint8)
            off += nbytes
            validity = np.unpackbits(
                np.frombuffer(data, np.uint8, count=vbytes, offset=off), bitorder="little"
            )[:n].astype(np.bool_)
            off += vbytes
            lengths = np.frombuffer(data, np.int32, count=n, offset=off)
            off += 4 * n
            d = np.zeros((cap, width), np.uint8)
            d[:n] = raw
            cols.append(
                Column(
                    f.dtype,
                    d,
                    _pad_1d(validity, cap),
                    _pad_1d(lengths.copy(), cap),
                )
            )
        else:
            dt = f.dtype.np_dtype
            count = nbytes // dt.itemsize
            raw = np.frombuffer(data, dt, count=count, offset=off)
            off += nbytes
            validity = np.unpackbits(
                np.frombuffer(data, np.uint8, count=vbytes, offset=off), bitorder="little"
            )[:n].astype(np.bool_)
            off += vbytes
            cols.append(Column(f.dtype, _pad_1d(raw.copy(), cap), _pad_1d(validity, cap)))
    return RecordBatch(schema, cols, n)
