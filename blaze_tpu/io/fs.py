"""Filesystem abstraction for scans and sinks.

≙ reference ``datafusion-ext-commons/src/hadoop_fs.rs:26-160``: ALL
scan/sink file IO in the reference goes through JVM FileSystem
callbacks over JNI (open/create/mkdirs + positioned reads), so HDFS,
S3A, etc. work wherever the JVM's Hadoop conf does.  Here the same
seam: ``get_fs(path)`` resolves a scheme-registered FileSystem; the
gateway registers a ``CallbackFileSystem`` whose callables cross the
C-FFI boundary to the host runtime (JVM or otherwise), while local
paths use ``LocalFileSystem`` directly.

Every reader in blaze_tpu.io opens files via this module, so remote
storage needs only a registration — no reader changes.
"""

from __future__ import annotations

import io
import os
import threading
from typing import BinaryIO, Callable, Dict, Optional, Tuple

_REGISTRY: Dict[str, "FileSystem"] = {}
_LOCK = threading.Lock()


def _split_scheme(path: str) -> Tuple[str, str]:
    if "://" in path:
        scheme, rest = path.split("://", 1)
        return scheme, rest
    return "", path


class FileSystem:
    """≙ hadoop_fs::Fs (open/create/mkdirs; readers must support
    read/seek/tell for positioned reads)."""

    def open(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def create(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path: str) -> BinaryIO:
        return open(_split_scheme(path)[1], "rb")

    def create(self, path: str) -> BinaryIO:
        p = _split_scheme(path)[1]
        d = os.path.dirname(p)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(p, "wb")

    def mkdirs(self, path: str) -> None:
        os.makedirs(_split_scheme(path)[1], exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(_split_scheme(path)[1])

    def size(self, path: str) -> int:
        return os.path.getsize(_split_scheme(path)[1])


class _CallbackReadStream(io.RawIOBase):
    """File-like over positioned-read callbacks (≙ the reference's
    FSDataInputStream wrapper: read(pos, n) round trips per call)."""

    def __init__(self, pread: Callable[[int, int], bytes], length: int):
        self._pread = pread
        self._len = length
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._len + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._len - self._pos
        n = max(0, min(n, self._len - self._pos))
        if n == 0:
            return b""
        out = self._pread(self._pos, n)
        self._pos += len(out)
        return out

    def readinto(self, b) -> int:  # BufferedReader's actual entry point
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)


class CallbackFileSystem(FileSystem):
    """FS over host callbacks — the gateway registers this with
    callables that cross into the host runtime (e.g. JNI->HDFS).

    open_cb(path) -> (pread: (pos, n) -> bytes, length: int)
    create_cb(path) -> writable file-like
    """

    def __init__(
        self,
        open_cb: Callable[[str], Tuple[Callable[[int, int], bytes], int]],
        create_cb: Optional[Callable[[str], BinaryIO]] = None,
        mkdirs_cb: Optional[Callable[[str], None]] = None,
        exists_cb: Optional[Callable[[str], bool]] = None,
    ):
        self._open_cb = open_cb
        self._create_cb = create_cb
        self._mkdirs_cb = mkdirs_cb
        self._exists_cb = exists_cb

    def open(self, path: str) -> BinaryIO:
        pread, length = self._open_cb(path)
        return io.BufferedReader(_CallbackReadStream(pread, length))

    def create(self, path: str) -> BinaryIO:
        assert self._create_cb is not None, "no create callback registered"
        return self._create_cb(path)

    def mkdirs(self, path: str) -> None:
        if self._mkdirs_cb is not None:
            self._mkdirs_cb(path)

    def exists(self, path: str) -> bool:
        assert self._exists_cb is not None, "no exists callback registered"
        return self._exists_cb(path)


_LOCAL = LocalFileSystem()


def register_fs(scheme: str, fs: FileSystem) -> None:
    with _LOCK:
        _REGISTRY[scheme] = fs


def unregister_fs(scheme: str) -> None:
    with _LOCK:
        _REGISTRY.pop(scheme, None)


def get_fs(path: str) -> FileSystem:
    scheme, _ = _split_scheme(path)
    with _LOCK:
        fs = _REGISTRY.get(scheme)
    if fs is not None:
        return fs
    if scheme in ("", "file"):
        return _LOCAL
    raise KeyError(
        f"no FileSystem registered for scheme {scheme!r} (register_fs)"
    )
