"""TPC-DS table schemas (the star-schema subset the query set uses;
column types as Spark reads them — decimal(7,2) money columns).

≙ the reference's TPC-DS differential CI (SURVEY.md §4,
tpcds-reusable.yml): the same tables back its 103-query matrix.
"""

from ..schema import DataType as T, Field, Schema

_m = lambda: T.decimal(7, 2)

TPCDS_SCHEMAS = {
    "date_dim": Schema([
        Field("d_date_sk", T.int64()),
        Field("d_date", T.date32()),
        Field("d_year", T.int32()),
        Field("d_moy", T.int32()),
        Field("d_dom", T.int32()),
        Field("d_qoy", T.int32()),
    ]),
    "time_dim": Schema([
        Field("t_time_sk", T.int64()),
        Field("t_hour", T.int32()),
        Field("t_minute", T.int32()),
    ]),
    "item": Schema([
        Field("i_item_sk", T.int64()),
        Field("i_color", T.string(16)),
        Field("i_item_id", T.string(16)),
        Field("i_item_desc", T.string(32)),
        Field("i_brand_id", T.int32()),
        Field("i_brand", T.string(32)),
        Field("i_class_id", T.int32()),
        Field("i_class", T.string(16)),
        Field("i_category_id", T.int32()),
        Field("i_category", T.string(16)),
        Field("i_manufact_id", T.int32()),
        Field("i_manufact", T.string(24)),
        Field("i_manager_id", T.int32()),
        Field("i_current_price", _m()),
    ]),
    "store": Schema([
        Field("s_store_sk", T.int64()),
        Field("s_store_name", T.string(16)),
        Field("s_state", T.string(8)),
        Field("s_company_name", T.string(16)),
        Field("s_county", T.string(24)),
        Field("s_zip", T.string(16)),
    ]),
    "promotion": Schema([
        Field("p_promo_sk", T.int64()),
        Field("p_channel_email", T.string(8)),
        Field("p_channel_event", T.string(8)),
    ]),
    "customer_demographics": Schema([
        Field("cd_demo_sk", T.int64()),
        Field("cd_gender", T.string(8)),
        Field("cd_marital_status", T.string(8)),
        Field("cd_education_status", T.string(24)),
        Field("cd_purchase_estimate", T.int32()),
        Field("cd_credit_rating", T.string(16)),
        Field("cd_dep_count", T.int32()),
        Field("cd_dep_employed_count", T.int32()),
        Field("cd_dep_college_count", T.int32()),
    ]),
    "household_demographics": Schema([
        Field("hd_demo_sk", T.int64()),
        Field("hd_dep_count", T.int32()),
        Field("hd_buy_potential", T.string(16)),
        Field("hd_vehicle_count", T.int32()),
    ]),
    "customer": Schema([
        Field("c_customer_sk", T.int64()),
        Field("c_current_addr_sk", T.int64()),
        Field("c_current_cdemo_sk", T.int64()),
        Field("c_salutation", T.string(8)),
        Field("c_first_name", T.string(16)),
        Field("c_last_name", T.string(16)),
        Field("c_preferred_cust_flag", T.string(8)),
    ]),
    "customer_address": Schema([
        Field("ca_address_sk", T.int64()),
        Field("ca_zip", T.string(16)),
        Field("ca_county", T.string(24)),
        Field("ca_state", T.string(8)),
        Field("ca_gmt_offset", T.decimal(5, 2)),
    ]),
    "call_center": Schema([
        Field("cc_call_center_sk", T.int64()),
        Field("cc_name", T.string(24)),
    ]),
    "reason": Schema([
        Field("r_reason_sk", T.int64()),
        Field("r_reason_desc", T.string(40)),
    ]),
    "store_sales": Schema([
        Field("ss_sold_date_sk", T.int64()),
        Field("ss_sold_time_sk", T.int64()),
        Field("ss_item_sk", T.int64()),
        Field("ss_customer_sk", T.int64()),
        Field("ss_cdemo_sk", T.int64()),
        Field("ss_hdemo_sk", T.int64()),
        Field("ss_store_sk", T.int64()),
        Field("ss_promo_sk", T.int64()),
        Field("ss_addr_sk", T.int64()),
        Field("ss_ticket_number", T.int64()),
        Field("ss_quantity", T.int32()),
        Field("ss_list_price", _m()),
        Field("ss_sales_price", _m()),
        Field("ss_ext_discount_amt", _m()),
        Field("ss_ext_sales_price", _m()),
        Field("ss_coupon_amt", _m()),
        Field("ss_net_profit", _m()),
    ]),
    "catalog_sales": Schema([
        Field("cs_sold_date_sk", T.int64()),
        Field("cs_item_sk", T.int64()),
        Field("cs_bill_customer_sk", T.int64()),
        Field("cs_ship_customer_sk", T.int64()),
        Field("cs_bill_addr_sk", T.int64()),
        Field("cs_call_center_sk", T.int64()),
        Field("cs_sales_price", _m()),
        Field("cs_ext_sales_price", _m()),
    ]),
    "web_sales": Schema([
        Field("ws_sold_date_sk", T.int64()),
        Field("ws_item_sk", T.int64()),
        Field("ws_bill_customer_sk", T.int64()),
        Field("ws_bill_addr_sk", T.int64()),
        Field("ws_ext_sales_price", _m()),
        Field("ws_net_paid", _m()),
    ]),
}
