"""TPC-DS query plans over the operator layer (star-join subset:
q3 q7 q42 q52 q55 q96 — the BASELINE.json TPC-DS configs plus the
classic reporting-join shapes).

Same architecture slot as tpch/queries.py: each builder plays Spark
planner + BlazeConverters for its query, wiring scans through
filters/broadcast star joins/two-stage aggregations/exchanges.

≙ reference end-to-end TPC-DS differential matrix
(.github/workflows/tpcds-reusable.yml:83-143).
"""

from __future__ import annotations

from typing import Callable, Dict

from ..exprs import col, lit
from ..ops import (
    AggExec,
    AggFunction,
    AggMode,
    ExecNode,
    FilterExec,
    GroupingExpr,
    ProjectExec,
    SortField,
    UnionExec,
)
from ..ops.joins import JoinType
from ..schema import DataType
from ..tpch.queries import broadcast_join, shuffle_join, single_sorted, two_stage_agg


def q3(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], col("d_moy") == lit(11))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manufact_id") == lit(128))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("d_year")), SortField(col("sum_agg"), ascending=False), SortField(col("brand_id"))],
        fetch=100,
    )


def q7(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    pr = FilterExec(
        t["promotion"],
        (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N")),
    )
    pr_p = ProjectExec(pr, [col("p_promo_sk")])
    sales = t["store_sales"]
    j = broadcast_join(cd_p, sales, [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col("ss_promo_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id")],
        [
            AggFunction("avg", col("ss_quantity"), "agg1"),
            AggFunction("avg", col("ss_list_price"), "agg2"),
            AggFunction("avg", col("ss_coupon_amt"), "agg3"),
            AggFunction("avg", col("ss_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("i_item_id"))], fetch=100)


def _brand_report(t, n_parts, *, year, moy, manager, order_year_first):
    """Shared shape of q52/q55 (and near-q3): month+year slice of
    store_sales by brand."""
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(moy)) & (col("d_year") == lit(year)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(manager))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "ext_price")],
        n_parts,
    )
    sort = (
        [SortField(col("d_year")), SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
        if order_year_first
        else [SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
    )
    return single_sorted(agg, sort, fetch=100)


def q52(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=2000, moy=11, manager=1, order_year_first=True)


def q55(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=1999, moy=11, manager=28, order_year_first=False)


def q42(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(1))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_category_id"), col("i_category")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_category_id"), "category_id"),
         GroupingExpr(col("i_category"), "category")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("sum_agg"), ascending=False),
         SortField(col("d_year")),
         SortField(col("category_id")),
         SortField(col("category"))],
        fetch=100,
    )


def q96(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    td = FilterExec(t["time_dim"], (col("t_hour") == lit(20)) & (col("t_minute") >= lit(30)))
    td_p = ProjectExec(td, [col("t_time_sk")])
    hd = FilterExec(t["household_demographics"], col("hd_dep_count") == lit(7))
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(t["store"], col("s_store_name") == lit("ese"))
    st_p = ProjectExec(st, [col("s_store_sk")])
    sales = ProjectExec(
        t["store_sales"], [col("ss_sold_time_sk"), col("ss_hdemo_sk"), col("ss_store_sk")]
    )
    j = broadcast_join(td_p, sales, [col("t_time_sk")], [col("ss_sold_time_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    return two_stage_agg(j, [], [AggFunction("count_star", None, "cnt")], n_parts)


def q26(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog-channel demographic averages — q7's star-join shape over
    catalog_sales (cd x date x promotion x item)."""
    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    pr = FilterExec(
        t["promotion"],
        (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N")),
    )
    pr_p = ProjectExec(pr, [col("p_promo_sk")])
    sales = t["catalog_sales"]
    j = broadcast_join(cd_p, sales, [col("cd_demo_sk")], [col("cs_bill_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col("cs_promo_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("cs_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id")],
        [
            AggFunction("avg", col("cs_quantity"), "agg1"),
            AggFunction("avg", col("cs_list_price"), "agg2"),
            AggFunction("avg", col("cs_coupon_amt"), "agg3"),
            AggFunction("avg", col("cs_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("i_item_id"))], fetch=100)


def q27(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """ROLLUP(i_item_id, s_state) — exercises ExpandExec + grouping-id
    the way Spark plans rollups (Expand with null-filled projections)."""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec
    from ..schema import DataType

    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2002))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    st = FilterExec(
        t["store"],
        col("s_state").isin(lit("TN"), lit("SD"), lit("AL"), lit("GA"), lit("OH")),
    )
    st_p = ProjectExec(st, [col("s_store_sk"), col("s_state")])
    j = broadcast_join(cd_p, t["store_sales"], [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    # rollup = Expand with (item,state,0) (item,null,1) (null,null,3)
    passthrough = [col("ss_quantity"), col("ss_list_price"), col("ss_coupon_amt"), col("ss_sales_price")]
    null_s16 = Lit(None, DataType.string(16))
    null_s8 = Lit(None, DataType.string(8))
    expand = ExpandExec(
        j,
        [
            passthrough + [col("i_item_id"), col("s_state"), lit(0)],
            passthrough + [col("i_item_id"), null_s8, lit(1)],
            passthrough + [null_s16, null_s8, lit(3)],
        ],
        ["ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
         "i_item_id", "s_state", "g_id"],
    )
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("s_state"), "s_state"),
         GroupingExpr(col("g_id"), "g_id")],
        [
            AggFunction("avg", col("ss_quantity"), "agg1"),
            AggFunction("avg", col("ss_list_price"), "agg2"),
            AggFunction("avg", col("ss_coupon_amt"), "agg3"),
            AggFunction("avg", col("ss_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("i_item_id")), SortField(col("s_state"))], fetch=100
    )


def q89(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Monthly brand sales vs yearly store average — WindowExec avg
    over the whole partition + CASE-guarded ratio filter."""
    from ..exprs.ir import Case, func
    from ..ops import WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning
    from ..schema import DataType

    cat_a = col("i_category").isin(lit("Books"), lit("Electronics"), lit("Sports"))
    cls_a = col("i_class").isin(lit("accessories"), lit("reference"), lit("football"))
    cat_b = col("i_category").isin(lit("Men"), lit("Jewelry"), lit("Women"))
    cls_b = col("i_class").isin(lit("shirts"), lit("birdal"), lit("dresses"))
    it = FilterExec(t["item"], (cat_a & cls_a) | (cat_b & cls_b))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_category"), col("i_class"), col("i_brand")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(1999))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_moy")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"), col("s_company_name")])
    j = broadcast_join(it_p, t["store_sales"], [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_category"), "i_category"),
         GroupingExpr(col("i_class"), "i_class"),
         GroupingExpr(col("i_brand"), "i_brand"),
         GroupingExpr(col("s_store_name"), "s_store_name"),
         GroupingExpr(col("s_company_name"), "s_company_name"),
         GroupingExpr(col("d_moy"), "d_moy")],
        [AggFunction("sum", col("ss_sales_price"), "sum_sales")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    from ..ops import SortExec

    pre = SortExec(single, [
        SortField(col("i_category")), SortField(col("i_brand")),
        SortField(col("s_store_name")), SortField(col("s_company_name")),
    ])
    w = WindowExec(
        pre,
        [WindowFunction("avg", "avg_monthly_sales", col("sum_sales"), whole_partition=True)],
        [col("i_category"), col("i_brand"), col("s_store_name"), col("s_company_name")],
        [],
    )
    f64 = DataType.float64()
    sum_f = col("sum_sales").cast(f64)
    avg_f = col("avg_monthly_sales").cast(f64)
    ratio = Case(
        [( avg_f != lit(0.0), func("abs", sum_f - avg_f) / avg_f )], None
    )
    filt = FilterExec(w, ratio > lit(0.1))
    proj = ProjectExec(
        filt,
        [col("i_category"), col("i_class"), col("i_brand"), col("s_store_name"),
         col("s_company_name"), col("d_moy"), col("sum_sales"), col("avg_monthly_sales"),
         (sum_f - avg_f)],
        ["i_category", "i_class", "i_brand", "s_store_name",
         "s_company_name", "d_moy", "sum_sales", "avg_monthly_sales", "delta"],
    )
    out = single_sorted(proj, [SortField(col("delta")), SortField(col("s_store_name"))], fetch=100)
    return out


def _class_share_report(t, n_parts, *, sales, date_col, item_col, price_col):
    """Shared q98/q20/q12 shape: item revenue share of its class —
    windowed sum over i_class, per channel."""
    import datetime

    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning
    from ..schema import DataType

    D = datetime.date
    dt = FilterExec(
        t["date_dim"],
        (col("d_date") >= lit(D(1999, 2, 22))) & (col("d_date") <= lit(D(1999, 3, 24))),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = FilterExec(
        t["item"],
        col("i_category").isin(lit("Sports"), lit("Books"), lit("Home")),
    )
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id"), col("i_item_desc"),
                            col("i_category"), col("i_class"), col("i_current_price")])
    sl = ProjectExec(t[sales], [col(date_col), col(item_col), col(price_col)],
                     [date_col, item_col, "ss_ext_sales_price"])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col(item_col)], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("i_category"), "i_category"),
         GroupingExpr(col("i_class"), "i_class"),
         GroupingExpr(col("i_current_price"), "i_current_price")],
        [AggFunction("sum", col("ss_ext_sales_price"), "itemrevenue")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    pre = SortExec(single, [SortField(col("i_class"))])
    w = WindowExec(
        pre,
        [WindowFunction("sum", "class_revenue", col("itemrevenue"), whole_partition=True)],
        [col("i_class")],
        [],
    )
    f64 = DataType.float64()
    ratio = (col("itemrevenue").cast(f64) * lit(100.0)) / col("class_revenue").cast(f64)
    proj = ProjectExec(
        w,
        [col("i_item_id"), col("i_item_desc"), col("i_category"), col("i_class"),
         col("i_current_price"), col("itemrevenue"), ratio],
        ["i_item_id", "i_item_desc", "i_category", "i_class",
         "i_current_price", "itemrevenue", "revenueratio"],
    )
    return single_sorted(
        proj,
        [SortField(col("i_category")), SortField(col("i_class")),
         SortField(col("i_item_id")), SortField(col("i_item_desc")),
         SortField(col("revenueratio"))],
    )


def q98(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Item revenue share of its class (store channel)."""
    return _class_share_report(
        t, n_parts, sales="store_sales", date_col="ss_sold_date_sk",
        item_col="ss_item_sk", price_col="ss_ext_sales_price",
    )


def q20(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q98's class-share report over the CATALOG channel."""
    return _class_share_report(
        t, n_parts, sales="catalog_sales", date_col="cs_sold_date_sk",
        item_col="cs_item_sk", price_col="cs_ext_sales_price",
    )


def q12(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q98's class-share report over the WEB channel."""
    return _class_share_report(
        t, n_parts, sales="web_sales", date_col="ws_sold_date_sk",
        item_col="ws_item_sk", price_col="ws_ext_sales_price",
    )


def _ticket_report(t, n_parts, *, dom_ranges, buy_potentials, cnt_lo, cnt_hi,
                   dep_vehicle_ratio, order_by):
    """Shared q34/q73 shape: per-(ticket, customer) line counts with a
    HAVING range, then join customer for the report — aggregation
    BELOW a join, with a post-agg filter."""
    dt_pred = None
    for lo, hi in dom_ranges:
        rng_p = (col("d_dom") >= lit(lo)) & (col("d_dom") <= lit(hi))
        dt_pred = rng_p if dt_pred is None else (dt_pred | rng_p)
    dt = FilterExec(
        t["date_dim"],
        dt_pred & col("d_year").isin(lit(1999), lit(2000), lit(2001)),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    hd_pred = None
    for bp in buy_potentials:
        p = col("hd_buy_potential") == lit(bp)
        hd_pred = p if hd_pred is None else (hd_pred | p)
    hd_pred = hd_pred & (col("hd_vehicle_count") > lit(0))
    # spec CASE WHEN vehicle_count > 0 THEN dep/vehicle END > ratio
    # (the > 0 guard above makes the CASE arm unconditional here)
    f64 = DataType.float64()
    hd_pred = hd_pred & (
        col("hd_dep_count").cast(f64) / col("hd_vehicle_count").cast(f64)
        > lit(dep_vehicle_ratio)
    )
    hd = FilterExec(t["household_demographics"], hd_pred)
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(
        t["store"],
        col("s_county").isin(
            lit("Williamson County"), lit("Franklin Parish"),
            lit("Bronx County"), lit("Orange County"),
        ),
    )
    st_p = ProjectExec(st, [col("s_store_sk")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("ss_ticket_number"), "ss_ticket_number"),
         GroupingExpr(col("ss_customer_sk"), "ss_customer_sk")],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    having = FilterExec(agg, (col("cnt") >= lit(cnt_lo)) & (col("cnt") <= lit(cnt_hi)))
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_salutation"), col("c_first_name"),
         col("c_last_name"), col("c_preferred_cust_flag")],
    )
    j2 = broadcast_join(cust, having, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    return single_sorted(j2, order_by)


def q34(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _ticket_report(
        t, n_parts,
        dom_ranges=[(1, 3), (25, 28)],
        buy_potentials=[">10000", "Unknown"],
        cnt_lo=15, cnt_hi=20,
        dep_vehicle_ratio=1.2,
        order_by=[  # spec q34 ordering
            SortField(col("c_last_name")), SortField(col("c_first_name")),
            SortField(col("c_salutation")),
            SortField(col("c_preferred_cust_flag"), ascending=False),
            SortField(col("ss_ticket_number")),
        ],
    )


def q73(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _ticket_report(
        t, n_parts,
        dom_ranges=[(1, 2)],
        buy_potentials=[">10000", "Unknown"],
        cnt_lo=1, cnt_hi=5,
        dep_vehicle_ratio=1.0,
        order_by=[SortField(col("cnt"), ascending=False), SortField(col("c_last_name"))],
    )


def _manufact_window_report(t, n_parts, *, group_col, avg_name, order_first):
    """Shared q53/q63 shape: quarterly/monthly manufacturer sales vs
    the manufacturer's window average, CASE-guarded ratio filter."""
    from ..exprs.ir import Case, func
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning

    cat_a = col("i_category").isin(lit("Books"), lit("Children"), lit("Electronics"))
    cls_a = col("i_class").isin(lit("personal"), lit("self-help"), lit("reference"))
    cat_b = col("i_category").isin(lit("Women"), lit("Music"), lit("Men"))
    cls_b = col("i_class").isin(lit("accessories"), lit("classical"), lit("fragrances"))
    it = FilterExec(t["item"], (cat_a & cls_a) | (cat_b & cls_b))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_manufact_id")])
    dt = FilterExec(t["date_dim"], col("d_year").isin(lit(1999), lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col(group_col)])
    st_p = ProjectExec(t["store"], [col("s_store_sk")])
    j = broadcast_join(it_p, t["store_sales"], [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_manufact_id"), "i_manufact_id"),
         GroupingExpr(col(group_col), group_col)],
        [AggFunction("sum", col("ss_sales_price"), "sum_sales")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    pre = SortExec(single, [SortField(col("i_manufact_id"))])
    w = WindowExec(
        pre,
        [WindowFunction("avg", avg_name, col("sum_sales"), whole_partition=True)],
        [col("i_manufact_id")],
        [],
    )
    f64 = DataType.float64()
    sum_f = col("sum_sales").cast(f64)
    avg_f = col(avg_name).cast(f64)
    ratio = Case([(avg_f > lit(0.0), func("abs", sum_f - avg_f) / avg_f)], None)
    filt = FilterExec(w, ratio > lit(0.1))
    # spec orderings (ascending): q53 avg, sum, manufact;
    # q63 manufact, avg, sum
    order = (
        [SortField(col(avg_name)), SortField(col("sum_sales")),
         SortField(col("i_manufact_id"))]
        if order_first == "avg"
        else [SortField(col("i_manufact_id")), SortField(col(avg_name)),
              SortField(col("sum_sales"))]
    )
    proj = ProjectExec(
        filt,
        [col("i_manufact_id"), col(group_col), col("sum_sales"), col(avg_name)],
        ["i_manufact_id", group_col, "sum_sales", avg_name],
    )
    return single_sorted(proj, order, fetch=100)


def q53(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _manufact_window_report(
        t, n_parts, group_col="d_qoy", avg_name="avg_quarterly_sales",
        order_first="avg",
    )


def q63(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _manufact_window_report(
        t, n_parts, group_col="d_moy", avg_name="avg_monthly_sales",
        order_first="manufact",
    )


def q19(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Brand revenue from out-of-zip customers: 5-way star join with a
    NON-EQUI residual (substr(ca_zip,1,5) <> substr(s_zip,1,5))."""
    from ..exprs.ir import func

    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11)) & (col("d_year") == lit(1998)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = FilterExec(t["item"], col("i_manager_id") == lit(8))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand"),
                            col("i_manufact_id"), col("i_manufact")])
    cust = ProjectExec(t["customer"], [col("c_customer_sk"), col("c_current_addr_sk")])
    addr = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_zip")])
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_zip")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cust, j, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(addr, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    j = FilterExec(
        j,
        func("substring", col("ca_zip"), lit(1), lit(5))
        != func("substring", col("s_zip"), lit(1), lit(5)),
    )
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand"),
         GroupingExpr(col("i_manufact_id"), "manufact_id"),
         GroupingExpr(col("i_manufact"), "manufact")],
        [AggFunction("sum", col("ss_ext_sales_price"), "ext_price")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("ext_price"), ascending=False), SortField(col("brand")),
         SortField(col("brand_id")), SortField(col("manufact_id")),
         SortField(col("manufact"))],
        fetch=100,
    )


def _channel_customers(t, n_parts, sales, date_col, cust_col, year):
    """DISTINCT (c_last_name, c_first_name, d_date) of one sales
    channel in a year — the common building block of q38/q87.
    (Deviation: the spec slices by d_month_seq, which this date_dim
    doesn't carry; a d_year slice keeps the same shape.)"""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(year))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_date")])
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_last_name"), col("c_first_name")],
    )
    sl = ProjectExec(t[sales], [col(date_col), col(cust_col)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cust, j, [col("c_customer_sk")], [col(cust_col)], JoinType.INNER, build_is_left=True)
    # DISTINCT = grouping-only two-stage aggregation
    return two_stage_agg(
        j,
        [GroupingExpr(col("c_last_name"), "c_last_name"),
         GroupingExpr(col("c_first_name"), "c_first_name"),
         GroupingExpr(col("d_date"), "d_date")],
        [],
        n_parts,
    )


_CHANNELS = [
    ("store_sales", "ss_sold_date_sk", "ss_customer_sk"),
    ("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk"),
    ("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk"),
]


def q38(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """count(*) of customers hot in ALL three channels — INTERSECT
    planned the way Spark does: left-semi joins between the DISTINCT
    per-channel sets on every output column."""
    ss, cs, ws = (
        _channel_customers(t, n_parts, s, d, c, year=2000) for s, d, c in _CHANNELS
    )
    keys = [col("c_last_name"), col("c_first_name"), col("d_date")]
    inter = broadcast_join(cs, ss, keys, keys, JoinType.LEFT_SEMI, build_is_left=False)
    inter = broadcast_join(ws, inter, keys, keys, JoinType.LEFT_SEMI, build_is_left=False)
    return two_stage_agg(
        inter, [], [AggFunction("count_star", None, "cnt")], n_parts
    )


def q87(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """count(*) of store-channel customers NOT in catalog and NOT in
    web — EXCEPT as chained left-ANTI joins over the distinct sets."""
    ss, cs, ws = (
        _channel_customers(t, n_parts, s, d, c, year=2000) for s, d, c in _CHANNELS
    )
    keys = [col("c_last_name"), col("c_first_name"), col("d_date")]
    rem = broadcast_join(cs, ss, keys, keys, JoinType.LEFT_ANTI, build_is_left=False)
    rem = broadcast_join(ws, rem, keys, keys, JoinType.LEFT_ANTI, build_is_left=False)
    return two_stage_agg(
        rem, [], [AggFunction("count_star", None, "cnt")], n_parts
    )


def _channel_by_item(t, n_parts, sales, date_col, item_col, addr_col, price_col,
                     *, group_col, item_filter, year, moy):
    """One UNION-ALL arm of q33/q56/q60: a channel's sales in a month
    for items in a filtered id-set, bought from -5 GMT addresses,
    grouped by the report column."""
    dt = FilterExec(t["date_dim"], (col("d_year") == lit(year)) & (col("d_moy") == lit(moy)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    ca = FilterExec(t["customer_address"], col("ca_gmt_offset") == lit("-5", DataType.decimal(5, 2)))
    ca_p = ProjectExec(ca, [col("ca_address_sk")])
    # the id-set subquery: item ids matching the attribute filter
    ids = two_stage_agg(
        ProjectExec(FilterExec(t["item"], item_filter), [col(group_col)]),
        [GroupingExpr(col(group_col), group_col)], [], n_parts,
    )
    it = ProjectExec(t["item"], [col("i_item_sk"), col(group_col)])
    it_f = broadcast_join(ids, it, [col(group_col)], [col(group_col)], JoinType.LEFT_SEMI, build_is_left=False)
    sl = ProjectExec(t[sales], [col(date_col), col(item_col), col(addr_col), col(price_col)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca_p, j, [col("ca_address_sk")], [col(addr_col)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_f, j, [col("i_item_sk")], [col(item_col)], JoinType.INNER, build_is_left=True)
    return ProjectExec(j, [col(group_col), col(price_col)], [group_col, "sales_price"])


def _three_channel_union(t, n_parts, *, group_col, item_filter, year, moy):
    from ..ops import UnionExec

    arms = [
        _channel_by_item(t, n_parts, s, d, i, a, p, group_col=group_col,
                         item_filter=item_filter, year=year, moy=moy)
        for s, d, i, a, p in [
            ("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk", "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk", "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
        ]
    ]
    u = UnionExec(arms)
    agg = two_stage_agg(
        u,
        [GroupingExpr(col(group_col), group_col)],
        [AggFunction("sum", col("sales_price"), "total_sales")],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("total_sales")), SortField(col(group_col))], fetch=100
    )


def q33(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Electronics manufacturers across all three channels."""
    return _three_channel_union(
        t, n_parts, group_col="i_manufact_id",
        item_filter=col("i_category") == lit("Electronics"), year=1998, moy=5,
    )


def q56(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Colored items across all three channels."""
    return _three_channel_union(
        t, n_parts, group_col="i_item_id",
        item_filter=col("i_color").isin(lit("slate"), lit("blanched"), lit("burnished")),
        year=2000, moy=2,
    )


def q60(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Music items across all three channels."""
    return _three_channel_union(
        t, n_parts, group_col="i_item_id",
        item_filter=col("i_category") == lit("Music"), year=1999, moy=9,
    )


def _rollup_rank_tail(j, n_parts, *, dims, num_col, den_col, measure_name,
                      measure_desc, measure_as_float=True):
    """Shared q36/q86/q70 tail: ROLLUP over two dimension columns with
    lochierarchy + rank-within-parent window + the spec's final sort.

    ``dims``: [(col_name, null_literal_dtype)] for the two rollup
    levels; ``den_col`` None = plain sum measure, else num/den ratio."""
    from ..exprs.ir import Case, Lit
    from ..ops import ExpandExec, LimitExec, SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning

    (d0, t0), (d1, t1) = dims
    null0 = Lit(None, t0)
    null1 = Lit(None, t1)
    vals = [col(num_col)] + ([col(den_col)] if den_col else [])
    val_names = [num_col] + ([den_col] if den_col else [])
    expand = ExpandExec(
        j,
        [
            vals + [col(d0), col(d1), lit(0)],
            vals + [col(d0), null1, lit(1)],
            vals + [null0, null1, lit(3)],
        ],
        val_names + [d0, d1, "g_id"],
    )
    aggs = [AggFunction("sum", col(num_col), "num_sum")] + (
        [AggFunction("sum", col(den_col), "den_sum")] if den_col else []
    )
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col(d0), d0), GroupingExpr(col(d1), d1),
         GroupingExpr(col("g_id"), "g_id")],
        aggs,
        n_parts,
    )
    f64 = DataType.float64()
    # lochierarchy = grouping(d0)+grouping(d1): 0, 1, 2
    loch = Case(
        [(col("g_id") == lit(0), lit(0)), (col("g_id") == lit(1), lit(1))],
        lit(2),
    )
    if den_col:
        measure = col("num_sum").cast(f64) / col("den_sum").cast(f64)
    elif measure_as_float:
        measure = col("num_sum").cast(f64)
    else:
        measure = col("num_sum")
    proj = ProjectExec(
        agg,
        [col(d0), col(d1), loch, measure],
        [d0, d1, "lochierarchy", measure_name],
    )
    single = NativeShuffleExchangeExec(proj, SinglePartitioning())
    # rank within parent: partition (lochierarchy, parent level-0 dim)
    parent = Case([(col("lochierarchy") == lit(0), col(d0))], None)
    pre = SortExec(single, [
        SortField(col("lochierarchy")),
        SortField(parent),
        SortField(col(measure_name), ascending=not measure_desc),
    ])
    w = WindowExec(
        pre,
        [WindowFunction("rank", "rank_within_parent")],
        [col("lochierarchy"), parent],
        [SortField(col(measure_name), ascending=not measure_desc)],
    )
    out = SortExec(w, [
        SortField(col("lochierarchy"), ascending=False),
        SortField(Case([(col("lochierarchy") == lit(0), col(d0))], None)),
        SortField(col("rank_within_parent")),
    ], fetch=100)
    return LimitExec(out, 100)


def _rollup_margin_report(t, n_parts, *, sales, date_col, item_col, num_col,
                          den_col, year, extra_build=None, ratio_desc=False):
    """Shared q36/q86 shape: ROLLUP(i_category, i_class) over a channel
    with lochierarchy + rank-within-parent window."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(year))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_category"), col("i_class")])
    cols = [col(date_col), col(item_col), col(num_col)] + (
        [col(den_col)] if den_col else []
    )
    sl = ProjectExec(t[sales], cols + ([col("ss_store_sk")] if extra_build else []))
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    if extra_build is not None:
        build, bkey, pkey = extra_build
        j = broadcast_join(build, j, [bkey], [pkey], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col(item_col)], JoinType.INNER, build_is_left=True)
    return _rollup_rank_tail(
        j, n_parts,
        dims=[("i_category", DataType.string(16)), ("i_class", DataType.string(16))],
        num_col=num_col, den_col=den_col, measure_name="measure",
        measure_desc=ratio_desc,
    )


def q36(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Gross-margin ROLLUP over store_sales with store-state slice."""
    st = FilterExec(
        t["store"],
        col("s_state").isin(lit("TN"), lit("SD"), lit("AL"), lit("GA"), lit("OH")),
    )
    st_p = ProjectExec(st, [col("s_store_sk")])
    return _rollup_margin_report(
        t, n_parts, sales="store_sales", date_col="ss_sold_date_sk",
        item_col="ss_item_sk", num_col="ss_net_profit",
        den_col="ss_ext_sales_price", year=2001,
        extra_build=(st_p, col("s_store_sk"), col("ss_store_sk")),
    )


def q86(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Net-paid ROLLUP over web_sales (rank by total desc)."""
    return _rollup_margin_report(
        t, n_parts, sales="web_sales", date_col="ws_sold_date_sk",
        item_col="ws_item_sk", num_col="ws_net_paid", den_col=None,
        year=2000, ratio_desc=True,
    )


_DOW_NAMES = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")


def q43(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Per-store weekly sales PIVOT: seven sum(CASE WHEN d_dow = k
    THEN price END) aggregates in one pass (the day-of-week report)."""
    from ..exprs.ir import Case

    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_dow")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"),
                      col("ss_sales_price")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    pivots = [
        Case([(col("d_dow") == lit(k), col("ss_sales_price"))], None)
        .alias(f"{name}_v")
        for k, name in enumerate(_DOW_NAMES)
    ]
    proj = ProjectExec(j, [col("s_store_name")] + pivots)
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("s_store_name"), "s_store_name")],
        [AggFunction("sum", col(f"{name}_v"), f"{name}_sales")
         for name in _DOW_NAMES],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("s_store_name"))], fetch=100)


def _excess_discount(t, n_parts, *, sales, date_col, item_col, amt_col):
    """Shared q32/q92 shape: sum of discounts exceeding 1.3x the
    ITEM'S OWN average over the window — the correlated scalar
    subquery decorrelated into a per-item aggregate join."""
    import datetime as _dt

    lo = _dt.date(2000, 1, 27)
    hi = _dt.date(2000, 4, 26)
    dt = FilterExec(t["date_dim"],
                    (col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t[sales], [col(date_col), col(item_col), col(amt_col)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    per_item = two_stage_agg(
        j,
        [GroupingExpr(col(item_col), "avg_item_sk")],
        [AggFunction("avg", col(amt_col), "avg_amt")],
        n_parts,
    )
    jj = broadcast_join(per_item, j, [col("avg_item_sk")], [col(item_col)], JoinType.INNER, build_is_left=True)
    f64 = DataType.float64()
    # avg_amt is decimal(11,6) (scale+4): compare in float dollars
    keep = col(amt_col).cast(f64) > col("avg_amt").cast(f64) * lit(1.3)
    it = FilterExec(t["item"], col("i_manufact_id") <= lit(Q32_MFG_MAX))
    it_p = ProjectExec(it, [col("i_item_sk")])
    f = FilterExec(jj, keep)
    f = broadcast_join(it_p, f, [col("i_item_sk")], [col(item_col)], JoinType.LEFT_SEMI, build_is_left=False)
    return two_stage_agg(
        f, [], [AggFunction("sum", col(amt_col), "excess_discount")], n_parts
    )


# the spec filters one manufacturer (977/356); at tiny scales a single
# id may be absent, so this subset uses a low-id RANGE that always
# keeps a real item slice — shared with the oracle
Q32_MFG_MAX = 40


def q32(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog excess-discount sum (correlated per-item average)."""
    return _excess_discount(
        t, n_parts, sales="catalog_sales", date_col="cs_sold_date_sk",
        item_col="cs_item_sk", amt_col="cs_ext_discount_amt",
    )


def q92(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web excess-discount sum — q32's shape over web_sales."""
    return _excess_discount(
        t, n_parts, sales="web_sales", date_col="ws_sold_date_sk",
        item_col="ws_item_sk", amt_col="ws_ext_discount_amt",
    )


def q61(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Promotional vs total store revenue for -5 GMT buyers of one
    category — TWO scalar-subquery aggregates cross-joined into one
    row with their ratio (the spec's promotions/total shape; channel
    flags restricted to this generator's email/event columns)."""
    from ..tpch.queries import scalar_subquery

    def revenue(with_promo: bool):
        dt = FilterExec(t["date_dim"],
                        (col("d_year") == lit(1998)) & (col("d_moy") == lit(11)))
        dt_p = ProjectExec(dt, [col("d_date_sk")])
        st_p = ProjectExec(t["store"], [col("s_store_sk")])
        it = FilterExec(t["item"], col("i_category") == lit("Jewelry"))
        it_p = ProjectExec(it, [col("i_item_sk")])
        ca = FilterExec(t["customer_address"],
                        col("ca_gmt_offset") == lit("-5", DataType.decimal(5, 2)))
        ca_p = ProjectExec(ca, [col("ca_address_sk")])
        cust = ProjectExec(t["customer"],
                           [col("c_customer_sk"), col("c_current_addr_sk")])
        cust = broadcast_join(ca_p, cust, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.LEFT_SEMI, build_is_left=False)
        sl = ProjectExec(t["store_sales"],
                         [col("ss_sold_date_sk"), col("ss_store_sk"),
                          col("ss_item_sk"), col("ss_customer_sk"),
                          col("ss_promo_sk"), col("ss_ext_sales_price")])
        j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(cust, j, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
        if with_promo:
            pr = FilterExec(
                t["promotion"],
                (col("p_channel_email") == lit("Y"))
                | (col("p_channel_event") == lit("Y")),
            )
            pr_p = ProjectExec(pr, [col("p_promo_sk")])
            j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col("ss_promo_sk")], JoinType.INNER, build_is_left=True)
        return two_stage_agg(
            j, [], [AggFunction("sum", col("ss_ext_sales_price"), "rev")], n_parts
        )

    promo = scalar_subquery(revenue(True), "rev")
    total = scalar_subquery(revenue(False), "rev")
    f64 = DataType.float64()
    ratio = promo.cast(f64) * lit(100.0) / total.cast(f64)
    src = FilterExec(t["reason"], col("r_reason_sk") == lit(1))
    return ProjectExec(src, [promo, total, ratio],
                       ["promotions", "total", "promo_pct"])


# q15's literal zip prefixes (the spec's 5-digit list, sized to this
# generator's distribution); shared with the oracle
Q15_ZIPS = ("85669", "86197", "88274", "83405", "86475",
            "35000", "35137", "60031", "60062", "60093")


def q15(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog sales by buyer zip for a quarter, kept when ANY of: zip
    prefix in a literal list, state in a set, or a high-ticket sale —
    the OR-of-unlike-predicates family."""
    from ..exprs.ir import func

    dt = FilterExec(t["date_dim"],
                    (col("d_qoy") == lit(2)) & (col("d_year") == lit(2001)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    cust = ProjectExec(t["customer"], [col("c_customer_sk"), col("c_current_addr_sk")])
    ca_p = ProjectExec(t["customer_address"],
                       [col("ca_address_sk"), col("ca_zip"), col("ca_state")])
    sl = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_bill_customer_sk"),
                      col("cs_sales_price")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cust, j, [col("c_customer_sk")], [col("cs_bill_customer_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca_p, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    zip5 = func("substring", col("ca_zip"), lit(1), lit(5))
    keep = (
        zip5.isin(*[lit(z) for z in Q15_ZIPS])
        | col("ca_state").isin(lit("TN"), lit("GA"), lit("OH"))
        | (col("cs_sales_price") > lit("250", DataType.decimal(7, 2)))
    )
    f = FilterExec(j, keep)
    agg = two_stage_agg(
        f,
        [GroupingExpr(col("ca_zip"), "ca_zip")],
        [AggFunction("sum", col("cs_sales_price"), "sum_price")],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("ca_zip"))], fetch=100)


def q70(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Net-profit ROLLUP over store GEOGRAPHY (state, county) with
    rank-within-parent — the q36/q86 shape grouped on the store
    dimension instead of the item hierarchy."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_state"), col("s_county")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"), col("ss_net_profit")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    return _rollup_rank_tail(
        j, n_parts,
        dims=[("s_state", DataType.string(8)), ("s_county", DataType.string(24))],
        num_col="ss_net_profit", den_col=None, measure_name="total_sum",
        measure_desc=True, measure_as_float=False,
    )


def _yoy_window_report(t, n_parts, *, sales, date_col, item_col, price_col,
                       entity_build, entity_cols, year):
    """Shared q47/q57 shape: monthly sums per (brand, entity), a
    whole-partition avg within the year, and lag/lead neighbours over
    the (year, moy) order — the windowed year-over-year family."""
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning

    dt = FilterExec(
        t["date_dim"],
        (col("d_year") == lit(year))
        | ((col("d_year") == lit(year - 1)) & (col("d_moy") == lit(12)))
        | ((col("d_year") == lit(year + 1)) & (col("d_moy") == lit(1))),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year"), col("d_moy")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_category"), col("i_brand")])
    build, bkey, pkey = entity_build
    sl = t[sales]
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(build, j, [bkey], [pkey], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col(item_col)], JoinType.INNER, build_is_left=True)
    groupings = (
        [GroupingExpr(col("i_category"), "i_category"),
         GroupingExpr(col("i_brand"), "i_brand")]
        + [GroupingExpr(col(c), c) for c in entity_cols]
        + [GroupingExpr(col("d_year"), "d_year"),
           GroupingExpr(col("d_moy"), "d_moy")]
    )
    agg = two_stage_agg(
        j, groupings, [AggFunction("sum", col(price_col), "sum_sales")], n_parts
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    part = [col("i_category"), col("i_brand")] + [col(c) for c in entity_cols]
    pre = SortExec(single, [SortField(e) for e in part]
                   + [SortField(col("d_year")), SortField(col("d_moy"))])
    # avg within (entity, year): separate window spec
    w_avg = WindowExec(
        pre,
        [WindowFunction("avg", "avg_monthly_sales", col("sum_sales"),
                        whole_partition=True)],
        part + [col("d_year")],
        [],
    )
    # lag/lead across the month sequence (year NOT in the partition)
    w = WindowExec(
        w_avg,
        [WindowFunction("lag", "psum", col("sum_sales"), offset=1),
         WindowFunction("lead", "nsum", col("sum_sales"), offset=1)],
        part,
        [SortField(col("d_year")), SortField(col("d_moy"))],
    )
    f64 = DataType.float64()
    sum_f = col("sum_sales").cast(f64)
    avg_f = col("avg_monthly_sales").cast(f64)
    from ..exprs.ir import func

    filt = FilterExec(
        w,
        (col("d_year") == lit(year))
        & (col("avg_monthly_sales") > lit(0))
        & ((func("abs", sum_f - avg_f) / avg_f) > lit(0.1)),
    )
    proj = ProjectExec(
        filt,
        [col("i_category"), col("i_brand")] + [col(c) for c in entity_cols]
        + [col("d_year"), col("d_moy"), col("sum_sales"),
           col("avg_monthly_sales"), col("psum"), col("nsum"),
           (sum_f - avg_f)],
        ["i_category", "i_brand"] + list(entity_cols)
        + ["d_year", "d_moy", "sum_sales", "avg_monthly_sales",
           "psum", "nsum", "delta"],
    )
    return single_sorted(
        proj, [SortField(col("delta")), SortField(col("d_moy"))], fetch=100
    )


def q47(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"),
                                    col("s_company_name")])
    return _yoy_window_report(
        t, n_parts, sales="store_sales", date_col="ss_sold_date_sk",
        item_col="ss_item_sk", price_col="ss_sales_price",
        entity_build=(st_p, col("s_store_sk"), col("ss_store_sk")),
        entity_cols=("s_store_name", "s_company_name"), year=1999,
    )


def q57(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    cc_p = ProjectExec(t["call_center"], [col("cc_call_center_sk"), col("cc_name")])
    return _yoy_window_report(
        t, n_parts, sales="catalog_sales", date_col="cs_sold_date_sk",
        item_col="cs_item_sk", price_col="cs_sales_price",
        entity_build=(cc_p, col("cc_call_center_sk"), col("cs_call_center_sk")),
        entity_cols=("cc_name",), year=1999,
    )


def _active_customer_set(t, n_parts, sales, date_col, cust_col, *, year, moys):
    """DISTINCT customer sks of a channel inside a (year, month-range)
    window — the correlated-EXISTS subquery body of q10/q35."""
    dt = FilterExec(
        t["date_dim"],
        (col("d_year") == lit(year))
        & (col("d_moy") >= lit(moys[0])) & (col("d_moy") <= lit(moys[1])),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t[sales], [col(date_col), col(cust_col)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_col)], JoinType.INNER, build_is_left=True)
    return two_stage_agg(
        ProjectExec(j, [col(cust_col)], ["cust_sk"]),
        [GroupingExpr(col("cust_sk"), "cust_sk")], [], n_parts,
    )


def _exists_or_channels(t, n_parts, cust, *, year, moys, combine=None):
    """cust + EXISTS(store) required, then web/catalog EXISTENCE flags
    combined by ``combine(ws, cs)`` (default: OR — q10/q35's correlated
    EXISTS; q69 negates both) — the LEFT_SEMI + two EXISTENCE joins +
    filter shape Spark plans for correlated (NOT) EXISTS."""
    from ..ops import RenameColumnsExec

    ss_set = _active_customer_set(t, n_parts, "store_sales", "ss_sold_date_sk",
                                  "ss_customer_sk", year=year, moys=moys)
    ws_set = _active_customer_set(t, n_parts, "web_sales", "ws_sold_date_sk",
                                  "ws_bill_customer_sk", year=year, moys=moys)
    cs_set = _active_customer_set(t, n_parts, "catalog_sales", "cs_sold_date_sk",
                                  "cs_ship_customer_sk", year=year, moys=moys)
    ck = [col("c_customer_sk")]
    j = broadcast_join(ss_set, cust, [col("cust_sk")], ck, JoinType.LEFT_SEMI, build_is_left=False)
    j = broadcast_join(ws_set, j, [col("cust_sk")], ck, JoinType.EXISTENCE, build_is_left=False)
    names = [f.name for f in j.schema.fields]
    names[names.index("exists#0")] = "exists_ws"
    j = RenameColumnsExec(j, names)
    j = broadcast_join(cs_set, j, [col("cust_sk")], ck, JoinType.EXISTENCE, build_is_left=False)
    names = [f.name for f in j.schema.fields]
    names[names.index("exists#0")] = "exists_cs"
    j = RenameColumnsExec(j, names)
    if combine is None:
        combine = lambda ws, cs: ws | cs
    return FilterExec(j, combine(col("exists_ws"), col("exists_cs")))


def q10(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Demographic counts of county customers active in-store AND on
    (web OR catalog) — correlated EXISTS via semi + existence joins."""
    ca = FilterExec(
        t["customer_address"],
        col("ca_county").isin(lit("Williamson County"), lit("Franklin Parish"),
                              lit("Bronx County")),
    )
    ca_p = ProjectExec(ca, [col("ca_address_sk")])
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_current_addr_sk"), col("c_current_cdemo_sk")],
    )
    cust = broadcast_join(ca_p, cust, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.LEFT_SEMI, build_is_left=False)
    act = _exists_or_channels(t, n_parts, cust, year=2002, moys=(1, 4))
    cd = t["customer_demographics"]
    j = broadcast_join(cd, act, [col("cd_demo_sk")], [col("c_current_cdemo_sk")], JoinType.INNER, build_is_left=True)
    group_cols = ["cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
                  "cd_dep_employed_count", "cd_dep_college_count"]
    agg = two_stage_agg(
        j,
        [GroupingExpr(col(c), c) for c in group_cols],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col(c)) for c in group_cols], fetch=100
    )


def q35(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """State/demographic profile of multi-channel customers — the q10
    EXISTS shape plus avg/max/sum aggregates over the dep counts."""
    ca_p = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_state")])
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_current_addr_sk"), col("c_current_cdemo_sk")],
    )
    cust = broadcast_join(ca_p, cust, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    act = _exists_or_channels(t, n_parts, cust, year=2002, moys=(1, 4))
    cd = ProjectExec(
        t["customer_demographics"],
        [col("cd_demo_sk"), col("cd_gender"), col("cd_marital_status"),
         col("cd_dep_count"), col("cd_dep_employed_count"),
         col("cd_dep_college_count")],
    )
    j = broadcast_join(cd, act, [col("cd_demo_sk")], [col("c_current_cdemo_sk")], JoinType.INNER, build_is_left=True)
    group_cols = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
                  "cd_dep_employed_count", "cd_dep_college_count"]
    aggs = [AggFunction("count_star", None, "cnt1")]
    for i, c in enumerate(("cd_dep_count", "cd_dep_employed_count",
                           "cd_dep_college_count"), 1):
        aggs += [
            AggFunction("avg", col(c), f"avg{i}"),
            AggFunction("max", col(c), f"max{i}"),
            AggFunction("sum", col(c), f"sum{i}"),
        ]
    agg = two_stage_agg(
        j, [GroupingExpr(col(c), c) for c in group_cols], aggs, n_parts
    )
    return single_sorted(
        agg, [SortField(col(c)) for c in group_cols], fetch=100
    )


# q8's literal zip list + preferred-count HAVING threshold, shrunk to
# this generator's scale (the spec ships 400 zips and count > 10);
# shared with the oracle
Q8_ZIPS = ("35000", "35137", "35274", "35411", "35548", "35685",
           "60031", "60062", "60093", "60124")
Q8_MIN_PREFERRED = 2


def q8(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Store net profit for stores whose 2-digit zip prefix appears in
    (literal zip list ∩ zips with >=N preferred customers) — the
    INTERSECT feeding a substring-keyed semi join."""
    from ..exprs.ir import func

    zip5 = func("substring", col("ca_zip"), lit(1), lit(5))
    # A1: literal-list zips
    a1 = two_stage_agg(
        ProjectExec(
            FilterExec(t["customer_address"],
                       zip5.isin(*[lit(z) for z in Q8_ZIPS])),
            [zip5], ["zip5"],
        ),
        [GroupingExpr(col("zip5"), "zip5")], [], n_parts,
    )
    # A2: zips of >=N preferred customers
    cust = FilterExec(t["customer"], col("c_preferred_cust_flag") == lit("Y"))
    cust_p = ProjectExec(cust, [col("c_current_addr_sk")])
    ca_p = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_zip")])
    cj = broadcast_join(ca_p, cust_p, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    a2 = FilterExec(
        two_stage_agg(
            ProjectExec(cj, [zip5], ["zip5"]),
            [GroupingExpr(col("zip5"), "zip5")],
            [AggFunction("count_star", None, "cnt")],
            n_parts,
        ),
        col("cnt") >= lit(Q8_MIN_PREFERRED),
    )
    inter = broadcast_join(ProjectExec(a2, [col("zip5")]), a1,
                           [col("zip5")], [col("zip5")],
                           JoinType.LEFT_SEMI, build_is_left=False)
    prefixes = two_stage_agg(
        ProjectExec(inter, [func("substring", col("zip5"), lit(1), lit(2))], ["zip2"]),
        [GroupingExpr(col("zip2"), "zip2")], [], n_parts,
    )
    st = broadcast_join(
        prefixes, ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"), col("s_zip")]),
        [col("zip2")], [func("substring", col("s_zip"), lit(1), lit(2))],
        JoinType.LEFT_SEMI, build_is_left=False,
    )
    dt = FilterExec(t["date_dim"], (col("d_year") == lit(1998)) & (col("d_qoy") == lit(2)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"), col("ss_net_profit")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ProjectExec(st, [col("s_store_sk"), col("s_store_name")]), j,
                       [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("s_store_name"), "s_store_name")],
        [AggFunction("sum", col("ss_net_profit"), "net_profit")],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("s_store_name"))], fetch=100)


# q9 bucket thresholds: constants shared with the oracle (the spec's
# dsdgen-scale literals, shrunk to this generator's row counts)
Q9_THRESHOLDS = (400, 300, 200, 100, 50)


def q9(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Five CASE buckets choosing between avg(ext_discount) and
    avg(net_profit) by a count threshold — 15 scalar subqueries over
    store_sales quantity bands, projected over the 1-row reason slice
    (≙ the reference's driver-side scalar-subquery evaluation)."""
    from ..exprs.ir import Case
    from ..tpch.queries import scalar_subquery

    exprs = []
    names = []
    for b, thresh in enumerate(Q9_THRESHOLDS):
        lo, hi = 20 * b + 1, 20 * (b + 1)
        band = FilterExec(
            t["store_sales"],
            (col("ss_quantity") >= lit(lo)) & (col("ss_quantity") <= lit(hi)),
        )
        cnt = scalar_subquery(
            two_stage_agg(band, [], [AggFunction("count_star", None, "c")], n_parts), "c"
        )
        avg_disc = scalar_subquery(
            two_stage_agg(band, [], [AggFunction("avg", col("ss_ext_discount_amt"), "a")], n_parts), "a"
        )
        avg_profit = scalar_subquery(
            two_stage_agg(band, [], [AggFunction("avg", col("ss_net_profit"), "a")], n_parts), "a"
        )
        exprs.append(Case([(cnt > lit(thresh), avg_disc)], avg_profit))
        names.append(f"bucket{b + 1}")
    src = FilterExec(t["reason"], col("r_reason_sk") == lit(1))
    return ProjectExec(src, exprs, names)


def q88(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Eight half-hour store traffic counts, 8:30..12:30 — the spec's
    cross join of eight scalar COUNT subqueries, evaluated driver-side
    and emitted as one row."""
    from ..tpch.queries import scalar_subquery

    hd = FilterExec(
        t["household_demographics"],
        ((col("hd_dep_count") == lit(4)) & (col("hd_vehicle_count") <= lit(6)))
        | ((col("hd_dep_count") == lit(2)) & (col("hd_vehicle_count") <= lit(4)))
        | ((col("hd_dep_count") == lit(0)) & (col("hd_vehicle_count") <= lit(2))),
    )
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(t["store"], col("s_store_name") == lit("ese"))
    st_p = ProjectExec(st, [col("s_store_sk")])
    exprs, names = [], []
    for k in range(8):
        h, half = divmod(k + 17, 2)  # 8:30, 9:00, ..., 12:00
        td = FilterExec(
            t["time_dim"],
            (col("t_hour") == lit(h))
            & ((col("t_minute") >= lit(30)) if half else (col("t_minute") < lit(30))),
        )
        td_p = ProjectExec(td, [col("t_time_sk")])
        sl = ProjectExec(t["store_sales"],
                         [col("ss_sold_time_sk"), col("ss_hdemo_sk"), col("ss_store_sk")])
        j = broadcast_join(td_p, sl, [col("t_time_sk")], [col("ss_sold_time_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
        cnt = scalar_subquery(
            two_stage_agg(j, [], [AggFunction("count_star", None, "c")], n_parts), "c"
        )
        exprs.append(cnt)
        names.append(f"h{h}_{30 if half else 0}")
    src = FilterExec(t["reason"], col("r_reason_sk") == lit(1))
    return ProjectExec(src, exprs, names)


# q13/q48 band constants, shared with the oracles
Q13_BANDS = [
    # (marital, education, sales_price_lo, sales_price_hi, dep_count)
    # ranges sized so each band keeps a real slice of this generator's
    # price distribution (the spec's dollar windows against dsdgen's)
    ("M", "Advanced Degree", 0, 150, 3),
    ("S", "College", 0, 100, 1),
    ("W", "2 yr Degree", 50, 200, 1),
]
Q13_STATE_BANDS = [
    # (states, net_profit_lo, net_profit_hi)
    (("TN", "SD", "AL"), 0, 1000),
    (("GA", "OH", "TN"), -500, 500),
    (("SD", "AL", "GA"), -1000, 250),
]


def _band_preds(*, price_col):
    """The OR-of-ANDs demographic and address bands shared by q13/q48:
    (cd band AND price range AND hd dep) OR ... , and
    (ca state set AND net profit range) OR ..."""
    demo = None
    for ms, ed, lo, hi, dep in Q13_BANDS:
        p = (
            (col("cd_marital_status") == lit(ms))
            & (col("cd_education_status") == lit(ed))
            & (col(price_col) >= lit(str(lo), DataType.decimal(7, 2)))
            & (col(price_col) <= lit(str(hi), DataType.decimal(7, 2)))
            & (col("hd_dep_count") == lit(dep))
        )
        demo = p if demo is None else (demo | p)
    geo = None
    for states, lo, hi in Q13_STATE_BANDS:
        p = (
            col("ca_state").isin(*[lit(s) for s in states])
            & (col("ss_net_profit") >= lit(str(lo), DataType.decimal(7, 2)))
            & (col("ss_net_profit") <= lit(str(hi), DataType.decimal(7, 2)))
        )
        geo = p if geo is None else (geo | p)
    return demo & geo


def q69(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Demographics of state-resident customers active in-store but on
    NEITHER web NOR catalog — q10's shape with the existence flags
    NEGATED (NOT EXISTS via the same existence joins)."""
    ca = FilterExec(
        t["customer_address"],
        col("ca_state").isin(lit("TN"), lit("SD"), lit("AL")),
    )
    ca_p = ProjectExec(ca, [col("ca_address_sk")])
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_current_addr_sk"), col("c_current_cdemo_sk")],
    )
    cust = broadcast_join(ca_p, cust, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.LEFT_SEMI, build_is_left=False)
    act = _exists_or_channels(t, n_parts, cust, year=2002, moys=(1, 3),
                              combine=lambda ws, cs: ~ws & ~cs)
    cd = ProjectExec(
        t["customer_demographics"],
        [col("cd_demo_sk"), col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cd_purchase_estimate"),
         col("cd_credit_rating")],
    )
    j2 = broadcast_join(cd, act, [col("cd_demo_sk")], [col("c_current_cdemo_sk")], JoinType.INNER, build_is_left=True)
    group_cols = ["cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating"]
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col(c), c) for c in group_cols],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    return single_sorted(agg, [SortField(col(c)) for c in group_cols], fetch=100)


def q93(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Actual sales net of returns for one return reason — LEFT OUTER
    join on a COMPOSITE key (item, ticket) whose unmatched side feeds a
    CASE, then the reason filter (per the spec's comma-join, effectively
    keeping returned rows of that reason)."""
    from ..exprs.ir import Case

    sl = ProjectExec(t["store_sales"],
                     [col("ss_item_sk"), col("ss_ticket_number"),
                      col("ss_customer_sk"), col("ss_quantity"),
                      col("ss_sales_price")])
    sr = ProjectExec(t["store_returns"],
                     [col("sr_item_sk"), col("sr_ticket_number"),
                      col("sr_reason_sk"), col("sr_return_quantity")])
    lkeys = [col("ss_item_sk"), col("ss_ticket_number")]
    rkeys = [col("sr_item_sk"), col("sr_ticket_number")]
    from ..tpch.queries import shuffle_join
    j = shuffle_join(sl, sr, lkeys, rkeys, JoinType.LEFT, n_parts,
                     build_left=False)
    reason = FilterExec(t["reason"],
                        col("r_reason_desc") == lit("Stopped working"))
    reason_p = ProjectExec(reason, [col("r_reason_sk")])
    j = broadcast_join(reason_p, j, [col("r_reason_sk")], [col("sr_reason_sk")],
                       JoinType.INNER, build_is_left=True)
    qty32 = col("ss_quantity")
    act = Case(
        [(col("sr_return_quantity").is_not_null(),
          (qty32 - col("sr_return_quantity")).cast(DataType.int64())
          * col("ss_sales_price"))],
        qty32.cast(DataType.int64()) * col("ss_sales_price"),
    )
    proj = ProjectExec(j, [col("ss_customer_sk"), act.alias("act_sales")])
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("ss_customer_sk"), "ss_customer_sk")],
        [AggFunction("sum", col("act_sales"), "sumsales")],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("sumsales")), SortField(col("ss_customer_sk"))],
        fetch=100,
    )


def q65(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Under-performing items: per-(store, item) revenue joined against
    10% of the store's average item revenue — aggregation OVER an
    aggregation, then a filtered join between the two levels."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"),
                      col("ss_item_sk"), col("ss_sales_price")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    per_item = two_stage_agg(
        j,
        [GroupingExpr(col("ss_store_sk"), "ss_store_sk"),
         GroupingExpr(col("ss_item_sk"), "ss_item_sk")],
        [AggFunction("sum", col("ss_sales_price"), "revenue")],
        n_parts,
    )
    per_store = two_stage_agg(
        per_item,
        [GroupingExpr(col("ss_store_sk"), "sb_store_sk")],
        [AggFunction("avg", col("revenue"), "ave")],
        n_parts,
    )
    jj = broadcast_join(per_store, per_item, [col("sb_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    f64 = DataType.float64()
    low = FilterExec(
        jj, col("revenue").cast(f64) <= col("ave").cast(f64) * lit(0.1)
    )
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    it_p = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_desc"),
                                   col("i_current_price"), col("i_brand")])
    out = broadcast_join(st_p, low, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    out = broadcast_join(it_p, out, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(out, [col("s_store_name"), col("i_item_desc"),
                             col("revenue"), col("i_current_price"), col("i_brand")])
    return single_sorted(
        proj, [SortField(col("s_store_name")), SortField(col("i_item_desc"))],
        fetch=100,
    )


def _q13_source(t) -> ExecNode:
    """The shared q13/q48 source: 5-way demographic/address star join
    over store_sales, filtered by the OR-ed bands."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2001))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    st_p = ProjectExec(t["store"], [col("s_store_sk")])
    cd_p = ProjectExec(
        t["customer_demographics"],
        [col("cd_demo_sk"), col("cd_marital_status"), col("cd_education_status")],
    )
    hd_p = ProjectExec(t["household_demographics"],
                       [col("hd_demo_sk"), col("hd_dep_count")])
    ca_p = ProjectExec(t["customer_address"],
                       [col("ca_address_sk"), col("ca_state")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cd_p, j, [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca_p, j, [col("ca_address_sk")], [col("ss_addr_sk")], JoinType.INNER, build_is_left=True)
    return FilterExec(j, _band_preds(price_col="ss_sales_price"))


def q13(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Average store-sales measures under OR-ed demographic x address
    bands — the wide-predicate star join."""
    return two_stage_agg(
        _q13_source(t), [],
        [AggFunction("avg", col("ss_quantity"), "avg_qty"),
         AggFunction("avg", col("ss_ext_sales_price"), "avg_ext_sales"),
         AggFunction("avg", col("ss_ext_discount_amt"), "avg_ext_disc"),
         AggFunction("count_star", None, "cnt")],
        n_parts,
    )


def q48(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """sum(ss_quantity) under the same band structure (q13's sibling
    shape without the averages)."""
    return two_stage_agg(
        _q13_source(t), [], [AggFunction("sum", col("ss_quantity"), "qty_sum")], n_parts
    )



# --------------------------------------------------- channel reports

_DEC72 = DataType.decimal(7, 2)


def _dz():
    """decimal(7,2) zero literal."""
    return lit("0", _DEC72)


def _d8(e):
    """Widen a decimal(7,2) expr to the union-wide decimal(8,2)."""
    return e + _dz()


def _coalesce0(e):
    """COALESCE(e, 0) at decimal(8,2) for the outer-join null side."""
    from ..exprs.ir import Case

    return Case([(e.is_not_null(), _d8(e))], _d8(_dz()))


def _date_window(t, lo, hi, *, extra=()):
    """date_dim slice d_date BETWEEN lo AND hi projected to d_date_sk
    (+extras) — the q5/q77/q80 family's n-day report window."""
    dt = FilterExec(
        t["date_dim"], (col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi))
    )
    return ProjectExec(dt, [col("d_date_sk")] + [col(c) for c in extra])


def _channel_report_tail(union_plan, n_parts, id_t):
    """Shared q5/q77/q80 tail: ROLLUP(channel, id) over
    (sales, returns, profit) + ORDER BY channel, id LIMIT 100
    (≙ the reference runs these through ExpandExec + two-phase agg,
    expand_exec.rs:39, agg_exec.rs)."""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec

    ch_t = DataType.string(16)
    vals = [col("sales"), col("returns"), col("profit")]
    expand = ExpandExec(
        union_plan,
        [
            vals + [col("channel"), col("id"), lit(0)],
            vals + [col("channel"), Lit(None, id_t), lit(1)],
            vals + [Lit(None, ch_t), Lit(None, id_t), lit(3)],
        ],
        ["sales", "returns", "profit", "channel", "id", "g_id"],
    )
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col("channel"), "channel"), GroupingExpr(col("id"), "id"),
         GroupingExpr(col("g_id"), "g_id")],
        [AggFunction("sum", col("sales"), "sales"),
         AggFunction("sum", col("returns"), "returns"),
         AggFunction("sum", col("profit"), "profit")],
        n_parts,
    )
    proj = ProjectExec(
        agg, [col("channel"), col("id"), col("sales"), col("returns"), col("profit")]
    )
    return single_sorted(
        proj, [SortField(col("channel")), SortField(col("id"))], fetch=100
    )


def q5(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Per-channel sales/returns/profit ROLLUP over a 14-day window:
    each channel UNIONs sales rows with returns rows before the
    aggregate, web returns recover their site via the (item, order)
    join back to web_sales."""
    import datetime

    lo, hi = datetime.date(2000, 8, 23), datetime.date(2000, 9, 5)
    dt = _date_window(t, lo, hi)
    dz = _dz

    def tag(plan, channel):
        return ProjectExec(
            plan,
            [lit(channel, DataType.string(16)), col("id"), col("sales"),
             col("returns"), col("profit")],
            ["channel", "id", "sales", "returns", "profit"],
        )

    # --- store: sales rows + returns rows keyed by s_store_name
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"),
                      col("ss_ext_sales_price"), col("ss_net_profit")])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    s_sales = ProjectExec(
        j,
        [col("s_store_name"), _d8(col("ss_ext_sales_price")), _d8(dz()),
         _d8(col("ss_net_profit"))],
        ["id", "sales", "returns", "profit"],
    )
    sr = ProjectExec(t["store_returns"],
                     [col("sr_returned_date_sk"), col("sr_store_sk"),
                      col("sr_return_amt"), col("sr_net_loss")])
    jr = broadcast_join(dt, sr, [col("d_date_sk")], [col("sr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    jr = broadcast_join(st, jr, [col("s_store_sk")], [col("sr_store_sk")], JoinType.INNER, build_is_left=True)
    s_ret = ProjectExec(
        jr,
        [col("s_store_name"), _d8(dz()), _d8(col("sr_return_amt")),
         dz() - col("sr_net_loss")],
        ["id", "sales", "returns", "profit"],
    )
    store_rows = tag(UnionExec([s_sales, s_ret]), "store channel")

    # --- catalog: keyed by cp_catalog_page_id
    cp = ProjectExec(t["catalog_page"], [col("cp_catalog_page_sk"), col("cp_catalog_page_id")])
    cl = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_catalog_page_sk"),
                      col("cs_ext_sales_price"), col("cs_net_profit")])
    j = broadcast_join(dt, cl, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cp, j, [col("cp_catalog_page_sk")], [col("cs_catalog_page_sk")], JoinType.INNER, build_is_left=True)
    c_sales = ProjectExec(
        j,
        [col("cp_catalog_page_id"), _d8(col("cs_ext_sales_price")), _d8(dz()),
         _d8(col("cs_net_profit"))],
        ["id", "sales", "returns", "profit"],
    )
    cr = ProjectExec(t["catalog_returns"],
                     [col("cr_returned_date_sk"), col("cr_catalog_page_sk"),
                      col("cr_return_amount"), col("cr_net_loss")])
    jr = broadcast_join(dt, cr, [col("d_date_sk")], [col("cr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    jr = broadcast_join(cp, jr, [col("cp_catalog_page_sk")], [col("cr_catalog_page_sk")], JoinType.INNER, build_is_left=True)
    c_ret = ProjectExec(
        jr,
        [col("cp_catalog_page_id"), _d8(dz()), _d8(col("cr_return_amount")),
         dz() - col("cr_net_loss")],
        ["id", "sales", "returns", "profit"],
    )
    cat_rows = tag(UnionExec([c_sales, c_ret]), "catalog channel")

    # --- web: keyed by web_name; returns recover the site via the
    # (item, order) join back to web_sales (the spec's LEFT JOIN whose
    # null-site rows the web_site inner join then drops)
    wsit = ProjectExec(t["web_site"], [col("web_site_sk"), col("web_name")])
    wl = ProjectExec(t["web_sales"],
                     [col("ws_sold_date_sk"), col("ws_web_site_sk"),
                      col("ws_ext_sales_price"), col("ws_net_profit")])
    j = broadcast_join(dt, wl, [col("d_date_sk")], [col("ws_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(wsit, j, [col("web_site_sk")], [col("ws_web_site_sk")], JoinType.INNER, build_is_left=True)
    w_sales = ProjectExec(
        j,
        [col("web_name"), _d8(col("ws_ext_sales_price")), _d8(dz()),
         _d8(col("ws_net_profit"))],
        ["id", "sales", "returns", "profit"],
    )
    wr = ProjectExec(t["web_returns"],
                     [col("wr_returned_date_sk"), col("wr_item_sk"),
                      col("wr_order_number"), col("wr_return_amt"), col("wr_net_loss")])
    jr = broadcast_join(dt, wr, [col("d_date_sk")], [col("wr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    ws_keys = ProjectExec(t["web_sales"],
                          [col("ws_item_sk"), col("ws_order_number"), col("ws_web_site_sk")])
    jr = shuffle_join(jr, ws_keys,
                      [col("wr_item_sk"), col("wr_order_number")],
                      [col("ws_item_sk"), col("ws_order_number")],
                      JoinType.INNER, n_parts, build_left=False)
    jr = broadcast_join(wsit, jr, [col("web_site_sk")], [col("ws_web_site_sk")], JoinType.INNER, build_is_left=True)
    w_ret = ProjectExec(
        jr,
        [col("web_name"), _d8(dz()), _d8(col("wr_return_amt")),
         dz() - col("wr_net_loss")],
        ["id", "sales", "returns", "profit"],
    )
    web_rows = tag(UnionExec([w_sales, w_ret]), "web channel")

    return _channel_report_tail(
        UnionExec([store_rows, cat_rows, web_rows]), n_parts, DataType.string(16)
    )


def q77(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Per-location channel totals over a 30-day window: each channel
    aggregates sales and returns SEPARATELY, then outer-joins them
    (catalog's ungrouped returns total rides a scalar subquery, the
    reference's SparkScalarSubqueryWrapperExpr seam)."""
    import datetime

    from ..tpch.queries import scalar_subquery_row

    lo, hi = datetime.date(2000, 8, 3), datetime.date(2000, 9, 1)
    dt = _date_window(t, lo, hi)

    def agg_by(plan, key, sums, names):
        return two_stage_agg(
            plan, [GroupingExpr(col(key), key)],
            [AggFunction("sum", e, n) for e, n in zip(sums, names)],
            n_parts,
        )

    def tag(plan, channel, idc, sales, returns, profit):
        return ProjectExec(
            plan,
            [lit(channel, DataType.string(16)), col(idc), sales, returns, profit],
            ["channel", "id", "sales", "returns", "profit"],
        )

    # --- store
    st = ProjectExec(t["store"], [col("s_store_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"),
                      col("ss_ext_sales_price"), col("ss_net_profit")])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    ss_agg = agg_by(j, "s_store_sk", [col("ss_ext_sales_price"), col("ss_net_profit")],
                    ["sales", "profit"])
    sret = ProjectExec(t["store_returns"],
                       [col("sr_returned_date_sk"), col("sr_store_sk"),
                        col("sr_return_amt"), col("sr_net_loss")])
    jr = broadcast_join(dt, sret, [col("d_date_sk")], [col("sr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    jr = broadcast_join(st, jr, [col("s_store_sk")], [col("sr_store_sk")], JoinType.INNER, build_is_left=True)
    jr = ProjectExec(jr, [col("s_store_sk").alias("r_store_sk"),
                          col("sr_return_amt"), col("sr_net_loss")])
    sr_agg = agg_by(jr, "r_store_sk", [col("sr_return_amt"), col("sr_net_loss")],
                    ["returns", "profit_loss"])
    sj = broadcast_join(sr_agg, ss_agg, [col("r_store_sk")], [col("s_store_sk")],
                        JoinType.LEFT, build_is_left=False)
    store_rows = tag(
        sj, "store channel", "s_store_sk",
        _d8(col("sales")), _coalesce0(col("returns")),
        _d8(col("profit")) - _coalesce0(col("profit_loss")),
    )

    # --- catalog (returns total is ungrouped: scalar subquery x2)
    cl = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_call_center_sk"),
                      col("cs_ext_sales_price"), col("cs_net_profit")])
    j = broadcast_join(dt, cl, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    cs_agg = agg_by(j, "cs_call_center_sk",
                    [col("cs_ext_sales_price"), col("cs_net_profit")],
                    ["sales", "profit"])
    cret = ProjectExec(t["catalog_returns"],
                       [col("cr_returned_date_sk"), col("cr_return_amount"),
                        col("cr_net_loss")])
    jr = broadcast_join(dt, cret, [col("d_date_sk")], [col("cr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    cr_tot = two_stage_agg(
        jr, [],
        [AggFunction("sum", col("cr_return_amount"), "returns"),
         AggFunction("sum", col("cr_net_loss"), "profit_loss")],
        n_parts,
    )
    ret_lit, loss_lit = scalar_subquery_row(cr_tot, ["returns", "profit_loss"])
    cat_rows = tag(
        cs_agg, "catalog channel", "cs_call_center_sk",
        _d8(col("sales")), _coalesce0(ret_lit),
        _d8(col("profit")) - _coalesce0(loss_lit),
    )

    # --- web
    wl = ProjectExec(t["web_sales"],
                     [col("ws_sold_date_sk"), col("ws_web_page_sk"),
                      col("ws_ext_sales_price"), col("ws_net_profit")])
    j = broadcast_join(dt, wl, [col("d_date_sk")], [col("ws_sold_date_sk")], JoinType.INNER, build_is_left=True)
    ws_agg = agg_by(j, "ws_web_page_sk",
                    [col("ws_ext_sales_price"), col("ws_net_profit")],
                    ["sales", "profit"])
    wret = ProjectExec(t["web_returns"],
                       [col("wr_returned_date_sk"), col("wr_web_page_sk"),
                        col("wr_return_amt"), col("wr_net_loss")])
    jr = broadcast_join(dt, wret, [col("d_date_sk")], [col("wr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    wr_agg = agg_by(jr, "wr_web_page_sk", [col("wr_return_amt"), col("wr_net_loss")],
                    ["returns", "profit_loss"])
    wj = broadcast_join(wr_agg, ws_agg, [col("wr_web_page_sk")], [col("ws_web_page_sk")],
                        JoinType.LEFT, build_is_left=False)
    web_rows = tag(
        wj, "web channel", "ws_web_page_sk",
        _d8(col("sales")), _coalesce0(col("returns")),
        _d8(col("profit")) - _coalesce0(col("profit_loss")),
    )

    return _channel_report_tail(
        UnionExec([store_rows, cat_rows, web_rows]), n_parts, DataType.int64()
    )


def q80(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Per-item channel totals net of returns: line-level LEFT joins
    sales->returns on the (item, ticket/order) composite key, with
    date window + i_current_price > 50 + promo filters.
    (Deviation: the promo predicate is p_channel_email = 'N'; this
    datagen carries no p_channel_tv column.)"""
    import datetime

    lo, hi = datetime.date(2000, 8, 3), datetime.date(2000, 9, 1)
    dt = _date_window(t, lo, hi)
    it = FilterExec(t["item"], col("i_current_price") > lit("50", _DEC72))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id")])
    pr = FilterExec(t["promotion"], col("p_channel_email") == lit("N"))
    pr_p = ProjectExec(pr, [col("p_promo_sk")])

    def channel(sales, ret, skeys, rkeys, date_c, item_c, promo_c, price_c,
                profit_c, ramt_c, rloss_c, channel_name):
        j = broadcast_join(dt, sales, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        j = broadcast_join(it_p, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
        j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col(promo_c)], JoinType.INNER, build_is_left=True)
        j = shuffle_join(j, ret, [col(k) for k in skeys], [col(k) for k in rkeys],
                         JoinType.LEFT, n_parts, build_left=False)
        return ProjectExec(
            j,
            [lit(channel_name, DataType.string(16)), col("i_item_id"),
             _d8(col(price_c)), _coalesce0(col(ramt_c)),
             _d8(col(profit_c)) - _coalesce0(col(rloss_c))],
            ["channel", "id", "sales", "returns", "profit"],
        )

    store_rows = channel(
        ProjectExec(t["store_sales"],
                    [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_promo_sk"),
                     col("ss_ticket_number"), col("ss_ext_sales_price"),
                     col("ss_net_profit")]),
        ProjectExec(t["store_returns"],
                    [col("sr_item_sk"), col("sr_ticket_number"),
                     col("sr_return_amt"), col("sr_net_loss")]),
        ["ss_item_sk", "ss_ticket_number"], ["sr_item_sk", "sr_ticket_number"],
        "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
        "ss_ext_sales_price", "ss_net_profit", "sr_return_amt", "sr_net_loss",
        "store channel",
    )
    cat_rows = channel(
        ProjectExec(t["catalog_sales"],
                    [col("cs_sold_date_sk"), col("cs_item_sk"), col("cs_promo_sk"),
                     col("cs_order_number"), col("cs_ext_sales_price"),
                     col("cs_net_profit")]),
        ProjectExec(t["catalog_returns"],
                    [col("cr_item_sk"), col("cr_order_number"),
                     col("cr_return_amount"), col("cr_net_loss")]),
        ["cs_item_sk", "cs_order_number"], ["cr_item_sk", "cr_order_number"],
        "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
        "cs_ext_sales_price", "cs_net_profit", "cr_return_amount", "cr_net_loss",
        "catalog channel",
    )
    web_rows = channel(
        ProjectExec(t["web_sales"],
                    [col("ws_sold_date_sk"), col("ws_item_sk"), col("ws_promo_sk"),
                     col("ws_order_number"), col("ws_ext_sales_price"),
                     col("ws_net_profit")]),
        ProjectExec(t["web_returns"],
                    [col("wr_item_sk"), col("wr_order_number"),
                     col("wr_return_amt"), col("wr_net_loss")]),
        ["ws_item_sk", "ws_order_number"], ["wr_item_sk", "wr_order_number"],
        "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
        "ws_ext_sales_price", "ws_net_profit", "wr_return_amt", "wr_net_loss",
        "web channel",
    )
    return _channel_report_tail(
        UnionExec([store_rows, cat_rows, web_rows]), n_parts, DataType.string(16)
    )



# ------------------------------------------- distinct-count EXISTS


def _multi_wh_orders(t, n_parts, fact, order_c, wh_c):
    """Orders whose lines span >= 2 distinct warehouses — the exact
    rewrite of the spec's EXISTS (same order, different warehouse)
    self-join: a line qualifies iff its order's distinct-warehouse set
    has another member, which is order-level."""
    pairs = two_stage_agg(
        ProjectExec(t[fact], [col(order_c), col(wh_c)]),
        [GroupingExpr(col(order_c), order_c), GroupingExpr(col(wh_c), wh_c)],
        [],
        n_parts,
    )
    per_order = two_stage_agg(
        pairs, [GroupingExpr(col(order_c), order_c)],
        [AggFunction("count_star", None, "wh_cnt")],
        n_parts,
    )
    hot = FilterExec(per_order, col("wh_cnt") > lit(1, DataType.int64()))
    return ProjectExec(hot, [col(order_c)])


def _ship_report_tail(rows, n_parts, order_c, ship_c, profit_c):
    """count(DISTINCT order) + sums in one engine plan: group by order
    first (partial sums per order), then a global count_star/sum/sum —
    the group count IS the distinct count."""
    per_order = two_stage_agg(
        rows, [GroupingExpr(col(order_c), order_c)],
        [AggFunction("sum", col(ship_c), "s1"),
         AggFunction("sum", col(profit_c), "p1")],
        n_parts,
    )
    return two_stage_agg(
        per_order, [],
        [AggFunction("count_star", None, "order_count"),
         AggFunction("sum", col("s1"), "total_shipping_cost"),
         AggFunction("sum", col("p1"), "total_net_profit")],
        n_parts,
    )


def _q94_shape(t, n_parts, returns_join):
    """q94/q95 shared pipeline: filtered web lines restricted to
    multi-warehouse orders, then a semi (returned) or anti
    (never-returned) join against web_returns."""
    import datetime

    dt = _date_window(t, datetime.date(1999, 2, 1), datetime.date(1999, 12, 31))
    ca = FilterExec(t["customer_address"], col("ca_state") == lit("TN"))
    ca_p = ProjectExec(ca, [col("ca_address_sk")])
    site = FilterExec(t["web_site"], col("web_company_name") == lit("pri"))
    site_p = ProjectExec(site, [col("web_site_sk")])
    ws1 = ProjectExec(t["web_sales"],
                      [col("ws_ship_date_sk"), col("ws_ship_addr_sk"),
                       col("ws_web_site_sk"), col("ws_order_number"),
                       col("ws_ext_ship_cost"), col("ws_net_profit")])
    j = broadcast_join(dt, ws1, [col("d_date_sk")], [col("ws_ship_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca_p, j, [col("ca_address_sk")], [col("ws_ship_addr_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(site_p, j, [col("web_site_sk")], [col("ws_web_site_sk")], JoinType.INNER, build_is_left=True)
    hot = _multi_wh_orders(t, n_parts, "web_sales", "ws_order_number", "ws_warehouse_sk")
    j = broadcast_join(hot, j, [col("ws_order_number")], [col("ws_order_number")],
                       JoinType.LEFT_SEMI, build_is_left=False)
    wr = ProjectExec(t["web_returns"], [col("wr_order_number")])
    j = broadcast_join(wr, j, [col("wr_order_number")], [col("ws_order_number")],
                       returns_join, build_is_left=False)
    return _ship_report_tail(j, n_parts, "ws_order_number",
                             "ws_ext_ship_cost", "ws_net_profit")


def q94(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web orders shipped from >1 warehouse with no returns: 11-month
    ship window, TN ship address, 'pri' site; count(DISTINCT order) +
    cost/profit totals."""
    return _q94_shape(t, n_parts, JoinType.LEFT_ANTI)


def q95(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q94's RETURNED twin: multi-warehouse web orders that DO have a
    return (both IN-subqueries range over the multi-warehouse set)."""
    return _q94_shape(t, n_parts, JoinType.LEFT_SEMI)


def q16(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q94's catalog twin: multi-warehouse catalog orders with no
    catalog returns, GA ship address + Williamson County call centers."""
    import datetime

    dt = _date_window(t, datetime.date(2002, 2, 1), datetime.date(2002, 12, 31))
    ca = FilterExec(t["customer_address"], col("ca_state") == lit("GA"))
    ca_p = ProjectExec(ca, [col("ca_address_sk")])
    cc = FilterExec(t["call_center"], col("cc_county") == lit("Williamson County"))
    cc_p = ProjectExec(cc, [col("cc_call_center_sk")])
    cs1 = ProjectExec(t["catalog_sales"],
                      [col("cs_ship_date_sk"), col("cs_ship_addr_sk"),
                       col("cs_call_center_sk"), col("cs_order_number"),
                       col("cs_ext_ship_cost"), col("cs_net_profit")])
    j = broadcast_join(dt, cs1, [col("d_date_sk")], [col("cs_ship_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca_p, j, [col("ca_address_sk")], [col("cs_ship_addr_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cc_p, j, [col("cc_call_center_sk")], [col("cs_call_center_sk")], JoinType.INNER, build_is_left=True)
    hot = _multi_wh_orders(t, n_parts, "catalog_sales", "cs_order_number", "cs_warehouse_sk")
    j = broadcast_join(hot, j, [col("cs_order_number")], [col("cs_order_number")],
                       JoinType.LEFT_SEMI, build_is_left=False)
    cr = ProjectExec(t["catalog_returns"], [col("cr_order_number")])
    j = broadcast_join(cr, j, [col("cr_order_number")], [col("cs_order_number")],
                       JoinType.LEFT_ANTI, build_is_left=False)
    return _ship_report_tail(j, n_parts, "cs_order_number",
                             "cs_ext_ship_cost", "cs_net_profit")


# ------------------------------------------- year-over-year customers


def _year_total(t, n_parts, *, fact, date_c, cust_c, fact_cols, measure,
                year, names=False):
    """Per-customer yearly total of ``measure`` over one channel — the
    q74/q11 year_total CTE for a single (channel, year) slice."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(year))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    fc = ProjectExec(t[fact], [col(date_c), col(cust_c)] + [col(c) for c in fact_cols])
    cust_cols = [col("c_customer_sk")] + (
        [col("c_customer_id"), col("c_first_name"), col("c_last_name"),
         col("c_preferred_cust_flag")] if names else []
    )
    cu = ProjectExec(t["customer"], cust_cols)
    j = broadcast_join(dt_p, fc, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col(cust_c)], JoinType.INNER, build_is_left=True)
    groups = [GroupingExpr(col("c_customer_sk"), "c_customer_sk")] + (
        [GroupingExpr(col(c), c) for c in
         ("c_customer_id", "c_first_name", "c_last_name", "c_preferred_cust_flag")]
        if names else []
    )
    return two_stage_agg(j, groups, [AggFunction("sum", measure, "year_total")], n_parts)


def _yoy_customer(t, n_parts, *, store_measure, store_cols, web_measure,
                  web_cols, y1, y2, out_cols):
    """q74/q11 shape: join the four (channel, year) totals per customer,
    keep rows whose web growth ratio beats the store growth ratio."""
    f64 = DataType.float64()

    def slice_(fact, date_c, cust_c, cols, measure, year, alias, names=False):
        yt = _year_total(t, n_parts, fact=fact, date_c=date_c, cust_c=cust_c,
                         fact_cols=cols, measure=measure, year=year, names=names)
        keep = [col("c_customer_sk").alias(f"sk_{alias}"),
                col("year_total").alias(alias)]
        if names:
            keep += [col(c) for c in
                     ("c_customer_id", "c_first_name", "c_last_name",
                      "c_preferred_cust_flag")]
        return ProjectExec(yt, keep)

    s1 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk",
                store_cols, store_measure, y1, "s1")
    s2 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk",
                store_cols, store_measure, y2, "s2", names=True)
    w1 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                web_cols, web_measure, y1, "w1")
    w2 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                web_cols, web_measure, y2, "w2")
    j = broadcast_join(s1, s2, [col("sk_s1")], [col("sk_s2")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(w1, j, [col("sk_w1")], [col("sk_s2")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(w2, j, [col("sk_w2")], [col("sk_s2")], JoinType.INNER, build_is_left=True)
    s1f, s2f = col("s1").cast(f64), col("s2").cast(f64)
    w1f, w2f = col("w1").cast(f64), col("w2").cast(f64)
    f = FilterExec(
        j,
        (s1f > lit(0.0)) & (w1f > lit(0.0)) & ((w2f / w1f) > (s2f / s1f)),
    )
    proj = ProjectExec(f, [col(c) for c in out_cols])
    return single_sorted(proj, [SortField(col(out_cols[0]))], fetch=100)


def q74(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Customers whose web net-paid grew faster than store net-paid
    1999 -> 2000 (the four-way year_total self-join)."""
    return _yoy_customer(
        t, n_parts,
        store_measure=col("ss_net_paid"), store_cols=["ss_net_paid"],
        web_measure=col("ws_net_paid"), web_cols=["ws_net_paid"],
        y1=1999, y2=2000,
        out_cols=["c_customer_id", "c_first_name", "c_last_name"],
    )


def q11(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q74's list-price twin (measure = ext_list_price - ext_discount),
    2000 -> 2001, reporting the preferred-customer flag."""
    return _yoy_customer(
        t, n_parts,
        store_measure=col("ss_ext_list_price") - col("ss_ext_discount_amt"),
        store_cols=["ss_ext_list_price", "ss_ext_discount_amt"],
        web_measure=col("ws_ext_list_price") - col("ws_ext_discount_amt"),
        web_cols=["ws_ext_list_price", "ws_ext_discount_amt"],
        y1=2000, y2=2001,
        out_cols=["c_customer_id", "c_preferred_cust_flag",
                  "c_first_name", "c_last_name"],
    )



# ------------------------------------------- q23 frequent/best CTEs


def _q23_frequent_items(t, n_parts):
    """Items appearing > 4 times in a (item, month) sales cell across
    1998-2002.  (Deviation: the spec's cell is (item, d_date); this
    datagen's uniform item draws never repeat an item 4x in one DAY at
    test scales, so the cell is monthly — same CTE shape:
    join -> group -> HAVING -> DISTINCT -> semi-join.)"""
    from ..exprs.ir import func

    dt = ProjectExec(t["date_dim"],
                     [col("d_date_sk"), col("d_year"), col("d_moy")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_desc")])
    sl = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk")])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(
        j,
        [col("i_item_sk"),
         func("substring", col("i_item_desc"), lit(1), lit(30)).alias("itemdesc"),
         (col("d_year") * lit(12) + col("d_moy")).alias("cell")],
    )
    cells = two_stage_agg(
        proj,
        [GroupingExpr(col("i_item_sk"), "i_item_sk"),
         GroupingExpr(col("itemdesc"), "itemdesc"),
         GroupingExpr(col("cell"), "cell")],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    hot = FilterExec(cells, col("cnt") > lit(4, DataType.int64()))
    distinct = two_stage_agg(
        ProjectExec(hot, [col("i_item_sk")]),
        [GroupingExpr(col("i_item_sk"), "i_item_sk")], [], n_parts,
    )
    return distinct


def _q23_best_customers(t, n_parts):
    """Customers whose lifetime store spend beats 50% of the max.
    (Deviation: the spec's 95% cut keeps exactly one customer under
    this datagen's uniform spend totals, emptying the final join; 50%
    keeps the HAVING > fraction-of-max scalar-subquery shape with a
    populated result.)"""
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    sl = ProjectExec(
        t["store_sales"],
        [col("ss_customer_sk"),
         (col("ss_quantity").cast(DataType.int64()) * col("ss_sales_price"))
         .alias("spend")],
    )
    per_cust = two_stage_agg(
        sl, [GroupingExpr(col("ss_customer_sk"), "ss_customer_sk")],
        [AggFunction("sum", col("spend"), "csales")],
        n_parts,
    )
    cmax = two_stage_agg(
        per_cust, [], [AggFunction("max", col("csales"), "tpcds_cmax")], n_parts
    )
    max_lit = scalar_subquery(cmax, "tpcds_cmax")
    best = FilterExec(
        per_cust,
        col("csales").cast(f64) > lit(0.5) * max_lit.cast(f64),
    )
    return ProjectExec(best, [col("ss_customer_sk")])


def _q23_month_sales(t, n_parts, fact, date_c, item_c, cust_c, qty_c, price_c,
                     hot_items, best_cust, names):
    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(5)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    fc = ProjectExec(t[fact], [col(date_c), col(item_c), col(cust_c),
                               col(qty_c), col(price_c)])
    j = broadcast_join(dt_p, fc, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hot_items, j, [col("i_item_sk")], [col(item_c)],
                       JoinType.LEFT_SEMI, build_is_left=False)
    j = broadcast_join(best_cust, j, [col("ss_customer_sk")], [col(cust_c)],
                       JoinType.LEFT_SEMI, build_is_left=False)
    cols = [(col(qty_c).cast(DataType.int64()) * col(price_c)).alias("sales")]
    if names:
        cu = ProjectExec(t["customer"],
                         [col("c_customer_sk"), col("c_last_name"), col("c_first_name")])
        j = broadcast_join(cu, j, [col("c_customer_sk")], [col(cust_c)], JoinType.INNER, build_is_left=True)
        cols = [col("c_last_name"), col("c_first_name")] + cols
    return ProjectExec(j, cols)


def _q23_rows(t, n_parts, names):
    # the CTE subplans are built ONCE and shared by both union branches
    # (node sharing is safe: each broadcast_join wraps its own
    # exchange, and _q23_best_customers runs its scalar subquery
    # eagerly — building it twice would double that work)
    hot = _q23_frequent_items(t, n_parts)
    best = _q23_best_customers(t, n_parts)
    return UnionExec([
        _q23_month_sales(t, n_parts, "catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_bill_customer_sk", "cs_quantity",
                         "cs_list_price", hot, best, names=names),
        _q23_month_sales(t, n_parts, "web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_bill_customer_sk", "ws_quantity",
                         "ws_list_price", hot, best, names=names),
    ])


def q23a(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """May-2000 catalog+web spend of best customers on frequent items
    (single global total)."""
    rows = _q23_rows(t, n_parts, names=False)
    return two_stage_agg(rows, [], [AggFunction("sum", col("sales"), "sum_sales")],
                         n_parts)


def q23b(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q23a grouped by customer name, top 100."""
    rows = _q23_rows(t, n_parts, names=True)
    agg = two_stage_agg(
        rows,
        [GroupingExpr(col("c_last_name"), "c_last_name"),
         GroupingExpr(col("c_first_name"), "c_first_name")],
        [AggFunction("sum", col("sales"), "sales")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("sales"), ascending=False),
         SortField(col("c_last_name")), SortField(col("c_first_name"))],
        fetch=100,
    )



# ------------------------------------------- q24 returned-sales netpaid


def _q24_ssales(t, n_parts):
    """ssales CTE: returned store lines (ticket,item join) x market-8
    stores x customers living in the store's county, grouped netpaid
    per (last, first, store_name, color).  (Deviation: the customer-
    near-store predicate is ca_county = s_county; this datagen's
    ca_zip carries a -nnnn suffix so the spec's zip equality never
    matches.)"""
    sl = ProjectExec(t["store_sales"],
                     [col("ss_item_sk"), col("ss_ticket_number"),
                      col("ss_store_sk"), col("ss_customer_sk"),
                      col("ss_net_paid")])
    sr = ProjectExec(t["store_returns"],
                     [col("sr_item_sk"), col("sr_ticket_number")])
    j = shuffle_join(sl, sr,
                     [col("ss_item_sk"), col("ss_ticket_number")],
                     [col("sr_item_sk"), col("sr_ticket_number")],
                     JoinType.INNER, n_parts, build_left=False)
    st = FilterExec(t["store"], col("s_market_id") == lit(8))
    st_p = ProjectExec(st, [col("s_store_sk"), col("s_store_name"), col("s_county")])
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    cu = ProjectExec(t["customer"],
                     [col("c_customer_sk"), col("c_last_name"),
                      col("c_first_name"), col("c_current_addr_sk")])
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_county")])
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    j = FilterExec(j, col("ca_county") == col("s_county"))
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_color")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    return two_stage_agg(
        j,
        [GroupingExpr(col("c_last_name"), "c_last_name"),
         GroupingExpr(col("c_first_name"), "c_first_name"),
         GroupingExpr(col("s_store_name"), "s_store_name"),
         GroupingExpr(col("i_color"), "i_color")],
        [AggFunction("sum", col("ss_net_paid"), "netpaid")],
        n_parts,
    )


def _q24(t, n_parts, color):
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    avg_all = two_stage_agg(
        _q24_ssales(t, n_parts), [],
        [AggFunction("avg", col("netpaid"), "avg_netpaid")], n_parts,
    )
    avg_lit = scalar_subquery(avg_all, "avg_netpaid")
    cells = FilterExec(_q24_ssales(t, n_parts), col("i_color") == lit(color))
    agg = two_stage_agg(
        cells,
        [GroupingExpr(col("c_last_name"), "c_last_name"),
         GroupingExpr(col("c_first_name"), "c_first_name"),
         GroupingExpr(col("s_store_name"), "s_store_name")],
        [AggFunction("sum", col("netpaid"), "paid")],
        n_parts,
    )
    f = FilterExec(agg, col("paid").cast(f64) > lit(0.05) * avg_lit.cast(f64))
    return single_sorted(
        f,
        [SortField(col("c_last_name")), SortField(col("c_first_name")),
         SortField(col("s_store_name"))],
    )


def q24a(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Peach-colored returned-sales netpaid above 5% of the all-color
    average."""
    return _q24(t, n_parts, "peach")


def q24b(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q24a for saddle."""
    return _q24(t, n_parts, "saddle")



# ------------------------------------------- cross-channel item YoY


def _q75_channel(t, n_parts, fact, date_c, item_c, qty_c, amt_c, rtab,
                 r_item_c, r_key2_c, key2_c, r_qty_c, r_amt_c, category):
    """One q75 channel: line-level LEFT join sales->returns, item
    category slice, rows (d_year, ids, qty_net, amt_net)."""
    dt = ProjectExec(t["date_dim"], [col("d_date_sk"), col("d_year")])
    it = FilterExec(t["item"], col("i_category") == lit(category))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_class_id"),
                            col("i_category_id"), col("i_manufact_id")])
    sl = ProjectExec(t[fact], [col(date_c), col(item_c), col(key2_c),
                               col(qty_c), col(amt_c)])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
    ret = ProjectExec(t[rtab], [col(r_item_c), col(r_key2_c), col(r_qty_c), col(r_amt_c)])
    j = shuffle_join(j, ret, [col(item_c), col(key2_c)],
                     [col(r_item_c), col(r_key2_c)],
                     JoinType.LEFT, n_parts, build_left=False)
    from ..exprs.ir import Case

    i64 = DataType.int64()
    qty_net = (col(qty_c).cast(i64)
               - Case([(col(r_qty_c).is_not_null(), col(r_qty_c).cast(i64))],
                      lit(0, i64)))
    amt_net = _d8(col(amt_c)) - _coalesce0(col(r_amt_c))
    return ProjectExec(
        j,
        [col("d_year"), col("i_brand_id"), col("i_class_id"),
         col("i_category_id"), col("i_manufact_id"),
         qty_net.alias("qty"), amt_net.alias("amt")],
    )


def q75(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Items whose current-year unit sales dropped below 90% of the
    prior year, net of returns, across all three channels."""
    f64 = DataType.float64()
    rows = UnionExec([
        _q75_channel(t, n_parts, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                     "ss_quantity", "ss_ext_sales_price", "store_returns",
                     "sr_item_sk", "sr_ticket_number", "ss_ticket_number",
                     "sr_return_quantity", "sr_return_amt", "Books"),
        _q75_channel(t, n_parts, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_quantity", "cs_ext_sales_price", "catalog_returns",
                     "cr_item_sk", "cr_order_number", "cs_order_number",
                     "cr_return_quantity", "cr_return_amount", "Books"),
        _q75_channel(t, n_parts, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_quantity", "ws_ext_sales_price", "web_returns",
                     "wr_item_sk", "wr_order_number", "ws_order_number",
                     "wr_return_quantity", "wr_return_amt", "Books"),
    ])
    agg = two_stage_agg(
        rows,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "i_brand_id"),
         GroupingExpr(col("i_class_id"), "i_class_id"),
         GroupingExpr(col("i_category_id"), "i_category_id"),
         GroupingExpr(col("i_manufact_id"), "i_manufact_id")],
        [AggFunction("sum", col("qty"), "sales_cnt"),
         AggFunction("sum", col("amt"), "sales_amt")],
        n_parts,
    )
    ids = ["i_brand_id", "i_class_id", "i_category_id", "i_manufact_id"]
    curr = FilterExec(agg, col("d_year") == lit(2002))
    curr = ProjectExec(curr, [col(c) for c in ids]
                       + [col("sales_cnt").alias("curr_cnt"),
                          col("sales_amt").alias("curr_amt")])
    prev = FilterExec(agg, col("d_year") == lit(2001))
    prev = ProjectExec(prev, [col(c).alias(f"p_{c}") for c in ids]
                       + [col("sales_cnt").alias("prev_cnt"),
                          col("sales_amt").alias("prev_amt")])
    j = shuffle_join(curr, prev, [col(c) for c in ids],
                     [col(f"p_{c}") for c in ids],
                     JoinType.INNER, n_parts, build_left=False)
    f = FilterExec(
        j,
        (col("prev_cnt").cast(f64) > lit(0.0))
        & ((col("curr_cnt").cast(f64) / col("prev_cnt").cast(f64)) < lit(0.9)),
    )
    proj = ProjectExec(
        f,
        [lit(2001).alias("prev_year"), lit(2002).alias("year"),
         col("i_brand_id"), col("i_class_id"), col("i_category_id"),
         col("i_manufact_id"),
         (col("curr_cnt") - col("prev_cnt")).alias("sales_cnt_diff"),
         (col("curr_amt") - col("prev_amt")).alias("sales_amt_diff")],
    )
    return single_sorted(
        proj,
        [SortField(col("sales_cnt_diff")), SortField(col("sales_amt_diff"))],
        fetch=100,
    )


def _q78_channel(t, n_parts, fact, date_c, item_c, cust_c, qty_c, wc_c, sp_c,
                 rtab, r_item_c, r_key2_c, key2_c, prefix):
    """One q78 channel: never-returned lines of year 2000 grouped per
    (item, customer)."""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t[fact], [col(date_c), col(item_c), col(cust_c),
                               col(key2_c), col(qty_c), col(wc_c), col(sp_c)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    ret = ProjectExec(t[rtab], [col(r_item_c), col(r_key2_c)])
    j = shuffle_join(j, ret, [col(item_c), col(key2_c)],
                     [col(r_item_c), col(r_key2_c)],
                     JoinType.LEFT_ANTI, n_parts, build_left=False)
    i64 = DataType.int64()
    return two_stage_agg(
        ProjectExec(j, [col(item_c).alias(f"{prefix}_item_sk"),
                        col(cust_c).alias(f"{prefix}_customer_sk"),
                        col(qty_c).cast(i64).alias("q"),
                        col(wc_c), col(sp_c)]),
        [GroupingExpr(col(f"{prefix}_item_sk"), f"{prefix}_item_sk"),
         GroupingExpr(col(f"{prefix}_customer_sk"), f"{prefix}_customer_sk")],
        [AggFunction("sum", col("q"), f"{prefix}_qty"),
         AggFunction("sum", col(wc_c), f"{prefix}_wc"),
         AggFunction("sum", col(sp_c), f"{prefix}_sp")],
        n_parts,
    )


def q78(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Store-channel loyalty per (item, customer) vs other channels:
    never-returned year-2000 lines, store sums LEFT-joined with web
    and catalog sums, keeping pairs with any cross-channel activity."""
    from ..exprs.ir import Case

    f64 = DataType.float64()
    i64 = DataType.int64()
    ss = _q78_channel(t, n_parts, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                      "ss_customer_sk", "ss_quantity", "ss_wholesale_cost",
                      "ss_sales_price", "store_returns", "sr_item_sk",
                      "sr_ticket_number", "ss_ticket_number", "ss")
    ws = _q78_channel(t, n_parts, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                      "ws_bill_customer_sk", "ws_quantity", "ws_wholesale_cost",
                      "ws_sales_price", "web_returns", "wr_item_sk",
                      "wr_order_number", "ws_order_number", "ws")
    cs = _q78_channel(t, n_parts, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                      "cs_bill_customer_sk", "cs_quantity", "cs_wholesale_cost",
                      "cs_sales_price", "catalog_returns", "cr_item_sk",
                      "cr_order_number", "cs_order_number", "cs")
    j = shuffle_join(ss, ws, [col("ss_item_sk"), col("ss_customer_sk")],
                     [col("ws_item_sk"), col("ws_customer_sk")],
                     JoinType.LEFT, n_parts, build_left=False)
    j = shuffle_join(j, cs, [col("ss_item_sk"), col("ss_customer_sk")],
                     [col("cs_item_sk"), col("cs_customer_sk")],
                     JoinType.LEFT, n_parts, build_left=False)

    def czero(c):
        return Case([(c.is_not_null(), c)], lit(0, i64))

    f = FilterExec(j, (czero(col("ws_qty")) > lit(0, i64))
                   | (czero(col("cs_qty")) > lit(0, i64)))
    other = (czero(col("ws_qty")) + czero(col("cs_qty"))).cast(f64)
    den = Case([(other > lit(0.0), other)], lit(1.0))
    proj = ProjectExec(
        f,
        [col("ss_item_sk"), col("ss_customer_sk"),
         col("ss_qty"), col("ss_wc"), col("ss_sp"),
         (col("ss_qty").cast(f64) / den).alias("ratio"),
         (czero(col("ws_qty")) + czero(col("cs_qty"))).alias("other_chan_qty")],
    )
    return single_sorted(
        proj,
        [SortField(col("ss_qty"), ascending=False),
         SortField(col("ss_item_sk")), SortField(col("ss_customer_sk"))],
        fetch=100,
    )



# ------------------------------------------- cumulative-window pair


def _q51_cume(t, n_parts, fact, date_c, item_c, price_c, prefix):
    """Per-item daily cumulative sales of one channel in year 2000."""
    from ..ops import WindowExec, WindowFunction
    from ..parallel import HashPartitioning, NativeShuffleExchangeExec

    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_date")])
    sl = ProjectExec(t[fact], [col(date_c), col(item_c), col(price_c)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    daily = two_stage_agg(
        j,
        [GroupingExpr(col(item_c), f"{prefix}_item_sk"),
         GroupingExpr(col("d_date"), f"{prefix}_date")],
        [AggFunction("sum", col(price_c), "sales")],
        n_parts,
    )
    ex = NativeShuffleExchangeExec(daily, HashPartitioning([col(f"{prefix}_item_sk")], n_parts))
    from ..ops import SortExec

    srt = SortExec(ex, [SortField(col(f"{prefix}_item_sk")),
                        SortField(col(f"{prefix}_date"))])
    w = WindowExec(
        srt,
        [WindowFunction("sum", f"{prefix}_cume", col("sales"))],
        [col(f"{prefix}_item_sk")],
        [SortField(col(f"{prefix}_date"))],
    )
    return ProjectExec(w, [col(f"{prefix}_item_sk"), col(f"{prefix}_date"),
                           col(f"{prefix}_cume")])


def q51(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Items whose web cumulative sales overtake the store cumulative:
    two per-item running sums FULL-OUTER joined by (item, day), with
    running maxes carrying values across the join's null gaps."""
    from ..exprs.ir import Case
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import HashPartitioning, NativeShuffleExchangeExec

    web = _q51_cume(t, n_parts, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                    "ws_sales_price", "w")
    store = _q51_cume(t, n_parts, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                      "ss_sales_price", "s")
    j = shuffle_join(web, store, [col("w_item_sk"), col("w_date")],
                     [col("s_item_sk"), col("s_date")],
                     JoinType.FULL, n_parts, build_left=False)
    proj = ProjectExec(
        j,
        [Case([(col("w_item_sk").is_not_null(), col("w_item_sk"))],
              col("s_item_sk")).alias("item_sk"),
         Case([(col("w_date").is_not_null(), col("w_date"))],
              col("s_date")).alias("d_date"),
         col("w_cume"), col("s_cume")],
    )
    single = NativeShuffleExchangeExec(proj, HashPartitioning([col("item_sk")], n_parts))
    srt = SortExec(single, [SortField(col("item_sk")), SortField(col("d_date"))])
    w = WindowExec(
        srt,
        [WindowFunction("max", "web_cumulative", col("w_cume")),
         WindowFunction("max", "store_cumulative", col("s_cume"))],
        [col("item_sk")],
        [SortField(col("d_date"))],
    )
    f = FilterExec(w, col("web_cumulative") > col("store_cumulative"))
    out = ProjectExec(f, [col("item_sk"), col("d_date"), col("web_cumulative"),
                          col("store_cumulative")])
    return single_sorted(
        out, [SortField(col("item_sk")), SortField(col("d_date"))], fetch=100
    )


def q67(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """The 8-dimension ROLLUP x rank-within-category giant: store sales
    expanded over 9 rollup levels, top-100 ranked per category.
    (Deviation: i_item_id/s_store_name stand in for the spec's
    i_product_name/s_store_id, absent from this datagen.)"""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec, SortExec, WindowExec, WindowFunction
    from ..parallel import HashPartitioning, NativeShuffleExchangeExec

    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year"), col("d_qoy"), col("d_moy")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    it_p = ProjectExec(t["item"], [col("i_item_sk"), col("i_category"),
                                   col("i_class"), col("i_brand"), col("i_item_id")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"), col("ss_item_sk"),
                      col("ss_quantity"), col("ss_sales_price")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    sales = (col("ss_quantity").cast(DataType.int64()) * col("ss_sales_price")).alias("val")
    base = ProjectExec(
        j,
        [col("i_category"), col("i_class"), col("i_brand"), col("i_item_id"),
         col("d_year"), col("d_qoy"), col("d_moy"), col("s_store_name"), sales],
    )
    s16 = DataType.string(16)
    s32 = DataType.string(32)
    i32 = DataType.int32()
    dims = [("i_category", s16), ("i_class", s16), ("i_brand", s32),
            ("i_item_id", s16), ("d_year", i32), ("d_qoy", i32),
            ("d_moy", i32), ("s_store_name", s16)]
    projections = []
    for level in range(8, -1, -1):
        row = [col("val")]
        for k, (name, dt_) in enumerate(dims):
            row.append(col(name) if k < level else Lit(None, dt_))
        row.append(lit(8 - level))
        projections.append(row)
    expand = ExpandExec(base, projections,
                        ["val"] + [d[0] for d in dims] + ["g_id"])
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col(d[0]), d[0]) for d in dims]
        + [GroupingExpr(col("g_id"), "g_id")],
        [AggFunction("sum", col("val"), "sumsales")],
        n_parts,
    )
    ex = NativeShuffleExchangeExec(agg, HashPartitioning([col("i_category")], n_parts))
    srt = SortExec(ex, [SortField(col("i_category")),
                        SortField(col("sumsales"), ascending=False)])
    w = WindowExec(
        srt,
        [WindowFunction("rank", "rk")],
        [col("i_category")],
        [SortField(col("sumsales"), ascending=False)],
    )
    f = FilterExec(w, col("rk") <= lit(100, DataType.int64()))
    out = ProjectExec(f, [col(d[0]) for d in dims] + [col("g_id"), col("sumsales"), col("rk")])
    return single_sorted(
        out,
        [SortField(col("i_category")), SortField(col("rk")),
         SortField(col("sumsales"), ascending=False)],
        fetch=100,
    )



# ------------------------------------------- q14 cross-channel INTERSECT


_Q14_CHANNELS = [
    ("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_list_price"),
    ("catalog_sales", "cs_sold_date_sk", "cs_item_sk", "cs_quantity", "cs_list_price"),
    ("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_quantity", "ws_list_price"),
]


def _q14_cross_items(t, n_parts):
    """Items whose (brand, class, category) id-triple sells in ALL
    three channels 1998-2000 — the INTERSECT planned as Spark does:
    left-semi joins between the per-channel DISTINCT triple sets."""
    def triples(fact, date_c, item_c):
        dt = FilterExec(t["date_dim"],
                        (col("d_year") >= lit(1998)) & (col("d_year") <= lit(2000)))
        dt_p = ProjectExec(dt, [col("d_date_sk")])
        it = ProjectExec(t["item"], [col("i_item_sk"), col("i_brand_id"),
                                     col("i_class_id"), col("i_category_id")])
        sl = ProjectExec(t[fact], [col(date_c), col(item_c)])
        j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        j = broadcast_join(it, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
        return two_stage_agg(
            j,
            [GroupingExpr(col("i_brand_id"), "i_brand_id"),
             GroupingExpr(col("i_class_id"), "i_class_id"),
             GroupingExpr(col("i_category_id"), "i_category_id")],
            [], n_parts,
        )

    ss, cs, ws = (triples(f, d, i) for f, d, i, _, _ in _Q14_CHANNELS)
    keys = [col("i_brand_id"), col("i_class_id"), col("i_category_id")]
    inter = broadcast_join(cs, ss, keys, keys, JoinType.LEFT_SEMI, build_is_left=False)
    inter = broadcast_join(ws, inter, keys, keys, JoinType.LEFT_SEMI, build_is_left=False)
    items = ProjectExec(t["item"], [col("i_item_sk"), col("i_brand_id"),
                                    col("i_class_id"), col("i_category_id")])
    hot = broadcast_join(inter, items, keys, keys, JoinType.LEFT_SEMI,
                         build_is_left=False)
    return ProjectExec(hot, [col("i_item_sk")])


def _q14_avg_sales(t, n_parts):
    """avg(quantity*list_price) over all three channels 1998-2000."""
    branches = []
    for fact, date_c, item_c, q_c, p_c in _Q14_CHANNELS:
        dt = FilterExec(t["date_dim"],
                        (col("d_year") >= lit(1998)) & (col("d_year") <= lit(2000)))
        dt_p = ProjectExec(dt, [col("d_date_sk")])
        sl = ProjectExec(t[fact], [col(date_c), col(q_c), col(p_c)])
        j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        branches.append(ProjectExec(
            j,
            [(col(q_c).cast(DataType.int64()) * col(p_c)).alias("v")],
        ))
    return two_stage_agg(UnionExec(branches), [],
                         [AggFunction("avg", col("v"), "average_sales")], n_parts)


def _q14_channel_cells(t, n_parts, fact, date_c, item_c, q_c, p_c, cross,
                       avg_lit, year, moy=11):
    """One channel's November cells over cross_items with the
    above-average HAVING."""
    f64 = DataType.float64()
    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(year)) & (col("d_moy") == lit(moy)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_brand_id"),
                                 col("i_class_id"), col("i_category_id")])
    sl = ProjectExec(t[fact], [col(date_c), col(item_c), col(q_c), col(p_c)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cross, j, [col("i_item_sk")], [col(item_c)],
                       JoinType.LEFT_SEMI, build_is_left=False)
    j = broadcast_join(it, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(
        j,
        [col("i_brand_id"), col("i_class_id"), col("i_category_id"),
         (col(q_c).cast(DataType.int64()) * col(p_c)).alias("v")],
    )
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("i_brand_id"), "i_brand_id"),
         GroupingExpr(col("i_class_id"), "i_class_id"),
         GroupingExpr(col("i_category_id"), "i_category_id")],
        [AggFunction("sum", col("v"), "sales"),
         AggFunction("count_star", None, "number_sales")],
        n_parts,
    )
    return FilterExec(agg, col("sales").cast(f64) > avg_lit.cast(f64))


def q14a(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """November-2002 above-average sales of cross-channel items,
    ROLLUP(channel, brand, class, category).  (Deviation: the spec's
    d_week_seq/moy arithmetic is pinned to year 2002 / November.)"""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec
    from ..tpch.queries import scalar_subquery

    cross = _q14_cross_items(t, n_parts)
    avg_lit = scalar_subquery(_q14_avg_sales(t, n_parts), "average_sales")
    branches = []
    for (fact, date_c, item_c, q_c, p_c), name in zip(
        _Q14_CHANNELS, ("store", "catalog", "web")
    ):
        cells = _q14_channel_cells(t, n_parts, fact, date_c, item_c, q_c, p_c,
                                   cross, avg_lit, 2002)
        branches.append(ProjectExec(
            cells,
            [lit(name, DataType.string(16)), col("i_brand_id"),
             col("i_class_id"), col("i_category_id"), col("sales"),
             col("number_sales")],
            ["channel", "i_brand_id", "i_class_id", "i_category_id",
             "sales", "number_sales"],
        ))
    u = UnionExec(branches)
    s16 = DataType.string(16)
    i32 = DataType.int32()
    dims = [("channel", s16), ("i_brand_id", i32), ("i_class_id", i32),
            ("i_category_id", i32)]
    projections = []
    for level in range(4, -1, -1):
        row = [col("sales"), col("number_sales")]
        for k, (name, dt_) in enumerate(dims):
            row.append(col(name) if k < level else Lit(None, dt_))
        row.append(lit(4 - level))
        projections.append(row)
    expand = ExpandExec(u, projections,
                        ["sales", "number_sales"] + [d[0] for d in dims] + ["g_id"])
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col(d[0]), d[0]) for d in dims]
        + [GroupingExpr(col("g_id"), "g_id")],
        [AggFunction("sum", col("sales"), "sum_sales"),
         AggFunction("sum", col("number_sales"), "sum_number_sales")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("channel")), SortField(col("i_brand_id")),
         SortField(col("i_class_id")), SortField(col("i_category_id")),
         SortField(col("g_id"))],
        fetch=100,
    )


def q14b(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """This-November vs last-November store cells of cross-channel
    items, kept where sales grew."""
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    cross = _q14_cross_items(t, n_parts)
    avg_lit = scalar_subquery(_q14_avg_sales(t, n_parts), "average_sales")
    fact, date_c, item_c, q_c, p_c = _Q14_CHANNELS[0]
    ty = _q14_channel_cells(t, n_parts, fact, date_c, item_c, q_c, p_c,
                            cross, avg_lit, 2002)
    ly = _q14_channel_cells(t, n_parts, fact, date_c, item_c, q_c, p_c,
                            cross, avg_lit, 2001)
    ly = ProjectExec(ly, [col("i_brand_id").alias("l_brand_id"),
                          col("i_class_id").alias("l_class_id"),
                          col("i_category_id").alias("l_category_id"),
                          col("sales").alias("last_sales"),
                          col("number_sales").alias("last_number_sales")])
    j = shuffle_join(ty, ly,
                     [col("i_brand_id"), col("i_class_id"), col("i_category_id")],
                     [col("l_brand_id"), col("l_class_id"), col("l_category_id")],
                     JoinType.INNER, n_parts, build_left=False)
    f = FilterExec(j, col("sales").cast(f64) > col("last_sales").cast(f64))
    proj = ProjectExec(f, [col("i_brand_id"), col("i_class_id"),
                           col("i_category_id"), col("sales"),
                           col("number_sales"), col("last_sales"),
                           col("last_number_sales")])
    return single_sorted(
        proj,
        [SortField(col("i_brand_id")), SortField(col("i_class_id")),
         SortField(col("i_category_id"))],
        fetch=100,
    )



# ------------------------------------------- inventory / first-sale giants


def q72(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog lines promised from under-stocked warehouses: inventory
    snapshot of the SALE week has less on hand than the ordered
    quantity, ship lag > 5 days, divorced '>10000'-potential buyers."""
    hd = FilterExec(t["household_demographics"],
                    col("hd_buy_potential") == lit(">10000"))
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    cd = FilterExec(t["customer_demographics"],
                    col("cd_marital_status") == lit("D"))
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    d1 = ProjectExec(t["date_dim"],
                     [col("d_date_sk"), col("d_date"), col("d_week_seq")])
    d3 = ProjectExec(t["date_dim"],
                     [col("d_date_sk").alias("d3_date_sk"),
                      col("d_date").alias("d3_date")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_desc")])
    wh = ProjectExec(t["warehouse"], [col("w_warehouse_sk"), col("w_warehouse_name")])
    d2 = ProjectExec(t["date_dim"],
                     [col("d_date_sk").alias("d2_date_sk"),
                      col("d_week_seq").alias("d2_week_seq")])

    cs = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_ship_date_sk"),
                      col("cs_item_sk"), col("cs_bill_cdemo_sk"),
                      col("cs_bill_hdemo_sk"), col("cs_quantity")])
    j = broadcast_join(hd_p, cs, [col("hd_demo_sk")], [col("cs_bill_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cd_p, j, [col("cd_demo_sk")], [col("cs_bill_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(d1, j, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(d3, j, [col("d3_date_sk")], [col("cs_ship_date_sk")], JoinType.INNER, build_is_left=True)
    j = FilterExec(j, col("d3_date").cast(DataType.int64())
                   > (col("d_date").cast(DataType.int64()) + lit(5, DataType.int64())))
    inv = ProjectExec(t["inventory"],
                      [col("inv_date_sk"), col("inv_item_sk"),
                       col("inv_warehouse_sk"), col("inv_quantity_on_hand")])
    j = shuffle_join(j, inv, [col("cs_item_sk")], [col("inv_item_sk")],
                     JoinType.INNER, n_parts, build_left=True)
    j = broadcast_join(d2, j, [col("d2_date_sk")], [col("inv_date_sk")], JoinType.INNER, build_is_left=True)
    j = FilterExec(j, (col("d2_week_seq") == col("d_week_seq"))
                   & (col("inv_quantity_on_hand") < col("cs_quantity")))
    j = broadcast_join(it, j, [col("i_item_sk")], [col("cs_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col("inv_warehouse_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("w_warehouse_name"), "w_warehouse_name"),
         GroupingExpr(col("d_week_seq"), "d_week_seq")],
        [AggFunction("count_star", None, "no_promo")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("no_promo"), ascending=False),
         SortField(col("i_item_desc")), SortField(col("w_warehouse_name")),
         SortField(col("d_week_seq"))],
        fetch=100,
    )


def _q64_cross_sales(t, n_parts, year):
    """q64 cross_sales (reduced): returned store lines of cheap-color
    items, grouped per (item_id, store, zip, year) with cost sums.
    (Deviation: the spec's income-band/first-sale-date/address-pair
    chain is absent from this datagen; the self-join-across-years
    HAVING shape is preserved.)"""
    sl = ProjectExec(t["store_sales"],
                     [col("ss_item_sk"), col("ss_ticket_number"),
                      col("ss_store_sk"), col("ss_sold_date_sk"),
                      col("ss_wholesale_cost"), col("ss_list_price"),
                      col("ss_coupon_amt")])
    # year slice BEFORE the (item, ticket) shuffle join: q64 builds
    # this subplan twice (2001/2002), so shuffling the whole fact
    # table each time would double the largest exchange for nothing
    dt = FilterExec(t["date_dim"], col("d_year") == lit(year))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    sl = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    sr = ProjectExec(t["store_returns"],
                     [col("sr_item_sk"), col("sr_ticket_number")])
    j = shuffle_join(sl, sr, [col("ss_item_sk"), col("ss_ticket_number")],
                     [col("sr_item_sk"), col("sr_ticket_number")],
                     JoinType.INNER, n_parts, build_left=False)
    it = FilterExec(
        t["item"],
        col("i_color").isin(lit("purple"), lit("burlywood"), lit("indian"),
                            lit("spring"), lit("floral"), lit("medium"),
                            lit("peach"), lit("saddle"), lit("navy"), lit("slate")),
    )
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"), col("s_zip")])
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    return two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("s_store_name"), "s_store_name"),
         GroupingExpr(col("s_zip"), "s_zip")],
        [AggFunction("count_star", None, "cnt"),
         AggFunction("sum", col("ss_wholesale_cost"), "s1"),
         AggFunction("sum", col("ss_list_price"), "s2"),
         AggFunction("sum", col("ss_coupon_amt"), "s3")],
        n_parts,
    )


def q64(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Returned-item store sales joined with themselves across two
    years on (item, store, zip), kept where the later year repeats at
    most as often."""
    cs1 = _q64_cross_sales(t, n_parts, 2001)
    cs2 = _q64_cross_sales(t, n_parts, 2002)
    cs2 = ProjectExec(cs2, [col("i_item_id").alias("r_item_id"),
                            col("s_store_name").alias("r_store_name"),
                            col("s_zip").alias("r_zip"),
                            col("cnt").alias("cnt2"),
                            col("s1").alias("s1_2"),
                            col("s2").alias("s2_2"),
                            col("s3").alias("s3_2")])
    j = shuffle_join(cs1, cs2,
                     [col("i_item_id"), col("s_store_name"), col("s_zip")],
                     [col("r_item_id"), col("r_store_name"), col("r_zip")],
                     JoinType.INNER, n_parts, build_left=False)
    f = FilterExec(j, col("cnt2") <= col("cnt"))
    proj = ProjectExec(f, [col("i_item_id"), col("s_store_name"), col("s_zip"),
                           col("cnt"), col("s1"), col("s2"), col("s3"),
                           col("cnt2"), col("s1_2"), col("s2_2"), col("s3_2")])
    return single_sorted(
        proj,
        [SortField(col("s1"), ascending=False), SortField(col("i_item_id")),
         SortField(col("s_store_name")), SortField(col("s_zip"))],
        fetch=100,
    )



# ------------------------------------------- round-4 moderates


def q97(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Channel overlap of (customer, item) pairs in year 2000: the
    FULL OUTER join between the store and catalog DISTINCT pair sets,
    counted into store-only / catalog-only / both."""
    from ..exprs.ir import Case

    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])

    def pairs(fact, date_c, cust_c, item_c, pc, pi):
        sl = ProjectExec(t[fact], [col(date_c), col(cust_c), col(item_c)])
        j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        proj = ProjectExec(j, [col(cust_c).alias(pc), col(item_c).alias(pi)])
        return two_stage_agg(
            proj, [GroupingExpr(col(pc), pc), GroupingExpr(col(pi), pi)],
            [], n_parts,
        )

    ss = pairs("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_item_sk", "sc", "si")
    cs = pairs("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
               "cs_item_sk", "cc", "ci")
    j = shuffle_join(ss, cs, [col("sc"), col("si")], [col("cc"), col("ci")],
                     JoinType.FULL, n_parts, build_left=False)
    i64 = DataType.int64()
    one, zero = lit(1, i64), lit(0, i64)
    flags = ProjectExec(
        j,
        [Case([(col("sc").is_not_null() & col("cc").is_null(), one)], zero)
         .alias("store_only"),
         Case([(col("sc").is_null() & col("cc").is_not_null(), one)], zero)
         .alias("catalog_only"),
         Case([(col("sc").is_not_null() & col("cc").is_not_null(), one)], zero)
         .alias("store_and_catalog")],
    )
    return two_stage_agg(
        flags, [],
        [AggFunction("sum", col("store_only"), "store_only"),
         AggFunction("sum", col("catalog_only"), "catalog_only"),
         AggFunction("sum", col("store_and_catalog"), "store_and_catalog")],
        n_parts,
    )


def _city_ticket_report(t, n_parts, *, dow, cities, hd_pred, amt_c, extra_sums):
    """Shared q46/q68 shape: weekend/bought-city tickets whose buyer
    lives in a DIFFERENT city, with per-ticket sums."""
    dt = FilterExec(t["date_dim"], col("d_dow").isin(*[lit(d) for d in dow]))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    st = FilterExec(t["store"], col("s_city").isin(*[lit(c) for c in cities]))
    st_p = ProjectExec(st, [col("s_store_sk")])
    hd = FilterExec(t["household_demographics"], hd_pred)
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    ca = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_city")])
    sum_cols = list(dict.fromkeys([amt_c] + extra_sums))
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"), col("ss_hdemo_sk"),
                      col("ss_addr_sk"), col("ss_ticket_number"),
                      col("ss_customer_sk")] + [col(c) for c in sum_cols])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col("ss_addr_sk")], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(
        j,
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city").alias("bought_city")] + [col(c) for c in sum_cols],
    )
    sums = [AggFunction("sum", col(amt_c), "amt")] + [
        AggFunction("sum", col(c), f"sum_{c}") for c in extra_sums
    ]
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("ss_ticket_number"), "ss_ticket_number"),
         GroupingExpr(col("ss_customer_sk"), "ss_customer_sk"),
         GroupingExpr(col("bought_city"), "bought_city")],
        sums, n_parts,
    )
    cu = ProjectExec(t["customer"],
                     [col("c_customer_sk"), col("c_last_name"),
                      col("c_first_name"), col("c_current_addr_sk")])
    j2 = broadcast_join(cu, agg, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    ca2 = ProjectExec(t["customer_address"],
                      [col("ca_address_sk").alias("cur_addr_sk"),
                       col("ca_city").alias("current_city")])
    j2 = broadcast_join(ca2, j2, [col("cur_addr_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    f = FilterExec(j2, ~(col("current_city") == col("bought_city")))
    out_cols = [col("c_last_name"), col("c_first_name"), col("current_city"),
                col("bought_city"), col("ss_ticket_number"), col("amt")] + [
        col(f"sum_{c}") for c in extra_sums
    ]
    proj2 = ProjectExec(f, out_cols)
    return single_sorted(
        proj2,
        [SortField(col("c_last_name")), SortField(col("c_first_name")),
         SortField(col("current_city")), SortField(col("bought_city")),
         SortField(col("ss_ticket_number"))],
        fetch=100,
    )


def q46(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Weekend tickets in named store cities, bought city != home city
    (coupon + net-profit sums per ticket)."""
    hd_pred = (col("hd_dep_count") == lit(4)) | (col("hd_vehicle_count") == lit(3))
    return _city_ticket_report(
        t, n_parts, dow=(6, 0), cities=("Midway", "Fairview"),
        hd_pred=hd_pred, amt_c="ss_coupon_amt", extra_sums=["ss_net_profit"],
    )


def q68(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q46's list-price twin (ext_sales_price + ext_list_price sums,
    dep-count-5 households)."""
    hd_pred = (col("hd_dep_count") == lit(5)) | (col("hd_vehicle_count") == lit(3))
    return _city_ticket_report(
        t, n_parts, dow=(6, 0), cities=("Midway", "Fairview"),
        hd_pred=hd_pred, amt_c="ss_ext_sales_price",
        extra_sums=["ss_ext_list_price"],
    )


def q79(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Monday tickets of big-household buyers per store city.
    (Deviation: the spec's s_number_of_employees band is absent from
    this datagen; every store qualifies.)"""
    dt = FilterExec(t["date_dim"],
                    (col("d_dow") == lit(1))
                    & (col("d_year") >= lit(1998)) & (col("d_year") <= lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    hd = FilterExec(t["household_demographics"],
                    (col("hd_dep_count") == lit(6)) | (col("hd_vehicle_count") > lit(2)))
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_city")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_hdemo_sk"), col("ss_store_sk"),
                      col("ss_ticket_number"), col("ss_customer_sk"),
                      col("ss_coupon_amt"), col("ss_net_profit")])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("ss_ticket_number"), "ss_ticket_number"),
         GroupingExpr(col("ss_customer_sk"), "ss_customer_sk"),
         GroupingExpr(col("s_city"), "s_city")],
        [AggFunction("sum", col("ss_coupon_amt"), "amt"),
         AggFunction("sum", col("ss_net_profit"), "profit")],
        n_parts,
    )
    cu = ProjectExec(t["customer"],
                     [col("c_customer_sk"), col("c_last_name"), col("c_first_name")])
    j2 = broadcast_join(cu, agg, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(j2, [col("c_last_name"), col("c_first_name"), col("s_city"),
                            col("ss_ticket_number"), col("amt"), col("profit")])
    return single_sorted(
        proj,
        [SortField(col("c_last_name")), SortField(col("c_first_name")),
         SortField(col("s_city")), SortField(col("profit")),
         SortField(col("ss_ticket_number"))],
        fetch=100,
    )


def _ship_lag_pivot(t, n_parts, *, fact, sold_c, ship_c, wh_c, sm_c, dim_tab,
                    dim_sk, dim_name, dim_fk, year):
    """Shared q62/q99 shape: 30-day ship-lag buckets pivoted per
    (warehouse, ship mode, site/call-center)."""
    from ..exprs.ir import Case

    i64 = DataType.int64()
    dt = FilterExec(t["date_dim"], col("d_year") == lit(year))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_date")])
    d2 = ProjectExec(t["date_dim"],
                     [col("d_date_sk").alias("d2_sk"), col("d_date").alias("ship_date")])
    wh = ProjectExec(t["warehouse"], [col("w_warehouse_sk"), col("w_warehouse_name")])
    sm = ProjectExec(t["ship_mode"], [col("sm_ship_mode_sk"), col("sm_type")])
    dim = ProjectExec(t[dim_tab], [col(dim_sk), col(dim_name)])
    sl = ProjectExec(t[fact], [col(sold_c), col(ship_c), col(wh_c), col(sm_c),
                               col(dim_fk)])
    j = broadcast_join(dt_p, sl, [col("d_date_sk")], [col(sold_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(d2, j, [col("d2_sk")], [col(ship_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col(wh_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(sm, j, [col("sm_ship_mode_sk")], [col(sm_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dim, j, [col(dim_sk)], [col(dim_fk)], JoinType.INNER, build_is_left=True)
    lag = (col("ship_date").cast(i64) - col("d_date").cast(i64)).alias("lag")
    base = ProjectExec(j, [col("w_warehouse_name"), col("sm_type"),
                           col(dim_name), lag])
    one, zero = lit(1, i64), lit(0, i64)
    buckets = [
        ("d30", Case([(col("lag") <= lit(30, i64), one)], zero)),
        ("d60", Case([((col("lag") > lit(30, i64)) & (col("lag") <= lit(60, i64)), one)], zero)),
        ("d90", Case([((col("lag") > lit(60, i64)) & (col("lag") <= lit(90, i64)), one)], zero)),
        ("d120", Case([((col("lag") > lit(90, i64)) & (col("lag") <= lit(120, i64)), one)], zero)),
        ("dmore", Case([(col("lag") > lit(120, i64), one)], zero)),
    ]
    proj = ProjectExec(
        base,
        [col("w_warehouse_name"), col("sm_type"), col(dim_name)]
        + [e.alias(nm) for nm, e in buckets],
    )
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("w_warehouse_name"), "w_warehouse_name"),
         GroupingExpr(col("sm_type"), "sm_type"),
         GroupingExpr(col(dim_name), dim_name)],
        [AggFunction("sum", col(nm), nm) for nm, _ in buckets],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("w_warehouse_name")), SortField(col("sm_type")),
         SortField(col(dim_name))],
        fetch=100,
    )


def q62(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web ship-lag pivot per (warehouse, ship mode, site)."""
    return _ship_lag_pivot(
        t, n_parts, fact="web_sales", sold_c="ws_sold_date_sk",
        ship_c="ws_ship_date_sk", wh_c="ws_warehouse_sk",
        sm_c="ws_ship_mode_sk", dim_tab="web_site", dim_sk="web_site_sk",
        dim_name="web_name", dim_fk="ws_web_site_sk", year=2001,
    )


def q99(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog ship-lag pivot per (warehouse, ship mode, call center)."""
    return _ship_lag_pivot(
        t, n_parts, fact="catalog_sales", sold_c="cs_sold_date_sk",
        ship_c="cs_ship_date_sk", wh_c="cs_warehouse_sk",
        sm_c="cs_ship_mode_sk", dim_tab="call_center",
        dim_sk="cc_call_center_sk", dim_name="cc_name",
        dim_fk="cs_call_center_sk", year=2001,
    )


def _inv_price_items(t, n_parts, fact, item_c):
    """Shared q37/q82: items in a price band with a well-stocked
    inventory snapshot in a 60-day window that also SOLD in the
    channel.  (Deviation: the spec's manufact-id list is dropped;
    this datagen's manufact ids are uniform 1-199.)"""
    import datetime

    dec = DataType.decimal(7, 2)
    it = FilterExec(
        t["item"],
        (col("i_current_price") >= lit("30", dec))
        & (col("i_current_price") <= lit("60", dec)),
    )
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id"),
                            col("i_item_desc"), col("i_current_price")])
    dt = _date_window(t, datetime.date(2000, 2, 1), datetime.date(2000, 4, 1))
    inv = FilterExec(
        t["inventory"],
        (col("inv_quantity_on_hand") >= lit(100))
        & (col("inv_quantity_on_hand") <= lit(500)),
    )
    inv_p = ProjectExec(inv, [col("inv_date_sk"), col("inv_item_sk")])
    j = broadcast_join(dt, inv_p, [col("d_date_sk")], [col("inv_date_sk")], JoinType.INNER, build_is_left=True)
    j = shuffle_join(it_p, j, [col("i_item_sk")], [col("inv_item_sk")],
                     JoinType.INNER, n_parts, build_left=True)
    sold = ProjectExec(t[fact], [col(item_c)])
    j = broadcast_join(sold, j, [col(item_c)], [col("i_item_sk")],
                       JoinType.LEFT_SEMI, build_is_left=False)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("i_current_price"), "i_current_price")],
        [], n_parts,
    )
    return single_sorted(agg, [SortField(col("i_item_id"))], fetch=100)


def q37(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog-sold items with healthy inventory in a price band."""
    return _inv_price_items(t, n_parts, "catalog_sales", "cs_item_sk")


def q82(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q37's store twin."""
    return _inv_price_items(t, n_parts, "store_sales", "ss_item_sk")



# ------------------------------------------- round-4 batch B


def q41(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Distinct items of manufacturers that produce a qualifying
    color/size/unit combination — the correlated per-manufact EXISTS
    rewritten as a semi-join on i_manufact.  (Deviation: i_item_id
    stands in for the spec's i_product_name.)"""
    combo = (
        (col("i_color").isin(lit("powder"), lit("navy"))
         & col("i_units").isin(lit("Each"), lit("Dozen")))
        | (col("i_color").isin(lit("peach"), lit("saddle"))
           & col("i_units").isin(lit("Case"), lit("Pallet")))
    )
    qual = FilterExec(t["item"], combo)
    manufacts = two_stage_agg(
        ProjectExec(qual, [col("i_manufact")]),
        [GroupingExpr(col("i_manufact"), "i_manufact")], [], n_parts,
    )
    i1 = FilterExec(t["item"],
                    (col("i_manufact_id") >= lit(50)) & (col("i_manufact_id") <= lit(120)))
    i1 = ProjectExec(i1, [col("i_manufact"), col("i_item_id")])
    j = broadcast_join(manufacts, i1, [col("i_manufact")], [col("i_manufact")],
                       JoinType.LEFT_SEMI, build_is_left=False)
    distinct = two_stage_agg(
        ProjectExec(j, [col("i_item_id")]),
        [GroupingExpr(col("i_item_id"), "i_item_id")], [], n_parts,
    )
    return single_sorted(distinct, [SortField(col("i_item_id"))], fetch=100)


def q4(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q11's three-channel giant: per-customer yearly profit measure
    ((ext_list - wholesale - ext_discount + ext_sales) / 2) in all
    THREE channels; keep customers whose catalog growth beats store
    growth AND web growth beats store growth.  (Deviation: catalog/web
    use cs_wholesale_cost/ws_wholesale_cost — this datagen carries no
    *_ext_wholesale_cost for those channels.)"""
    f64 = DataType.float64()
    two = lit("2", DataType.decimal(7, 2))

    def measure(lp, wc, dc, sp):
        return (col(lp) - col(wc) - col(dc) + col(sp)) / two

    def slice_(fact, date_c, cust_c, cols, m, year, alias, names=False):
        yt = _year_total(t, n_parts, fact=fact, date_c=date_c, cust_c=cust_c,
                         fact_cols=cols, measure=m, year=year, names=names)
        keep = [col("c_customer_sk").alias(f"sk_{alias}"),
                col("year_total").alias(alias)]
        if names:
            keep += [col("c_customer_id"), col("c_first_name"), col("c_last_name")]
        return ProjectExec(yt, keep)

    ss_cols = ["ss_ext_list_price", "ss_ext_wholesale_cost",
               "ss_ext_discount_amt", "ss_ext_sales_price"]
    cs_cols = ["cs_ext_list_price", "cs_wholesale_cost",
               "cs_ext_discount_amt", "cs_ext_sales_price"]
    ws_cols = ["ws_ext_list_price", "ws_wholesale_cost",
               "ws_ext_discount_amt", "ws_ext_sales_price"]
    ss_m = measure(*ss_cols)
    cs_m = measure(*cs_cols)
    ws_m = measure(*ws_cols)
    s1 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_cols, ss_m, 2000, "s1")
    s2 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_cols, ss_m, 2001, "s2", names=True)
    c1 = slice_("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk", cs_cols, cs_m, 2000, "c1")
    c2 = slice_("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk", cs_cols, cs_m, 2001, "c2")
    w1 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_cols, ws_m, 2000, "w1")
    w2 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_cols, ws_m, 2001, "w2")
    j = broadcast_join(s1, s2, [col("sk_s1")], [col("sk_s2")], JoinType.INNER, build_is_left=True)
    for b, key in ((c1, "sk_c1"), (c2, "sk_c2"), (w1, "sk_w1"), (w2, "sk_w2")):
        j = broadcast_join(b, j, [col(key)], [col("sk_s2")], JoinType.INNER, build_is_left=True)
    s1f, s2f = col("s1").cast(f64), col("s2").cast(f64)
    c1f, c2f = col("c1").cast(f64), col("c2").cast(f64)
    w1f, w2f = col("w1").cast(f64), col("w2").cast(f64)
    f = FilterExec(
        j,
        (s1f > lit(0.0)) & (c1f > lit(0.0)) & (w1f > lit(0.0))
        & ((c2f / c1f) > (s2f / s1f)) & ((w2f / w1f) > (s2f / s1f)),
    )
    proj = ProjectExec(f, [col("c_customer_id"), col("c_first_name"),
                           col("c_last_name")])
    return single_sorted(proj, [SortField(col("c_customer_id"))], fetch=100)


def q50(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Store return-lag pivot: returns booked in Aug 2001 joined to
    their originating line, bucketed by days-to-return per store."""
    from ..exprs.ir import Case

    i64 = DataType.int64()
    sl = ProjectExec(t["store_sales"],
                     [col("ss_item_sk"), col("ss_ticket_number"),
                      col("ss_customer_sk"), col("ss_store_sk"),
                      col("ss_sold_date_sk")])
    sr = ProjectExec(t["store_returns"],
                     [col("sr_item_sk"), col("sr_ticket_number"),
                      col("sr_customer_sk"), col("sr_returned_date_sk")])
    j = shuffle_join(sl, sr,
                     [col("ss_item_sk"), col("ss_ticket_number"), col("ss_customer_sk")],
                     [col("sr_item_sk"), col("sr_ticket_number"), col("sr_customer_sk")],
                     JoinType.INNER, n_parts, build_left=False)
    d1 = ProjectExec(t["date_dim"], [col("d_date_sk"), col("d_date")])
    d2f = FilterExec(t["date_dim"],
                     (col("d_year") == lit(2001)) & (col("d_moy") == lit(8)))
    d2 = ProjectExec(d2f, [col("d_date_sk").alias("d2_sk"),
                           col("d_date").alias("ret_date")])
    j = broadcast_join(d1, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(d2, j, [col("d2_sk")], [col("sr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"),
                                  col("s_county"), col("s_state"), col("s_zip")])
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    lag = (col("ret_date").cast(i64) - col("d_date").cast(i64)).alias("lag")
    base = ProjectExec(j, [col("s_store_name"), col("s_county"), col("s_state"),
                           col("s_zip"), lag])
    one, zero = lit(1, i64), lit(0, i64)
    buckets = [
        ("d30", Case([(col("lag") <= lit(30, i64), one)], zero)),
        ("d60", Case([((col("lag") > lit(30, i64)) & (col("lag") <= lit(60, i64)), one)], zero)),
        ("d90", Case([((col("lag") > lit(60, i64)) & (col("lag") <= lit(90, i64)), one)], zero)),
        ("d120", Case([((col("lag") > lit(90, i64)) & (col("lag") <= lit(120, i64)), one)], zero)),
        ("dmore", Case([(col("lag") > lit(120, i64), one)], zero)),
    ]
    proj = ProjectExec(
        base,
        [col("s_store_name"), col("s_county"), col("s_state"), col("s_zip")]
        + [e.alias(nm) for nm, e in buckets],
    )
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col(c), c) for c in
         ("s_store_name", "s_county", "s_state", "s_zip")],
        [AggFunction("sum", col(nm), nm) for nm, _ in buckets],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("s_store_name")), SortField(col("s_county")),
         SortField(col("s_state")), SortField(col("s_zip"))],
        fetch=100,
    )


def q22(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Average inventory quantity ROLLUP over the product hierarchy
    (year-2000 snapshots).  (Deviation: i_item_id stands in for
    i_product_name.)"""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec

    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id"),
                                 col("i_brand"), col("i_class"), col("i_category")])
    inv = ProjectExec(t["inventory"],
                      [col("inv_date_sk"), col("inv_item_sk"),
                       col("inv_quantity_on_hand")])
    j = broadcast_join(dt_p, inv, [col("d_date_sk")], [col("inv_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col("inv_item_sk")], JoinType.INNER, build_is_left=True)
    s16 = DataType.string(16)
    s32 = DataType.string(32)
    dims = [("i_item_id", s16), ("i_brand", s32), ("i_class", s16),
            ("i_category", s16)]
    base = ProjectExec(j, [col("inv_quantity_on_hand")] + [col(d[0]) for d in dims])
    projections = []
    for level in range(4, -1, -1):
        row = [col("inv_quantity_on_hand")]
        for k, (name, dt_) in enumerate(dims):
            row.append(col(name) if k < level else Lit(None, dt_))
        row.append(lit(4 - level))
        projections.append(row)
    expand = ExpandExec(base, projections,
                        ["inv_quantity_on_hand"] + [d[0] for d in dims] + ["g_id"])
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col(d[0]), d[0]) for d in dims]
        + [GroupingExpr(col("g_id"), "g_id")],
        [AggFunction("avg", col("inv_quantity_on_hand"), "qoh")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("qoh")), SortField(col("i_item_id")),
         SortField(col("i_brand")), SortField(col("i_class")),
         SortField(col("i_category"))],
        fetch=100,
    )


def q21(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Inventory rebalance check: per (warehouse, item), on-hand sums
    30 days before vs after 2000-03-11 must stay within [2/3, 3/2]."""
    import datetime

    from ..exprs.ir import Case

    f64 = DataType.float64()
    i64 = DataType.int64()
    pivot = datetime.date(2000, 3, 11)
    dt = _date_window(t, pivot - datetime.timedelta(days=30),
                      pivot + datetime.timedelta(days=30), extra=("d_date",))
    dec = DataType.decimal(7, 2)
    it = FilterExec(
        t["item"],
        (col("i_current_price") >= lit("20", dec))
        & (col("i_current_price") <= lit("50", dec)),
    )
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id")])
    wh = ProjectExec(t["warehouse"], [col("w_warehouse_sk"), col("w_warehouse_name")])
    inv = ProjectExec(t["inventory"],
                      [col("inv_date_sk"), col("inv_item_sk"),
                       col("inv_warehouse_sk"), col("inv_quantity_on_hand")])
    j = broadcast_join(dt, inv, [col("d_date_sk")], [col("inv_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("inv_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col("inv_warehouse_sk")], JoinType.INNER, build_is_left=True)
    pivot_days = (pivot - datetime.date(1970, 1, 1)).days
    qoh = col("inv_quantity_on_hand").cast(i64)
    before = Case([(col("d_date").cast(i64) < lit(pivot_days, i64), qoh)], lit(0, i64))
    after = Case([(col("d_date").cast(i64) >= lit(pivot_days, i64), qoh)], lit(0, i64))
    proj = ProjectExec(j, [col("w_warehouse_name"), col("i_item_id"),
                           before.alias("b"), after.alias("a")])
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("w_warehouse_name"), "w_warehouse_name"),
         GroupingExpr(col("i_item_id"), "i_item_id")],
        [AggFunction("sum", col("b"), "inv_before"),
         AggFunction("sum", col("a"), "inv_after")],
        n_parts,
    )
    bf, af = col("inv_before").cast(f64), col("inv_after").cast(f64)
    f = FilterExec(
        agg,
        (bf > lit(0.0)) & ((af / bf) >= lit(2.0 / 3.0)) & ((af / bf) <= lit(1.5)),
    )
    return single_sorted(
        f, [SortField(col("w_warehouse_name")), SortField(col("i_item_id"))],
        fetch=100,
    )



# ------------------------------------------- round-4 batch C


def q28(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Six store-sales price-band buckets of (avg list price, count,
    count distinct) cross-joined into one row — each bucket a
    scalar-subquery trio, the way Spark executes the six subqueries."""
    from ..tpch.queries import scalar_subquery_row

    bands = [
        ("B1", 0, 5, 0, 10, 0, 50),
        ("B2", 6, 10, 10, 20, 50, 100),
        ("B3", 11, 15, 20, 30, 100, 150),
        ("B4", 16, 20, 30, 40, 150, 200),
        ("B5", 21, 25, 40, 50, 200, 250),
        ("B6", 26, 30, 50, 60, 250, 300),
    ]
    dec = DataType.decimal(7, 2)
    lits = []
    for name, q_lo, q_hi, c_lo, c_hi, w_lo, w_hi in bands:
        f = FilterExec(
            t["store_sales"],
            (col("ss_quantity") >= lit(q_lo)) & (col("ss_quantity") <= lit(q_hi))
            & ((col("ss_list_price") >= lit(str(c_lo), dec))
               & (col("ss_list_price") <= lit(str(c_lo + 10), dec))
               | (col("ss_coupon_amt") >= lit(str(w_lo), dec))
               & (col("ss_coupon_amt") <= lit(str(w_lo + 1000), dec))
               | (col("ss_wholesale_cost") >= lit(str(c_hi), dec))
               & (col("ss_wholesale_cost") <= lit(str(c_hi + 20), dec))),
        )
        lp = ProjectExec(f, [col("ss_list_price")])
        distinct = two_stage_agg(
            lp, [GroupingExpr(col("ss_list_price"), "ss_list_price")], [],
            n_parts,
        )
        per_band = two_stage_agg(
            lp, [],
            [AggFunction("avg", col("ss_list_price"), f"{name}_lp"),
             AggFunction("count", col("ss_list_price"), f"{name}_cnt")],
            n_parts,
        )
        dcnt = two_stage_agg(
            distinct, [], [AggFunction("count_star", None, f"{name}_cntd")],
            n_parts,
        )
        lits.extend(scalar_subquery_row(per_band, [f"{name}_lp", f"{name}_cnt"]))
        lits.extend(scalar_subquery_row(dcnt, [f"{name}_cntd"]))
    one_row = two_stage_agg(
        ProjectExec(t["store"], [col("s_store_sk")]), [],
        [AggFunction("count_star", None, "ignore")], n_parts,
    )
    names = []
    for name, *_ in bands:
        names += [f"{name}_lp", f"{name}_cnt", f"{name}_cntd"]
    return ProjectExec(one_row, list(lits), names)


def q90(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """AM/PM web-sales ratio for big web pages: two filtered counts
    (hour windows x page char counts) divided.  (Deviation: the spec's
    household-deps filter needs ws_ship_hdemo_sk, absent from this
    datagen.)"""
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    wp = FilterExec(t["web_page"],
                    (col("wp_char_count") >= lit(2000))
                    & (col("wp_char_count") <= lit(6000)))
    wp_p = ProjectExec(wp, [col("wp_web_page_sk")])

    def half(lo, hi, name):
        td = FilterExec(t["time_dim"],
                        (col("t_hour") >= lit(lo)) & (col("t_hour") <= lit(hi)))
        td_p = ProjectExec(td, [col("t_time_sk")])
        ws = ProjectExec(t["web_sales"],
                         [col("ws_sold_time_sk"), col("ws_web_page_sk")])
        j = broadcast_join(td_p, ws, [col("t_time_sk")], [col("ws_sold_time_sk")], JoinType.INNER, build_is_left=True)
        j = broadcast_join(wp_p, j, [col("wp_web_page_sk")], [col("ws_web_page_sk")], JoinType.INNER, build_is_left=True)
        return two_stage_agg(j, [], [AggFunction("count_star", None, name)],
                             n_parts)

    am = scalar_subquery(half(8, 9, "amc"), "amc")
    pm = scalar_subquery(half(19, 20, "pmc"), "pmc")
    one_row = two_stage_agg(
        ProjectExec(t["web_page"], [col("wp_web_page_sk")]), [],
        [AggFunction("count_star", None, "ignore")], n_parts,
    )
    from ..exprs.ir import Case

    pmf = pm.cast(f64)
    den = Case([(pmf > lit(0.0), pmf)], lit(1.0))
    return ProjectExec(one_row, [am.cast(f64), pmf, (am.cast(f64) / den)],
                       ["am_count", "pm_count", "am_pm_ratio"])


def q76(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Sales with MISSING dimension keys per channel/year/category.
    (Deviation: this datagen writes -1 sentinels for the spec's NULL
    foreign keys; the predicate tests the sentinel.)"""
    dt = ProjectExec(t["date_dim"], [col("d_date_sk"), col("d_year"), col("d_qoy")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_category")])

    def channel(fact, date_c, item_c, null_c, price_c, name):
        f = FilterExec(t[fact], col(null_c) == lit(-1, DataType.int64()))
        sl = ProjectExec(f, [col(date_c), col(item_c), col(price_c)])
        j = broadcast_join(dt, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        j = broadcast_join(it, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
        return ProjectExec(
            j,
            [lit(name, DataType.string(16)), lit(null_c, DataType.string(24)),
             col("d_year"), col("d_qoy"), col("i_category"),
             col(price_c).alias("ext_sales_price")],
            ["channel", "col_name", "d_year", "d_qoy", "i_category",
             "ext_sales_price"],
        )

    u = UnionExec([
        channel("store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_customer_sk", "ss_ext_sales_price", "store"),
        channel("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_promo_sk", "ws_ext_sales_price", "web"),
        channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                "cs_bill_customer_sk", "cs_ext_sales_price", "catalog"),
    ])
    agg = two_stage_agg(
        u,
        [GroupingExpr(col(c), c) for c in
         ("channel", "col_name", "d_year", "d_qoy", "i_category")],
        [AggFunction("count_star", None, "sales_cnt"),
         AggFunction("sum", col("ext_sales_price"), "sales_amt")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("channel")), SortField(col("col_name")),
         SortField(col("d_year")), SortField(col("d_qoy")),
         SortField(col("i_category"))],
        fetch=100,
    )


def _returns_above_avg(t, n_parts, *, rtab, r_cust, r_amt, r_date, r_loc,
                       loc_tab=None, loc_sk=None, loc_filter_col=None,
                       loc_filter_val=None, names=False):
    """q1/q30/q81 family: per-customer yearly returns per location,
    kept where the total beats 1.2x the location average, joined back
    to customer identity.  The correlated per-location average is the
    classic decorrelation: a location-grouped avg joined on location."""
    f64 = DataType.float64()
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    rt = ProjectExec(t[rtab], [col(r_date), col(r_cust), col(r_loc), col(r_amt)])
    j = broadcast_join(dt_p, rt, [col("d_date_sk")], [col(r_date)], JoinType.INNER, build_is_left=True)
    if loc_tab is not None:
        loc = FilterExec(t[loc_tab], col(loc_filter_col) == lit(loc_filter_val))
        loc_p = ProjectExec(loc, [col(loc_sk)])
        j = broadcast_join(loc_p, j, [col(loc_sk)], [col(r_loc)], JoinType.INNER, build_is_left=True)
    per_cust = two_stage_agg(
        ProjectExec(j, [col(r_cust), col(r_loc), col(r_amt)]),
        [GroupingExpr(col(r_cust), "ctr_customer_sk"),
         GroupingExpr(col(r_loc), "ctr_loc_sk")],
        [AggFunction("sum", col(r_amt), "ctr_total_return")],
        n_parts,
    )
    loc_avg = two_stage_agg(
        ProjectExec(per_cust, [col("ctr_loc_sk").alias("avg_loc_sk"),
                               col("ctr_total_return")]),
        [GroupingExpr(col("avg_loc_sk"), "avg_loc_sk")],
        [AggFunction("avg", col("ctr_total_return"), "avg_return")],
        n_parts,
    )
    j2 = broadcast_join(loc_avg, per_cust, [col("avg_loc_sk")], [col("ctr_loc_sk")],
                        JoinType.INNER, build_is_left=True)
    f = FilterExec(
        j2,
        col("ctr_total_return").cast(f64) > lit(1.2) * col("avg_return").cast(f64),
    )
    cu_cols = [col("c_customer_sk"), col("c_customer_id")] + (
        [col("c_first_name"), col("c_last_name")] if names else []
    )
    cu = ProjectExec(t["customer"], cu_cols)
    j3 = broadcast_join(cu, f, [col("c_customer_sk")], [col("ctr_customer_sk")], JoinType.INNER, build_is_left=True)
    if names:
        proj = ProjectExec(j3, [col("c_customer_id"), col("c_first_name"),
                                col("c_last_name"), col("ctr_total_return")])
        return single_sorted(
            proj,
            [SortField(col("c_customer_id")), SortField(col("ctr_total_return"))],
            fetch=100,
        )
    proj = ProjectExec(j3, [col("c_customer_id")])
    return single_sorted(proj, [SortField(col("c_customer_id"))], fetch=100)


def q1(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Customers whose year-2000 STORE returns beat 1.2x their store's
    per-customer average (TN stores)."""
    return _returns_above_avg(
        t, n_parts, rtab="store_returns", r_cust="sr_customer_sk",
        r_amt="sr_return_amt", r_date="sr_returned_date_sk",
        r_loc="sr_store_sk", loc_tab="store", loc_sk="s_store_sk",
        loc_filter_col="s_state", loc_filter_val="TN",
    )


def q30(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q1's WEB twin, per web page, reporting customer identity.
    (Deviation: this datagen's web_page has no state column, so no
    location filter applies.)"""
    return _returns_above_avg(
        t, n_parts, rtab="web_returns", r_cust="wr_returning_customer_sk",
        r_amt="wr_return_amt", r_date="wr_returned_date_sk",
        r_loc="wr_web_page_sk", names=True,
    )


def q81(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q1's CATALOG twin, per call center, reporting customer
    identity."""
    return _returns_above_avg(
        t, n_parts, rtab="catalog_returns",
        r_cust="cr_returning_customer_sk", r_amt="cr_return_amount",
        r_date="cr_returned_date_sk", r_loc="cr_call_center_sk", names=True,
    )


# ------------------------------------------- round-4 batch D


_DOW7 = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")


def _dow_ratio_projection(f64):
    """The 7 per-dow (year1/year2) ratio exprs with the Case guard on
    NULL/zero denominators — shared by q2/q59."""
    from ..exprs.ir import Case

    ratios = []
    for nm in _DOW7:
        den = col(f"{nm}2").cast(f64)
        den = Case([(den > lit(0.0), den)], lit(1.0))
        ratios.append((col(f"{nm}1").cast(f64) / den).alias(f"{nm}_ratio"))
    return ratios


def _weekly_dow_pivot(rows_plan, n_parts, group_cols, price_c):
    """Group rows by (group_cols) pivoting price sums into 7 dow
    buckets — the q2/q59 weekly building block (q43's pivot shape)."""
    from ..exprs.ir import Case

    pivots = [
        Case([(col("d_dow") == lit(k), col(price_c))], None).alias(f"{nm}_v")
        for k, nm in enumerate(_DOW7)
    ]
    proj = ProjectExec(rows_plan, [col(c) for c in group_cols] + pivots)
    return two_stage_agg(
        proj,
        [GroupingExpr(col(c), c) for c in group_cols],
        [AggFunction("sum", col(f"{nm}_v"), f"{nm}_sales") for nm in _DOW7],
        n_parts,
    )


def q2(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web+catalog weekly day-of-week sales, each 2001 week ratioed
    against the same week one year on.  (Deviation: this date_dim's
    week_seq is anchored at the dataset start, so the year offset is
    52 weeks, not the spec's 53.)"""
    f64 = DataType.float64()
    dt = ProjectExec(t["date_dim"],
                     [col("d_date_sk"), col("d_week_seq"), col("d_dow"),
                      col("d_year")])
    branches = []
    for fact, date_c, price_c in (
        ("web_sales", "ws_sold_date_sk", "ws_ext_sales_price"),
        ("catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price"),
    ):
        sl = ProjectExec(t[fact], [col(date_c).alias("sold_date_sk"),
                                   col(price_c).alias("sales_price")])
        branches.append(sl)
    u = UnionExec(branches)
    j = broadcast_join(dt, u, [col("d_date_sk")], [col("sold_date_sk")], JoinType.INNER, build_is_left=True)
    wk = _weekly_dow_pivot(j, n_parts, ["d_week_seq"], "sales_price")

    y1_weeks = FilterExec(t["date_dim"], col("d_year") == lit(2001))
    y1_weeks = two_stage_agg(
        ProjectExec(y1_weeks, [col("d_week_seq").alias("wk1")]),
        [GroupingExpr(col("wk1"), "wk1")], [], n_parts,
    )
    y2_weeks = FilterExec(t["date_dim"], col("d_year") == lit(2002))
    y2_weeks = two_stage_agg(
        ProjectExec(y2_weeks, [col("d_week_seq").alias("wk2")]),
        [GroupingExpr(col("wk2"), "wk2")], [], n_parts,
    )
    wk1 = broadcast_join(y1_weeks, wk, [col("wk1")], [col("d_week_seq")],
                         JoinType.LEFT_SEMI, build_is_left=False)
    wk1 = ProjectExec(wk1, [col("d_week_seq")] + [
        col(f"{nm}_sales").alias(f"{nm}1") for nm in _DOW7
    ])
    wk2 = broadcast_join(y2_weeks, wk, [col("wk2")], [col("d_week_seq")],
                         JoinType.LEFT_SEMI, build_is_left=False)
    wk2 = ProjectExec(wk2, [(col("d_week_seq") - lit(52)).alias("wk_m52")] + [
        col(f"{nm}_sales").alias(f"{nm}2") for nm in _DOW7
    ])
    j2 = shuffle_join(wk1, wk2, [col("d_week_seq")], [col("wk_m52")],
                      JoinType.INNER, n_parts, build_left=False)
    proj = ProjectExec(j2, [col("d_week_seq")] + _dow_ratio_projection(f64))
    return single_sorted(proj, [SortField(col("d_week_seq"))], fetch=100)


def q59(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q2's per-store STORE-channel twin: weekly dow sales per store,
    each week ratioed against the week 52 later."""
    f64 = DataType.float64()
    dt = ProjectExec(t["date_dim"],
                     [col("d_date_sk"), col("d_week_seq"), col("d_dow")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_store_sk"),
                      col("ss_sales_price")])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    wk = _weekly_dow_pivot(j, n_parts, ["ss_store_sk", "d_week_seq"],
                           "ss_sales_price")
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    wk = broadcast_join(st, wk, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    y1 = FilterExec(t["date_dim"], col("d_year") == lit(2001))
    y1 = two_stage_agg(ProjectExec(y1, [col("d_week_seq").alias("wk1")]),
                       [GroupingExpr(col("wk1"), "wk1")], [], n_parts)
    wk1 = broadcast_join(y1, wk, [col("wk1")], [col("d_week_seq")],
                         JoinType.LEFT_SEMI, build_is_left=False)
    wk1 = ProjectExec(wk1, [col("s_store_name"), col("ss_store_sk"),
                            col("d_week_seq")] + [
        col(f"{nm}_sales").alias(f"{nm}1") for nm in _DOW7
    ])
    y2 = FilterExec(t["date_dim"], col("d_year") == lit(2002))
    y2 = two_stage_agg(ProjectExec(y2, [col("d_week_seq").alias("wk2")]),
                       [GroupingExpr(col("wk2"), "wk2")], [], n_parts)
    wk2 = broadcast_join(y2, wk, [col("wk2")], [col("d_week_seq")],
                         JoinType.LEFT_SEMI, build_is_left=False)
    wk2 = ProjectExec(wk2, [col("ss_store_sk").alias("store2"),
                            (col("d_week_seq") - lit(52)).alias("wk_m52")] + [
        col(f"{nm}_sales").alias(f"{nm}2") for nm in _DOW7
    ])
    j2 = shuffle_join(wk1, wk2, [col("ss_store_sk"), col("d_week_seq")],
                      [col("store2"), col("wk_m52")],
                      JoinType.INNER, n_parts, build_left=False)
    proj = ProjectExec(j2, [col("s_store_name"), col("d_week_seq")]
                       + _dow_ratio_projection(f64))
    return single_sorted(
        proj, [SortField(col("s_store_name")), SortField(col("d_week_seq"))],
        fetch=100,
    )


def _srcandc_join(t, n_parts):
    """The q17/q25/q29 provenance chain: store line sold in year 2000,
    returned within 2000-2002, re-bought from the catalog 2000-2002 by
    the same customer, joined to store + item.  (Deviation: the spec's
    one-month / six-month windows leave this datagen's uniform triple
    chain empty at test scales; the year-wide windows keep it
    populated.)"""
    d1 = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    d1 = ProjectExec(d1, [col("d_date_sk")])
    d2 = FilterExec(t["date_dim"],
                    (col("d_year") >= lit(2000)) & (col("d_year") <= lit(2002)))
    d2 = ProjectExec(d2, [col("d_date_sk").alias("d2_sk")])
    d3 = FilterExec(t["date_dim"],
                    (col("d_year") >= lit(2000)) & (col("d_year") <= lit(2002)))
    d3 = ProjectExec(d3, [col("d_date_sk").alias("d3_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_item_sk"),
                      col("ss_ticket_number"), col("ss_customer_sk"),
                      col("ss_store_sk"), col("ss_net_profit"),
                      col("ss_quantity")])
    j = broadcast_join(d1, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    sr = ProjectExec(t["store_returns"],
                     [col("sr_item_sk"), col("sr_ticket_number"),
                      col("sr_customer_sk"), col("sr_returned_date_sk"),
                      col("sr_net_loss"), col("sr_return_quantity")])
    j = shuffle_join(j, sr,
                     [col("ss_item_sk"), col("ss_ticket_number")],
                     [col("sr_item_sk"), col("sr_ticket_number")],
                     JoinType.INNER, n_parts, build_left=False)
    j = broadcast_join(d2, j, [col("d2_sk")], [col("sr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    cs = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_bill_customer_sk"),
                      col("cs_item_sk"), col("cs_net_profit"),
                      col("cs_quantity")])
    j = shuffle_join(j, cs,
                     [col("sr_customer_sk"), col("sr_item_sk")],
                     [col("cs_bill_customer_sk"), col("cs_item_sk")],
                     JoinType.INNER, n_parts, build_left=True)
    j = broadcast_join(d3, j, [col("d3_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name")])
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id"),
                                 col("i_item_desc")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    return j


def _sales_returns_catalog(t, n_parts, *, sums, sum_names):
    """q25/q29 tail: grouped sums per (item, store)."""
    j = _srcandc_join(t, n_parts)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("s_store_name"), "s_store_name")],
        [AggFunction("sum", e, n) for e, n in zip(sums, sum_names)],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("i_item_id")), SortField(col("i_item_desc")),
         SortField(col("s_store_name"))],
        fetch=100,
    )


def q25(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Sold-returned-rebought profit report per (item, store)."""
    return _sales_returns_catalog(
        t, n_parts,
        sums=[col("ss_net_profit"), col("sr_net_loss"), col("cs_net_profit")],
        sum_names=["store_sales_profit", "store_returns_loss",
                   "catalog_sales_profit"],
    )


def q29(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q25's quantity twin."""
    i64 = DataType.int64()
    return _sales_returns_catalog(
        t, n_parts,
        sums=[col("ss_quantity").cast(i64), col("sr_return_quantity").cast(i64),
              col("cs_quantity").cast(i64)],
        sum_names=["store_sales_quantity", "store_returns_quantity",
                   "catalog_sales_quantity"],
    )


def q91(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Call-center losses from well-profiled returners: catalog
    returns of year 2000 by (call center, customer demographic pair).
    (Deviation: year-wide window, and no gmt-offset filter — the
    spec's single-month + gmt slice is empty at test scales.)"""
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt = ProjectExec(dt, [col("d_date_sk")])
    cr = ProjectExec(t["catalog_returns"],
                     [col("cr_returned_date_sk"), col("cr_returning_customer_sk"),
                      col("cr_call_center_sk"), col("cr_net_loss")])
    j = broadcast_join(dt, cr, [col("d_date_sk")], [col("cr_returned_date_sk")], JoinType.INNER, build_is_left=True)
    cc = ProjectExec(t["call_center"],
                     [col("cc_call_center_sk"), col("cc_name")])
    j = broadcast_join(cc, j, [col("cc_call_center_sk")], [col("cr_call_center_sk")], JoinType.INNER, build_is_left=True)
    cu = ProjectExec(t["customer"],
                     [col("c_customer_sk"), col("c_current_cdemo_sk"),
                      col("c_current_addr_sk")])
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col("cr_returning_customer_sk")], JoinType.INNER, build_is_left=True)
    cd = FilterExec(
        t["customer_demographics"],
        ((col("cd_marital_status") == lit("M"))
         & (col("cd_education_status") == lit("Unknown")))
        | ((col("cd_marital_status") == lit("W"))
           & (col("cd_education_status") == lit("Advanced Degree"))),
    )
    cd = ProjectExec(cd, [col("cd_demo_sk"), col("cd_marital_status"),
                          col("cd_education_status")])
    j = broadcast_join(cd, j, [col("cd_demo_sk")], [col("c_current_cdemo_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("cc_name"), "cc_name"),
         GroupingExpr(col("cd_marital_status"), "cd_marital_status"),
         GroupingExpr(col("cd_education_status"), "cd_education_status")],
        [AggFunction("sum", col("cr_net_loss"), "returns_loss")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("returns_loss"), ascending=False),
         SortField(col("cc_name"))],
        fetch=100,
    )


def _collect_column(plan, column):
    """Driver-side evaluation of a small subplan into a literal list —
    the IN-subquery sibling of scalar_subquery (the JVM evaluates the
    subquery; the native side sees literals)."""
    from ..batch import batch_to_pydict
    from ..runtime.context import TaskContext

    out = []
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            out.extend(batch_to_pydict(b)[column])
    return out


def q45(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web revenue by customer geography for zip-listed OR hot-item
    buyers (the OR of a zip prefix list with an item IN-subquery,
    evaluated driver-side into literals)."""
    from ..exprs.ir import func

    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2000)) & (col("d_qoy") == lit(2)))
    dt = ProjectExec(dt, [col("d_date_sk")])
    hot = FilterExec(t["item"], col("i_item_sk").isin(
        *[lit(v, DataType.int64()) for v in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)]))
    hot_ids = _collect_column(ProjectExec(hot, [col("i_item_id")]), "i_item_id")
    ws = ProjectExec(t["web_sales"],
                     [col("ws_sold_date_sk"), col("ws_item_sk"),
                      col("ws_bill_customer_sk"), col("ws_sales_price")])
    j = broadcast_join(dt, ws, [col("d_date_sk")], [col("ws_sold_date_sk")], JoinType.INNER, build_is_left=True)
    cu = ProjectExec(t["customer"], [col("c_customer_sk"), col("c_current_addr_sk")])
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col("ws_bill_customer_sk")], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"],
                     [col("ca_address_sk"), col("ca_city"), col("ca_zip")])
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ws_item_sk")], JoinType.INNER, build_is_left=True)
    zips = ("35000", "35137", "60031", "60062", "60093")
    pred = func("substring", col("ca_zip"), lit(1), lit(5)).isin(
        *[lit(z) for z in zips])
    if hot_ids:
        pred = pred | col("i_item_id").isin(*[lit(v) for v in hot_ids])
    f = FilterExec(j, pred)
    agg = two_stage_agg(
        f,
        [GroupingExpr(col("ca_zip"), "ca_zip"),
         GroupingExpr(col("ca_city"), "ca_city")],
        [AggFunction("sum", col("ws_sales_price"), "sum_sales")],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("ca_zip")), SortField(col("ca_city"))], fetch=100
    )



# ------------------------------------------- stddev pair


def q17(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Quantity spread statistics over the sold-returned-rebought
    chain: count/avg/stddev (+cov) of each leg's quantity per
    (item, store state).  (Deviation: grouped by s_store_name — this
    datagen's stores span one state per name anyway.)"""
    from ..exprs.ir import Case

    j = _srcandc_join(t, n_parts)
    i64 = DataType.int64()
    qs = [("ss_quantity", "store"), ("sr_return_quantity", "returns"),
          ("cs_quantity", "catalog")]
    aggs = []
    for src, nm in qs:
        e = col(src).cast(i64)
        aggs += [
            AggFunction("count", e, f"{nm}_qty_count"),
            AggFunction("avg", e, f"{nm}_qty_avg"),
            AggFunction("stddev_samp", e, f"{nm}_qty_stdev"),
        ]
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("s_store_name"), "s_store_name")],
        aggs, n_parts,
    )
    outs = [col("i_item_id"), col("i_item_desc"), col("s_store_name")]
    for _, nm in qs:
        avg = col(f"{nm}_qty_avg")
        sd = col(f"{nm}_qty_stdev")
        cov = Case([(avg > lit(0.0), sd / avg)], None)
        outs += [col(f"{nm}_qty_count"), avg, sd, cov.alias(f"{nm}_qty_cov")]
    proj = ProjectExec(agg, outs)
    return single_sorted(
        proj,
        [SortField(col("i_item_id")), SortField(col("i_item_desc")),
         SortField(col("s_store_name"))],
        fetch=100,
    )


def _q39_monthly_cov(t, n_parts, moy):
    """Per (warehouse, item) inventory cov for one month of 2001."""
    from ..exprs.ir import Case

    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2001)) & (col("d_moy") == lit(moy)))
    dt = ProjectExec(dt, [col("d_date_sk")])
    inv = ProjectExec(t["inventory"],
                      [col("inv_date_sk"), col("inv_item_sk"),
                       col("inv_warehouse_sk"), col("inv_quantity_on_hand")])
    j = broadcast_join(dt, inv, [col("d_date_sk")], [col("inv_date_sk")], JoinType.INNER, build_is_left=True)
    wh = ProjectExec(t["warehouse"], [col("w_warehouse_sk"), col("w_warehouse_name")])
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col("inv_warehouse_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("w_warehouse_name"), "w_warehouse_name"),
         GroupingExpr(col("inv_item_sk"), "inv_item_sk")],
        [AggFunction("avg", col("inv_quantity_on_hand"), "mean"),
         AggFunction("stddev_samp", col("inv_quantity_on_hand"), "stdev")],
        n_parts,
    )
    cov = Case([(col("mean") > lit(0.0), col("stdev") / col("mean"))], None)
    proj = ProjectExec(agg, [col("w_warehouse_name"), col("inv_item_sk"),
                             col("mean"), cov.alias("cov")])
    return proj


def _q39(t, n_parts, thr1, thr2):
    m1 = FilterExec(_q39_monthly_cov(t, n_parts, 1), col("cov") > lit(thr1))
    m2 = FilterExec(_q39_monthly_cov(t, n_parts, 2), col("cov") > lit(thr2))
    m2 = ProjectExec(m2, [col("w_warehouse_name").alias("w2"),
                          col("inv_item_sk").alias("i2"),
                          col("mean").alias("mean2"),
                          col("cov").alias("cov2")])
    j = shuffle_join(m1, m2, [col("w_warehouse_name"), col("inv_item_sk")],
                     [col("w2"), col("i2")], JoinType.INNER, n_parts,
                     build_left=False)
    proj = ProjectExec(j, [col("w_warehouse_name"), col("inv_item_sk"),
                           col("mean"), col("cov"), col("mean2"), col("cov2")])
    return single_sorted(
        proj,
        [SortField(col("w_warehouse_name")), SortField(col("inv_item_sk"))],
        fetch=100,
    )


def q39a(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """High-variance inventory (cov > 0.7) in BOTH Jan and Feb 2001
    per (warehouse, item).  (Deviation: the spec's cov > 1 cut is
    near-empty under this datagen's uniform on-hand draws; 0.7 keeps
    the month-over-month self-join populated.)"""
    return _q39(t, n_parts, 0.7, 0.7)


def q39b(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """q39a with the January side tightened (cov > 0.85 — the spec's
    1.5, scaled to this datagen's cov distribution)."""
    return _q39(t, n_parts, 0.85, 0.7)



# ------------------------------------------- round-4 batch E


def q18(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog demographic averages ROLLUP over customer geography:
    avg quantities/prices per (item, county, state) rollup for young
    buyers' households."""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec

    f64 = DataType.float64()
    i64 = DataType.int64()
    cd = FilterExec(t["customer_demographics"],
                    (col("cd_gender") == lit("F"))
                    & (col("cd_education_status") == lit("College")))
    cd = ProjectExec(cd, [col("cd_demo_sk"), col("cd_dep_count")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2001))
    dt = ProjectExec(dt, [col("d_date_sk")])
    cu = FilterExec(t["customer"],
                    (col("c_birth_year") >= lit(1966)) & (col("c_birth_year") <= lit(1980)))
    cu = ProjectExec(cu, [col("c_customer_sk"), col("c_current_addr_sk"),
                          col("c_birth_year")])
    ca = ProjectExec(t["customer_address"],
                     [col("ca_address_sk"), col("ca_county"), col("ca_state")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    cs = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_item_sk"),
                      col("cs_bill_customer_sk"), col("cs_bill_cdemo_sk"),
                      col("cs_quantity"), col("cs_list_price"),
                      col("cs_coupon_amt"), col("cs_sales_price"),
                      col("cs_net_profit")])
    j = broadcast_join(dt, cs, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cd, j, [col("cd_demo_sk")], [col("cs_bill_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col("cs_bill_customer_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col("cs_item_sk")], JoinType.INNER, build_is_left=True)
    measures = [("cs_quantity", "agg1"), ("cs_list_price", "agg2"),
                ("cs_coupon_amt", "agg3"), ("cs_sales_price", "agg4"),
                ("cs_net_profit", "agg5"), ("c_birth_year", "agg6"),
                ("cd_dep_count", "agg7")]
    base = ProjectExec(
        j,
        [col(src).cast(f64).alias(nm) for src, nm in measures]
        + [col("i_item_id"), col("ca_county"), col("ca_state")],
    )
    s16 = DataType.string(16)
    s24 = DataType.string(24)
    s8 = DataType.string(8)
    dims = [("i_item_id", s16), ("ca_county", s24), ("ca_state", s8)]
    projections = []
    for level in range(3, -1, -1):
        row = [col(nm) for _, nm in measures]
        for k, (name, dt_) in enumerate(dims):
            row.append(col(name) if k < level else Lit(None, dt_))
        row.append(lit(3 - level, i64))
        projections.append(row)
    expand = ExpandExec(base, projections,
                        [nm for _, nm in measures] + [d[0] for d in dims] + ["g_id"])
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col(d[0]), d[0]) for d in dims]
        + [GroupingExpr(col("g_id"), "g_id")],
        [AggFunction("avg", col(nm), nm) for _, nm in measures],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("ca_county")), SortField(col("ca_state")),
         SortField(col("i_item_id")), SortField(col("g_id"))],
        fetch=100,
    )


def q40(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Catalog sales net of returns by (warehouse state, item), split
    into before/after the 2000-03-11 pivot (the q21 shape over the
    sales side, with the line-level cr LEFT join)."""
    import datetime

    from ..exprs.ir import Case

    i64 = DataType.int64()
    pivot = datetime.date(2000, 3, 11)
    pivot_days = (pivot - datetime.date(1970, 1, 1)).days
    dt = _date_window(t, pivot - datetime.timedelta(days=30),
                      pivot + datetime.timedelta(days=30), extra=("d_date",))
    dec = DataType.decimal(7, 2)
    it = FilterExec(
        t["item"],
        (col("i_current_price") >= lit("20", dec))
        & (col("i_current_price") <= lit("50", dec)),
    )
    it = ProjectExec(it, [col("i_item_sk"), col("i_item_id")])
    wh = ProjectExec(t["warehouse"], [col("w_warehouse_sk"), col("w_state")])
    cs = ProjectExec(t["catalog_sales"],
                     [col("cs_sold_date_sk"), col("cs_item_sk"),
                      col("cs_order_number"), col("cs_warehouse_sk"),
                      col("cs_sales_price")])
    j = broadcast_join(dt, cs, [col("d_date_sk")], [col("cs_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col("cs_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col("cs_warehouse_sk")], JoinType.INNER, build_is_left=True)
    cr = ProjectExec(t["catalog_returns"],
                     [col("cr_item_sk"), col("cr_order_number"),
                      col("cr_refunded_cash")])
    j = shuffle_join(j, cr, [col("cs_item_sk"), col("cs_order_number")],
                     [col("cr_item_sk"), col("cr_order_number")],
                     JoinType.LEFT, n_parts, build_left=False)
    net = (_d8(col("cs_sales_price")) - _coalesce0(col("cr_refunded_cash")))
    before = Case([(col("d_date").cast(i64) < lit(pivot_days, i64), net)], None)
    after = Case([(col("d_date").cast(i64) >= lit(pivot_days, i64), net)], None)
    proj = ProjectExec(j, [col("w_state"), col("i_item_id"),
                           before.alias("b"), after.alias("a")])
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col("w_state"), "w_state"),
         GroupingExpr(col("i_item_id"), "i_item_id")],
        [AggFunction("sum", col("b"), "sales_before"),
         AggFunction("sum", col("a"), "sales_after")],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("w_state")), SortField(col("i_item_id"))],
        fetch=100,
    )


def q6(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Customer states buying items priced over 1.2x their category
    average in May 2000 (the correlated category-average subquery
    decorrelated into a grouped-avg join), HAVING >= 10 customers."""
    f64 = DataType.float64()
    cat_avg = two_stage_agg(
        ProjectExec(t["item"], [col("i_category").alias("avg_cat"),
                                col("i_current_price")]),
        [GroupingExpr(col("avg_cat"), "avg_cat")],
        [AggFunction("avg", col("i_current_price"), "cat_avg_price")],
        n_parts,
    )
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_category"),
                                 col("i_current_price")])
    it = broadcast_join(cat_avg, it, [col("avg_cat")], [col("i_category")], JoinType.INNER, build_is_left=True)
    it = FilterExec(
        it,
        col("i_current_price").cast(f64)
        > lit(1.2) * col("cat_avg_price").cast(f64),
    )
    it = ProjectExec(it, [col("i_item_sk")])
    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(5)))
    dt = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_item_sk"),
                      col("ss_customer_sk")])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")],
                       JoinType.LEFT_SEMI, build_is_left=False)
    cu = ProjectExec(t["customer"], [col("c_customer_sk"), col("c_current_addr_sk")])
    j = broadcast_join(cu, j, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_state")])
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j, [GroupingExpr(col("ca_state"), "state")],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    f = FilterExec(agg, col("cnt") >= lit(10, DataType.int64()))
    return single_sorted(
        f, [SortField(col("cnt")), SortField(col("state"))], fetch=100
    )


def q83(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Per-item returns across all three channels in year 2000, each
    channel's share against the three-channel average."""
    f64 = DataType.float64()
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt = ProjectExec(dt, [col("d_date_sk")])
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])

    def channel(rtab, r_date, r_item, r_qty, nm):
        rt = ProjectExec(t[rtab], [col(r_date), col(r_item), col(r_qty)])
        j = broadcast_join(dt, rt, [col("d_date_sk")], [col(r_date)], JoinType.INNER, build_is_left=True)
        j = broadcast_join(it, j, [col("i_item_sk")], [col(r_item)], JoinType.INNER, build_is_left=True)
        agg = two_stage_agg(
            ProjectExec(j, [col("i_item_id").alias(f"{nm}_item_id"),
                            col(r_qty).cast(DataType.int64()).alias("q")]),
            [GroupingExpr(col(f"{nm}_item_id"), f"{nm}_item_id")],
            [AggFunction("sum", col("q"), f"{nm}_qty")],
            n_parts,
        )
        return agg

    sr = channel("store_returns", "sr_returned_date_sk", "sr_item_sk",
                 "sr_return_quantity", "sr")
    cr = channel("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
                 "cr_return_quantity", "cr")
    wr = channel("web_returns", "wr_returned_date_sk", "wr_item_sk",
                 "wr_return_quantity", "wr")
    j = shuffle_join(sr, cr, [col("sr_item_id")], [col("cr_item_id")],
                     JoinType.INNER, n_parts, build_left=False)
    j = shuffle_join(j, wr, [col("sr_item_id")], [col("wr_item_id")],
                     JoinType.INNER, n_parts, build_left=False)
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty")).cast(f64)
    third = total / lit(3.0)
    outs = [col("sr_item_id").alias("item_id"),
            col("sr_qty"), col("cr_qty"), col("wr_qty")]
    for nm in ("sr", "cr", "wr"):
        outs.append(
            (col(f"{nm}_qty").cast(f64) / total * lit(100.0)).alias(f"{nm}_dev"))
    outs.append(third.alias("average"))
    proj = ProjectExec(j, outs)
    return single_sorted(
        proj, [SortField(col("item_id")), SortField(col("sr_qty"))], fetch=100
    )



def q44(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Best/worst items by average net profit at one store, paired by
    rank: two rank() windows (asc/desc) over per-item averages above
    90% of the store's null-address baseline, joined on rank.
    (Deviation: i_item_id stands in for i_product_name; the null
    ss_addr_sk baseline uses this datagen's -1 sentinel.)"""
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    i64 = DataType.int64()
    store = lit(4, i64)
    base = FilterExec(t["store_sales"], col("ss_store_sk") == store)
    per_item = two_stage_agg(
        ProjectExec(base, [col("ss_item_sk"), col("ss_net_profit")]),
        [GroupingExpr(col("ss_item_sk"), "item_sk")],
        [AggFunction("avg", col("ss_net_profit"), "rank_col")],
        n_parts,
    )
    null_addr = FilterExec(
        t["store_sales"],
        (col("ss_store_sk") == store) & (col("ss_addr_sk") == lit(-1, i64)),
    )
    thr_plan = two_stage_agg(
        ProjectExec(null_addr, [col("ss_net_profit")]), [],
        [AggFunction("avg", col("ss_net_profit"), "thr")],
        n_parts,
    )
    thr = scalar_subquery(thr_plan, "thr")
    keep = FilterExec(
        per_item,
        col("rank_col").cast(f64) > lit(0.9) * thr.cast(f64),
    )

    # ONE materialized single-partition exchange shared by both ranked
    # branches (exchanges memoize their map side per instance)
    single = NativeShuffleExchangeExec(keep, SinglePartitioning())

    def ranked(asc, alias_i, alias_r):
        srt = SortExec(single, [SortField(col("rank_col"), ascending=asc)])
        w = WindowExec(srt, [WindowFunction("rank", "rnk")], [],
                       [SortField(col("rank_col"), ascending=asc)])
        f = FilterExec(w, col("rnk") <= lit(10, i64))
        return ProjectExec(f, [col("item_sk").alias(alias_i),
                               col("rnk").alias(alias_r)])

    asc = ranked(True, "best_sk", "rnk")
    desc = ranked(False, "worst_sk", "rnk_d")
    j = shuffle_join(asc, desc, [col("rnk")], [col("rnk_d")],
                     JoinType.INNER, n_parts, build_left=False)
    i1 = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id").alias("best_name")])
    j = broadcast_join(i1, j, [col("i_item_sk")], [col("best_sk")], JoinType.INNER, build_is_left=True)
    i2 = ProjectExec(t["item"], [col("i_item_sk").alias("i2_sk"),
                                 col("i_item_id").alias("worst_name")])
    j = broadcast_join(i2, j, [col("i2_sk")], [col("worst_sk")], JoinType.INNER, build_is_left=True)
    proj = ProjectExec(j, [col("rnk"), col("best_name"), col("worst_name")])
    return single_sorted(
        proj,
        [SortField(col("rnk")), SortField(col("best_name")),
         SortField(col("worst_name"))],
        fetch=100,
    )


QUERIES: Dict[str, Callable[[Dict[str, ExecNode], int], ExecNode]] = {
    "q1": q1,
    "q2": q2,
    "q6": q6,
    "q18": q18,
    "q40": q40,
    "q83": q83,
    "q17": q17,
    "q39a": q39a,
    "q39b": q39b,
    "q3": q3,
    "q25": q25,
    "q29": q29,
    "q45": q45,
    "q59": q59,
    "q91": q91,
    "q4": q4,
    "q21": q21,
    "q22": q22,
    "q28": q28,
    "q30": q30,
    "q41": q41,
    "q44": q44,
    "q50": q50,
    "q76": q76,
    "q81": q81,
    "q90": q90,
    "q5": q5,
    "q37": q37,
    "q46": q46,
    "q62": q62,
    "q68": q68,
    "q79": q79,
    "q82": q82,
    "q97": q97,
    "q99": q99,
    "q64": q64,
    "q72": q72,
    "q14a": q14a,
    "q14b": q14b,
    "q51": q51,
    "q67": q67,
    "q75": q75,
    "q78": q78,
    "q24a": q24a,
    "q24b": q24b,
    "q23a": q23a,
    "q23b": q23b,
    "q11": q11,
    "q74": q74,
    "q16": q16,
    "q94": q94,
    "q95": q95,
    "q77": q77,
    "q80": q80,
    "q32": q32,
    "q33": q33,
    "q36": q36,
    "q38": q38,
    "q47": q47,
    "q48": q48,
    "q56": q56,
    "q57": q57,
    "q60": q60,
    "q61": q61,
    "q86": q86,
    "q87": q87,
    "q7": q7,
    "q8": q8,
    "q9": q9,
    "q10": q10,
    "q12": q12,
    "q13": q13,
    "q15": q15,
    "q35": q35,
    "q88": q88,
    "q19": q19,
    "q20": q20,
    "q26": q26,
    "q27": q27,
    "q34": q34,
    "q42": q42,
    "q43": q43,
    "q53": q53,
    "q52": q52,
    "q55": q55,
    "q63": q63,
    "q65": q65,
    "q69": q69,
    "q70": q70,
    "q73": q73,
    "q89": q89,
    "q92": q92,
    "q93": q93,
    "q96": q96,
    "q98": q98,
}


def _q31_channel(t, n_parts, fact, date_c, addr_c, price_c, qoy, pre):
    """One ss/ws CTE branch of q31: county sales for (2000, qoy)."""
    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2000)) & (col("d_qoy") == lit(qoy)))
    dt = ProjectExec(dt, [col("d_date_sk")])
    sl = ProjectExec(t[fact], [col(date_c), col(addr_c), col(price_c)])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"],
                     [col("ca_address_sk"), col("ca_county")])
    j = broadcast_join(ca, j, [col("ca_address_sk")], [col(addr_c)], JoinType.INNER, build_is_left=True)
    return two_stage_agg(
        j,
        [GroupingExpr(col("ca_county"), f"{pre}_county")],
        [AggFunction("sum", col(price_c), f"{pre}_sales")],
        n_parts,
    )


def q31(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """County-level store-vs-web quarterly growth (spec q31): six
    (county, qoy) sales aggs self-joined on county, keeping counties
    whose web growth beats store growth in BOTH q1->q2 and q2->q3 of
    2000.  ≙ reference CI matrix query q31 (tpcds-reusable.yml:91)."""
    from ..exprs.ir import Case

    f64 = DataType.float64()
    branches = {}
    for pre, fact, date_c, addr_c, price_c in (
        ("ss1", "store_sales", "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price"),
        ("ss2", "store_sales", "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price"),
        ("ss3", "store_sales", "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price"),
        ("ws1", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
        ("ws2", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
        ("ws3", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
    ):
        branches[pre] = _q31_channel(t, n_parts, fact, date_c, addr_c,
                                     price_c, int(pre[-1]), pre)
    j = branches["ss1"]
    for pre in ("ss2", "ss3", "ws1", "ws2", "ws3"):
        j = shuffle_join(j, branches[pre], [col("ss1_county")],
                         [col(f"{pre}_county")], JoinType.INNER, n_parts,
                         build_left=False)

    def ratio(num, den):
        return num.cast(f64) / den.cast(f64)

    def guarded(num, den):
        return Case([(den.cast(f64) > lit(0.0), ratio(num, den))], None)

    web12 = guarded(col("ws2_sales"), col("ws1_sales"))
    store12 = guarded(col("ss2_sales"), col("ss1_sales"))
    web23 = guarded(col("ws3_sales"), col("ws2_sales"))
    store23 = guarded(col("ss3_sales"), col("ss2_sales"))
    # (Deviation: the spec ANDs the two growth comparisons; on this
    # uniform datagen no county passes both at test scales, so they are
    # OR'd — both CASE-guarded null-compare branches stay in the plan.)
    f = FilterExec(j, (web12 > store12) | (web23 > store23))
    proj = ProjectExec(f, [
        col("ss1_county").alias("ca_county"),
        lit(2000).alias("d_year"),
        ratio(col("ws2_sales"), col("ws1_sales")).alias("web_q1_q2_increase"),
        ratio(col("ss2_sales"), col("ss1_sales")).alias("store_q1_q2_increase"),
        ratio(col("ws3_sales"), col("ws2_sales")).alias("web_q2_q3_increase"),
        ratio(col("ss3_sales"), col("ss2_sales")).alias("store_q2_q3_increase"),
    ])
    return single_sorted(proj, [SortField(col("ca_county"))])


def _q49_channel(t, n_parts, channel, fact, ret, s_item, s_ord, s_qty,
                 s_paid, s_profit, r_item, r_ord, r_qty, r_amt, date_c):
    """One channel of q49: per-item return ratios double-ranked.
    (Deviation: the spec's `return_amt > 10000` filter is scaled to
    `> 250` — this datagen draws return amounts in [0, 300], and the
    spec constant would select zero rows; oracle mirrors.)"""
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning

    f64 = DataType.float64()
    dt = FilterExec(t["date_dim"],
                    (col("d_year") == lit(2001)) & (col("d_moy") == lit(12)))
    dt = ProjectExec(dt, [col("d_date_sk")])
    sl = FilterExec(
        t[fact],
        (col(s_profit).cast(f64) > lit(1.0))
        & (col(s_paid).cast(f64) > lit(0.0))
        & (col(s_qty) > lit(0)),
    )
    sl = ProjectExec(sl, [col(date_c), col(s_item), col(s_ord),
                          col(s_qty), col(s_paid)])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    rt = FilterExec(t[ret], col(r_amt).cast(f64) > lit(250.0))
    rt = ProjectExec(rt, [col(r_item), col(r_ord), col(r_qty), col(r_amt)])
    j = shuffle_join(j, rt, [col(s_ord), col(s_item)],
                     [col(r_ord), col(r_item)], JoinType.INNER, n_parts,
                     build_left=False)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col(s_item), "item")],
        [AggFunction("sum", col(r_qty), "ret_q"),
         AggFunction("sum", col(s_qty), "qty"),
         AggFunction("sum", col(r_amt), "ret_amt"),
         AggFunction("sum", col(s_paid), "paid")],
        n_parts,
    )
    ratios = ProjectExec(agg, [
        col("item"),
        (col("ret_q").cast(f64) / col("qty").cast(f64)).alias("return_ratio"),
        (col("ret_amt").cast(f64) / col("paid").cast(f64)).alias("currency_ratio"),
    ])
    single = NativeShuffleExchangeExec(ratios, SinglePartitioning())
    s1 = SortExec(single, [SortField(col("return_ratio"))])
    w1 = WindowExec(s1, [WindowFunction("rank", "return_rank")], [],
                    [SortField(col("return_ratio"))])
    s2 = SortExec(w1, [SortField(col("currency_ratio"))])
    w2 = WindowExec(s2, [WindowFunction("rank", "currency_rank")], [],
                    [SortField(col("currency_ratio"))])
    i64 = DataType.int64()
    f = FilterExec(w2, (col("return_rank") <= lit(10, i64))
                   | (col("currency_rank") <= lit(10, i64)))
    return ProjectExec(f, [
        lit(channel).alias("channel"),
        col("item"),
        col("return_ratio"),
        col("return_rank"),
        col("currency_rank"),
    ])


def q49(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Worst return ratios by channel (spec q49): per-item quantity and
    currency return ratios, rank() over each, keep rank<=10 on either,
    union the three channels.  Channel rows are distinct by (channel,
    item), so UNION is realized as UNION ALL.
    ≙ reference CI matrix query q49 (tpcds-reusable.yml:92)."""
    web = _q49_channel(t, n_parts, "web", "web_sales", "web_returns",
                       "ws_item_sk", "ws_order_number", "ws_quantity",
                       "ws_net_paid", "ws_net_profit",
                       "wr_item_sk", "wr_order_number",
                       "wr_return_quantity", "wr_return_amt",
                       "ws_sold_date_sk")
    cat = _q49_channel(t, n_parts, "catalog", "catalog_sales", "catalog_returns",
                       "cs_item_sk", "cs_order_number", "cs_quantity",
                       "cs_net_paid", "cs_net_profit",
                       "cr_item_sk", "cr_order_number",
                       "cr_return_quantity", "cr_return_amount",
                       "cs_sold_date_sk")
    store = _q49_channel(t, n_parts, "store", "store_sales", "store_returns",
                         "ss_item_sk", "ss_ticket_number", "ss_quantity",
                         "ss_net_paid", "ss_net_profit",
                         "sr_item_sk", "sr_ticket_number",
                         "sr_return_quantity", "sr_return_amt",
                         "ss_sold_date_sk")
    u = UnionExec([web, cat, store])
    return single_sorted(
        u,
        [SortField(col("channel")), SortField(col("return_rank")),
         SortField(col("currency_rank"))],
        fetch=100,
    )


def q54(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Revenue segments of maternity buyers (spec q54): customers who
    bought Women-category items from catalog or web in 1998, their
    store revenue in the 3 months after Dec 1998 at stores in their own
    county+state, bucketed into $50 segments.
    (Deviations, both needed to keep the differential populated at test
    scales: the buyer window is all of 1998 instead of Dec only — the
    month_seq scalar subquery stays anchored at (1998, 12) — and the
    item filter keeps only the category conjunct, since this datagen
    draws category and class independently.)
    ≙ reference CI matrix query q54 (tpcds-reusable.yml:92)."""
    from ..tpch.queries import scalar_subquery

    f64 = DataType.float64()
    i32 = DataType.int32()
    cs = ProjectExec(t["catalog_sales"], [
        col("cs_sold_date_sk").alias("sold_date_sk"),
        col("cs_bill_customer_sk").alias("customer_sk"),
        col("cs_item_sk").alias("item_sk"),
    ])
    ws = ProjectExec(t["web_sales"], [
        col("ws_sold_date_sk").alias("sold_date_sk"),
        col("ws_bill_customer_sk").alias("customer_sk"),
        col("ws_item_sk").alias("item_sk"),
    ])
    u = UnionExec([cs, ws])
    it = FilterExec(t["item"], col("i_category") == lit("Women"))
    it = ProjectExec(it, [col("i_item_sk")])
    j = broadcast_join(it, u, [col("i_item_sk")], [col("item_sk")], JoinType.INNER, build_is_left=True)
    dt = FilterExec(t["date_dim"], col("d_year") == lit(1998))
    dt = ProjectExec(dt, [col("d_date_sk")])
    j = broadcast_join(dt, j, [col("d_date_sk")], [col("sold_date_sk")], JoinType.INNER, build_is_left=True)
    cust = ProjectExec(t["customer"],
                       [col("c_customer_sk"), col("c_current_addr_sk")])
    j = shuffle_join(cust, j, [col("c_customer_sk")], [col("customer_sk")],
                     JoinType.INNER, n_parts, build_left=True)
    my_customers = two_stage_agg(
        ProjectExec(j, [col("c_customer_sk"), col("c_current_addr_sk")]),
        [GroupingExpr(col("c_customer_sk"), "c_customer_sk"),
         GroupingExpr(col("c_current_addr_sk"), "c_current_addr_sk")],
        [],
        n_parts,
    )
    # scalar subqueries: the month_seq window (Dec 1998 + 1 .. + 3)
    mseq = FilterExec(t["date_dim"],
                      (col("d_year") == lit(1998)) & (col("d_moy") == lit(12)))
    mseq = two_stage_agg(ProjectExec(mseq, [col("d_month_seq").alias("ms")]),
                         [GroupingExpr(col("ms"), "ms")], [], n_parts)
    ms = scalar_subquery(mseq, "ms")
    dt2 = FilterExec(t["date_dim"],
                     (col("d_month_seq") >= ms + lit(1))
                     & (col("d_month_seq") <= ms + lit(3)))
    dt2 = ProjectExec(dt2, [col("d_date_sk").alias("d2_sk")])
    sl = ProjectExec(t["store_sales"],
                     [col("ss_sold_date_sk"), col("ss_customer_sk"),
                      col("ss_ext_sales_price")])
    rev = broadcast_join(my_customers, sl, [col("c_customer_sk")],
                         [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    rev = broadcast_join(dt2, rev, [col("d2_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"],
                     [col("ca_address_sk"), col("ca_county"), col("ca_state")])
    rev = broadcast_join(ca, rev, [col("ca_address_sk")],
                         [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    st = ProjectExec(t["store"], [col("s_county"), col("s_state")])
    rev = broadcast_join(st, rev, [col("s_county"), col("s_state")],
                         [col("ca_county"), col("ca_state")], JoinType.INNER, build_is_left=True)
    my_revenue = two_stage_agg(
        rev,
        [GroupingExpr(col("c_customer_sk"), "c_customer_sk")],
        [AggFunction("sum", col("ss_ext_sales_price"), "revenue")],
        n_parts,
    )
    seg = ProjectExec(my_revenue, [
        (col("revenue").cast(f64) / lit(50.0)).cast(i32).alias("segment"),
    ])
    agg = two_stage_agg(
        seg,
        [GroupingExpr(col("segment"), "segment")],
        [AggFunction("count", lit(1), "num_customers")],
        n_parts,
    )
    proj = ProjectExec(agg, [
        col("segment"),
        col("num_customers"),
        (col("segment") * lit(50)).alias("segment_base"),
    ])
    return single_sorted(
        proj,
        [SortField(col("segment")), SortField(col("num_customers"))],
        fetch=100,
    )


def q58(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Cross-channel items sold evenly (spec q58): per-item revenue in
    the month of 2000-01-03 for each of the three channels, kept when
    every channel's revenue is within a band of each other.
    (Deviations: the spec's week window is widened to the containing
    month — same nested scalar-subquery + date-slice shape — and the
    90%..110% band to 25%..400%; the spec constants select zero rows
    from this datagen's sparse per-item-week cells.)
    ≙ reference CI matrix query q58 (tpcds-reusable.yml:92)."""
    import datetime

    from ..tpch.queries import scalar_subquery

    D = datetime.date
    f64 = DataType.float64()
    wk = FilterExec(t["date_dim"], col("d_date") == lit(D(2000, 1, 3)))
    wk = two_stage_agg(ProjectExec(wk, [col("d_month_seq").alias("wk_sel")]),
                       [GroupingExpr(col("wk_sel"), "wk_sel")], [], n_parts)
    wk_seq = scalar_subquery(wk, "wk_sel")

    def channel(fact, item_c, date_c, price_c, rev_name, id_name):
        dd = FilterExec(t["date_dim"], col("d_month_seq") == wk_seq)
        dd = ProjectExec(dd, [col("d_date_sk")])
        sl = ProjectExec(t[fact], [col(date_c), col(item_c), col(price_c)])
        j = broadcast_join(dd, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
        it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
        j = broadcast_join(it, j, [col("i_item_sk")], [col(item_c)], JoinType.INNER, build_is_left=True)
        return two_stage_agg(
            j,
            [GroupingExpr(col("i_item_id"), id_name)],
            [AggFunction("sum", col(price_c), rev_name)],
            n_parts,
        )

    ss_items = channel("store_sales", "ss_item_sk", "ss_sold_date_sk",
                       "ss_ext_sales_price", "ss_item_rev", "item_id")
    cs_items = channel("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                       "cs_ext_sales_price", "cs_item_rev", "cs_item_id")
    ws_items = channel("web_sales", "ws_item_sk", "ws_sold_date_sk",
                       "ws_ext_sales_price", "ws_item_rev", "ws_item_id")
    j = shuffle_join(ss_items, cs_items, [col("item_id")], [col("cs_item_id")],
                     JoinType.INNER, n_parts, build_left=False)
    j = shuffle_join(j, ws_items, [col("item_id")], [col("ws_item_id")],
                     JoinType.INNER, n_parts, build_left=False)
    ssr = col("ss_item_rev").cast(f64)
    csr = col("cs_item_rev").cast(f64)
    wsr = col("ws_item_rev").cast(f64)

    def near(a, b):
        return (a >= lit(0.25) * b) & (a <= lit(4.0) * b)

    f = FilterExec(j, near(ssr, csr) & near(ssr, wsr) & near(csr, ssr)
                   & near(csr, wsr) & near(wsr, ssr) & near(wsr, csr))
    total = ssr + csr + wsr
    proj = ProjectExec(f, [
        col("item_id"),
        col("ss_item_rev"),
        (ssr / total / lit(3.0) * lit(100.0)).alias("ss_dev"),
        col("cs_item_rev"),
        (csr / total / lit(3.0) * lit(100.0)).alias("cs_dev"),
        col("ws_item_rev"),
        (wsr / total / lit(3.0) * lit(100.0)).alias("ws_dev"),
        (total / lit(3.0)).alias("average"),
    ])
    return single_sorted(
        proj,
        [SortField(col("item_id")), SortField(col("ss_item_rev"))],
        fetch=100,
    )


_MONTHS = ("jan", "feb", "mar", "apr", "may", "jun",
           "jul", "aug", "sep", "oct", "nov", "dec")

_Q66_KEYS = ("w_warehouse_name", "w_warehouse_sq_ft", "w_city",
             "w_county", "w_state", "w_country")


def _q66_channel(t, n_parts, fact, wh_c, date_c, time_c, mode_c, qty_c,
                 sales_c, net_c):
    """One channel of q66: warehouse x month pivot of sales and net.
    Empty month buckets are NULL sums (house pivot convention, see
    _weekly_dow_pivot; spec writes ELSE 0)."""
    from ..exprs.ir import Case

    f64 = DataType.float64()
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2001))
    dt = ProjectExec(dt, [col("d_date_sk"), col("d_moy")])
    tm = FilterExec(t["time_dim"], (col("t_time") >= lit(30838))
                    & (col("t_time") <= lit(30838 + 28800)))
    tm = ProjectExec(tm, [col("t_time_sk")])
    sm = FilterExec(t["ship_mode"],
                    col("sm_carrier").isin(lit("DHL"), lit("BARIAN")))
    sm = ProjectExec(sm, [col("sm_ship_mode_sk")])
    sl = ProjectExec(t[fact], [col(wh_c), col(date_c), col(time_c),
                               col(mode_c), col(qty_c), col(sales_c),
                               col(net_c)])
    j = broadcast_join(dt, sl, [col("d_date_sk")], [col(date_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(tm, j, [col("t_time_sk")], [col(time_c)], JoinType.INNER, build_is_left=True)
    j = broadcast_join(sm, j, [col("sm_ship_mode_sk")], [col(mode_c)], JoinType.INNER, build_is_left=True)
    wh = ProjectExec(t["warehouse"],
                     [col("w_warehouse_sk")] + [col(k) for k in _Q66_KEYS])
    j = broadcast_join(wh, j, [col("w_warehouse_sk")], [col(wh_c)], JoinType.INNER, build_is_left=True)
    sales = col(sales_c) * col(qty_c)
    net = col(net_c) * col(qty_c)
    pivots = [
        Case([(col("d_moy") == lit(m), sales)], None).alias(f"{nm}_sales_v")
        for m, nm in enumerate(_MONTHS, start=1)
    ] + [
        Case([(col("d_moy") == lit(m), net)], None).alias(f"{nm}_net_v")
        for m, nm in enumerate(_MONTHS, start=1)
    ]
    proj = ProjectExec(j, [col(k) for k in _Q66_KEYS] + pivots)
    agg = two_stage_agg(
        proj,
        [GroupingExpr(col(k), k) for k in _Q66_KEYS],
        [AggFunction("sum", col(f"{nm}_sales_v"), f"{nm}_sales")
         for nm in _MONTHS]
        + [AggFunction("sum", col(f"{nm}_net_v"), f"{nm}_net")
           for nm in _MONTHS],
        n_parts,
    )
    per = [
        (col(f"{nm}_sales").cast(f64) / col("w_warehouse_sq_ft").cast(f64))
        .alias(f"{nm}_sales_per_sq_foot")
        for nm in _MONTHS
    ]
    return ProjectExec(
        agg,
        [col(k) for k in _Q66_KEYS]
        + [lit("DHL,BARIAN").alias("ship_carriers"), lit(2001).alias("year")]
        + [col(f"{nm}_sales") for nm in _MONTHS]
        + per
        + [col(f"{nm}_net") for nm in _MONTHS],
    )


def q66(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Warehouse monthly pivot (spec q66): web + catalog 2001 sales and
    net by warehouse and month within an 8-hour sold-time window on
    DHL/BARIAN ship modes, re-aggregated over the channel union with
    per-square-foot ratios.
    ≙ reference CI matrix query q66 (tpcds-reusable.yml:93)."""
    web = _q66_channel(t, n_parts, "web_sales", "ws_warehouse_sk",
                       "ws_sold_date_sk", "ws_sold_time_sk",
                       "ws_ship_mode_sk", "ws_quantity",
                       "ws_ext_sales_price", "ws_net_paid")
    cat = _q66_channel(t, n_parts, "catalog_sales", "cs_warehouse_sk",
                       "cs_sold_date_sk", "cs_sold_time_sk",
                       "cs_ship_mode_sk", "cs_quantity",
                       "cs_sales_price", "cs_net_paid_inc_tax")
    u = UnionExec([web, cat])
    keys = list(_Q66_KEYS) + ["ship_carriers", "year"]
    measures = ([f"{nm}_sales" for nm in _MONTHS]
                + [f"{nm}_sales_per_sq_foot" for nm in _MONTHS]
                + [f"{nm}_net" for nm in _MONTHS])
    agg = two_stage_agg(
        u,
        [GroupingExpr(col(k), k) for k in keys],
        [AggFunction("sum", col(m), m) for m in measures],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("w_warehouse_name"))], fetch=100)


def q71(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Brand sales by meal-time minute (spec q71): Nov 1999 sales from
    all three channels for manager-1 items, restricted to
    breakfast/dinner time_dim rows, grouped by brand and minute.
    ≙ reference CI matrix query q71 (tpcds-reusable.yml:93)."""
    it = FilterExec(t["item"], col("i_manager_id") == lit(1))
    it = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    parts = []
    for fact, price_c, date_c, item_c, time_c in (
        ("web_sales", "ws_ext_sales_price", "ws_sold_date_sk",
         "ws_item_sk", "ws_sold_time_sk"),
        ("catalog_sales", "cs_ext_sales_price", "cs_sold_date_sk",
         "cs_item_sk", "cs_sold_time_sk"),
        ("store_sales", "ss_ext_sales_price", "ss_sold_date_sk",
         "ss_item_sk", "ss_sold_time_sk"),
    ):
        dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11))
                        & (col("d_year") == lit(1999)))
        dt = ProjectExec(dt, [col("d_date_sk")])
        sl = ProjectExec(t[fact], [
            col(price_c).alias("ext_price"),
            col(date_c).alias("sold_date_sk"),
            col(item_c).alias("sold_item_sk"),
            col(time_c).alias("time_sk"),
        ])
        parts.append(broadcast_join(dt, sl, [col("d_date_sk")],
                                    [col("sold_date_sk")], JoinType.INNER, build_is_left=True))
    u = UnionExec(parts)
    j = broadcast_join(it, u, [col("i_item_sk")], [col("sold_item_sk")], JoinType.INNER, build_is_left=True)
    tm = FilterExec(t["time_dim"], (col("t_meal_time") == lit("breakfast"))
                    | (col("t_meal_time") == lit("dinner")))
    tm = ProjectExec(tm, [col("t_time_sk"), col("t_hour"), col("t_minute")])
    j = broadcast_join(tm, j, [col("t_time_sk")], [col("time_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand"),
         GroupingExpr(col("t_hour"), "t_hour"),
         GroupingExpr(col("t_minute"), "t_minute")],
        [AggFunction("sum", col("ext_price"), "ext_price")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("ext_price"), ascending=False),
         SortField(col("brand_id"))],
    )


def q84(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Returning customers by city and income band (spec q84): Midway
    customers in income bands [38128, 88128] joined to their store
    returns via the shared demographics edge.
    (Deviation: the spec city 'Edgewood' is not in this datagen's city
    domain; 'Midway' stands in.)
    ≙ reference CI matrix query q84 (tpcds-reusable.yml:96)."""
    from ..exprs.ir import ScalarFunc

    ca = FilterExec(t["customer_address"], col("ca_city") == lit("Midway"))
    ca = ProjectExec(ca, [col("ca_address_sk")])
    cust = ProjectExec(t["customer"], [
        col("c_customer_id"), col("c_first_name"), col("c_last_name"),
        col("c_current_addr_sk"), col("c_current_cdemo_sk"),
        col("c_current_hdemo_sk"),
    ])
    j = broadcast_join(ca, cust, [col("ca_address_sk")],
                       [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    ib = FilterExec(t["income_band"],
                    (col("ib_lower_bound") >= lit(38128))
                    & (col("ib_upper_bound") <= lit(38128 + 50000)))
    ib = ProjectExec(ib, [col("ib_income_band_sk")])
    hd = ProjectExec(t["household_demographics"],
                     [col("hd_demo_sk"), col("hd_income_band_sk")])
    hd = broadcast_join(ib, hd, [col("ib_income_band_sk")],
                        [col("hd_income_band_sk")], JoinType.INNER, build_is_left=True)
    hd = ProjectExec(hd, [col("hd_demo_sk")])
    j = broadcast_join(hd, j, [col("hd_demo_sk")],
                       [col("c_current_hdemo_sk")], JoinType.INNER, build_is_left=True)
    cd = ProjectExec(t["customer_demographics"], [col("cd_demo_sk")])
    j = broadcast_join(cd, j, [col("cd_demo_sk")],
                       [col("c_current_cdemo_sk")], JoinType.INNER, build_is_left=True)
    sr = ProjectExec(t["store_returns"], [col("sr_cdemo_sk")])
    j = shuffle_join(j, sr, [col("cd_demo_sk")], [col("sr_cdemo_sk")],
                     JoinType.INNER, n_parts, build_left=True)
    proj = ProjectExec(j, [
        col("c_customer_id").alias("customer_id"),
        ScalarFunc("concat", [col("c_last_name"), lit(", "),
                              col("c_first_name")]).alias("customername"),
    ])
    return single_sorted(proj, [SortField(col("customer_id"))], fetch=100)


def q85(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Web-return reasons by demographic/geographic bands (spec q85):
    web sales joined to their returns, both demographics of the return,
    the refund address and the reason, filtered by OR'd band triples,
    averaged per reason.
    (Deviations, tuned so the triple-AND-of-ORs keeps rows at test
    scales: the education conjuncts are dropped from the demographic
    branches and the price/profit bands are widened to thirds of this
    datagen's domains; state sets are drawn from its 5-state domain.)
    ≙ reference CI matrix query q85 (tpcds-reusable.yml:96)."""
    from ..exprs.ir import ScalarFunc

    f64 = DataType.float64()
    ws = ProjectExec(t["web_sales"], [
        col("ws_item_sk"), col("ws_order_number"), col("ws_web_page_sk"),
        col("ws_sold_date_sk"), col("ws_quantity"), col("ws_sales_price"),
        col("ws_net_profit"),
    ])
    wr = ProjectExec(t["web_returns"], [
        col("wr_item_sk"), col("wr_order_number"),
        col("wr_refunded_cdemo_sk"), col("wr_returning_cdemo_sk"),
        col("wr_refunded_addr_sk"), col("wr_reason_sk"),
        col("wr_refunded_cash"), col("wr_fee"),
    ])
    j = shuffle_join(ws, wr, [col("ws_order_number"), col("ws_item_sk")],
                     [col("wr_order_number"), col("wr_item_sk")],
                     JoinType.INNER, n_parts, build_left=False)
    wp = ProjectExec(t["web_page"], [col("wp_web_page_sk")])
    j = broadcast_join(wp, j, [col("wp_web_page_sk")],
                       [col("ws_web_page_sk")], JoinType.INNER, build_is_left=True)
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt = ProjectExec(dt, [col("d_date_sk")])
    j = broadcast_join(dt, j, [col("d_date_sk")],
                       [col("ws_sold_date_sk")], JoinType.INNER, build_is_left=True)
    cd1 = ProjectExec(t["customer_demographics"], [
        col("cd_demo_sk").alias("cd1_sk"),
        col("cd_marital_status").alias("cd1_ms"),
    ])
    j = broadcast_join(cd1, j, [col("cd1_sk")],
                       [col("wr_refunded_cdemo_sk")], JoinType.INNER, build_is_left=True)
    cd2 = ProjectExec(t["customer_demographics"], [
        col("cd_demo_sk").alias("cd2_sk"),
        col("cd_marital_status").alias("cd2_ms"),
    ])
    j = broadcast_join(cd2, j, [col("cd2_sk")],
                       [col("wr_returning_cdemo_sk")], JoinType.INNER, build_is_left=True)
    ca = ProjectExec(t["customer_address"], [
        col("ca_address_sk"), col("ca_country"), col("ca_state")])
    j = broadcast_join(ca, j, [col("ca_address_sk")],
                       [col("wr_refunded_addr_sk")], JoinType.INNER, build_is_left=True)
    rs = ProjectExec(t["reason"], [col("r_reason_sk"), col("r_reason_desc")])
    j = broadcast_join(rs, j, [col("r_reason_sk")],
                       [col("wr_reason_sk")], JoinType.INNER, build_is_left=True)
    price = col("ws_sales_price").cast(f64)
    profit = col("ws_net_profit").cast(f64)

    def demo(ms, lo, hi):
        return ((col("cd1_ms") == lit(ms))
                & (col("cd1_ms") == col("cd2_ms"))
                & (price >= lit(lo)) & (price <= lit(hi)))

    def geo(states, lo, hi):
        return ((col("ca_country") == lit("United States"))
                & col("ca_state").isin(*[lit(s) for s in states])
                & (profit >= lit(lo)) & (profit <= lit(hi)))

    f = FilterExec(
        j,
        (demo("M", 0.0, 150.0) | demo("S", 50.0, 250.0)
         | demo("W", 100.0, 300.0))
        & (geo(("OH", "TN", "SD"), -1000.0, 500.0)
           | geo(("AL", "GA", "SD"), 0.0, 1500.0)
           | geo(("TN", "GA", "AL"), -500.0, 1000.0)),
    )
    agg = two_stage_agg(
        f,
        [GroupingExpr(col("r_reason_desc"), "r")],
        [AggFunction("avg", col("ws_quantity"), "avg_q"),
         AggFunction("avg", col("wr_refunded_cash"), "avg_cash"),
         AggFunction("avg", col("wr_fee"), "avg_fee")],
        n_parts,
    )
    proj = ProjectExec(agg, [
        ScalarFunc("substring", [col("r"), lit(1), lit(20)]).alias("reason"),
        col("avg_q"), col("avg_cash"), col("avg_fee"),
    ])
    return single_sorted(
        proj,
        [SortField(col("reason")), SortField(col("avg_q")),
         SortField(col("avg_cash")), SortField(col("avg_fee"))],
        fetch=100,
    )


QUERIES.update({
    "q31": q31,
    "q49": q49,
    "q54": q54,
    "q58": q58,
    "q66": q66,
    "q71": q71,
    "q84": q84,
    "q85": q85,
})


def build_query(name: str, scans: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return QUERIES[name](scans, n_parts)
