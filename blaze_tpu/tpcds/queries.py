"""TPC-DS query plans over the operator layer (star-join subset:
q3 q7 q42 q52 q55 q96 — the BASELINE.json TPC-DS configs plus the
classic reporting-join shapes).

Same architecture slot as tpch/queries.py: each builder plays Spark
planner + BlazeConverters for its query, wiring scans through
filters/broadcast star joins/two-stage aggregations/exchanges.

≙ reference end-to-end TPC-DS differential matrix
(.github/workflows/tpcds-reusable.yml:83-143).
"""

from __future__ import annotations

from typing import Callable, Dict

from ..exprs import col, lit
from ..ops import (
    AggExec,
    AggFunction,
    AggMode,
    ExecNode,
    FilterExec,
    GroupingExpr,
    ProjectExec,
    SortField,
)
from ..ops.joins import JoinType
from ..tpch.queries import broadcast_join, single_sorted, two_stage_agg


def q3(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], col("d_moy") == lit(11))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manufact_id") == lit(128))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("d_year")), SortField(col("sum_agg"), ascending=False), SortField(col("brand_id"))],
        fetch=100,
    )


def q7(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    pr = FilterExec(
        t["promotion"],
        (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N")),
    )
    pr_p = ProjectExec(pr, [col("p_promo_sk")])
    sales = t["store_sales"]
    j = broadcast_join(cd_p, sales, [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col("ss_promo_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id")],
        [
            AggFunction("avg", col("ss_quantity"), "agg1"),
            AggFunction("avg", col("ss_list_price"), "agg2"),
            AggFunction("avg", col("ss_coupon_amt"), "agg3"),
            AggFunction("avg", col("ss_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("i_item_id"))], fetch=100)


def _brand_report(t, n_parts, *, year, moy, manager, order_year_first):
    """Shared shape of q52/q55 (and near-q3): month+year slice of
    store_sales by brand."""
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(moy)) & (col("d_year") == lit(year)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(manager))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "ext_price")],
        n_parts,
    )
    sort = (
        [SortField(col("d_year")), SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
        if order_year_first
        else [SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
    )
    return single_sorted(agg, sort, fetch=100)


def q52(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=2000, moy=11, manager=1, order_year_first=True)


def q55(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=1999, moy=11, manager=28, order_year_first=False)


def q42(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(1))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_category_id"), col("i_category")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_category_id"), "category_id"),
         GroupingExpr(col("i_category"), "category")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("sum_agg"), ascending=False),
         SortField(col("d_year")),
         SortField(col("category_id")),
         SortField(col("category"))],
        fetch=100,
    )


def q96(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    td = FilterExec(t["time_dim"], (col("t_hour") == lit(20)) & (col("t_minute") >= lit(30)))
    td_p = ProjectExec(td, [col("t_time_sk")])
    hd = FilterExec(t["household_demographics"], col("hd_dep_count") == lit(7))
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(t["store"], col("s_store_name") == lit("ese"))
    st_p = ProjectExec(st, [col("s_store_sk")])
    sales = ProjectExec(
        t["store_sales"], [col("ss_sold_time_sk"), col("ss_hdemo_sk"), col("ss_store_sk")]
    )
    j = broadcast_join(td_p, sales, [col("t_time_sk")], [col("ss_sold_time_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    return two_stage_agg(j, [], [AggFunction("count_star", None, "cnt")], n_parts)


QUERIES: Dict[str, Callable[[Dict[str, ExecNode], int], ExecNode]] = {
    "q3": q3,
    "q7": q7,
    "q42": q42,
    "q52": q52,
    "q55": q55,
    "q96": q96,
}


def build_query(name: str, scans: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return QUERIES[name](scans, n_parts)
