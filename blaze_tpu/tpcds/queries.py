"""TPC-DS query plans over the operator layer (star-join subset:
q3 q7 q42 q52 q55 q96 — the BASELINE.json TPC-DS configs plus the
classic reporting-join shapes).

Same architecture slot as tpch/queries.py: each builder plays Spark
planner + BlazeConverters for its query, wiring scans through
filters/broadcast star joins/two-stage aggregations/exchanges.

≙ reference end-to-end TPC-DS differential matrix
(.github/workflows/tpcds-reusable.yml:83-143).
"""

from __future__ import annotations

from typing import Callable, Dict

from ..exprs import col, lit
from ..ops import (
    AggExec,
    AggFunction,
    AggMode,
    ExecNode,
    FilterExec,
    GroupingExpr,
    ProjectExec,
    SortField,
)
from ..ops.joins import JoinType
from ..schema import DataType
from ..tpch.queries import broadcast_join, single_sorted, two_stage_agg


def q3(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], col("d_moy") == lit(11))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manufact_id") == lit(128))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("d_year")), SortField(col("sum_agg"), ascending=False), SortField(col("brand_id"))],
        fetch=100,
    )


def q7(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2000))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    pr = FilterExec(
        t["promotion"],
        (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N")),
    )
    pr_p = ProjectExec(pr, [col("p_promo_sk")])
    sales = t["store_sales"]
    j = broadcast_join(cd_p, sales, [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(pr_p, j, [col("p_promo_sk")], [col("ss_promo_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id")],
        [
            AggFunction("avg", col("ss_quantity"), "agg1"),
            AggFunction("avg", col("ss_list_price"), "agg2"),
            AggFunction("avg", col("ss_coupon_amt"), "agg3"),
            AggFunction("avg", col("ss_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(agg, [SortField(col("i_item_id"))], fetch=100)


def _brand_report(t, n_parts, *, year, moy, manager, order_year_first):
    """Shared shape of q52/q55 (and near-q3): month+year slice of
    store_sales by brand."""
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(moy)) & (col("d_year") == lit(year)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(manager))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand")],
        [AggFunction("sum", col("ss_ext_sales_price"), "ext_price")],
        n_parts,
    )
    sort = (
        [SortField(col("d_year")), SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
        if order_year_first
        else [SortField(col("ext_price"), ascending=False), SortField(col("brand_id"))]
    )
    return single_sorted(agg, sort, fetch=100)


def q52(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=2000, moy=11, manager=1, order_year_first=True)


def q55(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _brand_report(t, n_parts, year=1999, moy=11, manager=28, order_year_first=False)


def q42(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_year")])
    sales = ProjectExec(t["store_sales"], [col("ss_sold_date_sk"), col("ss_item_sk"), col("ss_ext_sales_price")])
    j1 = broadcast_join(dt_p, sales, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    it = FilterExec(t["item"], col("i_manager_id") == lit(1))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_category_id"), col("i_category")])
    j2 = broadcast_join(it_p, j1, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j2,
        [GroupingExpr(col("d_year"), "d_year"),
         GroupingExpr(col("i_category_id"), "category_id"),
         GroupingExpr(col("i_category"), "category")],
        [AggFunction("sum", col("ss_ext_sales_price"), "sum_agg")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("sum_agg"), ascending=False),
         SortField(col("d_year")),
         SortField(col("category_id")),
         SortField(col("category"))],
        fetch=100,
    )


def q96(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    td = FilterExec(t["time_dim"], (col("t_hour") == lit(20)) & (col("t_minute") >= lit(30)))
    td_p = ProjectExec(td, [col("t_time_sk")])
    hd = FilterExec(t["household_demographics"], col("hd_dep_count") == lit(7))
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(t["store"], col("s_store_name") == lit("ese"))
    st_p = ProjectExec(st, [col("s_store_sk")])
    sales = ProjectExec(
        t["store_sales"], [col("ss_sold_time_sk"), col("ss_hdemo_sk"), col("ss_store_sk")]
    )
    j = broadcast_join(td_p, sales, [col("t_time_sk")], [col("ss_sold_time_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    return two_stage_agg(j, [], [AggFunction("count_star", None, "cnt")], n_parts)


def q27(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """ROLLUP(i_item_id, s_state) — exercises ExpandExec + grouping-id
    the way Spark plans rollups (Expand with null-filled projections)."""
    from ..exprs.ir import Lit
    from ..ops import ExpandExec
    from ..schema import DataType

    cd = FilterExec(
        t["customer_demographics"],
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")),
    )
    cd_p = ProjectExec(cd, [col("cd_demo_sk")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(2002))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    st = FilterExec(
        t["store"],
        col("s_state").isin(lit("TN"), lit("SD"), lit("AL"), lit("GA"), lit("OH")),
    )
    st_p = ProjectExec(st, [col("s_store_sk"), col("s_state")])
    j = broadcast_join(cd_p, t["store_sales"], [col("cd_demo_sk")], [col("ss_cdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    it = ProjectExec(t["item"], [col("i_item_sk"), col("i_item_id")])
    j = broadcast_join(it, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    # rollup = Expand with (item,state,0) (item,null,1) (null,null,3)
    passthrough = [col("ss_quantity"), col("ss_list_price"), col("ss_coupon_amt"), col("ss_sales_price")]
    null_s16 = Lit(None, DataType.string(16))
    null_s8 = Lit(None, DataType.string(8))
    expand = ExpandExec(
        j,
        [
            passthrough + [col("i_item_id"), col("s_state"), lit(0)],
            passthrough + [col("i_item_id"), null_s8, lit(1)],
            passthrough + [null_s16, null_s8, lit(3)],
        ],
        ["ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
         "i_item_id", "s_state", "g_id"],
    )
    agg = two_stage_agg(
        expand,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("s_state"), "s_state"),
         GroupingExpr(col("g_id"), "g_id")],
        [
            AggFunction("avg", col("ss_quantity"), "agg1"),
            AggFunction("avg", col("ss_list_price"), "agg2"),
            AggFunction("avg", col("ss_coupon_amt"), "agg3"),
            AggFunction("avg", col("ss_sales_price"), "agg4"),
        ],
        n_parts,
    )
    return single_sorted(
        agg, [SortField(col("i_item_id")), SortField(col("s_state"))], fetch=100
    )


def q89(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Monthly brand sales vs yearly store average — WindowExec avg
    over the whole partition + CASE-guarded ratio filter."""
    from ..exprs.ir import Case, func
    from ..ops import WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning
    from ..schema import DataType

    cat_a = col("i_category").isin(lit("Books"), lit("Electronics"), lit("Sports"))
    cls_a = col("i_class").isin(lit("accessories"), lit("reference"), lit("football"))
    cat_b = col("i_category").isin(lit("Men"), lit("Jewelry"), lit("Women"))
    cls_b = col("i_class").isin(lit("shirts"), lit("birdal"), lit("dresses"))
    it = FilterExec(t["item"], (cat_a & cls_a) | (cat_b & cls_b))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_category"), col("i_class"), col("i_brand")])
    dt = FilterExec(t["date_dim"], col("d_year") == lit(1999))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col("d_moy")])
    st_p = ProjectExec(t["store"], [col("s_store_sk"), col("s_store_name"), col("s_company_name")])
    j = broadcast_join(it_p, t["store_sales"], [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_category"), "i_category"),
         GroupingExpr(col("i_class"), "i_class"),
         GroupingExpr(col("i_brand"), "i_brand"),
         GroupingExpr(col("s_store_name"), "s_store_name"),
         GroupingExpr(col("s_company_name"), "s_company_name"),
         GroupingExpr(col("d_moy"), "d_moy")],
        [AggFunction("sum", col("ss_sales_price"), "sum_sales")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    from ..ops import SortExec

    pre = SortExec(single, [
        SortField(col("i_category")), SortField(col("i_brand")),
        SortField(col("s_store_name")), SortField(col("s_company_name")),
    ])
    w = WindowExec(
        pre,
        [WindowFunction("avg", "avg_monthly_sales", col("sum_sales"), whole_partition=True)],
        [col("i_category"), col("i_brand"), col("s_store_name"), col("s_company_name")],
        [],
    )
    f64 = DataType.float64()
    sum_f = col("sum_sales").cast(f64)
    avg_f = col("avg_monthly_sales").cast(f64)
    ratio = Case(
        [( avg_f != lit(0.0), func("abs", sum_f - avg_f) / avg_f )], None
    )
    filt = FilterExec(w, ratio > lit(0.1))
    proj = ProjectExec(
        filt,
        [col("i_category"), col("i_class"), col("i_brand"), col("s_store_name"),
         col("s_company_name"), col("d_moy"), col("sum_sales"), col("avg_monthly_sales"),
         (sum_f - avg_f)],
        ["i_category", "i_class", "i_brand", "s_store_name",
         "s_company_name", "d_moy", "sum_sales", "avg_monthly_sales", "delta"],
    )
    out = single_sorted(proj, [SortField(col("delta")), SortField(col("s_store_name"))], fetch=100)
    return out


def q98(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Item revenue share of its class — windowed sum over i_class."""
    import datetime

    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning
    from ..schema import DataType

    D = datetime.date
    dt = FilterExec(
        t["date_dim"],
        (col("d_date") >= lit(D(1999, 2, 22))) & (col("d_date") <= lit(D(1999, 3, 24))),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = FilterExec(
        t["item"],
        col("i_category").isin(lit("Sports"), lit("Books"), lit("Home")),
    )
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_item_id"), col("i_item_desc"),
                            col("i_category"), col("i_class"), col("i_current_price")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_item_id"), "i_item_id"),
         GroupingExpr(col("i_item_desc"), "i_item_desc"),
         GroupingExpr(col("i_category"), "i_category"),
         GroupingExpr(col("i_class"), "i_class"),
         GroupingExpr(col("i_current_price"), "i_current_price")],
        [AggFunction("sum", col("ss_ext_sales_price"), "itemrevenue")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    pre = SortExec(single, [SortField(col("i_class"))])
    w = WindowExec(
        pre,
        [WindowFunction("sum", "class_revenue", col("itemrevenue"), whole_partition=True)],
        [col("i_class")],
        [],
    )
    f64 = DataType.float64()
    ratio = (col("itemrevenue").cast(f64) * lit(100.0)) / col("class_revenue").cast(f64)
    proj = ProjectExec(
        w,
        [col("i_item_id"), col("i_item_desc"), col("i_category"), col("i_class"),
         col("i_current_price"), col("itemrevenue"), ratio],
        ["i_item_id", "i_item_desc", "i_category", "i_class",
         "i_current_price", "itemrevenue", "revenueratio"],
    )
    return single_sorted(
        proj,
        [SortField(col("i_category")), SortField(col("i_class")),
         SortField(col("i_item_id")), SortField(col("i_item_desc")),
         SortField(col("revenueratio"))],
    )


def _ticket_report(t, n_parts, *, dom_ranges, buy_potentials, cnt_lo, cnt_hi,
                   dep_vehicle_ratio, order_by):
    """Shared q34/q73 shape: per-(ticket, customer) line counts with a
    HAVING range, then join customer for the report — aggregation
    BELOW a join, with a post-agg filter."""
    dt_pred = None
    for lo, hi in dom_ranges:
        rng_p = (col("d_dom") >= lit(lo)) & (col("d_dom") <= lit(hi))
        dt_pred = rng_p if dt_pred is None else (dt_pred | rng_p)
    dt = FilterExec(
        t["date_dim"],
        dt_pred & col("d_year").isin(lit(1999), lit(2000), lit(2001)),
    )
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    hd_pred = None
    for bp in buy_potentials:
        p = col("hd_buy_potential") == lit(bp)
        hd_pred = p if hd_pred is None else (hd_pred | p)
    hd_pred = hd_pred & (col("hd_vehicle_count") > lit(0))
    # spec CASE WHEN vehicle_count > 0 THEN dep/vehicle END > ratio
    # (the > 0 guard above makes the CASE arm unconditional here)
    f64 = DataType.float64()
    hd_pred = hd_pred & (
        col("hd_dep_count").cast(f64) / col("hd_vehicle_count").cast(f64)
        > lit(dep_vehicle_ratio)
    )
    hd = FilterExec(t["household_demographics"], hd_pred)
    hd_p = ProjectExec(hd, [col("hd_demo_sk")])
    st = FilterExec(
        t["store"],
        col("s_county").isin(
            lit("Williamson County"), lit("Franklin Parish"),
            lit("Bronx County"), lit("Orange County"),
        ),
    )
    st_p = ProjectExec(st, [col("s_store_sk")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(hd_p, j, [col("hd_demo_sk")], [col("ss_hdemo_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("ss_ticket_number"), "ss_ticket_number"),
         GroupingExpr(col("ss_customer_sk"), "ss_customer_sk")],
        [AggFunction("count_star", None, "cnt")],
        n_parts,
    )
    having = FilterExec(agg, (col("cnt") >= lit(cnt_lo)) & (col("cnt") <= lit(cnt_hi)))
    cust = ProjectExec(
        t["customer"],
        [col("c_customer_sk"), col("c_salutation"), col("c_first_name"),
         col("c_last_name"), col("c_preferred_cust_flag")],
    )
    j2 = broadcast_join(cust, having, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    return single_sorted(j2, order_by)


def q34(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _ticket_report(
        t, n_parts,
        dom_ranges=[(1, 3), (25, 28)],
        buy_potentials=[">10000", "Unknown"],
        cnt_lo=15, cnt_hi=20,
        dep_vehicle_ratio=1.2,
        order_by=[  # spec q34 ordering
            SortField(col("c_last_name")), SortField(col("c_first_name")),
            SortField(col("c_salutation")),
            SortField(col("c_preferred_cust_flag"), ascending=False),
            SortField(col("ss_ticket_number")),
        ],
    )


def q73(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _ticket_report(
        t, n_parts,
        dom_ranges=[(1, 2)],
        buy_potentials=[">10000", "Unknown"],
        cnt_lo=1, cnt_hi=5,
        dep_vehicle_ratio=1.0,
        order_by=[SortField(col("cnt"), ascending=False), SortField(col("c_last_name"))],
    )


def _manufact_window_report(t, n_parts, *, group_col, avg_name, order_first):
    """Shared q53/q63 shape: quarterly/monthly manufacturer sales vs
    the manufacturer's window average, CASE-guarded ratio filter."""
    from ..exprs.ir import Case, func
    from ..ops import SortExec, WindowExec, WindowFunction
    from ..parallel import NativeShuffleExchangeExec, SinglePartitioning

    cat_a = col("i_category").isin(lit("Books"), lit("Children"), lit("Electronics"))
    cls_a = col("i_class").isin(lit("personal"), lit("self-help"), lit("reference"))
    cat_b = col("i_category").isin(lit("Women"), lit("Music"), lit("Men"))
    cls_b = col("i_class").isin(lit("accessories"), lit("classical"), lit("fragrances"))
    it = FilterExec(t["item"], (cat_a & cls_a) | (cat_b & cls_b))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_manufact_id")])
    dt = FilterExec(t["date_dim"], col("d_year").isin(lit(1999), lit(2000)))
    dt_p = ProjectExec(dt, [col("d_date_sk"), col(group_col)])
    st_p = ProjectExec(t["store"], [col("s_store_sk")])
    j = broadcast_join(it_p, t["store_sales"], [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(dt_p, j, [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st_p, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_manufact_id"), "i_manufact_id"),
         GroupingExpr(col(group_col), group_col)],
        [AggFunction("sum", col("ss_sales_price"), "sum_sales")],
        n_parts,
    )
    single = NativeShuffleExchangeExec(agg, SinglePartitioning())
    pre = SortExec(single, [SortField(col("i_manufact_id"))])
    w = WindowExec(
        pre,
        [WindowFunction("avg", avg_name, col("sum_sales"), whole_partition=True)],
        [col("i_manufact_id")],
        [],
    )
    f64 = DataType.float64()
    sum_f = col("sum_sales").cast(f64)
    avg_f = col(avg_name).cast(f64)
    ratio = Case([(avg_f > lit(0.0), func("abs", sum_f - avg_f) / avg_f)], None)
    filt = FilterExec(w, ratio > lit(0.1))
    # spec orderings (ascending): q53 avg, sum, manufact;
    # q63 manufact, avg, sum
    order = (
        [SortField(col(avg_name)), SortField(col("sum_sales")),
         SortField(col("i_manufact_id"))]
        if order_first == "avg"
        else [SortField(col("i_manufact_id")), SortField(col(avg_name)),
              SortField(col("sum_sales"))]
    )
    proj = ProjectExec(
        filt,
        [col("i_manufact_id"), col(group_col), col("sum_sales"), col(avg_name)],
        ["i_manufact_id", group_col, "sum_sales", avg_name],
    )
    return single_sorted(proj, order, fetch=100)


def q53(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _manufact_window_report(
        t, n_parts, group_col="d_qoy", avg_name="avg_quarterly_sales",
        order_first="avg",
    )


def q63(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return _manufact_window_report(
        t, n_parts, group_col="d_moy", avg_name="avg_monthly_sales",
        order_first="manufact",
    )


def q19(t: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    """Brand revenue from out-of-zip customers: 5-way star join with a
    NON-EQUI residual (substr(ca_zip,1,5) <> substr(s_zip,1,5))."""
    from ..exprs.ir import func

    dt = FilterExec(t["date_dim"], (col("d_moy") == lit(11)) & (col("d_year") == lit(1998)))
    dt_p = ProjectExec(dt, [col("d_date_sk")])
    it = FilterExec(t["item"], col("i_manager_id") == lit(8))
    it_p = ProjectExec(it, [col("i_item_sk"), col("i_brand_id"), col("i_brand"),
                            col("i_manufact_id"), col("i_manufact")])
    cust = ProjectExec(t["customer"], [col("c_customer_sk"), col("c_current_addr_sk")])
    addr = ProjectExec(t["customer_address"], [col("ca_address_sk"), col("ca_zip")])
    st = ProjectExec(t["store"], [col("s_store_sk"), col("s_zip")])
    j = broadcast_join(dt_p, t["store_sales"], [col("d_date_sk")], [col("ss_sold_date_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(it_p, j, [col("i_item_sk")], [col("ss_item_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(cust, j, [col("c_customer_sk")], [col("ss_customer_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(addr, j, [col("ca_address_sk")], [col("c_current_addr_sk")], JoinType.INNER, build_is_left=True)
    j = broadcast_join(st, j, [col("s_store_sk")], [col("ss_store_sk")], JoinType.INNER, build_is_left=True)
    j = FilterExec(
        j,
        func("substring", col("ca_zip"), lit(1), lit(5))
        != func("substring", col("s_zip"), lit(1), lit(5)),
    )
    agg = two_stage_agg(
        j,
        [GroupingExpr(col("i_brand_id"), "brand_id"),
         GroupingExpr(col("i_brand"), "brand"),
         GroupingExpr(col("i_manufact_id"), "manufact_id"),
         GroupingExpr(col("i_manufact"), "manufact")],
        [AggFunction("sum", col("ss_ext_sales_price"), "ext_price")],
        n_parts,
    )
    return single_sorted(
        agg,
        [SortField(col("ext_price"), ascending=False), SortField(col("brand")),
         SortField(col("brand_id")), SortField(col("manufact_id")),
         SortField(col("manufact"))],
        fetch=100,
    )


QUERIES: Dict[str, Callable[[Dict[str, ExecNode], int], ExecNode]] = {
    "q3": q3,
    "q7": q7,
    "q19": q19,
    "q27": q27,
    "q34": q34,
    "q42": q42,
    "q53": q53,
    "q52": q52,
    "q55": q55,
    "q63": q63,
    "q73": q73,
    "q89": q89,
    "q96": q96,
    "q98": q98,
}


def build_query(name: str, scans: Dict[str, ExecNode], n_parts: int) -> ExecNode:
    return QUERIES[name](scans, n_parts)
