"""Independent numpy/python oracles for the TPC-DS query subset.

Same differential role as tpch/oracle.py: each query re-implemented
from the spec over the generated host tables, no engine code reused.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..tpch.datagen import HostTable, _days
from ..tpch.oracle import _round_half_up, _s_eq, _sv


def _index_by(table: HostTable, key: str) -> Dict[int, int]:
    keys = table[key][0]
    return {int(k): i for i, k in enumerate(keys)}


def _brand_rollup(tables, *, year, moy, item_filter_col, item_filter_val, group_cols):
    """Shared star-join: date slice × item slice × store_sales, grouped
    sums of ss_ext_sales_price."""
    dd = tables["date_dim"]
    it = tables["item"]
    ss = tables["store_sales"]

    d_mask = dd["d_moy"][0] == moy
    if year is not None:
        d_mask &= dd["d_year"][0] == year
    d_sk = dd["d_date_sk"][0][d_mask]
    d_year_by_sk = dict(zip(d_sk.tolist(), dd["d_year"][0][d_mask].tolist()))

    i_mask = it[item_filter_col][0] == item_filter_val
    i_sk = it["i_item_sk"][0][i_mask]
    group_by_sk = {}
    gvals = []
    for gc in group_cols:
        if it[gc][1] is not None:  # string col
            gvals.append(np.array(_sv(it, gc)))
        else:
            gvals.append(it[gc][0])
    for idx in np.flatnonzero(i_mask):
        group_by_sk[int(it["i_item_sk"][0][idx])] = tuple(
            (gv[idx] if isinstance(gv[idx], str) else int(gv[idx])) for gv in gvals
        )

    sums: Dict[tuple, int] = {}
    date_sk = ss["ss_sold_date_sk"][0]
    item_sk = ss["ss_item_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(date_sk.shape[0]):
        dsk = int(date_sk[i])
        isk = int(item_sk[i])
        if dsk not in d_year_by_sk or isk not in group_by_sk:
            continue
        key = (d_year_by_sk[dsk],) + group_by_sk[isk]
        sums[key] = sums.get(key, 0) + int(price[i])
    return sums


def oracle_q3(tables):
    return _brand_rollup(
        tables, year=None, moy=11,
        item_filter_col="i_manufact_id", item_filter_val=128,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q52(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q55(tables):
    return _brand_rollup(
        tables, year=1999, moy=11,
        item_filter_col="i_manager_id", item_filter_val=28,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q42(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_category_id", "i_category"],
    )


def oracle_q7(tables):
    cd = tables["customer_demographics"]
    cd_ok = (
        _s_eq(cd, "cd_gender", "M")
        & _s_eq(cd, "cd_marital_status", "S")
        & _s_eq(cd, "cd_education_status", "College")
    )
    cd_set = set(cd["cd_demo_sk"][0][cd_ok].tolist())

    dd = tables["date_dim"]
    d_set = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())

    pr = tables["promotion"]
    p_ok = _s_eq(pr, "p_channel_email", "N") | _s_eq(pr, "p_channel_event", "N")
    p_set = set(pr["p_promo_sk"][0][p_ok].tolist())

    it = tables["item"]
    item_id_by_sk = dict(zip(it["i_item_sk"][0].tolist(), _sv(it, "i_item_id")))

    ss = tables["store_sales"]
    acc: Dict[str, list] = {}
    cols = [ss[c][0] for c in (
        "ss_cdemo_sk", "ss_sold_date_sk", "ss_promo_sk", "ss_item_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
    )]
    for i in range(cols[0].shape[0]):
        if int(cols[0][i]) not in cd_set:
            continue
        if int(cols[1][i]) not in d_set:
            continue
        if int(cols[2][i]) not in p_set:
            continue
        iid = item_id_by_sk.get(int(cols[3][i]))
        if iid is None:
            continue
        acc.setdefault(iid, []).append(tuple(int(c[i]) for c in cols[4:]))

    out = {}
    for iid, rows in acc.items():
        n = len(rows)

        def avg_dec(idx):
            # decimal avg: result scale +4, float64 HALF_UP (engine path)
            s = sum(r[idx] for r in rows)
            f = float(s) * float(10**4) / n
            return int(_round_half_up(np.array([f]))[0])

        avg_qty = float(sum(r[0] for r in rows)) / n  # int avg -> float64
        out[iid] = (avg_qty, avg_dec(1), avg_dec(2), avg_dec(3), n)
    return out


def oracle_q96(tables):
    td = tables["time_dim"]
    t_set = set(
        td["t_time_sk"][0][(td["t_hour"][0] == 20) & (td["t_minute"][0] >= 30)].tolist()
    )
    hd = tables["household_demographics"]
    h_set = set(hd["hd_demo_sk"][0][hd["hd_dep_count"][0] == 7].tolist())
    st = tables["store"]
    s_set = set(st["s_store_sk"][0][_s_eq(st, "s_store_name", "ese")].tolist())

    ss = tables["store_sales"]
    t_sk = ss["ss_sold_time_sk"][0]
    h_sk = ss["ss_hdemo_sk"][0]
    s_sk = ss["ss_store_sk"][0]
    cnt = 0
    for i in range(t_sk.shape[0]):
        if int(t_sk[i]) in t_set and int(h_sk[i]) in h_set and int(s_sk[i]) in s_set:
            cnt += 1
    return cnt


def oracle_q27(tables):
    """ROLLUP(i_item_id, s_state): returns {(item_id|None, state|None,
    g_id): (avg_qty, avg_list, avg_coupon, avg_sales)} with decimal
    averages as unscaled ints (scale+4, HALF_UP)."""
    cd = tables["customer_demographics"]
    cd_ok = (
        _s_eq(cd, "cd_gender", "M")
        & _s_eq(cd, "cd_marital_status", "S")
        & _s_eq(cd, "cd_education_status", "College")
    )
    cd_set = set(cd["cd_demo_sk"][0][cd_ok].tolist())
    dd = tables["date_dim"]
    d_set = set(dd["d_date_sk"][0][dd["d_year"][0] == 2002].tolist())
    st = tables["store"]
    states = _sv(st, "s_state")
    state_by_sk = {
        int(sk): states[i]
        for i, sk in enumerate(st["s_store_sk"][0])
        if states[i] in ("TN", "SD", "AL", "GA", "OH")
    }
    it = tables["item"]
    item_id_by_sk = dict(zip(it["i_item_sk"][0].tolist(), _sv(it, "i_item_id")))

    ss = tables["store_sales"]
    cols = [ss[c][0] for c in (
        "ss_cdemo_sk", "ss_sold_date_sk", "ss_store_sk", "ss_item_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
    )]
    acc: Dict[tuple, list] = {}
    for i in range(cols[0].shape[0]):
        if int(cols[0][i]) not in cd_set or int(cols[1][i]) not in d_set:
            continue
        state = state_by_sk.get(int(cols[2][i]))
        if state is None:
            continue
        iid = item_id_by_sk.get(int(cols[3][i]))
        if iid is None:
            continue
        row = tuple(int(c[i]) for c in cols[4:])
        for key in ((iid, state, 0), (iid, None, 1), (None, None, 3)):
            acc.setdefault(key, []).append(row)

    out = {}
    for key, rows in acc.items():
        n = len(rows)
        avg_qty = float(sum(r[0] for r in rows)) / n

        def avg_dec(idx):
            f = float(sum(r[idx] for r in rows)) * float(10**4) / n
            return int(_round_half_up(np.array([f]))[0])

        out[key] = (avg_qty, avg_dec(1), avg_dec(2), avg_dec(3))
    return out


def oracle_q89(tables):
    """{(cat, cls, brand, store, company, moy): (sum, avg)} for rows
    passing the |sum-avg|/avg > 0.1 filter; sums unscaled ints, avg as
    unscaled int at scale+4."""
    it = tables["item"]
    cats = _sv(it, "i_category")
    clss = _sv(it, "i_class")
    brands = _sv(it, "i_brand")
    a = {("Books", "accessories"), ("Books", "reference"), ("Books", "football"),
         ("Electronics", "accessories"), ("Electronics", "reference"), ("Electronics", "football"),
         ("Sports", "accessories"), ("Sports", "reference"), ("Sports", "football")}
    b = {(c, k) for c in ("Men", "Jewelry", "Women") for k in ("shirts", "birdal", "dresses")}
    keep = a | b
    item_by_sk = {}
    for i, sk in enumerate(it["i_item_sk"][0]):
        if (cats[i], clss[i]) in keep:
            item_by_sk[int(sk)] = (cats[i], clss[i], brands[i])
    dd = tables["date_dim"]
    moy_by_sk = {
        int(sk): int(m)
        for sk, m, y in zip(dd["d_date_sk"][0], dd["d_moy"][0], dd["d_year"][0])
        if y == 1999
    }
    st = tables["store"]
    store_by_sk = dict(zip(
        st["s_store_sk"][0].tolist(),
        zip(_sv(st, "s_store_name"), _sv(st, "s_company_name")),
    ))
    ss = tables["store_sales"]
    sums: Dict[tuple, int] = {}
    i_sk = ss["ss_item_sk"][0]; d_sk = ss["ss_sold_date_sk"][0]
    s_sk = ss["ss_store_sk"][0]; price = ss["ss_sales_price"][0]
    for i in range(i_sk.shape[0]):
        itm = item_by_sk.get(int(i_sk[i]))
        if itm is None:
            continue
        moy = moy_by_sk.get(int(d_sk[i]))
        if moy is None:
            continue
        stn = store_by_sk.get(int(s_sk[i]))
        if stn is None:
            continue
        key = itm + stn + (moy,)
        sums[key] = sums.get(key, 0) + int(price[i])
    # window avg over (cat, brand, store, company)
    parts: Dict[tuple, list] = {}
    for key, s in sums.items():
        cat, cls, brand, stn, co, moy = key
        parts.setdefault((cat, brand, stn, co), []).append(s)
    out = {}
    for key, s in sums.items():
        cat, cls, brand, stn, co, moy = key
        vals = parts[(cat, brand, stn, co)]
        # engine: avg of decimal(7,2) sums -> scale+4 unscaled, HALF_UP
        avg_unscaled = int(_round_half_up(np.array(
            [float(sum(vals)) * float(10**4) / len(vals)]
        ))[0])
        sum_f = float(s) / 100.0
        avg_f = avg_unscaled / 1e6
        if avg_f != 0 and abs(sum_f - avg_f) / avg_f > 0.1:
            out[key] = (s, avg_unscaled)
    return out


def oracle_q98(tables):
    return _class_share_oracle(tables, sales="store_sales",
                               date_col="ss_sold_date_sk",
                               item_col="ss_item_sk",
                               price_col="ss_ext_sales_price")

def _oracle_ticket_report(tables, *, dom_ranges, buy_potentials, cnt_lo, cnt_hi,
                          dep_vehicle_ratio=None):
    dd = tables["date_dim"]
    d_ok = np.zeros(dd["d_dom"][0].shape[0], bool)
    for lo, hi in dom_ranges:
        d_ok |= (dd["d_dom"][0] >= lo) & (dd["d_dom"][0] <= hi)
    d_ok &= np.isin(dd["d_year"][0], (1999, 2000, 2001))
    d_set = set(dd["d_date_sk"][0][d_ok].tolist())

    hd = tables["household_demographics"]
    bps = _sv(hd, "hd_buy_potential")
    h_ok = np.array([b in buy_potentials for b in bps])
    h_ok &= hd["hd_vehicle_count"][0] > 0
    with np.errstate(divide="ignore"):
        ratio = hd["hd_dep_count"][0] / np.maximum(hd["hd_vehicle_count"][0], 1)
    h_ok &= np.where(hd["hd_vehicle_count"][0] > 0, ratio > dep_vehicle_ratio, False)
    h_set = set(hd["hd_demo_sk"][0][h_ok].tolist())

    st = tables["store"]
    counties = _sv(st, "s_county")
    s_set = {
        int(sk) for i, sk in enumerate(st["s_store_sk"][0])
        if counties[i] in ("Williamson County", "Franklin Parish",
                           "Bronx County", "Orange County")
    }

    ss = tables["store_sales"]
    counts = {}
    d_sk = ss["ss_sold_date_sk"][0]; h_sk = ss["ss_hdemo_sk"][0]
    s_sk = ss["ss_store_sk"][0]; tick = ss["ss_ticket_number"][0]
    cust = ss["ss_customer_sk"][0]
    for i in range(d_sk.shape[0]):
        if int(d_sk[i]) in d_set and int(h_sk[i]) in h_set and int(s_sk[i]) in s_set:
            key = (int(tick[i]), int(cust[i]))
            counts[key] = counts.get(key, 0) + 1

    c = tables["customer"]
    sal = _sv(c, "c_salutation")
    fn_ = _sv(c, "c_first_name")
    ln_ = _sv(c, "c_last_name")
    pf = _sv(c, "c_preferred_cust_flag")
    cust_by_sk = {
        int(sk): (sal[i], fn_[i], ln_[i], pf[i])
        for i, sk in enumerate(c["c_customer_sk"][0])
    }
    out = {}
    for (tick_no, csk), n in counts.items():
        if not (cnt_lo <= n <= cnt_hi):
            continue
        info = cust_by_sk.get(csk)
        if info is None:
            continue
        out[(tick_no, csk)] = info + (n,)
    return out


def oracle_q34(tables):
    return _oracle_ticket_report(
        tables, dom_ranges=[(1, 3), (25, 28)],
        buy_potentials={">10000", "Unknown"}, cnt_lo=15, cnt_hi=20,
        dep_vehicle_ratio=1.2,
    )


def oracle_q73(tables):
    return _oracle_ticket_report(
        tables, dom_ranges=[(1, 2)],
        buy_potentials={">10000", "Unknown"}, cnt_lo=1, cnt_hi=5,
        dep_vehicle_ratio=1.0,
    )


def oracle_q19(tables):
    """{(brand_id, brand, manufact_id, manufact): ext_price} for
    out-of-zip sales in 1998-11 by manager-8 items."""
    dd = tables["date_dim"]
    d_set = set(
        dd["d_date_sk"][0][(dd["d_moy"][0] == 11) & (dd["d_year"][0] == 1998)].tolist()
    )
    it = tables["item"]
    i_ok = it["i_manager_id"][0] == 8
    brands = _sv(it, "i_brand")
    manufs = _sv(it, "i_manufact")
    item_by_sk = {
        int(sk): (int(it["i_brand_id"][0][i]), brands[i],
                  int(it["i_manufact_id"][0][i]), manufs[i])
        for i, sk in enumerate(it["i_item_sk"][0]) if i_ok[i]
    }
    c = tables["customer"]
    addr_by_cust = dict(zip(
        c["c_customer_sk"][0].tolist(), c["c_current_addr_sk"][0].tolist()
    ))
    ca = tables["customer_address"]
    zips = _sv(ca, "ca_zip")
    zip_by_addr = {int(sk): zips[i][:5] for i, sk in enumerate(ca["ca_address_sk"][0])}
    st = tables["store"]
    szips = _sv(st, "s_zip")
    zip_by_store = {int(sk): szips[i][:5] for i, sk in enumerate(st["s_store_sk"][0])}

    ss = tables["store_sales"]
    sums = {}
    d_sk = ss["ss_sold_date_sk"][0]; i_sk = ss["ss_item_sk"][0]
    c_sk = ss["ss_customer_sk"][0]; s_sk = ss["ss_store_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(d_sk.shape[0]):
        if int(d_sk[i]) not in d_set:
            continue
        itm = item_by_sk.get(int(i_sk[i]))
        if itm is None:
            continue
        a_sk = addr_by_cust.get(int(c_sk[i]))
        if a_sk is None:
            continue
        czip = zip_by_addr.get(int(a_sk))
        szip = zip_by_store.get(int(s_sk[i]))
        if czip is None or szip is None or czip == szip:
            continue
        sums[itm] = sums.get(itm, 0) + int(price[i])
    return sums


def _oracle_manufact_window(tables, group_col):
    """{(manufact_id, qoy_or_moy): (sum, avg_unscaled)} rows passing
    the |sum-avg|/avg > 0.1 filter (avg at scale+4 HALF_UP)."""
    it = tables["item"]
    cats = _sv(it, "i_category")
    clss = _sv(it, "i_class")
    a = {(c, k) for c in ("Books", "Children", "Electronics")
         for k in ("personal", "self-help", "reference")}
    b = {(c, k) for c in ("Women", "Music", "Men")
         for k in ("accessories", "classical", "fragrances")}
    keep = a | b
    manu_by_sk = {
        int(sk): int(it["i_manufact_id"][0][i])
        for i, sk in enumerate(it["i_item_sk"][0])
        if (cats[i], clss[i]) in keep
    }
    dd = tables["date_dim"]
    grp_by_sk = {
        int(sk): int(g)
        for sk, g, y in zip(dd["d_date_sk"][0], dd[group_col][0], dd["d_year"][0])
        if y in (1999, 2000)
    }
    st_set = set(tables["store"]["s_store_sk"][0].tolist())
    ss = tables["store_sales"]
    sums = {}
    i_sk = ss["ss_item_sk"][0]; d_sk = ss["ss_sold_date_sk"][0]
    s_sk = ss["ss_store_sk"][0]; price = ss["ss_sales_price"][0]
    for i in range(i_sk.shape[0]):
        m = manu_by_sk.get(int(i_sk[i]))
        if m is None:
            continue
        g = grp_by_sk.get(int(d_sk[i]))
        if g is None or int(s_sk[i]) not in st_set:
            continue
        sums[(m, g)] = sums.get((m, g), 0) + int(price[i])
    parts = {}
    for (m, g), sv in sums.items():
        parts.setdefault(m, []).append(sv)
    out = {}
    for (m, g), sv in sums.items():
        vals = parts[m]
        avg_unscaled = int(_round_half_up(np.array(
            [float(sum(vals)) * float(10**4) / len(vals)]
        ))[0])
        sum_f = float(sv) / 100.0
        avg_f = avg_unscaled / 1e6
        if avg_f > 0 and abs(sum_f - avg_f) / avg_f > 0.1:
            out[(m, g)] = (sv, avg_unscaled)
    return out


def oracle_q53(tables):
    return _oracle_manufact_window(tables, "d_qoy")


def oracle_q63(tables):
    return _oracle_manufact_window(tables, "d_moy")


def _channel_customer_set(tables, sales, date_col, cust_col, year):
    """Distinct (last, first, d_date) triples of one channel in a year
    (q38/q87 building block)."""
    dd = tables["date_dim"]
    cu = tables["customer"]
    sl = tables[sales]
    d_mask = dd["d_year"][0] == year
    date_by_sk = dict(zip(dd["d_date_sk"][0][d_mask].tolist(),
                          dd["d_date"][0][d_mask].tolist()))
    last = _sv(cu, "c_last_name")
    first = _sv(cu, "c_first_name")
    by_sk = {int(k): i for i, k in enumerate(cu["c_customer_sk"][0])}
    out = set()
    ds = sl[date_col][0]
    cs = sl[cust_col][0]
    for i in range(ds.shape[0]):
        d = date_by_sk.get(int(ds[i]))
        ci = by_sk.get(int(cs[i]))
        if d is None or ci is None:
            continue
        out.add((last[ci], first[ci], int(d)))
    return out


def oracle_q38(tables):
    ss = _channel_customer_set(tables, "store_sales", "ss_sold_date_sk", "ss_customer_sk", 2000)
    cs = _channel_customer_set(tables, "catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk", 2000)
    ws = _channel_customer_set(tables, "web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", 2000)
    return len(ss & cs & ws)


def oracle_q87(tables):
    ss = _channel_customer_set(tables, "store_sales", "ss_sold_date_sk", "ss_customer_sk", 2000)
    cs = _channel_customer_set(tables, "catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk", 2000)
    ws = _channel_customer_set(tables, "web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", 2000)
    return len(ss - cs - ws)


def _channel_union_sums(tables, *, group_col, item_mask_fn, year, moy):
    """q33/q56/q60 oracle: per-group total across the three channels,
    restricted to -5 GMT buyer addresses."""
    dd = tables["date_dim"]
    it = tables["item"]
    ca = tables["customer_address"]
    d_mask = (dd["d_year"][0] == year) & (dd["d_moy"][0] == moy)
    d_sks = set(dd["d_date_sk"][0][d_mask].tolist())
    ca_ok = set(ca["ca_address_sk"][0][ca["ca_gmt_offset"][0] == -500].tolist())
    gv = (_sv(it, group_col) if it[group_col][1] is not None
          else [int(v) for v in it[group_col][0]])
    id_set = {gv[i] for i in np.flatnonzero(item_mask_fn(it))}
    grp_by_sk = {
        int(sk): gv[i]
        for i, sk in enumerate(it["i_item_sk"][0])
        if gv[i] in id_set
    }
    sums = {}
    for sales, date_col, item_col, addr_col, price_col in [
        ("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk", "ss_ext_sales_price"),
        ("catalog_sales", "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk", "cs_ext_sales_price"),
        ("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
    ]:
        sl = tables[sales]
        ds, its, ads, pr = (sl[date_col][0], sl[item_col][0],
                            sl[addr_col][0], sl[price_col][0])
        for i in range(ds.shape[0]):
            if int(ds[i]) not in d_sks or int(ads[i]) not in ca_ok:
                continue
            g = grp_by_sk.get(int(its[i]))
            if g is None:
                continue
            sums[g] = sums.get(g, 0) + int(pr[i])
    return sums


def oracle_q33(tables):
    return _channel_union_sums(
        tables, group_col="i_manufact_id",
        item_mask_fn=lambda it: np.array(_sv(it, "i_category")) == "Electronics",
        year=1998, moy=5,
    )


def oracle_q56(tables):
    return _channel_union_sums(
        tables, group_col="i_item_id",
        item_mask_fn=lambda it: np.isin(np.array(_sv(it, "i_color")),
                                        ["slate", "blanched", "burnished"]),
        year=2000, moy=2,
    )


def oracle_q60(tables):
    return _channel_union_sums(
        tables, group_col="i_item_id",
        item_mask_fn=lambda it: np.array(_sv(it, "i_category")) == "Music",
        year=1999, moy=9,
    )


def _rank_within_parent(rows, *, parent_of, measure_of, descending):
    """Competition rank within (lochierarchy, parent) partitions —
    shared by the rollup oracles (q36/q86/q70)."""
    from collections import defaultdict
    parts = defaultdict(list)
    for r in rows:
        parts[(r[2], parent_of(r))].append(r)
    out = {}
    for plist in parts.values():
        plist.sort(key=lambda r: -measure_of(r) if descending else measure_of(r))
        rank, prev = 0, None
        for i, r in enumerate(plist, 1):
            if prev is None or measure_of(r) != prev:
                rank = i
            prev = measure_of(r)
            out[(r[0], r[1], r[2])] = (measure_of(r), rank)
    return out


def _rollup_margin_oracle(tables, *, sales, date_col, item_col, num_col,
                          den_col, year, store_filter=False, ratio_desc=False):
    """q36/q86 oracle: rollup sums, lochierarchy, rank within parent."""
    dd = tables["date_dim"]
    it = tables["item"]
    sl = tables[sales]
    d_sks = set(dd["d_date_sk"][0][dd["d_year"][0] == year].tolist())
    cats = _sv(it, "i_category")
    clss = _sv(it, "i_class")
    by_item = {int(sk): (cats[i], clss[i]) for i, sk in enumerate(it["i_item_sk"][0])}
    ok_stores = None
    if store_filter:
        st = tables["store"]
        states = _sv(st, "s_state")
        ok_stores = {int(sk) for i, sk in enumerate(st["s_store_sk"][0])
                     if states[i] in ("TN", "SD", "AL", "GA", "OH")}
    sums = {}  # (cat|None, cls|None, gid) -> [num, den]
    ds = sl[date_col][0]
    its = sl[item_col][0]
    num = sl[num_col][0]
    den = sl[den_col][0] if den_col else None
    store_sk = sl["ss_store_sk"][0] if store_filter else None
    for i in range(ds.shape[0]):
        if int(ds[i]) not in d_sks:
            continue
        if ok_stores is not None and int(store_sk[i]) not in ok_stores:
            continue
        ic = by_item.get(int(its[i]))
        if ic is None:
            continue
        cat, cls = ic
        for key in [(cat, cls, 0), (cat, None, 1), (None, None, 3)]:
            acc = sums.setdefault(key, [0, 0])
            acc[0] += int(num[i])
            if den is not None:
                acc[1] += int(den[i])
    rows = []
    for (cat, cls, gid), (n, d) in sums.items():
        loch = {0: 0, 1: 1, 3: 2}[gid]
        # money sums are decimal(17,2): the engine's float cast yields
        # dollars, so divide unscaled by 100 (ratio measures cancel)
        measure = (n / d) if den_col else (n / 100.0)
        rows.append([cat, cls, loch, measure])
    return _rank_within_parent(
        rows, parent_of=lambda r: r[0] if r[2] == 0 else None,
        measure_of=lambda r: r[3], descending=ratio_desc,
    )


def oracle_q36(tables):
    return _rollup_margin_oracle(
        tables, sales="store_sales", date_col="ss_sold_date_sk",
        item_col="ss_item_sk", num_col="ss_net_profit",
        den_col="ss_ext_sales_price", year=2001, store_filter=True,
    )


def oracle_q86(tables):
    return _rollup_margin_oracle(
        tables, sales="web_sales", date_col="ws_sold_date_sk",
        item_col="ws_item_sk", num_col="ws_net_paid", den_col=None,
        year=2000, ratio_desc=True,
    )


def _yoy_oracle(tables, *, sales, date_col, item_col, price_col,
                entity, year):
    """q47/q57 oracle: monthly sums, year-partition avg, lag/lead over
    the month sequence, filtered to the target year + ratio > 0.1.

    ``entity``: (table, sk_col in sales, entity sk col, [entity cols])
    """
    dd = tables["date_dim"]
    it = tables["item"]
    sl = tables[sales]
    etab, fk_col, esk_col, ecols = entity
    et = tables[etab]
    d_ok = {}
    for i in range(dd["d_date_sk"][0].shape[0]):
        y = int(dd["d_year"][0][i]); m = int(dd["d_moy"][0][i])
        if y == year or (y == year - 1 and m == 12) or (y == year + 1 and m == 1):
            d_ok[int(dd["d_date_sk"][0][i])] = (y, m)
    cats = _sv(it, "i_category")
    brands = _sv(it, "i_brand")
    by_item = {int(sk): (cats[i], brands[i]) for i, sk in enumerate(it["i_item_sk"][0])}
    evals = [ _sv(et, c) for c in ecols ]
    by_ent = {int(sk): tuple(ev[i] for ev in evals)
              for i, sk in enumerate(et[esk_col][0])}
    sums = {}
    ds = sl[date_col][0]; its = sl[item_col][0]
    eks = sl[fk_col][0]; pr = sl[price_col][0]
    for i in range(ds.shape[0]):
        ym = d_ok.get(int(ds[i]))
        ic = by_item.get(int(its[i]))
        ev = by_ent.get(int(eks[i]))
        if ym is None or ic is None or ev is None:
            continue
        key = ic + ev + ym
        sums[key] = sums.get(key, 0) + int(pr[i])
    # avg over (entity-part incl year), lag/lead over month order
    from collections import defaultdict
    by_year_part = defaultdict(list)
    by_part = defaultdict(list)
    for key, s in sums.items():
        part, y, m = key[:-2], key[-2], key[-1]
        by_year_part[part + (y,)].append(s)
        by_part[part].append((y, m, s))
    out = {}
    for part, rows in by_part.items():
        rows.sort()
        for i, (y, m, s) in enumerate(rows):
            if y != year:
                continue
            vals = by_year_part[part + (y,)]
            avg = sum(vals) / len(vals)
            if avg <= 0 or abs(s - avg) / avg <= 0.1:
                continue
            # engine avg(decimal(17,2)) carries scale 6: unscaled*10^4
            avg = int(_round_half_up(np.array([avg * 10**4]))[0])
            psum = rows[i - 1][2] if i > 0 else None
            nsum = rows[i + 1][2] if i + 1 < len(rows) else None
            out[part + (y, m)] = (s, avg, psum, nsum)
    return out


def oracle_q47(tables):
    return _yoy_oracle(
        tables, sales="store_sales", date_col="ss_sold_date_sk",
        item_col="ss_item_sk", price_col="ss_sales_price",
        entity=("store", "ss_store_sk", "s_store_sk",
                ["s_store_name", "s_company_name"]),
        year=1999,
    )


def oracle_q57(tables):
    return _yoy_oracle(
        tables, sales="catalog_sales", date_col="cs_sold_date_sk",
        item_col="cs_item_sk", price_col="cs_sales_price",
        entity=("call_center", "cs_call_center_sk", "cc_call_center_sk",
                ["cc_name"]),
        year=1999,
    )


def _active_set(tables, sales, date_col, cust_col, *, year, moys):
    dd = tables["date_dim"]
    sl = tables[sales]
    m = (dd["d_year"][0] == year) & (dd["d_moy"][0] >= moys[0]) & (dd["d_moy"][0] <= moys[1])
    d_sks = set(dd["d_date_sk"][0][m].tolist())
    ds = sl[date_col][0]
    cs = sl[cust_col][0]
    return {int(cs[i]) for i in range(ds.shape[0]) if int(ds[i]) in d_sks}


def _channel_sets(tables, *, year, moys):
    """(store, web, catalog) active-customer sets for a window — the
    shared wiring of the q10/q35/q69 oracles."""
    ss = _active_set(tables, "store_sales", "ss_sold_date_sk", "ss_customer_sk",
                     year=year, moys=moys)
    ws = _active_set(tables, "web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                     year=year, moys=moys)
    cs = _active_set(tables, "catalog_sales", "cs_sold_date_sk", "cs_ship_customer_sk",
                     year=year, moys=moys)
    return ss, ws, cs


def _q10_customers(tables, *, year=2002, moys=(1, 4)):
    """c_customer_sk of customers with in-store activity AND (web OR
    catalog) activity in the window."""
    ss, ws, cs = _channel_sets(tables, year=year, moys=moys)
    return ss & (ws | cs)


def oracle_q10(tables):
    cu = tables["customer"]
    ca = tables["customer_address"]
    cd = tables["customer_demographics"]
    counties = {"Williamson County", "Franklin Parish", "Bronx County"}
    co = _sv(ca, "ca_county")
    ok_addr = {int(sk) for i, sk in enumerate(ca["ca_address_sk"][0]) if co[i] in counties}
    active = _q10_customers(tables)
    cd_cols = [
        _sv(cd, "cd_gender"), _sv(cd, "cd_marital_status"),
        _sv(cd, "cd_education_status"),
        [int(v) for v in cd["cd_purchase_estimate"][0]],
        _sv(cd, "cd_credit_rating"),
        [int(v) for v in cd["cd_dep_count"][0]],
        [int(v) for v in cd["cd_dep_employed_count"][0]],
        [int(v) for v in cd["cd_dep_college_count"][0]],
    ]
    cd_by_sk = {int(sk): tuple(c[i] for c in cd_cols)
                for i, sk in enumerate(cd["cd_demo_sk"][0])}
    counts = {}
    for i, csk in enumerate(cu["c_customer_sk"][0]):
        if int(csk) not in active:
            continue
        if int(cu["c_current_addr_sk"][0][i]) not in ok_addr:
            continue
        key = cd_by_sk.get(int(cu["c_current_cdemo_sk"][0][i]))
        if key is None:
            continue
        counts[key] = counts.get(key, 0) + 1
    return counts


def oracle_q35(tables):
    cu = tables["customer"]
    ca = tables["customer_address"]
    cd = tables["customer_demographics"]
    states = _sv(ca, "ca_state")
    state_by_addr = {int(sk): states[i] for i, sk in enumerate(ca["ca_address_sk"][0])}
    active = _q10_customers(tables)
    gd = _sv(cd, "cd_gender")
    ms = _sv(cd, "cd_marital_status")
    dep = [int(v) for v in cd["cd_dep_count"][0]]
    emp = [int(v) for v in cd["cd_dep_employed_count"][0]]
    colg = [int(v) for v in cd["cd_dep_college_count"][0]]
    cd_by_sk = {int(sk): i for i, sk in enumerate(cd["cd_demo_sk"][0])}
    rows = {}
    for i, csk in enumerate(cu["c_customer_sk"][0]):
        if int(csk) not in active:
            continue
        st = state_by_addr.get(int(cu["c_current_addr_sk"][0][i]))
        ci = cd_by_sk.get(int(cu["c_current_cdemo_sk"][0][i]))
        if st is None or ci is None:
            continue
        key = (st, gd[ci], ms[ci], dep[ci], emp[ci], colg[ci])
        rows.setdefault(key, []).append((dep[ci], emp[ci], colg[ci]))
    out = {}
    for key, vals in rows.items():
        n = len(vals)
        aggs = [n]
        for j in range(3):
            vs = [v[j] for v in vals]
            # engine avg(int) is float64
            aggs += [sum(vs) / n, max(vs), sum(vs)]
        out[key] = tuple(aggs)
    return out


def oracle_q9(tables, thresholds):
    ss = tables["store_sales"]
    q = ss["ss_quantity"][0]
    disc = ss["ss_ext_discount_amt"][0]
    prof = ss["ss_net_profit"][0]
    out = []
    for b, thresh in enumerate(thresholds):
        lo, hi = 20 * b + 1, 20 * (b + 1)
        m = (q >= lo) & (q <= hi)
        n = int(m.sum())
        vals = disc[m] if n > thresh else prof[m]
        # engine avg(decimal(7,2)) carries scale 6: unscaled*10^4
        avg = int(_round_half_up(np.array([float(vals.sum()) * 10**4 / max(n, 1)]))[0])
        out.append(avg if n else None)
    return out


def oracle_q88(tables):
    ss = tables["store_sales"]
    hd = tables["household_demographics"]
    td = tables["time_dim"]
    st = tables["store"]
    dep = hd["hd_dep_count"][0]
    veh = hd["hd_vehicle_count"][0]
    hd_ok = set(hd["hd_demo_sk"][0][
        ((dep == 4) & (veh <= 6)) | ((dep == 2) & (veh <= 4)) | ((dep == 0) & (veh <= 2))
    ].tolist())
    names = _sv(st, "s_store_name")
    st_ok = {int(sk) for i, sk in enumerate(st["s_store_sk"][0]) if names[i] == "ese"}
    out = []
    for k in range(8):
        h, half = divmod(k + 17, 2)
        tm = (td["t_hour"][0] == h) & (
            (td["t_minute"][0] >= 30) if half else (td["t_minute"][0] < 30)
        )
        t_ok = set(td["t_time_sk"][0][tm].tolist())
        cnt = 0
        ts = ss["ss_sold_time_sk"][0]
        hs = ss["ss_hdemo_sk"][0]
        sts = ss["ss_store_sk"][0]
        for i in range(ts.shape[0]):
            if int(ts[i]) in t_ok and int(hs[i]) in hd_ok and int(sts[i]) in st_ok:
                cnt += 1
        out.append(cnt)
    return out


def oracle_q8(tables, zips, min_preferred):
    ca = tables["customer_address"]
    cu = tables["customer"]
    st = tables["store"]
    dd = tables["date_dim"]
    ss = tables["store_sales"]
    zip5s = [z[:5] for z in _sv(ca, "ca_zip")]
    a1 = {z for z in zip5s if z in set(zips)}
    pf = _sv(cu, "c_preferred_cust_flag")
    zip_by_addr = {int(sk): zip5s[i] for i, sk in enumerate(ca["ca_address_sk"][0])}
    counts = {}
    for i in range(cu["c_customer_sk"][0].shape[0]):
        if pf[i] != "Y":
            continue
        z = zip_by_addr.get(int(cu["c_current_addr_sk"][0][i]))
        if z is not None:
            counts[z] = counts.get(z, 0) + 1
    a2 = {z for z, c in counts.items() if c >= min_preferred}
    prefixes = {z[:2] for z in (a1 & a2)}
    names = _sv(st, "s_store_name")
    szips = _sv(st, "s_zip")
    name_by_sk = {int(sk): names[i] for i, sk in enumerate(st["s_store_sk"][0])
                  if szips[i][:2] in prefixes}
    dm = (dd["d_year"][0] == 1998) & (dd["d_qoy"][0] == 2)
    d_sks = set(dd["d_date_sk"][0][dm].tolist())
    sums = {}
    ds = ss["ss_sold_date_sk"][0]
    sts = ss["ss_store_sk"][0]
    np_ = ss["ss_net_profit"][0]
    for i in range(ds.shape[0]):
        if int(ds[i]) not in d_sks:
            continue
        nm = name_by_sk.get(int(sts[i]))
        if nm is None:
            continue
        sums[nm] = sums.get(nm, 0) + int(np_[i])
    return sums


def _q13_mask(tables):
    """Row mask over store_sales for the q13/q48 band predicates."""
    from .queries import Q13_BANDS, Q13_STATE_BANDS

    ss = tables["store_sales"]
    dd = tables["date_dim"]
    cd = tables["customer_demographics"]
    hd = tables["household_demographics"]
    ca = tables["customer_address"]
    st = tables["store"]
    n = ss["ss_sold_date_sk"][0].shape[0]
    d_ok = set(dd["d_date_sk"][0][dd["d_year"][0] == 2001].tolist())
    st_ok = set(st["s_store_sk"][0].tolist())
    ms = _sv(cd, "cd_marital_status")
    ed = _sv(cd, "cd_education_status")
    cd_row = {int(sk): i for i, sk in enumerate(cd["cd_demo_sk"][0])}
    dep = hd["hd_dep_count"][0]
    hd_row = {int(sk): i for i, sk in enumerate(hd["hd_demo_sk"][0])}
    states = _sv(ca, "ca_state")
    ca_row = {int(sk): i for i, sk in enumerate(ca["ca_address_sk"][0])}
    mask = np.zeros(n, bool)
    sp = ss["ss_sales_price"][0]
    npf = ss["ss_net_profit"][0]
    geo_bands = [(frozenset(b_states), b_lo, b_hi)
                 for b_states, b_lo, b_hi in Q13_STATE_BANDS]
    for i in range(n):
        if int(ss["ss_sold_date_sk"][0][i]) not in d_ok:
            continue
        if int(ss["ss_store_sk"][0][i]) not in st_ok:
            continue
        ci = cd_row.get(int(ss["ss_cdemo_sk"][0][i]))
        hi = hd_row.get(int(ss["ss_hdemo_sk"][0][i]))
        ai = ca_row.get(int(ss["ss_addr_sk"][0][i]))
        if ci is None or hi is None or ai is None:
            continue
        demo = any(
            ms[ci] == b_ms and ed[ci] == b_ed
            and b_lo * 100 <= int(sp[i]) <= b_hi * 100
            and int(dep[hi]) == b_dep
            for b_ms, b_ed, b_lo, b_hi, b_dep in Q13_BANDS
        )
        if not demo:
            continue
        mask[i] = any(
            states[ai] in b_states
            and b_lo * 100 <= int(npf[i]) <= b_hi * 100
            for b_states, b_lo, b_hi in geo_bands
        )
    return mask


def oracle_q13(tables):
    ss = tables["store_sales"]
    m = _q13_mask(tables)
    n = int(m.sum())
    if n == 0:
        return None
    def avg(col, scale4):
        s = int(ss[col][0][m].astype(object).sum())
        if scale4:
            return (s * 10**4 + n // 2) // n
        return s / n
    return dict(
        avg_qty=avg("ss_quantity", False),
        avg_ext_sales=avg("ss_ext_sales_price", True),
        avg_ext_disc=avg("ss_ext_discount_amt", True),
        cnt=n,
    )


def oracle_q48(tables):
    ss = tables["store_sales"]
    m = _q13_mask(tables)
    return int(ss["ss_quantity"][0][m].sum())


def oracle_q69(tables):
    cu = tables["customer"]
    ca = tables["customer_address"]
    cd = tables["customer_demographics"]
    states = _sv(ca, "ca_state")
    ok_addr = {int(sk) for i, sk in enumerate(ca["ca_address_sk"][0])
               if states[i] in ("TN", "SD", "AL")}
    ss, ws, cs = _channel_sets(tables, year=2002, moys=(1, 3))
    active = ss - ws - cs
    gd = _sv(cd, "cd_gender")
    ms = _sv(cd, "cd_marital_status")
    ed = _sv(cd, "cd_education_status")
    pe = [int(v) for v in cd["cd_purchase_estimate"][0]]
    cr = _sv(cd, "cd_credit_rating")
    cd_by_sk = {int(sk): i for i, sk in enumerate(cd["cd_demo_sk"][0])}
    counts = {}
    for i, csk in enumerate(cu["c_customer_sk"][0]):
        if int(csk) not in active:
            continue
        if int(cu["c_current_addr_sk"][0][i]) not in ok_addr:
            continue
        ci = cd_by_sk.get(int(cu["c_current_cdemo_sk"][0][i]))
        if ci is None:
            continue
        key = (gd[ci], ms[ci], ed[ci], pe[ci], cr[ci])
        counts[key] = counts.get(key, 0) + 1
    return counts


def oracle_q65(tables):
    """{(store_name, item_desc): (revenue, current_price, brand)} for
    items at <= 10% of their store's average item revenue."""
    ss = tables["store_sales"]
    dd = tables["date_dim"]
    st = tables["store"]
    it = tables["item"]
    d_ok = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    rev = {}
    for i in range(ss["ss_sold_date_sk"][0].shape[0]):
        if int(ss["ss_sold_date_sk"][0][i]) not in d_ok:
            continue
        key = (int(ss["ss_store_sk"][0][i]), int(ss["ss_item_sk"][0][i]))
        rev[key] = rev.get(key, 0) + int(ss["ss_sales_price"][0][i])
    from collections import defaultdict
    per_store = defaultdict(list)
    for (sk, _), r in rev.items():
        per_store[sk].append(r)
    # engine avg(decimal(17,2)) carries scale 6: unscaled * 10^4
    ave = {sk: (sum(v) * 10**4 + len(v) // 2) // len(v)
           for sk, v in per_store.items()}
    names = _sv(st, "s_store_name")
    name_by_sk = {int(sk): names[i] for i, sk in enumerate(st["s_store_sk"][0])}
    descs = _sv(it, "i_item_desc")
    brands = _sv(it, "i_brand")
    prices = it["i_current_price"][0]
    item_by_sk = {int(sk): i for i, sk in enumerate(it["i_item_sk"][0])}
    # keyed by (store_sk, item_sk): distinct items may share a
    # description, and the engine emits one row per ITEM
    out = {}
    for (sk, ik), r in rev.items():
        if sk not in name_by_sk or ik not in item_by_sk:
            continue
        # engine: revenue/100 (float dollars) <= (ave/1e6) * 0.1
        if (r / 100.0) > (ave[sk] / 10**6) * 0.1:
            continue
        ii = item_by_sk[ik]
        out[(sk, ik)] = (name_by_sk[sk], descs[ii], r, int(prices[ii]), brands[ii])
    return out


def oracle_q26(tables):
    """{item_id: (avg_qty_float, avg_list, avg_coupon, avg_sales)} —
    decimal avgs in engine scale-6 unscaled units (q7's oracle shape
    over the catalog channel)."""
    cd = tables["customer_demographics"]
    dd = tables["date_dim"]
    pr = tables["promotion"]
    it = tables["item"]
    cs = tables["catalog_sales"]
    g = _sv(cd, "cd_gender"); m = _sv(cd, "cd_marital_status"); e = _sv(cd, "cd_education_status")
    cd_ok = {int(sk) for i, sk in enumerate(cd["cd_demo_sk"][0])
             if g[i] == "M" and m[i] == "S" and e[i] == "College"}
    d_ok = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    pe = _sv(pr, "p_channel_email"); pv = _sv(pr, "p_channel_event")
    p_ok = {int(sk) for i, sk in enumerate(pr["p_promo_sk"][0])
            if pe[i] == "N" or pv[i] == "N"}
    iid = _sv(it, "i_item_id")
    id_by_sk = {int(sk): iid[i] for i, sk in enumerate(it["i_item_sk"][0])}
    groups = {}
    for i in range(cs["cs_sold_date_sk"][0].shape[0]):
        if int(cs["cs_bill_cdemo_sk"][0][i]) not in cd_ok: continue
        if int(cs["cs_sold_date_sk"][0][i]) not in d_ok: continue
        if int(cs["cs_promo_sk"][0][i]) not in p_ok: continue
        key = id_by_sk.get(int(cs["cs_item_sk"][0][i]))
        if key is None: continue
        groups.setdefault(key, []).append((
            int(cs["cs_quantity"][0][i]), int(cs["cs_list_price"][0][i]),
            int(cs["cs_coupon_amt"][0][i]), int(cs["cs_sales_price"][0][i]),
        ))
    out = {}
    for key, rows in groups.items():
        n = len(rows)
        qty = sum(r[0] for r in rows) / n
        mids = []
        for j in range(1, 4):
            s = sum(r[j] for r in rows)
            mids.append((s * 10**4 + n // 2) // n)
        out[key] = (qty, *mids)
    return out


def oracle_q93(tables):
    """{customer_sk: sumsales} for returns with reason 'Stopped
    working' (unscaled scale-2 sums; LEFT-join + reason filter keeps
    only returned rows, matching the spec's comma-join semantics)."""
    ss = tables["store_sales"]
    sr = tables["store_returns"]
    rs = tables["reason"]
    descs = _sv(rs, "r_reason_desc")
    r_ok = {int(sk) for i, sk in enumerate(rs["r_reason_sk"][0])
            if descs[i] == "Stopped working"}
    ret = {}
    for i in range(sr["sr_item_sk"][0].shape[0]):
        if int(sr["sr_reason_sk"][0][i]) not in r_ok:
            continue
        key = (int(sr["sr_item_sk"][0][i]), int(sr["sr_ticket_number"][0][i]))
        # multiple returns for one line: both join-multiply (the engine
        # LEFT join emits one row per match)
        ret.setdefault(key, []).append(int(sr["sr_return_quantity"][0][i]))
    out = {}
    for i in range(ss["ss_item_sk"][0].shape[0]):
        key = (int(ss["ss_item_sk"][0][i]), int(ss["ss_ticket_number"][0][i]))
        if key not in ret:
            continue
        c = int(ss["ss_customer_sk"][0][i])
        for rq in ret[key]:
            act = (int(ss["ss_quantity"][0][i]) - rq) * int(ss["ss_sales_price"][0][i])
            out[c] = out.get(c, 0) + act
    return out


def oracle_q70(tables):
    """{(state|None, county|None, loch): (total, rank)} — q36's rollup
    oracle shape over store geography (rank by total desc)."""
    dd = tables["date_dim"]
    st = tables["store"]
    ss = tables["store_sales"]
    d_sks = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    states = _sv(st, "s_state")
    counties = _sv(st, "s_county")
    geo_by_sk = {int(sk): (states[i], counties[i])
                 for i, sk in enumerate(st["s_store_sk"][0])}
    sums = {}
    ds = ss["ss_sold_date_sk"][0]
    sts = ss["ss_store_sk"][0]
    np_ = ss["ss_net_profit"][0]
    for i in range(ds.shape[0]):
        if int(ds[i]) not in d_sks:
            continue
        geo = geo_by_sk.get(int(sts[i]))
        if geo is None:
            continue
        state, county = geo
        v = int(np_[i])
        for key in [(state, county, 0), (state, None, 1), (None, None, 2)]:
            sums[key] = sums.get(key, 0) + v
    rows = [(state, county, loch, v) for (state, county, loch), v in sums.items()]
    return _rank_within_parent(
        rows, parent_of=lambda r: r[0] if r[2] == 0 else None,
        measure_of=lambda r: r[3], descending=True,
    )


def oracle_q15(tables):
    from .queries import Q15_ZIPS

    dd = tables["date_dim"]
    cu = tables["customer"]
    ca = tables["customer_address"]
    cs = tables["catalog_sales"]
    d_ok = set(dd["d_date_sk"][0][
        (dd["d_qoy"][0] == 2) & (dd["d_year"][0] == 2001)].tolist())
    zips = _sv(ca, "ca_zip")
    states = _sv(ca, "ca_state")
    addr_row = {int(sk): i for i, sk in enumerate(ca["ca_address_sk"][0])}
    addr_of_cust = {int(c): int(a) for c, a in
                    zip(cu["c_customer_sk"][0], cu["c_current_addr_sk"][0])}
    zipset = set(Q15_ZIPS)
    stateset = {"TN", "GA", "OH"}
    sums = {}
    for i in range(cs["cs_sold_date_sk"][0].shape[0]):
        if int(cs["cs_sold_date_sk"][0][i]) not in d_ok:
            continue
        a = addr_of_cust.get(int(cs["cs_bill_customer_sk"][0][i]))
        ai = addr_row.get(a) if a is not None else None
        if ai is None:
            continue
        price = int(cs["cs_sales_price"][0][i])
        if not (zips[ai][:5] in zipset or states[ai] in stateset
                or price > 250 * 100):
            continue
        sums[zips[ai]] = sums.get(zips[ai], 0) + price
    return sums


def oracle_q61(tables):
    """(promo_rev, total_rev) unscaled for the q61 slice."""
    dd = tables["date_dim"]
    st = tables["store"]
    it = tables["item"]
    ca = tables["customer_address"]
    cu = tables["customer"]
    pr = tables["promotion"]
    ss = tables["store_sales"]
    d_ok = set(dd["d_date_sk"][0][
        (dd["d_year"][0] == 1998) & (dd["d_moy"][0] == 11)].tolist())
    st_ok = set(st["s_store_sk"][0].tolist())
    cats = _sv(it, "i_category")
    it_ok = {int(sk) for i, sk in enumerate(it["i_item_sk"][0])
             if cats[i] == "Jewelry"}
    ca_ok = set(ca["ca_address_sk"][0][ca["ca_gmt_offset"][0] == -500].tolist())
    cust_ok = {int(c) for c, a in zip(cu["c_customer_sk"][0],
                                      cu["c_current_addr_sk"][0])
               if int(a) in ca_ok}
    pe = _sv(pr, "p_channel_email")
    pv = _sv(pr, "p_channel_event")
    promo_ok = {int(sk) for i, sk in enumerate(pr["p_promo_sk"][0])
                if pe[i] == "Y" or pv[i] == "Y"}
    promo = total = 0
    for i in range(ss["ss_sold_date_sk"][0].shape[0]):
        if int(ss["ss_sold_date_sk"][0][i]) not in d_ok: continue
        if int(ss["ss_store_sk"][0][i]) not in st_ok: continue
        if int(ss["ss_item_sk"][0][i]) not in it_ok: continue
        if int(ss["ss_customer_sk"][0][i]) not in cust_ok: continue
        v = int(ss["ss_ext_sales_price"][0][i])
        total += v
        if int(ss["ss_promo_sk"][0][i]) in promo_ok:
            promo += v
    return promo, total


def _excess_discount_oracle(tables, *, sales, date_col, item_col, amt_col):
    from .queries import Q32_MFG_MAX

    import datetime as _dt
    dd = tables["date_dim"]
    it = tables["item"]
    sl = tables[sales]
    lo = (_dt.date(2000, 1, 27) - _dt.date(1970, 1, 1)).days
    hi = (_dt.date(2000, 4, 26) - _dt.date(1970, 1, 1)).days
    dm = (dd["d_date"][0] >= lo) & (dd["d_date"][0] <= hi)
    d_ok = set(dd["d_date_sk"][0][dm].tolist())
    mfg_ok = {int(sk) for sk, m in zip(it["i_item_sk"][0], it["i_manufact_id"][0])
              if int(m) <= Q32_MFG_MAX}
    rows = []
    per_item = {}
    for i in range(sl[date_col][0].shape[0]):
        if int(sl[date_col][0][i]) not in d_ok:
            continue
        ik = int(sl[item_col][0][i])
        amt = int(sl[amt_col][0][i])
        rows.append((ik, amt))
        per_item.setdefault(ik, []).append(amt)
    # engine avg carries scale 6 (unscaled*10^4, HALF_UP)
    avg_u = {ik: (sum(v) * 10**4 + len(v) // 2) // len(v)
             for ik, v in per_item.items()}
    total = 0
    matched = False
    for ik, amt in rows:
        if ik not in mfg_ok:
            continue
        # engine compares float dollars: amt/100 > (avg_u/1e6)*1.3
        if amt / 100.0 > (avg_u[ik] / 10**6) * 1.3:
            total += amt
            matched = True
    return total if matched else None


def oracle_q32(tables):
    return _excess_discount_oracle(
        tables, sales="catalog_sales", date_col="cs_sold_date_sk",
        item_col="cs_item_sk", amt_col="cs_ext_discount_amt")


def oracle_q92(tables):
    return _excess_discount_oracle(
        tables, sales="web_sales", date_col="ws_sold_date_sk",
        item_col="ws_item_sk", amt_col="ws_ext_discount_amt")


def oracle_q43(tables):
    """{store_name: [sun..sat unscaled sums]} for d_year 2000."""
    dd = tables["date_dim"]
    st = tables["store"]
    ss = tables["store_sales"]
    m = dd["d_year"][0] == 2000
    dow_by_sk = dict(zip(dd["d_date_sk"][0][m].tolist(),
                         dd["d_dow"][0][m].tolist()))
    names = _sv(st, "s_store_name")
    name_by_sk = {int(sk): names[i] for i, sk in enumerate(st["s_store_sk"][0])}
    out = {}
    for i in range(ss["ss_sold_date_sk"][0].shape[0]):
        dow = dow_by_sk.get(int(ss["ss_sold_date_sk"][0][i]))
        nm = name_by_sk.get(int(ss["ss_store_sk"][0][i]))
        if dow is None or nm is None:
            continue
        out.setdefault(nm, [0] * 7)[int(dow)] += int(ss["ss_sales_price"][0][i])
    return out


def _class_share_oracle(tables, *, sales, date_col, item_col, price_col):
    """q98/q20/q12 oracle: {(id, desc, cat, cls, price): (rev, ratio)}."""
    import datetime as _dt
    dd = tables["date_dim"]
    it = tables["item"]
    sl = tables[sales]
    lo = (_dt.date(1999, 2, 22) - _dt.date(1970, 1, 1)).days
    hi = (_dt.date(1999, 3, 24) - _dt.date(1970, 1, 1)).days
    dm = (dd["d_date"][0] >= lo) & (dd["d_date"][0] <= hi)
    d_ok = set(dd["d_date_sk"][0][dm].tolist())
    cats = _sv(it, "i_category")
    ids = _sv(it, "i_item_id")
    descs = _sv(it, "i_item_desc")
    clss = _sv(it, "i_class")
    prices = it["i_current_price"][0]
    keep = {"Sports", "Books", "Home"}
    meta = {int(sk): (ids[i], descs[i], cats[i], clss[i], int(prices[i]))
            for i, sk in enumerate(it["i_item_sk"][0]) if cats[i] in keep}
    rev = {}
    for i in range(sl[date_col][0].shape[0]):
        if int(sl[date_col][0][i]) not in d_ok:
            continue
        m = meta.get(int(sl[item_col][0][i]))
        if m is None:
            continue
        rev[m] = rev.get(m, 0) + int(sl[price_col][0][i])
    by_class = {}
    for m, r in rev.items():
        by_class[m[3]] = by_class.get(m[3], 0) + r
    return {m: (r, float(r) * 100.0 / float(by_class[m[3]]))
            for m, r in rev.items()}


def oracle_q20(tables):
    return _class_share_oracle(tables, sales="catalog_sales",
                               date_col="cs_sold_date_sk",
                               item_col="cs_item_sk",
                               price_col="cs_ext_sales_price")


def oracle_q12(tables):
    return _class_share_oracle(tables, sales="web_sales",
                               date_col="ws_sold_date_sk",
                               item_col="ws_item_sk",
                               price_col="ws_ext_sales_price")


# --------------------------------------------------- channel reports


def _win_sks(tables, lo, hi):
    """date_sks whose d_date lies in [lo, hi] (python dates)."""
    dd = tables["date_dim"]
    lo_d, hi_d = _days(*lo), _days(*hi)
    m = (dd["d_date"][0] >= lo_d) & (dd["d_date"][0] <= hi_d)
    return set(dd["d_date_sk"][0][m].tolist())


def _rollup2(detail):
    """detail {(ch, id): [s, r, p]} -> + (ch, None) + (None, None)."""
    out = {}
    for (ch, i), v in detail.items():
        for key in ((ch, i), (ch, None), (None, None)):
            acc = out.setdefault(key, [0, 0, 0])
            for k in range(3):
                acc[k] += v[k]
    return {k: tuple(v) for k, v in out.items()}


def oracle_q5(tables):
    win = _win_sks(tables, (2000, 8, 23), (2000, 9, 5))
    detail = {}

    def add(ch, ident, s, r, p):
        acc = detail.setdefault((ch, ident), [0, 0, 0])
        acc[0] += s
        acc[1] += r
        acc[2] += p

    st = tables["store"]
    sname = {int(k): v for k, v in zip(st["s_store_sk"][0], _sv(st, "s_store_name"))}
    ss = tables["store_sales"]
    for d, sk, pr, np_ in zip(ss["ss_sold_date_sk"][0], ss["ss_store_sk"][0],
                              ss["ss_ext_sales_price"][0], ss["ss_net_profit"][0]):
        if int(d) in win and int(sk) in sname:
            add("store channel", sname[int(sk)], int(pr), 0, int(np_))
    sr = tables["store_returns"]
    for d, sk, amt, loss in zip(sr["sr_returned_date_sk"][0], sr["sr_store_sk"][0],
                                sr["sr_return_amt"][0], sr["sr_net_loss"][0]):
        if int(d) in win and int(sk) in sname:
            add("store channel", sname[int(sk)], 0, int(amt), -int(loss))

    cp = tables["catalog_page"]
    cpid = {int(k): v for k, v in zip(cp["cp_catalog_page_sk"][0],
                                      _sv(cp, "cp_catalog_page_id"))}
    cs = tables["catalog_sales"]
    for d, pg, pr, np_ in zip(cs["cs_sold_date_sk"][0], cs["cs_catalog_page_sk"][0],
                              cs["cs_ext_sales_price"][0], cs["cs_net_profit"][0]):
        if int(d) in win and int(pg) in cpid:
            add("catalog channel", cpid[int(pg)], int(pr), 0, int(np_))
    cr = tables["catalog_returns"]
    for d, pg, amt, loss in zip(cr["cr_returned_date_sk"][0], cr["cr_catalog_page_sk"][0],
                                cr["cr_return_amount"][0], cr["cr_net_loss"][0]):
        if int(d) in win and int(pg) in cpid:
            add("catalog channel", cpid[int(pg)], 0, int(amt), -int(loss))

    wsite = tables["web_site"]
    wname = {int(k): v for k, v in zip(wsite["web_site_sk"][0], _sv(wsite, "web_name"))}
    ws = tables["web_sales"]
    for d, sk, pr, np_ in zip(ws["ws_sold_date_sk"][0], ws["ws_web_site_sk"][0],
                              ws["ws_ext_sales_price"][0], ws["ws_net_profit"][0]):
        if int(d) in win and int(sk) in wname:
            add("web channel", wname[int(sk)], int(pr), 0, int(np_))
    # web returns: (item, order) join back to web_sales (WITH the
    # engine join's fan-out multiplicity)
    by_io = {}
    for i, o, sk in zip(ws["ws_item_sk"][0], ws["ws_order_number"][0],
                        ws["ws_web_site_sk"][0]):
        by_io.setdefault((int(i), int(o)), []).append(int(sk))
    wr = tables["web_returns"]
    for d, i, o, amt, loss in zip(wr["wr_returned_date_sk"][0], wr["wr_item_sk"][0],
                                  wr["wr_order_number"][0], wr["wr_return_amt"][0],
                                  wr["wr_net_loss"][0]):
        if int(d) in win:
            for sk in by_io.get((int(i), int(o)), ()):
                if sk in wname:
                    add("web channel", wname[sk], 0, int(amt), -int(loss))
    return _rollup2(detail)


def oracle_q77(tables):
    win = _win_sks(tables, (2000, 8, 3), (2000, 9, 1))
    detail = {}

    st_sks = set(tables["store"]["s_store_sk"][0].tolist())
    ss = tables["store_sales"]
    sales = {}
    for d, sk, pr, np_ in zip(ss["ss_sold_date_sk"][0], ss["ss_store_sk"][0],
                              ss["ss_ext_sales_price"][0], ss["ss_net_profit"][0]):
        if int(d) in win and int(sk) in st_sks:
            a = sales.setdefault(int(sk), [0, 0])
            a[0] += int(pr)
            a[1] += int(np_)
    sr = tables["store_returns"]
    rets = {}
    for d, sk, amt, loss in zip(sr["sr_returned_date_sk"][0], sr["sr_store_sk"][0],
                                sr["sr_return_amt"][0], sr["sr_net_loss"][0]):
        if int(d) in win and int(sk) in st_sks:
            a = rets.setdefault(int(sk), [0, 0])
            a[0] += int(amt)
            a[1] += int(loss)
    for sk, (s, p) in sales.items():
        r, l = rets.get(sk, (0, 0))
        detail[("store channel", sk)] = [s, r, p - l]

    cs = tables["catalog_sales"]
    csales = {}
    for d, cc, pr, np_ in zip(cs["cs_sold_date_sk"][0], cs["cs_call_center_sk"][0],
                              cs["cs_ext_sales_price"][0], cs["cs_net_profit"][0]):
        if int(d) in win:
            a = csales.setdefault(int(cc), [0, 0])
            a[0] += int(pr)
            a[1] += int(np_)
    cr = tables["catalog_returns"]
    rtot = ltot = 0
    for d, amt, loss in zip(cr["cr_returned_date_sk"][0], cr["cr_return_amount"][0],
                            cr["cr_net_loss"][0]):
        if int(d) in win:
            rtot += int(amt)
            ltot += int(loss)
    for cc, (s, p) in csales.items():
        detail[("catalog channel", cc)] = [s, rtot, p - ltot]

    ws = tables["web_sales"]
    wsales = {}
    for d, pg, pr, np_ in zip(ws["ws_sold_date_sk"][0], ws["ws_web_page_sk"][0],
                              ws["ws_ext_sales_price"][0], ws["ws_net_profit"][0]):
        if int(d) in win:
            a = wsales.setdefault(int(pg), [0, 0])
            a[0] += int(pr)
            a[1] += int(np_)
    wr = tables["web_returns"]
    wrets = {}
    for d, pg, amt, loss in zip(wr["wr_returned_date_sk"][0], wr["wr_web_page_sk"][0],
                                wr["wr_return_amt"][0], wr["wr_net_loss"][0]):
        if int(d) in win:
            a = wrets.setdefault(int(pg), [0, 0])
            a[0] += int(amt)
            a[1] += int(loss)
    for pg, (s, p) in wsales.items():
        r, l = wrets.get(pg, (0, 0))
        detail[("web channel", pg)] = [s, r, p - l]
    return _rollup2(detail)


def oracle_q80(tables):
    win = _win_sks(tables, (2000, 8, 3), (2000, 9, 1))
    it = tables["item"]
    iid = {}
    for sk, price, ident in zip(it["i_item_sk"][0], it["i_current_price"][0],
                                _sv(it, "i_item_id")):
        if int(price) > 5000:
            iid[int(sk)] = ident
    pm = tables["promotion"]
    promo_ok = {
        int(sk)
        for sk, v in zip(pm["p_promo_sk"][0], _sv(pm, "p_channel_email"))
        if v == "N"
    }
    detail = {}

    def add(ch, ident, s, r, p):
        acc = detail.setdefault((ch, ident), [0, 0, 0])
        acc[0] += s
        acc[1] += r
        acc[2] += p

    def channel(ch, sales_cols, ret_cols):
        d_c, i_c, promo_c, key2_c, price_c, profit_c, tab = sales_cols
        ri_c, rkey2_c, ramt_c, rloss_c, rtab = ret_cols
        rt = tables[rtab]
        matches = {}
        for i, k2, amt, loss in zip(rt[ri_c][0], rt[rkey2_c][0],
                                    rt[ramt_c][0], rt[rloss_c][0]):
            matches.setdefault((int(i), int(k2)), []).append((int(amt), int(loss)))
        t = tables[tab]
        for d, i, pr_sk, k2, price, profit in zip(
            t[d_c][0], t[i_c][0], t[promo_c][0], t[key2_c][0],
            t[price_c][0], t[profit_c][0],
        ):
            if int(d) not in win or int(i) not in iid or int(pr_sk) not in promo_ok:
                continue
            ident = iid[int(i)]
            ms = matches.get((int(i), int(k2)))
            if not ms:
                add(ch, ident, int(price), 0, int(profit))
            else:
                for amt, loss in ms:
                    add(ch, ident, int(price), amt, int(profit) - loss)

    channel(
        "store channel",
        ("ss_sold_date_sk", "ss_item_sk", "ss_promo_sk", "ss_ticket_number",
         "ss_ext_sales_price", "ss_net_profit", "store_sales"),
        ("sr_item_sk", "sr_ticket_number", "sr_return_amt", "sr_net_loss",
         "store_returns"),
    )
    channel(
        "catalog channel",
        ("cs_sold_date_sk", "cs_item_sk", "cs_promo_sk", "cs_order_number",
         "cs_ext_sales_price", "cs_net_profit", "catalog_sales"),
        ("cr_item_sk", "cr_order_number", "cr_return_amount", "cr_net_loss",
         "catalog_returns"),
    )
    channel(
        "web channel",
        ("ws_sold_date_sk", "ws_item_sk", "ws_promo_sk", "ws_order_number",
         "ws_ext_sales_price", "ws_net_profit", "web_sales"),
        ("wr_item_sk", "wr_order_number", "wr_return_amt", "wr_net_loss",
         "web_returns"),
    )
    return _rollup2(detail)


# ------------------------------------------- distinct-count EXISTS


def _oracle_ship_report(tables, *, fact, order_c, wh_c, ship_date_c, addr_c,
                        dim_join, ship_c, profit_c, ret_tab, r_order_c,
                        lo, hi, state, returned):
    """Shared q16/q94/q95: filtered fact lines restricted to
    multi-warehouse orders, anti/semi returns, then
    (count distinct order, sum ship, sum profit)."""
    win = _win_sks(tables, lo, hi)
    ca = tables["customer_address"]
    ok_addr = set(
        ca["ca_address_sk"][0][np.array(_s_eq(ca, "ca_state", state))].tolist()
    )
    dim_ok = dim_join(tables)
    f = tables[fact]
    # multi-warehouse orders over the WHOLE fact table
    wh_by_order = {}
    for o, w in zip(f[order_c][0], f[wh_c][0]):
        wh_by_order.setdefault(int(o), set()).add(int(w))
    multi = {o for o, ws in wh_by_order.items() if len(ws) >= 2}
    returned_orders = {int(o) for o in tables[ret_tab][r_order_c][0]}
    orders = set()
    ship_tot = profit_tot = 0
    for d, a, dim, o, sc, pr in zip(
        f[ship_date_c][0], f[addr_c][0], f[dim_join.col][0],
        f[order_c][0], f[ship_c][0], f[profit_c][0],
    ):
        o = int(o)
        if int(d) not in win or int(a) not in ok_addr or int(dim) not in dim_ok:
            continue
        if o not in multi:
            continue
        if (o in returned_orders) != returned:
            continue
        orders.add(o)
        ship_tot += int(sc)
        profit_tot += int(pr)
    return len(orders), ship_tot, profit_tot


class _DimFilter:
    def __init__(self, col, fn):
        self.col = col
        self._fn = fn

    def __call__(self, tables):
        return self._fn(tables)


def oracle_q94(tables):
    dim = _DimFilter("ws_web_site_sk", lambda t: set(
        t["web_site"]["web_site_sk"][0][
            np.array(_s_eq(t["web_site"], "web_company_name", "pri"))
        ].tolist()))
    return _oracle_ship_report(
        tables, fact="web_sales", order_c="ws_order_number",
        wh_c="ws_warehouse_sk", ship_date_c="ws_ship_date_sk",
        addr_c="ws_ship_addr_sk", dim_join=dim,
        ship_c="ws_ext_ship_cost", profit_c="ws_net_profit",
        ret_tab="web_returns", r_order_c="wr_order_number",
        lo=(1999, 2, 1), hi=(1999, 12, 31), state="TN", returned=False,
    )


def oracle_q95(tables):
    dim = _DimFilter("ws_web_site_sk", lambda t: set(
        t["web_site"]["web_site_sk"][0][
            np.array(_s_eq(t["web_site"], "web_company_name", "pri"))
        ].tolist()))
    return _oracle_ship_report(
        tables, fact="web_sales", order_c="ws_order_number",
        wh_c="ws_warehouse_sk", ship_date_c="ws_ship_date_sk",
        addr_c="ws_ship_addr_sk", dim_join=dim,
        ship_c="ws_ext_ship_cost", profit_c="ws_net_profit",
        ret_tab="web_returns", r_order_c="wr_order_number",
        lo=(1999, 2, 1), hi=(1999, 12, 31), state="TN", returned=True,
    )


def oracle_q16(tables):
    dim = _DimFilter("cs_call_center_sk", lambda t: set(
        t["call_center"]["cc_call_center_sk"][0][
            np.array(_s_eq(t["call_center"], "cc_county", "Williamson County"))
        ].tolist()))
    return _oracle_ship_report(
        tables, fact="catalog_sales", order_c="cs_order_number",
        wh_c="cs_warehouse_sk", ship_date_c="cs_ship_date_sk",
        addr_c="cs_ship_addr_sk", dim_join=dim,
        ship_c="cs_ext_ship_cost", profit_c="cs_net_profit",
        ret_tab="catalog_returns", r_order_c="cr_order_number",
        lo=(2002, 2, 1), hi=(2002, 12, 31), state="GA", returned=False,
    )


# ------------------------------------------- year-over-year customers


def _oracle_yoy_customer(tables, *, store_m, web_m, y1, y2, out_cols):
    dd = tables["date_dim"]
    yr_by_sk = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_year"][0].tolist()))
    cu = tables["customer"]
    n_cust = cu["c_customer_sk"][0].shape[0]
    attrs = {}
    cols = {c: (_sv(cu, c) if cu[c][1] is not None else cu[c][0]) for c in out_cols}
    for i in range(n_cust):
        sk = int(cu["c_customer_sk"][0][i])
        attrs[sk] = tuple(
            cols[c][i] if isinstance(cols[c], list) else int(cols[c][i])
            for c in out_cols
        )

    def totals(fact, date_c, cust_c, measure):
        f = tables[fact]
        out = {y1: {}, y2: {}}
        m = measure(f)
        for d, c, v in zip(f[date_c][0], f[cust_c][0], m):
            y = yr_by_sk.get(int(d))
            if y in out:
                out[y][int(c)] = out[y].get(int(c), 0) + int(v)
        return out

    st = totals("store_sales", "ss_sold_date_sk", "ss_customer_sk", store_m)
    wb = totals("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", web_m)
    rows = {}
    for sk in attrs:
        if sk not in st[y1] or sk not in st[y2] or sk not in wb[y1] or sk not in wb[y2]:
            continue
        s1, s2, w1, w2 = st[y1][sk], st[y2][sk], wb[y1][sk], wb[y2][sk]
        if not (s1 > 0 and w1 > 0):
            continue
        # the plan casts decimal(17,2) -> float64 (unscaled/100.0)
        # before dividing; mirror that float path bit-for-bit
        if (w2 / 100.0) / (w1 / 100.0) > (s2 / 100.0) / (s1 / 100.0):
            rows[sk] = attrs[sk]
    return set(rows.values())


def oracle_q74(tables):
    return _oracle_yoy_customer(
        tables,
        store_m=lambda f: f["ss_net_paid"][0],
        web_m=lambda f: f["ws_net_paid"][0],
        y1=1999, y2=2000,
        out_cols=["c_customer_id", "c_first_name", "c_last_name"],
    )


def oracle_q11(tables):
    return _oracle_yoy_customer(
        tables,
        store_m=lambda f: f["ss_ext_list_price"][0] - f["ss_ext_discount_amt"][0],
        web_m=lambda f: f["ws_ext_list_price"][0] - f["ws_ext_discount_amt"][0],
        y1=2000, y2=2001,
        out_cols=["c_customer_id", "c_preferred_cust_flag",
                  "c_first_name", "c_last_name"],
    )


# ------------------------------------------- q23 frequent/best CTEs


def _oracle_q23_sets(tables):
    dd = tables["date_dim"]
    info = {int(k): (int(y), int(m)) for k, y, m in
            zip(dd["d_date_sk"][0], dd["d_year"][0], dd["d_moy"][0])}
    ss = tables["store_sales"]
    cells = {}
    for d, i in zip(ss["ss_sold_date_sk"][0], ss["ss_item_sk"][0]):
        ym = info.get(int(d))
        if ym is None:
            continue
        key = (int(i), ym[0] * 12 + ym[1])
        cells[key] = cells.get(key, 0) + 1
    hot_items = {i for (i, _), c in cells.items() if c > 4}

    spend = {}
    for c, q, p in zip(ss["ss_customer_sk"][0], ss["ss_quantity"][0],
                       ss["ss_sales_price"][0]):
        spend[int(c)] = spend.get(int(c), 0) + int(q) * int(p)
    cmax = max(spend.values())
    # mirror the plan: float64 compare of decimal-cast values.  BOTH
    # sides share scale 2 so the /100.0 cancels only in exact math —
    # reproduce the engine's exact operand order
    best = {c for c, v in spend.items() if v / 100.0 > 0.5 * (cmax / 100.0)}
    return hot_items, best, info


def _oracle_q23_rows(tables, hot, best, info):
    out = []
    for fact, d_c, i_c, c_c, q_c, p_c in (
        ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
         "cs_bill_customer_sk", "cs_quantity", "cs_list_price"),
        ("web_sales", "ws_sold_date_sk", "ws_item_sk",
         "ws_bill_customer_sk", "ws_quantity", "ws_list_price"),
    ):
        f = tables[fact]
        for d, i, c, q, p in zip(f[d_c][0], f[i_c][0], f[c_c][0],
                                 f[q_c][0], f[p_c][0]):
            if info.get(int(d)) != (2000, 5):
                continue
            if int(i) in hot and int(c) in best:
                out.append((int(c), int(q) * int(p)))
    return out


def oracle_q23a(tables):
    hot, best, info = _oracle_q23_sets(tables)
    rows = _oracle_q23_rows(tables, hot, best, info)
    return sum(v for _, v in rows) if rows else None


def oracle_q23b(tables):
    hot, best, info = _oracle_q23_sets(tables)
    rows = _oracle_q23_rows(tables, hot, best, info)
    cu = tables["customer"]
    names = {int(sk): (l, f) for sk, l, f in
             zip(cu["c_customer_sk"][0], _sv(cu, "c_last_name"),
                 _sv(cu, "c_first_name"))}
    out = {}
    for c, v in rows:
        key = names[c]
        out[key] = out.get(key, 0) + v
    return out


# ------------------------------------------- q24 returned-sales netpaid


def _oracle_q24_cells(tables):
    ss = tables["store_sales"]
    sr = tables["store_returns"]
    returned = {}
    for i, tk in zip(sr["sr_item_sk"][0], sr["sr_ticket_number"][0]):
        k = (int(i), int(tk))
        returned[k] = returned.get(k, 0) + 1
    st = tables["store"]
    stores = {}
    for sk, mid, nm, co in zip(st["s_store_sk"][0], st["s_market_id"][0],
                               _sv(st, "s_store_name"), _sv(st, "s_county")):
        if int(mid) == 8:
            stores[int(sk)] = (nm, co)
    cu = tables["customer"]
    custs = {int(sk): (l, f, int(a)) for sk, l, f, a in
             zip(cu["c_customer_sk"][0], _sv(cu, "c_last_name"),
                 _sv(cu, "c_first_name"), cu["c_current_addr_sk"][0])}
    ca = tables["customer_address"]
    county = {int(sk): c for sk, c in
              zip(ca["ca_address_sk"][0], _sv(ca, "ca_county"))}
    it = tables["item"]
    color = {int(sk): c for sk, c in
             zip(it["i_item_sk"][0], _sv(it, "i_color"))}
    cells = {}
    for i, tk, stk, csk, paid in zip(
        ss["ss_item_sk"][0], ss["ss_ticket_number"][0], ss["ss_store_sk"][0],
        ss["ss_customer_sk"][0], ss["ss_net_paid"][0],
    ):
        mult = returned.get((int(i), int(tk)), 0)
        if not mult or int(stk) not in stores or int(csk) not in custs:
            continue
        nm, sco = stores[int(stk)]
        last, first, addr = custs[int(csk)]
        if county.get(addr) != sco:
            continue
        key = (last, first, nm, color[int(i)])
        cells[key] = cells.get(key, 0) + int(paid) * mult
    return cells


def _oracle_q24(tables, c):
    cells = _oracle_q24_cells(tables)
    if not cells:
        return {}, None
    total = sum(cells.values())
    n = len(cells)
    # engine avg: decimal(17,2) state -> avg result decimal(21,6),
    # HALF_UP; mirror its unscaled arithmetic then the float compare
    num = total * 10_000
    q, r = divmod(num, n)
    avg_unscaled = q + (1 if 2 * r >= n else 0)
    out = {}
    for (last, first, store, color), v in cells.items():
        if color != c:
            continue
        key = (last, first, store)
        out[key] = out.get(key, 0) + v
    thr = 0.05 * (avg_unscaled / 1_000_000.0)
    return {k: v for k, v in out.items() if v / 100.0 > thr}, avg_unscaled


def oracle_q24a(tables):
    return _oracle_q24(tables, "peach")[0]


def oracle_q24b(tables):
    return _oracle_q24(tables, "saddle")[0]


# ------------------------------------------- cross-channel item YoY


def oracle_q75(tables):
    dd = tables["date_dim"]
    yr = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_year"][0].tolist()))
    it = tables["item"]
    cats = _sv(it, "i_category")
    ids = {}
    for i in range(it["i_item_sk"][0].shape[0]):
        if cats[i] == "Books":
            ids[int(it["i_item_sk"][0][i])] = (
                int(it["i_brand_id"][0][i]), int(it["i_class_id"][0][i]),
                int(it["i_category_id"][0][i]), int(it["i_manufact_id"][0][i]))
    agg = {}

    def channel(fact, d_c, i_c, k2_c, q_c, a_c, rtab, ri_c, rk2_c, rq_c, ra_c):
        rt = tables[rtab]
        matches = {}
        for i, k2, q, a in zip(rt[ri_c][0], rt[rk2_c][0], rt[rq_c][0], rt[ra_c][0]):
            matches.setdefault((int(i), int(k2)), []).append((int(q), int(a)))
        f = tables[fact]
        for d, i, k2, q, a in zip(f[d_c][0], f[i_c][0], f[k2_c][0],
                                  f[q_c][0], f[a_c][0]):
            y = yr.get(int(d))
            if y is None or int(i) not in ids:
                continue
            key = (y,) + ids[int(i)]
            ms = matches.get((int(i), int(k2)))
            acc = agg.setdefault(key, [0, 0])
            if not ms:
                acc[0] += int(q)
                acc[1] += int(a)
            else:
                for rq, ra in ms:
                    acc[0] += int(q) - rq
                    acc[1] += int(a) - ra

    channel("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_ticket_number",
            "ss_quantity", "ss_ext_sales_price", "store_returns", "sr_item_sk",
            "sr_ticket_number", "sr_return_quantity", "sr_return_amt")
    channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk", "cs_order_number",
            "cs_quantity", "cs_ext_sales_price", "catalog_returns", "cr_item_sk",
            "cr_order_number", "cr_return_quantity", "cr_return_amount")
    channel("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_order_number",
            "ws_quantity", "ws_ext_sales_price", "web_returns", "wr_item_sk",
            "wr_order_number", "wr_return_quantity", "wr_return_amt")
    out = {}
    for key, (cnt, amt) in agg.items():
        if key[0] != 2002:
            continue
        pkey = (2001,) + key[1:]
        if pkey not in agg:
            continue
        pcnt, pamt = agg[pkey]
        if not (pcnt > 0 and cnt / pcnt < 0.9):
            continue
        out[key[1:]] = (cnt - pcnt, amt - pamt)
    return out


def oracle_q78(tables):
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())

    def channel(fact, d_c, i_c, c_c, k2_c, q_c, w_c, s_c, rtab, ri_c, rk2_c):
        rt = tables[rtab]
        returned = {(int(i), int(k)) for i, k in zip(rt[ri_c][0], rt[rk2_c][0])}
        f = tables[fact]
        out = {}
        for d, i, c, k2, q, w, sp in zip(f[d_c][0], f[i_c][0], f[c_c][0],
                                         f[k2_c][0], f[q_c][0], f[w_c][0],
                                         f[s_c][0]):
            if int(d) not in y2000 or (int(i), int(k2)) in returned:
                continue
            acc = out.setdefault((int(i), int(c)), [0, 0, 0])
            acc[0] += int(q)
            acc[1] += int(w)
            acc[2] += int(sp)
        return out

    ss = channel("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                 "ss_ticket_number", "ss_quantity", "ss_wholesale_cost",
                 "ss_sales_price", "store_returns", "sr_item_sk", "sr_ticket_number")
    ws = channel("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
                 "ws_order_number", "ws_quantity", "ws_wholesale_cost",
                 "ws_sales_price", "web_returns", "wr_item_sk", "wr_order_number")
    cs = channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_bill_customer_sk", "cs_order_number", "cs_quantity",
                 "cs_wholesale_cost", "cs_sales_price", "catalog_returns",
                 "cr_item_sk", "cr_order_number")
    out = {}
    for key, (q, w, sp) in ss.items():
        wq = ws.get(key, (0, 0, 0))[0]
        cq = cs.get(key, (0, 0, 0))[0]
        if not (wq > 0 or cq > 0):
            continue
        other = float(wq + cq)
        ratio = q / (other if other > 0 else 1.0)
        out[key] = (q, w, sp, ratio, wq + cq)
    return out


# ------------------------------------------- cumulative-window pair


def oracle_q51(tables):
    dd = tables["date_dim"]
    y2000 = {int(k): int(dv) for k, y, dv in
             zip(dd["d_date_sk"][0], dd["d_year"][0], dd["d_date"][0])
             if int(y) == 2000}

    def cume(fact, d_c, i_c, p_c):
        f = tables[fact]
        daily = {}
        for d, i, p in zip(f[d_c][0], f[i_c][0], f[p_c][0]):
            dv = y2000.get(int(d))
            if dv is None:
                continue
            daily[(int(i), dv)] = daily.get((int(i), dv), 0) + int(p)
        out = {}
        by_item = {}
        for (i, dv), v in daily.items():
            by_item.setdefault(i, []).append((dv, v))
        for i, lst in by_item.items():
            lst.sort()
            run = 0
            for dv, v in lst:
                run += v
                out[(i, dv)] = run
        return out

    web = cume("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_sales_price")
    store = cume("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_sales_price")
    items = {i for i, _ in web} | {i for i, _ in store}
    out = {}
    for i in items:
        dates = sorted({d for (ii, d) in web if ii == i}
                       | {d for (ii, d) in store if ii == i})
        wmax = smax = None
        for d in dates:
            if (i, d) in web:
                wmax = web[(i, d)] if wmax is None else max(wmax, web[(i, d)])
            if (i, d) in store:
                smax = store[(i, d)] if smax is None else max(smax, store[(i, d)])
            if wmax is not None and smax is not None and wmax > smax:
                out[(i, d)] = (wmax, smax)
    return out


def oracle_q67(tables):
    dd = tables["date_dim"]
    dinfo = {int(k): (int(y), int(q), int(m)) for k, y, q, m in
             zip(dd["d_date_sk"][0], dd["d_year"][0], dd["d_qoy"][0],
                 dd["d_moy"][0]) if int(y) == 2000}
    st = tables["store"]
    sname = {int(k): v for k, v in zip(st["s_store_sk"][0], _sv(st, "s_store_name"))}
    it = tables["item"]
    iinfo = {int(sk): (c, cl, b, iid) for sk, c, cl, b, iid in
             zip(it["i_item_sk"][0], _sv(it, "i_category"), _sv(it, "i_class"),
                 _sv(it, "i_brand"), _sv(it, "i_item_id"))}
    ss = tables["store_sales"]
    cells = {}
    for d, stk, i, q, p in zip(ss["ss_sold_date_sk"][0], ss["ss_store_sk"][0],
                               ss["ss_item_sk"][0], ss["ss_quantity"][0],
                               ss["ss_sales_price"][0]):
        dv = dinfo.get(int(d))
        if dv is None or int(stk) not in sname or int(i) not in iinfo:
            continue
        cat, cl, b, iid = iinfo[int(i)]
        dims = (cat, cl, b, iid, dv[0], dv[1], dv[2], sname[int(stk)])
        val = int(q) * int(p)
        for level in range(8, -1, -1):
            key = tuple(dims[k] if k < level else None for k in range(8)) + (8 - level,)
            cells[key] = cells.get(key, 0) + val
    # rank within category (competition ranking by sumsales desc)
    by_cat = {}
    for key, v in cells.items():
        by_cat.setdefault(key[0], []).append((v, key))
    out = {}
    for cat, lst in by_cat.items():
        lst.sort(key=lambda t: -t[0])
        for pos, (v, key) in enumerate(lst):
            rk = 1 + sum(1 for w, _ in lst if w > v)
            if rk <= 100:
                out[key] = (v, rk)
    return out


# ------------------------------------------- q14 cross-channel INTERSECT


def _oracle_q14_base(tables):
    dd = tables["date_dim"]
    info = {int(k): (int(y), int(m)) for k, y, m in
            zip(dd["d_date_sk"][0], dd["d_year"][0], dd["d_moy"][0])}
    it = tables["item"]
    triple = {int(sk): (int(b), int(c), int(cat)) for sk, b, c, cat in
              zip(it["i_item_sk"][0], it["i_brand_id"][0], it["i_class_id"][0],
                  it["i_category_id"][0])}
    chans = [
        ("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_list_price"),
        ("catalog_sales", "cs_sold_date_sk", "cs_item_sk", "cs_quantity", "cs_list_price"),
        ("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_quantity", "ws_list_price"),
    ]
    sets = []
    total = 0
    cnt = 0
    for fact, d_c, i_c, q_c, p_c in chans:
        f = tables[fact]
        seen = set()
        for d, i, q, p in zip(f[d_c][0], f[i_c][0], f[q_c][0], f[p_c][0]):
            y_m = info.get(int(d))
            if y_m is None or not (1998 <= y_m[0] <= 2000):
                continue
            if int(i) in triple:
                seen.add(triple[int(i)])
            total += int(q) * int(p)
            cnt += 1
        sets.append(seen)
    inter = sets[0] & sets[1] & sets[2]
    cross_items = {sk for sk, tr in triple.items() if tr in inter}
    # engine avg: decimal HALF_UP at scale+4 (v is decimal(30,2) ->
    # avg decimal(34,6))
    num = total * 10_000
    q_, r_ = divmod(num, cnt)
    avg_unscaled = q_ + (1 if 2 * r_ >= cnt else 0)
    return info, triple, cross_items, avg_unscaled, chans


def _oracle_q14_cells(tables, info, triple, cross_items, avg_unscaled, chan,
                      year):
    fact, d_c, i_c, q_c, p_c = chan
    f = tables[fact]
    cells = {}
    for d, i, q, p in zip(f[d_c][0], f[i_c][0], f[q_c][0], f[p_c][0]):
        if info.get(int(d)) != (year, 11) or int(i) not in cross_items:
            continue
        key = triple[int(i)]
        acc = cells.setdefault(key, [0, 0])
        acc[0] += int(q) * int(p)
        acc[1] += 1
    thr = avg_unscaled / 1_000_000.0
    return {k: tuple(v) for k, v in cells.items() if v[0] / 100.0 > thr}


def oracle_q14a(tables):
    info, triple, cross_items, avg_u, chans = _oracle_q14_base(tables)
    out = {}
    for chan, name in zip(chans, ("store", "catalog", "web")):
        cells = _oracle_q14_cells(tables, info, triple, cross_items, avg_u,
                                  chan, 2002)
        for (b, c, cat), (s, n) in cells.items():
            for key in ((name, b, c, cat), (name, b, c, None),
                        (name, b, None, None), (name, None, None, None),
                        (None, None, None, None)):
                acc = out.setdefault(key, [0, 0])
                acc[0] += s
                acc[1] += n
    return {k: tuple(v) for k, v in out.items()}


def oracle_q14b(tables):
    info, triple, cross_items, avg_u, chans = _oracle_q14_base(tables)
    ty = _oracle_q14_cells(tables, info, triple, cross_items, avg_u, chans[0], 2002)
    ly = _oracle_q14_cells(tables, info, triple, cross_items, avg_u, chans[0], 2001)
    out = {}
    for key, (s, n) in ty.items():
        if key in ly and s / 100.0 > ly[key][0] / 100.0:
            out[key] = (s, n, ly[key][0], ly[key][1])
    return out


# ------------------------------------------- inventory / first-sale giants


def oracle_q72(tables):
    hd = tables["household_demographics"]
    hd_ok = set(hd["hd_demo_sk"][0][
        np.array(_s_eq(hd, "hd_buy_potential", ">10000"))].tolist())
    cd = tables["customer_demographics"]
    cd_ok = set(cd["cd_demo_sk"][0][
        np.array(_s_eq(cd, "cd_marital_status", "D"))].tolist())
    dd = tables["date_dim"]
    dinfo = {int(k): (int(dv), int(w)) for k, dv, w in
             zip(dd["d_date_sk"][0], dd["d_date"][0], dd["d_week_seq"][0])}
    it = tables["item"]
    desc = {int(k): v for k, v in zip(it["i_item_sk"][0], _sv(it, "i_item_desc"))}
    wh = tables["warehouse"]
    wname = {int(k): v for k, v in
             zip(wh["w_warehouse_sk"][0], _sv(wh, "w_warehouse_name"))}
    inv = tables["inventory"]
    by_item = {}
    for d, i, w, q in zip(inv["inv_date_sk"][0], inv["inv_item_sk"][0],
                          inv["inv_warehouse_sk"][0],
                          inv["inv_quantity_on_hand"][0]):
        by_item.setdefault(int(i), []).append((int(d), int(w), int(q)))
    cs = tables["catalog_sales"]
    out = {}
    for sd, shd, i, cdsk, hdsk, q in zip(
        cs["cs_sold_date_sk"][0], cs["cs_ship_date_sk"][0], cs["cs_item_sk"][0],
        cs["cs_bill_cdemo_sk"][0], cs["cs_bill_hdemo_sk"][0], cs["cs_quantity"][0],
    ):
        if int(hdsk) not in hd_ok or int(cdsk) not in cd_ok:
            continue
        d1 = dinfo.get(int(sd))
        d3 = dinfo.get(int(shd))
        if d1 is None or d3 is None or not (d3[0] > d1[0] + 5):
            continue
        for invd, w, onhand in by_item.get(int(i), ()):
            d2 = dinfo.get(invd)
            if d2 is None or d2[1] != d1[1] or not (onhand < int(q)):
                continue
            key = (desc[int(i)], wname[w], d1[1])
            out[key] = out.get(key, 0) + 1
    return out


def _oracle_q64_cells(tables, year):
    dd = tables["date_dim"]
    y_sks = set(dd["d_date_sk"][0][dd["d_year"][0] == year].tolist())
    sr = tables["store_returns"]
    mult = {}
    for i, tk in zip(sr["sr_item_sk"][0], sr["sr_ticket_number"][0]):
        k = (int(i), int(tk))
        mult[k] = mult.get(k, 0) + 1
    it = tables["item"]
    colors = _sv(it, "i_color")
    ok_colors = {"purple", "burlywood", "indian", "spring", "floral",
                 "medium", "peach", "saddle", "navy", "slate"}
    iid = {int(sk): i_id for sk, c, i_id in
           zip(it["i_item_sk"][0], colors, _sv(it, "i_item_id")) if c in ok_colors}
    st = tables["store"]
    sinfo = {int(k): (nm, z) for k, nm, z in
             zip(st["s_store_sk"][0], _sv(st, "s_store_name"), _sv(st, "s_zip"))}
    ss = tables["store_sales"]
    cells = {}
    for i, tk, stk, d, wc, lp, cp in zip(
        ss["ss_item_sk"][0], ss["ss_ticket_number"][0], ss["ss_store_sk"][0],
        ss["ss_sold_date_sk"][0], ss["ss_wholesale_cost"][0],
        ss["ss_list_price"][0], ss["ss_coupon_amt"][0],
    ):
        m = mult.get((int(i), int(tk)), 0)
        if not m or int(d) not in y_sks or int(i) not in iid or int(stk) not in sinfo:
            continue
        nm, z = sinfo[int(stk)]
        key = (iid[int(i)], nm, z)
        acc = cells.setdefault(key, [0, 0, 0, 0])
        acc[0] += m
        acc[1] += int(wc) * m
        acc[2] += int(lp) * m
        acc[3] += int(cp) * m
    return {k: tuple(v) for k, v in cells.items()}


def oracle_q64(tables):
    c1 = _oracle_q64_cells(tables, 2001)
    c2 = _oracle_q64_cells(tables, 2002)
    out = {}
    for key, v1 in c1.items():
        v2 = c2.get(key)
        if v2 is not None and v2[0] <= v1[0]:
            out[key] = v1 + v2
    return out


# ------------------------------------------- round-4 moderates


def oracle_q97(tables):
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())

    def pairs(fact, d_c, c_c, i_c):
        f = tables[fact]
        return {
            (int(c), int(i))
            for d, c, i in zip(f[d_c][0], f[c_c][0], f[i_c][0])
            if int(d) in y2000
        }

    ss = pairs("store_sales", "ss_sold_date_sk", "ss_customer_sk", "ss_item_sk")
    cs = pairs("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
               "cs_item_sk")
    return (len(ss - cs), len(cs - ss), len(ss & cs))


def _oracle_city_tickets(tables, *, dow, cities, hd_ok_fn, amt_c, extra):
    dd = tables["date_dim"]
    days = set(dd["d_date_sk"][0][np.isin(dd["d_dow"][0], list(dow))].tolist())
    st = tables["store"]
    st_ok = {int(k) for k, c in zip(st["s_store_sk"][0], _sv(st, "s_city"))
             if c in cities}
    hd = tables["household_demographics"]
    hd_ok = {int(k) for k, d, v in zip(hd["hd_demo_sk"][0], hd["hd_dep_count"][0],
                                       hd["hd_vehicle_count"][0])
             if hd_ok_fn(int(d), int(v))}
    ca = tables["customer_address"]
    city = {int(k): c for k, c in zip(ca["ca_address_sk"][0], _sv(ca, "ca_city"))}
    ss = tables["store_sales"]
    cells = {}
    cols = [ss[c][0] for c in extra]
    for idx in range(ss["ss_sold_date_sk"][0].shape[0]):
        if int(ss["ss_sold_date_sk"][0][idx]) not in days:
            continue
        if int(ss["ss_store_sk"][0][idx]) not in st_ok:
            continue
        if int(ss["ss_hdemo_sk"][0][idx]) not in hd_ok:
            continue
        addr = int(ss["ss_addr_sk"][0][idx])
        if addr not in city:
            continue
        key = (int(ss["ss_ticket_number"][0][idx]),
               int(ss["ss_customer_sk"][0][idx]), city[addr])
        acc = cells.setdefault(key, [0] * (1 + len(extra)))
        acc[0] += int(ss[amt_c][0][idx])
        for k, c in enumerate(cols):
            acc[1 + k] += int(c[idx])
    cu = tables["customer"]
    cust = {int(k): (l, f, int(a)) for k, l, f, a in
            zip(cu["c_customer_sk"][0], _sv(cu, "c_last_name"),
                _sv(cu, "c_first_name"), cu["c_current_addr_sk"][0])}
    out = {}
    for (tick, csk, bought), vals in cells.items():
        if csk not in cust:
            continue
        last, first, addr = cust[csk]
        cur = city.get(addr)
        if cur is None or cur == bought:
            continue
        out[(last, first, cur, bought, tick)] = tuple(vals)
    return out


def oracle_q46(tables):
    return _oracle_city_tickets(
        tables, dow=(6, 0), cities={"Midway", "Fairview"},
        hd_ok_fn=lambda d, v: d == 4 or v == 3,
        amt_c="ss_coupon_amt", extra=["ss_net_profit"],
    )


def oracle_q68(tables):
    return _oracle_city_tickets(
        tables, dow=(6, 0), cities={"Midway", "Fairview"},
        hd_ok_fn=lambda d, v: d == 5 or v == 3,
        amt_c="ss_ext_sales_price", extra=["ss_ext_list_price"],
    )


def oracle_q79(tables):
    dd = tables["date_dim"]
    days = set(dd["d_date_sk"][0][
        (dd["d_dow"][0] == 1) & (dd["d_year"][0] >= 1998)
        & (dd["d_year"][0] <= 2000)].tolist())
    hd = tables["household_demographics"]
    hd_ok = {int(k) for k, d, v in zip(hd["hd_demo_sk"][0], hd["hd_dep_count"][0],
                                       hd["hd_vehicle_count"][0])
             if int(d) == 6 or int(v) > 2}
    st = tables["store"]
    s_city = {int(k): c for k, c in zip(st["s_store_sk"][0], _sv(st, "s_city"))}
    ss = tables["store_sales"]
    cells = {}
    for d, h, stk, tick, csk, amt, prof in zip(
        ss["ss_sold_date_sk"][0], ss["ss_hdemo_sk"][0], ss["ss_store_sk"][0],
        ss["ss_ticket_number"][0], ss["ss_customer_sk"][0],
        ss["ss_coupon_amt"][0], ss["ss_net_profit"][0],
    ):
        if int(d) not in days or int(h) not in hd_ok or int(stk) not in s_city:
            continue
        key = (int(tick), int(csk), s_city[int(stk)])
        acc = cells.setdefault(key, [0, 0])
        acc[0] += int(amt)
        acc[1] += int(prof)
    cu = tables["customer"]
    names = {int(k): (l, f) for k, l, f in
             zip(cu["c_customer_sk"][0], _sv(cu, "c_last_name"),
                 _sv(cu, "c_first_name"))}
    out = {}
    for (tick, csk, city), (amt, prof) in cells.items():
        if csk not in names:
            continue
        last, first = names[csk]
        out[(last, first, city, tick)] = (amt, prof)
    return out


def _oracle_ship_lag(tables, fact, sold_c, ship_c, wh_c, sm_c, dim_tab,
                     dim_sk_c, dim_name_c, dim_fk, year):
    dd = tables["date_dim"]
    sold_days = {int(k): int(v) for k, v, y in
                 zip(dd["d_date_sk"][0], dd["d_date"][0], dd["d_year"][0])
                 if int(y) == year}
    all_days = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_date"][0].tolist()))
    wh = tables["warehouse"]
    wname = {int(k): v for k, v in
             zip(wh["w_warehouse_sk"][0], _sv(wh, "w_warehouse_name"))}
    sm = tables["ship_mode"]
    smt = {int(k): v for k, v in zip(sm["sm_ship_mode_sk"][0], _sv(sm, "sm_type"))}
    dim = tables[dim_tab]
    dname = {int(k): v for k, v in
             zip(dim[dim_sk_c][0], _sv(dim, dim_name_c))}
    f = tables[fact]
    out = {}
    for sd, shd, w, m, dk in zip(f[sold_c][0], f[ship_c][0], f[wh_c][0],
                                 f[sm_c][0], f[dim_fk][0]):
        sold = sold_days.get(int(sd))
        ship = all_days.get(int(shd))
        if sold is None or ship is None:
            continue
        if int(w) not in wname or int(m) not in smt or int(dk) not in dname:
            continue
        lag = ship - sold
        key = (wname[int(w)], smt[int(m)], dname[int(dk)])
        acc = out.setdefault(key, [0, 0, 0, 0, 0])
        if lag <= 30:
            acc[0] += 1
        elif lag <= 60:
            acc[1] += 1
        elif lag <= 90:
            acc[2] += 1
        elif lag <= 120:
            acc[3] += 1
        else:
            acc[4] += 1
    return {k: tuple(v) for k, v in out.items()}


def oracle_q62(tables):
    return _oracle_ship_lag(tables, "web_sales", "ws_sold_date_sk",
                            "ws_ship_date_sk", "ws_warehouse_sk",
                            "ws_ship_mode_sk", "web_site", "web_site_sk",
                            "web_name", "ws_web_site_sk", 2001)


def oracle_q99(tables):
    return _oracle_ship_lag(tables, "catalog_sales", "cs_sold_date_sk",
                            "cs_ship_date_sk", "cs_warehouse_sk",
                            "cs_ship_mode_sk", "call_center",
                            "cc_call_center_sk", "cc_name",
                            "cs_call_center_sk", 2001)


def _oracle_inv_price(tables, fact, item_c):
    it = tables["item"]
    win = _win_sks(tables, (2000, 2, 1), (2000, 4, 1))
    inv = tables["inventory"]
    stocked = {
        int(i)
        for d, i, q in zip(inv["inv_date_sk"][0], inv["inv_item_sk"][0],
                           inv["inv_quantity_on_hand"][0])
        if int(d) in win and 100 <= int(q) <= 500
    }
    sold = {int(i) for i in tables[fact][item_c][0]}
    out = set()
    ids = _sv(it, "i_item_id")
    descs = _sv(it, "i_item_desc")
    for k in range(it["i_item_sk"][0].shape[0]):
        price = int(it["i_current_price"][0][k])
        sk = int(it["i_item_sk"][0][k])
        if 3000 <= price <= 6000 and sk in stocked and sk in sold:
            out.add((ids[k], descs[k], price))
    return out


def oracle_q37(tables):
    return _oracle_inv_price(tables, "catalog_sales", "cs_item_sk")


def oracle_q82(tables):
    return _oracle_inv_price(tables, "store_sales", "ss_item_sk")


# ------------------------------------------- round-4 batch B


def oracle_q41(tables):
    it = tables["item"]
    colors = _sv(it, "i_color")
    units = _sv(it, "i_units")
    manufs = _sv(it, "i_manufact")
    ids = _sv(it, "i_item_id")
    mids = it["i_manufact_id"][0]
    ok_manufs = set()
    for c, u, m in zip(colors, units, manufs):
        if (c in ("powder", "navy") and u in ("Each", "Dozen")) or (
            c in ("peach", "saddle") and u in ("Case", "Pallet")
        ):
            ok_manufs.add(m)
    return sorted({
        ids[k] for k in range(len(ids))
        if 50 <= int(mids[k]) <= 120 and manufs[k] in ok_manufs
    })


def oracle_q4(tables):
    dd = tables["date_dim"]
    yr = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_year"][0].tolist()))
    cu = tables["customer"]
    info = {int(k): (i, f, l) for k, i, f, l in
            zip(cu["c_customer_sk"][0], _sv(cu, "c_customer_id"),
                _sv(cu, "c_first_name"), _sv(cu, "c_last_name"))}

    def totals(fact, d_c, c_c, lp, wc, dc, sp):
        f = tables[fact]
        out = {2000: {}, 2001: {}}
        x = f[lp][0] - f[wc][0] - f[dc][0] + f[sp][0]
        # engine measure: decimal(10,2)/decimal "2" -> (20,10) exact
        # HALF_UP; x*10^10/200 == x*5*10^7 exactly
        m = x.astype(object) * (5 * 10**7)
        for d, c, v in zip(f[d_c][0], f[c_c][0], m):
            y = yr.get(int(d))
            if y in out:
                out[y][int(c)] = out[y].get(int(c), 0) + int(v)
        return out

    st = totals("store_sales", "ss_sold_date_sk", "ss_customer_sk",
                "ss_ext_list_price", "ss_ext_wholesale_cost",
                "ss_ext_discount_amt", "ss_ext_sales_price")
    ct = totals("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
                "cs_ext_list_price", "cs_wholesale_cost",
                "cs_ext_discount_amt", "cs_ext_sales_price")
    wb = totals("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                "ws_ext_list_price", "ws_wholesale_cost",
                "ws_ext_discount_amt", "ws_ext_sales_price")
    out = set()
    for sk, attrs in info.items():
        try:
            s1, s2 = st[2000][sk], st[2001][sk]
            c1, c2 = ct[2000][sk], ct[2001][sk]
            w1, w2 = wb[2000][sk], wb[2001][sk]
        except KeyError:
            continue
        f = 1e10
        if not (s1 / f > 0 and c1 / f > 0 and w1 / f > 0):
            continue
        if (c2 / f) / (c1 / f) > (s2 / f) / (s1 / f) and (
            (w2 / f) / (w1 / f) > (s2 / f) / (s1 / f)
        ):
            out.add(attrs)
    return out


def oracle_q50(tables):
    dd = tables["date_dim"]
    dates = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_date"][0].tolist()))
    aug01 = {int(k) for k, y, m in zip(dd["d_date_sk"][0], dd["d_year"][0],
                                       dd["d_moy"][0])
             if int(y) == 2001 and int(m) == 8}
    sr = tables["store_returns"]
    rets = {}
    for i, tk, c, d in zip(sr["sr_item_sk"][0], sr["sr_ticket_number"][0],
                           sr["sr_customer_sk"][0], sr["sr_returned_date_sk"][0]):
        if int(d) in aug01:
            rets.setdefault((int(i), int(tk), int(c)), []).append(int(d))
    st = tables["store"]
    sinfo = {int(k): (n, co, stt, z) for k, n, co, stt, z in
             zip(st["s_store_sk"][0], _sv(st, "s_store_name"),
                 _sv(st, "s_county"), _sv(st, "s_state"), _sv(st, "s_zip"))}
    ss = tables["store_sales"]
    out = {}
    for i, tk, c, stk, d in zip(ss["ss_item_sk"][0], ss["ss_ticket_number"][0],
                                ss["ss_customer_sk"][0], ss["ss_store_sk"][0],
                                ss["ss_sold_date_sk"][0]):
        ms = rets.get((int(i), int(tk), int(c)))
        if not ms or int(stk) not in sinfo or int(d) not in dates:
            continue
        sold = dates[int(d)]
        for rd in ms:
            lag = dates[rd] - sold
            key = sinfo[int(stk)]
            acc = out.setdefault(key, [0, 0, 0, 0, 0])
            if lag <= 30:
                acc[0] += 1
            elif lag <= 60:
                acc[1] += 1
            elif lag <= 90:
                acc[2] += 1
            elif lag <= 120:
                acc[3] += 1
            else:
                acc[4] += 1
    return {k: tuple(v) for k, v in out.items()}


def oracle_q22(tables):
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    it = tables["item"]
    iinfo = {int(sk): (i, b, c, cat) for sk, i, b, c, cat in
             zip(it["i_item_sk"][0], _sv(it, "i_item_id"), _sv(it, "i_brand"),
                 _sv(it, "i_class"), _sv(it, "i_category"))}
    inv = tables["inventory"]
    cells = {}
    for d, i, q in zip(inv["inv_date_sk"][0], inv["inv_item_sk"][0],
                       inv["inv_quantity_on_hand"][0]):
        if int(d) not in y2000 or int(i) not in iinfo:
            continue
        dims = iinfo[int(i)]
        for level in range(4, -1, -1):
            key = tuple(dims[k] if k < level else None for k in range(4)) + (4 - level,)
            acc = cells.setdefault(key, [0, 0])
            acc[0] += int(q)
            acc[1] += 1
    # engine avg over int32 -> float64 (sum/count in float)
    return {k: v[0] / v[1] for k, v in cells.items()}


def oracle_q21(tables):
    import datetime

    pivot = (datetime.date(2000, 3, 11) - datetime.date(1970, 1, 1)).days
    win = _win_sks(tables, (2000, 2, 10), (2000, 4, 10))
    dd = tables["date_dim"]
    dval = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_date"][0].tolist()))
    it = tables["item"]
    ids = _sv(it, "i_item_id")
    ok_items = {int(sk): ids[k] for k, sk in enumerate(it["i_item_sk"][0])
                if 2000 <= int(it["i_current_price"][0][k]) <= 5000}
    wh = tables["warehouse"]
    wname = {int(k): v for k, v in
             zip(wh["w_warehouse_sk"][0], _sv(wh, "w_warehouse_name"))}
    inv = tables["inventory"]
    cells = {}
    for d, i, w, q in zip(inv["inv_date_sk"][0], inv["inv_item_sk"][0],
                          inv["inv_warehouse_sk"][0],
                          inv["inv_quantity_on_hand"][0]):
        if int(d) not in win or int(i) not in ok_items or int(w) not in wname:
            continue
        key = (wname[int(w)], ok_items[int(i)])
        acc = cells.setdefault(key, [0, 0])
        if dval[int(d)] < pivot:
            acc[0] += int(q)
        else:
            acc[1] += int(q)
    out = {}
    for key, (b, a) in cells.items():
        if b > 0 and 2.0 / 3.0 <= a / b <= 1.5:
            out[key] = (b, a)
    return out


# ------------------------------------------- round-4 batch C


def oracle_q28(tables):
    ss = tables["store_sales"]
    bands = [
        ("B1", 0, 5, 0, 10, 0, 50),
        ("B2", 6, 10, 10, 20, 50, 100),
        ("B3", 11, 15, 20, 30, 100, 150),
        ("B4", 16, 20, 30, 40, 150, 200),
        ("B5", 21, 25, 40, 50, 200, 250),
        ("B6", 26, 30, 50, 60, 250, 300),
    ]
    q = ss["ss_quantity"][0]
    lp = ss["ss_list_price"][0]
    cp = ss["ss_coupon_amt"][0]
    wc = ss["ss_wholesale_cost"][0]
    out = {}
    for name, q_lo, q_hi, c_lo, c_hi, w_lo, w_hi in bands:
        m = (q >= q_lo) & (q <= q_hi) & (
            ((lp >= c_lo * 100) & (lp <= (c_lo + 10) * 100))
            | ((cp >= w_lo * 100) & (cp <= (w_lo + 1000) * 100))
            | ((wc >= c_hi * 100) & (wc <= (c_hi + 20) * 100))
        )
        vals = lp[m]
        cnt = int(m.sum())
        if cnt:
            total = int(vals.sum())
            num = total * 10_000
            qq, r = divmod(num, cnt)
            avg_unscaled = qq + (1 if 2 * r >= cnt else 0)
        else:
            avg_unscaled = None
        out[name] = (avg_unscaled, cnt, len(set(vals.tolist())))
    return out


def oracle_q90(tables):
    wp = tables["web_page"]
    pages = {int(k) for k, c in zip(wp["wp_web_page_sk"][0],
                                    wp["wp_char_count"][0])
             if 2000 <= int(c) <= 6000}
    ws = tables["web_sales"]

    def count(lo, hi):
        n = 0
        for t_, pg in zip(ws["ws_sold_time_sk"][0], ws["ws_web_page_sk"][0]):
            if int(pg) in pages and lo * 60 <= int(t_) <= hi * 60 + 59:
                n += 1
        return n

    am = count(8, 9)
    pm = count(19, 20)
    return am, pm, am / (pm if pm > 0 else 1.0)


def oracle_q76(tables):
    dd = tables["date_dim"]
    dinfo = {int(k): (int(y), int(q)) for k, y, q in
             zip(dd["d_date_sk"][0], dd["d_year"][0], dd["d_qoy"][0])}
    it = tables["item"]
    cat = {int(k): c for k, c in zip(it["i_item_sk"][0], _sv(it, "i_category"))}
    out = {}

    def channel(fact, d_c, i_c, null_c, p_c, name):
        f = tables[fact]
        for d, i, nc, p in zip(f[d_c][0], f[i_c][0], f[null_c][0], f[p_c][0]):
            if int(nc) != -1:
                continue
            yq = dinfo.get(int(d))
            if yq is None or int(i) not in cat:
                continue
            key = (name, null_c, yq[0], yq[1], cat[int(i)])
            acc = out.setdefault(key, [0, 0])
            acc[0] += 1
            acc[1] += int(p)

    channel("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
            "ss_ext_sales_price", "store")
    channel("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
            "ws_ext_sales_price", "web")
    channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
            "cs_bill_customer_sk", "cs_ext_sales_price", "catalog")
    return {k: tuple(v) for k, v in out.items()}


def _oracle_returns_above_avg(tables, rtab, r_date, r_cust, r_loc, r_amt,
                              loc_ok, names=False):
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    rt = tables[rtab]
    per = {}
    for d, c, l, a in zip(rt[r_date][0], rt[r_cust][0], rt[r_loc][0],
                          rt[r_amt][0]):
        if int(d) not in y2000 or (loc_ok is not None and int(l) not in loc_ok):
            continue
        key = (int(c), int(l))
        per[key] = per.get(key, 0) + int(a)
    by_loc = {}
    for (c, l), v in per.items():
        by_loc.setdefault(l, []).append(v)
    # engine avg: decimal(17,2) -> (21,6) HALF_UP
    avg_u = {}
    for l, vs in by_loc.items():
        total = sum(vs)
        n = len(vs)
        num = total * 10_000
        if num >= 0:
            q, r = divmod(num, n)
            avg_u[l] = q + (1 if 2 * r >= n else 0)
        else:
            q, r = divmod(-num, n)
            avg_u[l] = -(q + (1 if 2 * r >= n else 0))
    cu = tables["customer"]
    info = {int(k): (i, f, l) for k, i, f, l in
            zip(cu["c_customer_sk"][0], _sv(cu, "c_customer_id"),
                _sv(cu, "c_first_name"), _sv(cu, "c_last_name"))}
    out = set()
    for (c, l), v in per.items():
        if c not in info:
            continue
        if v / 100.0 > 1.2 * (avg_u[l] / 1_000_000.0):
            if names:
                out.add(info[c] + (v,))
            else:
                out.add(info[c][0])
    return out


def oracle_q1(tables):
    st = tables["store"]
    tn = set(st["s_store_sk"][0][np.array(_s_eq(st, "s_state", "TN"))].tolist())
    return _oracle_returns_above_avg(
        tables, "store_returns", "sr_returned_date_sk", "sr_customer_sk",
        "sr_store_sk", "sr_return_amt", tn)


def oracle_q30(tables):
    return _oracle_returns_above_avg(
        tables, "web_returns", "wr_returned_date_sk",
        "wr_returning_customer_sk", "wr_web_page_sk", "wr_return_amt",
        None, names=True)


def oracle_q81(tables):
    return _oracle_returns_above_avg(
        tables, "catalog_returns", "cr_returned_date_sk",
        "cr_returning_customer_sk", "cr_call_center_sk", "cr_return_amount",
        None, names=True)


# ------------------------------------------- round-4 batch D


def _oracle_weekly_pivot(tables, rows_iter):
    dd = tables["date_dim"]
    dinfo = {int(k): (int(w), int(dow)) for k, w, dow in
             zip(dd["d_date_sk"][0], dd["d_week_seq"][0], dd["d_dow"][0])}
    out = {}
    counts = {}
    for key_extra, d, price in rows_iter:
        wd = dinfo.get(int(d))
        if wd is None:
            continue
        key = key_extra + (wd[0],)
        acc = out.setdefault(key, [0] * 7)
        cnt = counts.setdefault(key, [0] * 7)
        acc[wd[1]] += int(price)
        cnt[wd[1]] += 1
    return out, counts


def oracle_q2(tables):
    dd = tables["date_dim"]
    y1 = set(dd["d_week_seq"][0][dd["d_year"][0] == 2001].tolist())
    y2 = set(dd["d_week_seq"][0][dd["d_year"][0] == 2002].tolist())

    def rows():
        for fact, d_c, p_c in (("web_sales", "ws_sold_date_sk", "ws_ext_sales_price"),
                               ("catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price")):
            f = tables[fact]
            for d, p in zip(f[d_c][0], f[p_c][0]):
                yield (), d, p

    wk, cnts = _oracle_weekly_pivot(tables, rows())
    out = {}
    for (w1,), sums1 in wk.items():
        if w1 not in y1:
            continue
        k2 = w1 + 52
        if k2 not in y2 or (k2,) not in wk:
            continue
        sums2 = wk[(k2,)]
        c1, c2 = cnts[(w1,)], cnts[(k2,)]
        # engine: empty dow bucket -> NULL sum -> NULL ratio; NULL or
        # zero denominator -> 1.0 (the Case guard)
        ratios = tuple(
            None if n1 == 0 else
            (a / 100.0) / ((b / 100.0) if (n2 > 0 and b > 0) else 1.0)
            for a, b, n1, n2 in zip(sums1, sums2, c1, c2)
        )
        out[w1] = ratios
    return out


def oracle_q59(tables):
    dd = tables["date_dim"]
    y1 = set(dd["d_week_seq"][0][dd["d_year"][0] == 2001].tolist())
    y2 = set(dd["d_week_seq"][0][dd["d_year"][0] == 2002].tolist())
    st = tables["store"]
    sname = {int(k): v for k, v in zip(st["s_store_sk"][0], _sv(st, "s_store_name"))}

    def rows():
        f = tables["store_sales"]
        for d, sk, p in zip(f["ss_sold_date_sk"][0], f["ss_store_sk"][0],
                            f["ss_sales_price"][0]):
            if int(sk) in sname:
                yield (int(sk),), d, p

    wk, cnts = _oracle_weekly_pivot(tables, rows())
    out = {}
    for (sk, w1), sums1 in wk.items():
        if w1 not in y1:
            continue
        k2 = (sk, w1 + 52)
        if (w1 + 52) not in y2 or k2 not in wk:
            continue
        sums2 = wk[k2]
        c1, c2 = cnts[(sk, w1)], cnts[k2]
        ratios = tuple(
            None if n1 == 0 else
            (a / 100.0) / ((b / 100.0) if (n2 > 0 and b > 0) else 1.0)
            for a, b, n1, n2 in zip(sums1, sums2, c1, c2)
        )
        out[(sname[sk], w1)] = ratios
    return out


def _oracle_srcandc(tables, vals):
    dd = tables["date_dim"]
    apr = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    apr_oct = set(dd["d_date_sk"][0][
        (dd["d_year"][0] >= 2000) & (dd["d_year"][0] <= 2002)].tolist())
    ss = tables["store_sales"]
    sr = tables["store_returns"]
    cs = tables["catalog_sales"]
    st = tables["store"]
    it = tables["item"]
    sname = {int(k): v for k, v in zip(st["s_store_sk"][0], _sv(st, "s_store_name"))}
    iinfo = {int(k): (a, b) for k, a, b in
             zip(it["i_item_sk"][0], _sv(it, "i_item_id"), _sv(it, "i_item_desc"))}
    rets = {}
    for idx in range(sr["sr_item_sk"][0].shape[0]):
        if int(sr["sr_returned_date_sk"][0][idx]) not in apr_oct:
            continue
        key = (int(sr["sr_item_sk"][0][idx]), int(sr["sr_ticket_number"][0][idx]))
        rets.setdefault(key, []).append(idx)
    cs_by = {}
    for idx in range(cs["cs_item_sk"][0].shape[0]):
        if int(cs["cs_sold_date_sk"][0][idx]) not in apr_oct:
            continue
        key = (int(cs["cs_bill_customer_sk"][0][idx]), int(cs["cs_item_sk"][0][idx]))
        cs_by.setdefault(key, []).append(idx)
    out = {}
    for idx in range(ss["ss_item_sk"][0].shape[0]):
        if int(ss["ss_sold_date_sk"][0][idx]) not in apr:
            continue
        i = int(ss["ss_item_sk"][0][idx])
        stk = int(ss["ss_store_sk"][0][idx])
        if i not in iinfo or stk not in sname:
            continue
        for ridx in rets.get((i, int(ss["ss_ticket_number"][0][idx])), ()):
            for cidx in cs_by.get((int(sr["sr_customer_sk"][0][ridx]), i), ()):
                key = iinfo[i] + (sname[stk],)
                acc = out.setdefault(key, [0, 0, 0])
                a, b, c = vals(ss, sr, cs, idx, ridx, cidx)
                acc[0] += a
                acc[1] += b
                acc[2] += c
    return {k: tuple(v) for k, v in out.items()}


def oracle_q25(tables):
    return _oracle_srcandc(
        tables,
        lambda ss, sr, cs, i, r, c: (int(ss["ss_net_profit"][0][i]),
                                     int(sr["sr_net_loss"][0][r]),
                                     int(cs["cs_net_profit"][0][c])))


def oracle_q29(tables):
    return _oracle_srcandc(
        tables,
        lambda ss, sr, cs, i, r, c: (int(ss["ss_quantity"][0][i]),
                                     int(sr["sr_return_quantity"][0][r]),
                                     int(cs["cs_quantity"][0][c])))


def oracle_q91(tables):
    dd = tables["date_dim"]
    nov = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    cc = tables["call_center"]
    ccn = {int(k): v for k, v in zip(cc["cc_call_center_sk"][0], _sv(cc, "cc_name"))}
    cu = tables["customer"]
    cinfo = {int(k): (int(cd), int(ad)) for k, cd, ad in
             zip(cu["c_customer_sk"][0], cu["c_current_cdemo_sk"][0],
                 cu["c_current_addr_sk"][0])}
    cdt = tables["customer_demographics"]
    ms = _sv(cdt, "cd_marital_status")
    es = _sv(cdt, "cd_education_status")
    cd_ok = {int(k): (ms[j], es[j]) for j, k in enumerate(cdt["cd_demo_sk"][0])
             if (ms[j] == "M" and es[j] == "Unknown")
             or (ms[j] == "W" and es[j] == "Advanced Degree")}

    cr = tables["catalog_returns"]
    out = {}
    for d, c, ctr, loss in zip(cr["cr_returned_date_sk"][0],
                               cr["cr_returning_customer_sk"][0],
                               cr["cr_call_center_sk"][0],
                               cr["cr_net_loss"][0]):
        if int(d) not in nov or int(ctr) not in ccn or int(c) not in cinfo:
            continue
        cdsk, adsk = cinfo[int(c)]
        if cdsk not in cd_ok:
            continue
        key = (ccn[int(ctr)],) + cd_ok[cdsk]
        out[key] = out.get(key, 0) + int(loss)
    return out


def oracle_q45(tables):
    dd = tables["date_dim"]
    q2_2000 = {int(k) for k, y, q in zip(dd["d_date_sk"][0], dd["d_year"][0],
                                         dd["d_qoy"][0])
               if int(y) == 2000 and int(q) == 2}
    it = tables["item"]
    ids = _sv(it, "i_item_id")
    iid = {int(k): ids[j] for j, k in enumerate(it["i_item_sk"][0])}
    hot = {ids[j] for j, k in enumerate(it["i_item_sk"][0])
           if int(k) in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)}
    cu = tables["customer"]
    addr = dict(zip(cu["c_customer_sk"][0].tolist(),
                    cu["c_current_addr_sk"][0].tolist()))
    ca = tables["customer_address"]
    cainfo = {int(k): (z, c) for k, z, c in
              zip(ca["ca_address_sk"][0], _sv(ca, "ca_zip"), _sv(ca, "ca_city"))}
    zips = {"35000", "35137", "60031", "60062", "60093"}
    ws = tables["web_sales"]
    out = {}
    for d, i, c, p in zip(ws["ws_sold_date_sk"][0], ws["ws_item_sk"][0],
                          ws["ws_bill_customer_sk"][0], ws["ws_sales_price"][0]):
        if int(d) not in q2_2000 or int(c) not in addr or int(i) not in iid:
            continue
        ainfo = cainfo.get(int(addr[int(c)]))
        if ainfo is None:
            continue
        z, city = ainfo
        if z[:5] in zips or iid[int(i)] in hot:
            out[(z, city)] = out.get((z, city), 0) + int(p)
    return out


# ------------------------------------------- stddev pair


def _py_stats(vals):
    n = len(vals)
    fs = float(sum(vals))
    fq = float(sum(v * v for v in vals))
    mean = fs / n if n else None
    if n <= 1:
        return n, mean, None
    var = (fq - fs * fs / n) / (n - 1)
    var = max(var, 0.0)
    return n, mean, var ** 0.5


def oracle_q17(tables):
    # the q25/q29 provenance chain, but collecting raw value LISTS
    # (count/avg/stddev need the samples, not sums)
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    y00_02 = set(dd["d_date_sk"][0][
        (dd["d_year"][0] >= 2000) & (dd["d_year"][0] <= 2002)].tolist())
    ss = tables["store_sales"]
    sr = tables["store_returns"]
    cs = tables["catalog_sales"]
    st = tables["store"]
    it = tables["item"]
    sname = {int(k): v for k, v in zip(st["s_store_sk"][0], _sv(st, "s_store_name"))}
    iinfo = {int(k): (a, b) for k, a, b in
             zip(it["i_item_sk"][0], _sv(it, "i_item_id"), _sv(it, "i_item_desc"))}
    rets = {}
    for idx in range(sr["sr_item_sk"][0].shape[0]):
        if int(sr["sr_returned_date_sk"][0][idx]) not in y00_02:
            continue
        key = (int(sr["sr_item_sk"][0][idx]), int(sr["sr_ticket_number"][0][idx]))
        rets.setdefault(key, []).append(idx)
    cs_by = {}
    for idx in range(cs["cs_item_sk"][0].shape[0]):
        if int(cs["cs_sold_date_sk"][0][idx]) not in y00_02:
            continue
        key = (int(cs["cs_bill_customer_sk"][0][idx]), int(cs["cs_item_sk"][0][idx]))
        cs_by.setdefault(key, []).append(idx)
    rows = {}
    for idx in range(ss["ss_item_sk"][0].shape[0]):
        if int(ss["ss_sold_date_sk"][0][idx]) not in y2000:
            continue
        i = int(ss["ss_item_sk"][0][idx])
        stk = int(ss["ss_store_sk"][0][idx])
        if i not in iinfo or stk not in sname:
            continue
        for ridx in rets.get((i, int(ss["ss_ticket_number"][0][idx])), ()):
            for cidx in cs_by.get((int(sr["sr_customer_sk"][0][ridx]), i), ()):
                key = iinfo[i] + (sname[stk],)
                acc = rows.setdefault(key, ([], [], []))
                acc[0].append(int(ss["ss_quantity"][0][idx]))
                acc[1].append(int(sr["sr_return_quantity"][0][ridx]))
                acc[2].append(int(cs["cs_quantity"][0][cidx]))
    out = {}
    for key, (a, b, c) in rows.items():
        stats = []
        for vals in (a, b, c):
            n, mean, sd = _py_stats(vals)
            cov = (sd / mean) if (sd is not None and mean and mean > 0) else None
            stats.append((n, mean, sd, cov))
        out[key] = tuple(stats)
    return out


def _oracle_q39_month(tables, moy, thr):
    dd = tables["date_dim"]
    days = {int(k) for k, y, m in zip(dd["d_date_sk"][0], dd["d_year"][0],
                                      dd["d_moy"][0])
            if int(y) == 2001 and int(m) == moy}
    wh = tables["warehouse"]
    wname = {int(k): v for k, v in
             zip(wh["w_warehouse_sk"][0], _sv(wh, "w_warehouse_name"))}
    inv = tables["inventory"]
    vals = {}
    for d, i, w, q in zip(inv["inv_date_sk"][0], inv["inv_item_sk"][0],
                          inv["inv_warehouse_sk"][0],
                          inv["inv_quantity_on_hand"][0]):
        if int(d) not in days or int(w) not in wname:
            continue
        vals.setdefault((wname[int(w)], int(i)), []).append(int(q))
    out = {}
    for key, vs in vals.items():
        n, mean, sd = _py_stats(vs)
        if sd is None or not mean or mean <= 0:
            continue
        cov = sd / mean
        if cov > thr:
            out[key] = (mean, cov)
    return out


def oracle_q39(tables, thr1, thr2):
    m1 = _oracle_q39_month(tables, 1, thr1)
    m2 = _oracle_q39_month(tables, 2, thr2)
    return {k: m1[k] + m2[k] for k in m1 if k in m2}


def oracle_q39a(tables):
    return oracle_q39(tables, 0.7, 0.7)


def oracle_q39b(tables):
    return oracle_q39(tables, 0.85, 0.7)


# ------------------------------------------- round-4 batch E


def oracle_q18(tables):
    dd = tables["date_dim"]
    y2001 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2001].tolist())
    cdt = tables["customer_demographics"]
    g = _sv(cdt, "cd_gender")
    e = _sv(cdt, "cd_education_status")
    cd_ok = {int(k): int(dc) for j, (k, dc) in
             enumerate(zip(cdt["cd_demo_sk"][0], cdt["cd_dep_count"][0]))
             if g[j] == "F" and e[j] == "College"}
    cu = tables["customer"]
    cu_ok = {int(k): (int(a), int(b)) for k, a, b in
             zip(cu["c_customer_sk"][0], cu["c_current_addr_sk"][0],
                 cu["c_birth_year"][0]) if 1966 <= int(b) <= 1980}
    ca = tables["customer_address"]
    cainfo = {int(k): (co, stt) for k, co, stt in
              zip(ca["ca_address_sk"][0], _sv(ca, "ca_county"), _sv(ca, "ca_state"))}
    it = tables["item"]
    iid = {int(k): v for k, v in zip(it["i_item_sk"][0], _sv(it, "i_item_id"))}
    cs = tables["catalog_sales"]
    cells = {}
    for idx in range(cs["cs_item_sk"][0].shape[0]):
        if int(cs["cs_sold_date_sk"][0][idx]) not in y2001:
            continue
        cdsk = int(cs["cs_bill_cdemo_sk"][0][idx])
        if cdsk not in cd_ok:
            continue
        csk = int(cs["cs_bill_customer_sk"][0][idx])
        if csk not in cu_ok:
            continue
        adsk, byear = cu_ok[csk]
        if adsk not in cainfo:
            continue
        county, state = cainfo[adsk]
        i = int(cs["cs_item_sk"][0][idx])
        if i not in iid:
            continue
        vals = (int(cs["cs_quantity"][0][idx]),
                int(cs["cs_list_price"][0][idx]) / 100.0,
                int(cs["cs_coupon_amt"][0][idx]) / 100.0,
                int(cs["cs_sales_price"][0][idx]) / 100.0,
                int(cs["cs_net_profit"][0][idx]) / 100.0,
                byear, cd_ok[cdsk])
        dims = (iid[i], county, state)
        for level in range(3, -1, -1):
            key = tuple(dims[k] if k < level else None for k in range(3)) + (3 - level,)
            acc = cells.setdefault(key, [[0.0] * 7, 0])
            for k in range(7):
                acc[0][k] += vals[k]
            acc[1] += 1
    return {k: tuple(sv / n for sv in sums) for k, (sums, n) in cells.items()}


def oracle_q40(tables):
    import datetime

    pivot = (datetime.date(2000, 3, 11) - datetime.date(1970, 1, 1)).days
    win = _win_sks(tables, (2000, 2, 10), (2000, 4, 10))
    dd = tables["date_dim"]
    dval = dict(zip(dd["d_date_sk"][0].tolist(), dd["d_date"][0].tolist()))
    it = tables["item"]
    ids = _sv(it, "i_item_id")
    ok_items = {int(sk): ids[k] for k, sk in enumerate(it["i_item_sk"][0])
                if 2000 <= int(it["i_current_price"][0][k]) <= 5000}
    wh = tables["warehouse"]
    wstate = {int(k): v for k, v in zip(wh["w_warehouse_sk"][0], _sv(wh, "w_state"))}
    cr = tables["catalog_returns"]
    rets = {}
    for i, o, cash in zip(cr["cr_item_sk"][0], cr["cr_order_number"][0],
                          cr["cr_refunded_cash"][0]):
        rets.setdefault((int(i), int(o)), []).append(int(cash))
    cs = tables["catalog_sales"]
    cells = {}
    cnts = {}
    for d, i, o, w, p in zip(cs["cs_sold_date_sk"][0], cs["cs_item_sk"][0],
                             cs["cs_order_number"][0], cs["cs_warehouse_sk"][0],
                             cs["cs_sales_price"][0]):
        if int(d) not in win or int(i) not in ok_items or int(w) not in wstate:
            continue
        key = (wstate[int(w)], ok_items[int(i)])
        before = dval[int(d)] < pivot
        ms = rets.get((int(i), int(o)))
        nets = [int(p) - cash for cash in ms] if ms else [int(p)]
        acc = cells.setdefault(key, [0, 0])
        cnt = cnts.setdefault(key, [0, 0])
        for v in nets:
            acc[0 if before else 1] += v
            cnt[0 if before else 1] += 1
    out = {}
    for key, (b, a) in cells.items():
        nb, na = cnts[key]
        out[key] = (b if nb else None, a if na else None)
    return out


def oracle_q6(tables):
    it = tables["item"]
    cats = _sv(it, "i_category")
    by_cat = {}
    for c, p in zip(cats, it["i_current_price"][0]):
        by_cat.setdefault(c, []).append(int(p))
    # engine avg of decimal(7,2) -> (11,6) HALF_UP
    cat_avg = {}
    for c, vs in by_cat.items():
        num = sum(vs) * 10_000
        n = len(vs)
        q, r = divmod(num, n)
        cat_avg[c] = q + (1 if 2 * r >= n else 0)
    hot = {int(sk) for sk, c, p in zip(it["i_item_sk"][0], cats,
                                       it["i_current_price"][0])
           if int(p) / 100.0 > 1.2 * (cat_avg[c] / 1_000_000.0)}
    dd = tables["date_dim"]
    may = {int(k) for k, y, m in zip(dd["d_date_sk"][0], dd["d_year"][0],
                                     dd["d_moy"][0])
           if int(y) == 2000 and int(m) == 5}
    cu = tables["customer"]
    addr = dict(zip(cu["c_customer_sk"][0].tolist(),
                    cu["c_current_addr_sk"][0].tolist()))
    ca = tables["customer_address"]
    castate = {int(k): v for k, v in zip(ca["ca_address_sk"][0], _sv(ca, "ca_state"))}
    ss = tables["store_sales"]
    out = {}
    for d, i, c in zip(ss["ss_sold_date_sk"][0], ss["ss_item_sk"][0],
                       ss["ss_customer_sk"][0]):
        if int(d) not in may or int(i) not in hot or int(c) not in addr:
            continue
        stt = castate.get(int(addr[int(c)]))
        if stt is None:
            continue
        out[stt] = out.get(stt, 0) + 1
    return {k: v for k, v in out.items() if v >= 10}


def oracle_q83(tables):
    dd = tables["date_dim"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    it = tables["item"]
    iid = {int(k): v for k, v in zip(it["i_item_sk"][0], _sv(it, "i_item_id"))}

    def channel(rtab, r_date, r_item, r_qty):
        rt = tables[rtab]
        out = {}
        for d, i, q in zip(rt[r_date][0], rt[r_item][0], rt[r_qty][0]):
            if int(d) not in y2000 or int(i) not in iid:
                continue
            k = iid[int(i)]
            out[k] = out.get(k, 0) + int(q)
        return out

    sr = channel("store_returns", "sr_returned_date_sk", "sr_item_sk",
                 "sr_return_quantity")
    cr = channel("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
                 "cr_return_quantity")
    wr = channel("web_returns", "wr_returned_date_sk", "wr_item_sk",
                 "wr_return_quantity")
    out = {}
    for k in sr:
        if k in cr and k in wr:
            a, b, c = sr[k], cr[k], wr[k]
            tot = float(a + b + c)
            out[k] = (a, b, c, a / tot * 100.0, b / tot * 100.0,
                      c / tot * 100.0, tot / 3.0)
    return out


def _avg_unscaled(total, n, shift=10_000):
    """Exact HALF_UP integer mirror of the engine's decimal avg
    (scale + 4): unscaled-at-scale+4 average of ``total`` over ``n``."""
    num = total * shift
    if num >= 0:
        q, r = divmod(num, n)
        return q + (1 if 2 * r >= n else 0)
    q, r = divmod(-num, n)
    return -(q + (1 if 2 * r >= n else 0))


def oracle_q44(tables):
    ss = tables["store_sales"]
    per = {}
    base = []
    for stk, i, a, p in zip(ss["ss_store_sk"][0], ss["ss_item_sk"][0],
                            ss["ss_addr_sk"][0], ss["ss_net_profit"][0]):
        if int(stk) != 4:
            continue
        acc = per.setdefault(int(i), [0, 0])
        acc[0] += int(p)
        acc[1] += 1
        if int(a) == -1:
            base.append(int(p))

    avg_u = _avg_unscaled
    if not base:
        return {}
    thr = avg_u(sum(base), len(base))
    items = {i: avg_u(tv, n) for i, (tv, n) in per.items()
             if avg_u(tv, n) / 1e6 > 0.9 * (thr / 1e6)}
    it = tables["item"]
    iid = {int(k): v for k, v in zip(it["i_item_sk"][0], _sv(it, "i_item_id"))}
    asc = sorted(items.items(), key=lambda kv: kv[1])
    rnk_asc = {}
    for i, v in asc:
        r = 1 + sum(1 for _, w in asc if w < v)
        if r <= 10:
            rnk_asc.setdefault(r, []).append(i)
    rnk_desc = {}
    for i, v in asc:
        r = 1 + sum(1 for _, w in asc if w > v)
        if r <= 10:
            rnk_desc.setdefault(r, []).append(i)
    out = set()
    for r, bests in rnk_asc.items():
        for b in bests:
            for w in rnk_desc.get(r, ()):
                if b in iid and w in iid:
                    out.add((r, iid[b], iid[w]))
    return out


def oracle_q31(tables):
    """County web-vs-store quarterly growth.  Returns
    {county: (web12, store12, web23, store23)} float ratios, mirroring
    the engine's decimal->f64 cast (unscaled/100) before division."""
    dd = tables["date_dim"]
    ca = tables["customer_address"]
    county = {int(k): v for k, v in
              zip(ca["ca_address_sk"][0], _sv(ca, "ca_county"))}

    def branch(fact, date_c, addr_c, price_c, qoy):
        f = tables[fact]
        dmask = (dd["d_year"][0] == 2000) & (dd["d_qoy"][0] == qoy)
        dsk = set(dd["d_date_sk"][0][dmask].tolist())
        out = {}
        for d, a, p in zip(f[date_c][0], f[addr_c][0], f[price_c][0]):
            if int(d) in dsk and int(a) in county:
                c = county[int(a)]
                out[c] = out.get(c, 0) + int(p)
        return out

    ss = {q: branch("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                    "ss_ext_sales_price", q) for q in (1, 2, 3)}
    ws = {q: branch("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                    "ws_ext_sales_price", q) for q in (1, 2, 3)}
    out = {}
    for c in ss[1]:
        if any(c not in ss[q] for q in (2, 3)) or any(
                c not in ws[q] for q in (1, 2, 3)):
            continue

        def ratio(b, qa, qb):
            # np division: a zero denominator yields inf/nan exactly
            # like the engine's unguarded f64 projection, not a raise
            return float(np.float64(b[qb][c] / 100.0)
                         / np.float64(b[qa][c] / 100.0))

        w12, s12 = ratio(ws, 1, 2), ratio(ss, 1, 2)
        w23, s23 = ratio(ws, 2, 3), ratio(ss, 2, 3)
        # the engine filter CASE-guards each ratio (NULL when the
        # denominator sum is 0) and NULL comparisons are false; the
        # projection is UNGUARDED (inf survives into the output)
        arm1 = ws[1][c] > 0 and ss[1][c] > 0 and w12 > s12
        arm2 = ws[2][c] > 0 and ss[2][c] > 0 and w23 > s23
        if arm1 or arm2:
            out[c] = (w12, s12, w23, s23)
    return out


def _min_rank(vals):
    """rank() semantics: ties share the LOWEST position (1-based)."""
    arr = np.asarray(vals, dtype=np.float64)
    order = np.sort(arr)
    return (np.searchsorted(order, arr, side="left") + 1).tolist()


def oracle_q49(tables):
    """Worst return ratios per channel, double-ranked.  Returns the row
    set {(channel, item_sk, return_ratio, return_rank, currency_rank)}
    (deviation mirror: return amount filter > 250, see queries.q49)."""
    dd = tables["date_dim"]
    dsk = set(dd["d_date_sk"][0][(dd["d_year"][0] == 2001)
                                 & (dd["d_moy"][0] == 12)].tolist())

    def channel(name, fact, ret, s_item, s_ord, s_qty, s_paid, s_profit,
                r_item, r_ord, r_qty, r_amt, date_c):
        f, r = tables[fact], tables[ret]
        rmap = {}
        for i in range(r[r_item][0].shape[0]):
            amt = int(r[r_amt][0][i])
            if amt / 100.0 > 250.0:
                key = (int(r[r_ord][0][i]), int(r[r_item][0][i]))
                rmap.setdefault(key, []).append((int(r[r_qty][0][i]), amt))
        agg = {}
        for i in range(f[s_item][0].shape[0]):
            if int(f[date_c][0][i]) not in dsk:
                continue
            if not (int(f[s_profit][0][i]) / 100.0 > 1.0):
                continue
            if not (int(f[s_paid][0][i]) / 100.0 > 0.0):
                continue
            if not int(f[s_qty][0][i]) > 0:
                continue
            key = (int(f[s_ord][0][i]), int(f[s_item][0][i]))
            for rq, ra in rmap.get(key, ()):
                a = agg.setdefault(key[1], [0, 0, 0, 0])
                a[0] += rq
                a[1] += int(f[s_qty][0][i])
                a[2] += ra
                a[3] += int(f[s_paid][0][i])
        items = sorted(agg)
        if not items:
            return set()
        rr = [agg[i][0] / agg[i][1] for i in items]
        cr = [(agg[i][2] / 100.0) / (agg[i][3] / 100.0) for i in items]
        rrank = _min_rank(rr)
        crank = _min_rank(cr)
        return {
            (name, i, rr[k], rrank[k], crank[k])
            for k, i in enumerate(items)
            if rrank[k] <= 10 or crank[k] <= 10
        }

    out = set()
    out |= channel("web", "web_sales", "web_returns", "ws_item_sk",
                   "ws_order_number", "ws_quantity", "ws_net_paid",
                   "ws_net_profit", "wr_item_sk", "wr_order_number",
                   "wr_return_quantity", "wr_return_amt", "ws_sold_date_sk")
    out |= channel("catalog", "catalog_sales", "catalog_returns",
                   "cs_item_sk", "cs_order_number", "cs_quantity",
                   "cs_net_paid", "cs_net_profit", "cr_item_sk",
                   "cr_order_number", "cr_return_quantity",
                   "cr_return_amount", "cs_sold_date_sk")
    out |= channel("store", "store_sales", "store_returns", "ss_item_sk",
                   "ss_ticket_number", "ss_quantity", "ss_net_paid",
                   "ss_net_profit", "sr_item_sk", "sr_ticket_number",
                   "sr_return_quantity", "sr_return_amt", "ss_sold_date_sk")
    return out


def oracle_q54(tables):
    """Maternity-buyer revenue segments.  Returns {segment: count},
    segment = int((revenue_cents/100)/50) mirroring the engine's
    f64 cast + truncating int cast."""
    dd = tables["date_dim"]
    it = tables["item"]
    i_mask = _s_eq(it, "i_category", "Women")
    isk = set(it["i_item_sk"][0][i_mask].tolist())
    dec98 = (dd["d_year"][0] == 1998) & (dd["d_moy"][0] == 12)
    dsk = set(dd["d_date_sk"][0][dd["d_year"][0] == 1998].tolist())
    cust = tables["customer"]
    addr_of = dict(zip(cust["c_customer_sk"][0].tolist(),
                       cust["c_current_addr_sk"][0].tolist()))

    buyers = set()
    for fact, date_c, cust_c, item_c in (
        ("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk"),
        ("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", "ws_item_sk"),
    ):
        f = tables[fact]
        for d, c, i in zip(f[date_c][0], f[cust_c][0], f[item_c][0]):
            if int(d) in dsk and int(i) in isk and int(c) in addr_of:
                buyers.add(int(c))

    ms = int(dd["d_month_seq"][0][dec98][0])
    win = (dd["d_month_seq"][0] >= ms + 1) & (dd["d_month_seq"][0] <= ms + 3)
    wsk = set(dd["d_date_sk"][0][win].tolist())

    ca = tables["customer_address"]
    ca_loc = {int(k): (cy, st) for k, cy, st in
              zip(ca["ca_address_sk"][0], _sv(ca, "ca_county"), _sv(ca, "ca_state"))}
    st_tab = tables["store"]
    store_locs = {}
    for cy, stv in zip(_sv(st_tab, "s_county"), _sv(st_tab, "s_state")):
        store_locs[(cy, stv)] = store_locs.get((cy, stv), 0) + 1

    revenue = {}
    ss = tables["store_sales"]
    for d, c, p in zip(ss["ss_sold_date_sk"][0], ss["ss_customer_sk"][0],
                       ss["ss_ext_sales_price"][0]):
        c = int(c)
        if int(d) not in wsk or c not in buyers:
            continue
        loc = ca_loc.get(addr_of[c])
        mult = store_locs.get(loc, 0)
        if mult:
            revenue[c] = revenue.get(c, 0) + int(p) * mult

    segs = {}
    for cents in revenue.values():
        seg = int((cents / 100.0) / 50.0)
        segs[seg] = segs.get(seg, 0) + 1
    return segs


def oracle_q58(tables):
    """Cross-channel items sold evenly in the week of 2000-01-03.
    Returns {item_id: (ss_rev_cents, ss_dev, cs_rev_cents, cs_dev,
    ws_rev_cents, ws_dev, average)} mirroring f64 casts."""
    dd = tables["date_dim"]
    sel = dd["d_date"][0] == _days(2000, 1, 3)
    wk = int(dd["d_month_seq"][0][sel][0])
    dsk = set(dd["d_date_sk"][0][dd["d_month_seq"][0] == wk].tolist())
    it = tables["item"]
    iid = {int(k): v for k, v in zip(it["i_item_sk"][0], _sv(it, "i_item_id"))}

    def channel(fact, item_c, date_c, price_c):
        f = tables[fact]
        out = {}
        for d, i, p in zip(f[date_c][0], f[item_c][0], f[price_c][0]):
            if int(d) in dsk and int(i) in iid:
                k = iid[int(i)]
                out[k] = out.get(k, 0) + int(p)
        return out

    ssr = channel("store_sales", "ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price")
    csr = channel("catalog_sales", "cs_item_sk", "cs_sold_date_sk", "cs_ext_sales_price")
    wsr = channel("web_sales", "ws_item_sk", "ws_sold_date_sk", "ws_ext_sales_price")
    out = {}
    for k in ssr:
        if k not in csr or k not in wsr:
            continue
        s, c, w = ssr[k] / 100.0, csr[k] / 100.0, wsr[k] / 100.0

        def near(a, b):
            return 0.25 * b <= a <= 4.0 * b

        if not (near(s, c) and near(s, w) and near(c, s) and near(c, w)
                and near(w, s) and near(w, c)):
            continue
        total = s + c + w
        out[k] = (ssr[k], s / total / 3.0 * 100.0,
                  csr[k], c / total / 3.0 * 100.0,
                  wsr[k], w / total / 3.0 * 100.0, total / 3.0)
    return out


def oracle_q66(tables):
    """Warehouse monthly pivot over web+catalog.  Returns
    {w_name: (sq_ft, city, county, state, country, sales12, ratio12,
    net12)} — cents ints / floats, None for empty buckets, mirroring
    the engine's NULL pivot sums and channel-ratio float adds."""
    dd = tables["date_dim"]
    tm = tables["time_dim"]
    smt = tables["ship_mode"]
    wh = tables["warehouse"]
    moy_by_sk = {int(k): int(m) for k, m in
                 zip(dd["d_date_sk"][0][dd["d_year"][0] == 2001],
                     dd["d_moy"][0][dd["d_year"][0] == 2001])}
    tsel = set(tm["t_time_sk"][0][(tm["t_time"][0] >= 30838)
                                  & (tm["t_time"][0] <= 30838 + 28800)].tolist())
    carriers = _sv(smt, "sm_carrier")
    msel = {int(k) for k, c in zip(smt["sm_ship_mode_sk"][0], carriers)
            if c in ("DHL", "BARIAN")}
    wnames, wcities, wcounties, wstates, wcountries = (
        _sv(wh, c) for c in ("w_warehouse_name", "w_city", "w_county",
                             "w_state", "w_country"))
    winfo = {}
    for i, k in enumerate(wh["w_warehouse_sk"][0]):
        winfo[int(k)] = (
            wnames[i], int(wh["w_warehouse_sq_ft"][0][i]),
            wcities[i], wcounties[i], wstates[i], wcountries[i])

    def channel(fact, wh_c, date_c, time_c, mode_c, qty_c, sales_c, net_c):
        f = tables[fact]
        out = {}
        for i in range(f[wh_c][0].shape[0]):
            m = moy_by_sk.get(int(f[date_c][0][i]))
            if m is None or int(f[time_c][0][i]) not in tsel:
                continue
            if int(f[mode_c][0][i]) not in msel:
                continue
            w = int(f[wh_c][0][i])
            if w not in winfo:
                continue
            qty = int(f[qty_c][0][i])
            acc = out.setdefault(w, [[None] * 12, [None] * 12])
            for slot, c in ((0, sales_c), (1, net_c)):
                v = int(f[c][0][i]) * qty
                acc[slot][m - 1] = v if acc[slot][m - 1] is None else acc[slot][m - 1] + v
        return out

    web = channel("web_sales", "ws_warehouse_sk", "ws_sold_date_sk",
                  "ws_sold_time_sk", "ws_ship_mode_sk", "ws_quantity",
                  "ws_ext_sales_price", "ws_net_paid")
    cat = channel("catalog_sales", "cs_warehouse_sk", "cs_sold_date_sk",
                  "cs_sold_time_sk", "cs_ship_mode_sk", "cs_quantity",
                  "cs_sales_price", "cs_net_paid_inc_tax")
    out = {}
    for w in set(web) | set(cat):
        name, sq_ft, city, cty, state, country = winfo[w]
        sales, ratios, nets = [], [], []
        for m in range(12):
            svals = [ch[w][0][m] for ch in (web, cat)
                     if w in ch and ch[w][0][m] is not None]
            nvals = [ch[w][1][m] for ch in (web, cat)
                     if w in ch and ch[w][1][m] is not None]
            sales.append(sum(svals) if svals else None)
            nets.append(sum(nvals) if nvals else None)
            rvals = [(v / 100.0) / float(sq_ft) for v in svals]
            ratios.append(sum(rvals) if rvals else None)
        out[name] = (sq_ft, city, cty, state, country,
                     tuple(sales), tuple(ratios), tuple(nets))
    return out


def oracle_q71(tables):
    """Meal-time brand minutes.  Returns
    {(brand_id, brand, hour, minute): sum_cents}."""
    dd = tables["date_dim"]
    it = tables["item"]
    tm = tables["time_dim"]
    dsel = set(dd["d_date_sk"][0][(dd["d_year"][0] == 1999)
                                  & (dd["d_moy"][0] == 11)].tolist())
    brands = _sv(it, "i_brand")
    binfo = {int(k): (int(b), brands[i]) for i, (k, b) in
             enumerate(zip(it["i_item_sk"][0], it["i_brand_id"][0]))
             if int(it["i_manager_id"][0][i]) == 1}
    meal = _sv(tm, "t_meal_time")
    tinfo = {int(k): (int(h), int(mi)) for k, h, mi, ml in
             zip(tm["t_time_sk"][0], tm["t_hour"][0], tm["t_minute"][0], meal)
             if ml in ("breakfast", "dinner")}
    out = {}
    for fact, price_c, date_c, item_c, time_c in (
        ("web_sales", "ws_ext_sales_price", "ws_sold_date_sk",
         "ws_item_sk", "ws_sold_time_sk"),
        ("catalog_sales", "cs_ext_sales_price", "cs_sold_date_sk",
         "cs_item_sk", "cs_sold_time_sk"),
        ("store_sales", "ss_ext_sales_price", "ss_sold_date_sk",
         "ss_item_sk", "ss_sold_time_sk"),
    ):
        f = tables[fact]
        for d, i, tk, p in zip(f[date_c][0], f[item_c][0], f[time_c][0],
                               f[price_c][0]):
            if int(d) not in dsel or int(i) not in binfo:
                continue
            ht = tinfo.get(int(tk))
            if ht is None:
                continue
            bid, b = binfo[int(i)]
            key = (bid, b, ht[0], ht[1])
            out[key] = out.get(key, 0) + int(p)
    return out


def oracle_q84(tables):
    """Midway income-band returners.  Returns the SORTED row list
    [(customer_id, 'last, first')] with join multiplicity (one row per
    matching store return), truncated to 100."""
    ca = tables["customer_address"]
    midway = set(ca["ca_address_sk"][0][_s_eq(ca, "ca_city", "Midway")].tolist())
    ib = tables["income_band"]
    bands = set(ib["ib_income_band_sk"][0][
        (ib["ib_lower_bound"][0] >= 38128)
        & (ib["ib_upper_bound"][0] <= 38128 + 50000)].tolist())
    hd = tables["household_demographics"]
    hsel = set(hd["hd_demo_sk"][0][np.isin(hd["hd_income_band_sk"][0],
                                           list(bands))].tolist())
    sr = tables["store_returns"]
    ret_by_cdemo = {}
    for c in sr["sr_cdemo_sk"][0]:
        c = int(c)
        ret_by_cdemo[c] = ret_by_cdemo.get(c, 0) + 1
    cust = tables["customer"]
    ids = _sv(cust, "c_customer_id")
    firsts = _sv(cust, "c_first_name")
    lasts = _sv(cust, "c_last_name")
    rows = []
    for i in range(len(ids)):
        if int(cust["c_current_addr_sk"][0][i]) not in midway:
            continue
        if int(cust["c_current_hdemo_sk"][0][i]) not in hsel:
            continue
        n = ret_by_cdemo.get(int(cust["c_current_cdemo_sk"][0][i]), 0)
        rows.extend([(ids[i], f"{lasts[i]}, {firsts[i]}")] * n)
    rows.sort()
    return rows[:100]


def oracle_q85(tables):
    """Web-return reason averages under OR'd band triples.  Returns
    {reason[:20]: (avg_quantity_float, avg_cash_unscaled4,
    avg_fee_unscaled4)} (deviation mirror: widened bands, see
    queries.q85)."""
    dd = tables["date_dim"]
    ws, wr = tables["web_sales"], tables["web_returns"]
    cd = tables["customer_demographics"]
    ca = tables["customer_address"]
    rs = tables["reason"]
    y2000 = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())
    ms = _sv(cd, "cd_marital_status")
    states = _sv(ca, "ca_state")
    country = _sv(ca, "ca_country")
    rdesc = _sv(rs, "r_reason_desc")
    rmap = {int(k): rdesc[i] for i, k in enumerate(rs["r_reason_sk"][0])}
    smap = {}
    for i in range(len(ws["ws_item_sk"][0])):
        key = (int(ws["ws_order_number"][0][i]), int(ws["ws_item_sk"][0][i]))
        smap.setdefault(key, []).append(i)
    agg = {}
    for k in range(len(wr["wr_item_sk"][0])):
        key = (int(wr["wr_order_number"][0][k]), int(wr["wr_item_sk"][0][k]))
        for i in smap.get(key, ()):
            if int(ws["ws_sold_date_sk"][0][i]) not in y2000:
                continue
            c1 = int(wr["wr_refunded_cdemo_sk"][0][k]) - 1
            c2 = int(wr["wr_returning_cdemo_sk"][0][k]) - 1
            a = int(wr["wr_refunded_addr_sk"][0][k]) - 1
            price = int(ws["ws_sales_price"][0][i]) / 100.0
            profit = int(ws["ws_net_profit"][0][i]) / 100.0
            demo = ((ms[c1] == "M" and ms[c1] == ms[c2] and 0.0 <= price <= 150.0)
                    or (ms[c1] == "S" and ms[c1] == ms[c2] and 50.0 <= price <= 250.0)
                    or (ms[c1] == "W" and ms[c1] == ms[c2] and 100.0 <= price <= 300.0))
            geo = ((country[a] == "United States" and states[a] in ("OH", "TN", "SD")
                    and -1000.0 <= profit <= 500.0)
                   or (country[a] == "United States" and states[a] in ("AL", "GA", "SD")
                       and 0.0 <= profit <= 1500.0)
                   or (country[a] == "United States" and states[a] in ("TN", "GA", "AL")
                       and -500.0 <= profit <= 1000.0))
            if not (demo and geo):
                continue
            r = rmap[int(wr["wr_reason_sk"][0][k])]
            acc = agg.setdefault(r, [0, 0, 0, 0])
            acc[0] += int(ws["ws_quantity"][0][i])
            acc[1] += int(wr["wr_refunded_cash"][0][k])
            acc[2] += int(wr["wr_fee"][0][k])
            acc[3] += 1
    return {
        r[:20]: (tq / n, _avg_unscaled(tc, n), _avg_unscaled(tf, n))
        for r, (tq, tc, tf, n) in agg.items()
    }
