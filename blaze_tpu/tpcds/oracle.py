"""Independent numpy/python oracles for the TPC-DS query subset.

Same differential role as tpch/oracle.py: each query re-implemented
from the spec over the generated host tables, no engine code reused.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..tpch.datagen import HostTable
from ..tpch.oracle import _round_half_up, _s_eq, _sv


def _index_by(table: HostTable, key: str) -> Dict[int, int]:
    keys = table[key][0]
    return {int(k): i for i, k in enumerate(keys)}


def _brand_rollup(tables, *, year, moy, item_filter_col, item_filter_val, group_cols):
    """Shared star-join: date slice × item slice × store_sales, grouped
    sums of ss_ext_sales_price."""
    dd = tables["date_dim"]
    it = tables["item"]
    ss = tables["store_sales"]

    d_mask = dd["d_moy"][0] == moy
    if year is not None:
        d_mask &= dd["d_year"][0] == year
    d_sk = dd["d_date_sk"][0][d_mask]
    d_year_by_sk = dict(zip(d_sk.tolist(), dd["d_year"][0][d_mask].tolist()))

    i_mask = it[item_filter_col][0] == item_filter_val
    i_sk = it["i_item_sk"][0][i_mask]
    group_by_sk = {}
    gvals = []
    for gc in group_cols:
        if it[gc][1] is not None:  # string col
            gvals.append(np.array(_sv(it, gc)))
        else:
            gvals.append(it[gc][0])
    for idx in np.flatnonzero(i_mask):
        group_by_sk[int(it["i_item_sk"][0][idx])] = tuple(
            (gv[idx] if isinstance(gv[idx], str) else int(gv[idx])) for gv in gvals
        )

    sums: Dict[tuple, int] = {}
    date_sk = ss["ss_sold_date_sk"][0]
    item_sk = ss["ss_item_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(date_sk.shape[0]):
        dsk = int(date_sk[i])
        isk = int(item_sk[i])
        if dsk not in d_year_by_sk or isk not in group_by_sk:
            continue
        key = (d_year_by_sk[dsk],) + group_by_sk[isk]
        sums[key] = sums.get(key, 0) + int(price[i])
    return sums


def oracle_q3(tables):
    return _brand_rollup(
        tables, year=None, moy=11,
        item_filter_col="i_manufact_id", item_filter_val=128,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q52(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q55(tables):
    return _brand_rollup(
        tables, year=1999, moy=11,
        item_filter_col="i_manager_id", item_filter_val=28,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q42(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_category_id", "i_category"],
    )


def oracle_q7(tables):
    cd = tables["customer_demographics"]
    cd_ok = (
        _s_eq(cd, "cd_gender", "M")
        & _s_eq(cd, "cd_marital_status", "S")
        & _s_eq(cd, "cd_education_status", "College")
    )
    cd_set = set(cd["cd_demo_sk"][0][cd_ok].tolist())

    dd = tables["date_dim"]
    d_set = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())

    pr = tables["promotion"]
    p_ok = _s_eq(pr, "p_channel_email", "N") | _s_eq(pr, "p_channel_event", "N")
    p_set = set(pr["p_promo_sk"][0][p_ok].tolist())

    it = tables["item"]
    item_id_by_sk = dict(zip(it["i_item_sk"][0].tolist(), _sv(it, "i_item_id")))

    ss = tables["store_sales"]
    acc: Dict[str, list] = {}
    cols = [ss[c][0] for c in (
        "ss_cdemo_sk", "ss_sold_date_sk", "ss_promo_sk", "ss_item_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
    )]
    for i in range(cols[0].shape[0]):
        if int(cols[0][i]) not in cd_set:
            continue
        if int(cols[1][i]) not in d_set:
            continue
        if int(cols[2][i]) not in p_set:
            continue
        iid = item_id_by_sk.get(int(cols[3][i]))
        if iid is None:
            continue
        acc.setdefault(iid, []).append(tuple(int(c[i]) for c in cols[4:]))

    out = {}
    for iid, rows in acc.items():
        n = len(rows)

        def avg_dec(idx):
            # decimal avg: result scale +4, float64 HALF_UP (engine path)
            s = sum(r[idx] for r in rows)
            f = float(s) * float(10**4) / n
            return int(_round_half_up(np.array([f]))[0])

        avg_qty = float(sum(r[0] for r in rows)) / n  # int avg -> float64
        out[iid] = (avg_qty, avg_dec(1), avg_dec(2), avg_dec(3), n)
    return out


def oracle_q96(tables):
    td = tables["time_dim"]
    t_set = set(
        td["t_time_sk"][0][(td["t_hour"][0] == 20) & (td["t_minute"][0] >= 30)].tolist()
    )
    hd = tables["household_demographics"]
    h_set = set(hd["hd_demo_sk"][0][hd["hd_dep_count"][0] == 7].tolist())
    st = tables["store"]
    s_set = set(st["s_store_sk"][0][_s_eq(st, "s_store_name", "ese")].tolist())

    ss = tables["store_sales"]
    t_sk = ss["ss_sold_time_sk"][0]
    h_sk = ss["ss_hdemo_sk"][0]
    s_sk = ss["ss_store_sk"][0]
    cnt = 0
    for i in range(t_sk.shape[0]):
        if int(t_sk[i]) in t_set and int(h_sk[i]) in h_set and int(s_sk[i]) in s_set:
            cnt += 1
    return cnt


def oracle_q27(tables):
    """ROLLUP(i_item_id, s_state): returns {(item_id|None, state|None,
    g_id): (avg_qty, avg_list, avg_coupon, avg_sales)} with decimal
    averages as unscaled ints (scale+4, HALF_UP)."""
    cd = tables["customer_demographics"]
    cd_ok = (
        _s_eq(cd, "cd_gender", "M")
        & _s_eq(cd, "cd_marital_status", "S")
        & _s_eq(cd, "cd_education_status", "College")
    )
    cd_set = set(cd["cd_demo_sk"][0][cd_ok].tolist())
    dd = tables["date_dim"]
    d_set = set(dd["d_date_sk"][0][dd["d_year"][0] == 2002].tolist())
    st = tables["store"]
    states = _sv(st, "s_state")
    state_by_sk = {
        int(sk): states[i]
        for i, sk in enumerate(st["s_store_sk"][0])
        if states[i] in ("TN", "SD", "AL", "GA", "OH")
    }
    it = tables["item"]
    item_id_by_sk = dict(zip(it["i_item_sk"][0].tolist(), _sv(it, "i_item_id")))

    ss = tables["store_sales"]
    cols = [ss[c][0] for c in (
        "ss_cdemo_sk", "ss_sold_date_sk", "ss_store_sk", "ss_item_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
    )]
    acc: Dict[tuple, list] = {}
    for i in range(cols[0].shape[0]):
        if int(cols[0][i]) not in cd_set or int(cols[1][i]) not in d_set:
            continue
        state = state_by_sk.get(int(cols[2][i]))
        if state is None:
            continue
        iid = item_id_by_sk.get(int(cols[3][i]))
        if iid is None:
            continue
        row = tuple(int(c[i]) for c in cols[4:])
        for key in ((iid, state, 0), (iid, None, 1), (None, None, 3)):
            acc.setdefault(key, []).append(row)

    out = {}
    for key, rows in acc.items():
        n = len(rows)
        avg_qty = float(sum(r[0] for r in rows)) / n

        def avg_dec(idx):
            f = float(sum(r[idx] for r in rows)) * float(10**4) / n
            return int(_round_half_up(np.array([f]))[0])

        out[key] = (avg_qty, avg_dec(1), avg_dec(2), avg_dec(3))
    return out


def oracle_q89(tables):
    """{(cat, cls, brand, store, company, moy): (sum, avg)} for rows
    passing the |sum-avg|/avg > 0.1 filter; sums unscaled ints, avg as
    unscaled int at scale+4."""
    it = tables["item"]
    cats = _sv(it, "i_category")
    clss = _sv(it, "i_class")
    brands = _sv(it, "i_brand")
    a = {("Books", "accessories"), ("Books", "reference"), ("Books", "football"),
         ("Electronics", "accessories"), ("Electronics", "reference"), ("Electronics", "football"),
         ("Sports", "accessories"), ("Sports", "reference"), ("Sports", "football")}
    b = {(c, k) for c in ("Men", "Jewelry", "Women") for k in ("shirts", "birdal", "dresses")}
    keep = a | b
    item_by_sk = {}
    for i, sk in enumerate(it["i_item_sk"][0]):
        if (cats[i], clss[i]) in keep:
            item_by_sk[int(sk)] = (cats[i], clss[i], brands[i])
    dd = tables["date_dim"]
    moy_by_sk = {
        int(sk): int(m)
        for sk, m, y in zip(dd["d_date_sk"][0], dd["d_moy"][0], dd["d_year"][0])
        if y == 1999
    }
    st = tables["store"]
    store_by_sk = dict(zip(
        st["s_store_sk"][0].tolist(),
        zip(_sv(st, "s_store_name"), _sv(st, "s_company_name")),
    ))
    ss = tables["store_sales"]
    sums: Dict[tuple, int] = {}
    i_sk = ss["ss_item_sk"][0]; d_sk = ss["ss_sold_date_sk"][0]
    s_sk = ss["ss_store_sk"][0]; price = ss["ss_sales_price"][0]
    for i in range(i_sk.shape[0]):
        itm = item_by_sk.get(int(i_sk[i]))
        if itm is None:
            continue
        moy = moy_by_sk.get(int(d_sk[i]))
        if moy is None:
            continue
        stn = store_by_sk.get(int(s_sk[i]))
        if stn is None:
            continue
        key = itm + stn + (moy,)
        sums[key] = sums.get(key, 0) + int(price[i])
    # window avg over (cat, brand, store, company)
    parts: Dict[tuple, list] = {}
    for key, s in sums.items():
        cat, cls, brand, stn, co, moy = key
        parts.setdefault((cat, brand, stn, co), []).append(s)
    out = {}
    for key, s in sums.items():
        cat, cls, brand, stn, co, moy = key
        vals = parts[(cat, brand, stn, co)]
        # engine: avg of decimal(7,2) sums -> scale+4 unscaled, HALF_UP
        avg_unscaled = int(_round_half_up(np.array(
            [float(sum(vals)) * float(10**4) / len(vals)]
        ))[0])
        sum_f = float(s) / 100.0
        avg_f = avg_unscaled / 1e6
        if avg_f != 0 and abs(sum_f - avg_f) / avg_f > 0.1:
            out[key] = (s, avg_unscaled)
    return out


def oracle_q98(tables):
    """{(item_id, desc, cat, cls, price): (revenue, ratio)} over the
    1999-02-22..1999-03-24 date window and 3 categories."""
    import datetime as _dt

    dd = tables["date_dim"]
    epoch = _dt.date(1970, 1, 1)
    lo = (_dt.date(1999, 2, 22) - epoch).days
    hi = (_dt.date(1999, 3, 24) - epoch).days
    d_ok = (dd["d_date"][0] >= lo) & (dd["d_date"][0] <= hi)
    d_set = set(dd["d_date_sk"][0][d_ok].tolist())
    it = tables["item"]
    cats = _sv(it, "i_category")
    item_by_sk = {}
    for i, sk in enumerate(it["i_item_sk"][0]):
        if cats[i] in ("Sports", "Books", "Home"):
            item_by_sk[int(sk)] = (
                _sv(it, "i_item_id")[i], _sv(it, "i_item_desc")[i],
                cats[i], _sv(it, "i_class")[i], int(it["i_current_price"][0][i]),
            )
    ss = tables["store_sales"]
    sums: Dict[tuple, int] = {}
    i_sk = ss["ss_item_sk"][0]; d_sk = ss["ss_sold_date_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(i_sk.shape[0]):
        itm = item_by_sk.get(int(i_sk[i]))
        if itm is None or int(d_sk[i]) not in d_set:
            continue
        sums[itm] = sums.get(itm, 0) + int(price[i])
    class_total: Dict[str, int] = {}
    for itm, s in sums.items():
        class_total[itm[3]] = class_total.get(itm[3], 0) + s
    return {
        itm: (s, (float(s) * 100.0) / float(class_total[itm[3]]))
        for itm, s in sums.items()
    }


def _oracle_ticket_report(tables, *, dom_ranges, buy_potentials, cnt_lo, cnt_hi,
                          dep_vehicle_ratio=None):
    dd = tables["date_dim"]
    d_ok = np.zeros(dd["d_dom"][0].shape[0], bool)
    for lo, hi in dom_ranges:
        d_ok |= (dd["d_dom"][0] >= lo) & (dd["d_dom"][0] <= hi)
    d_ok &= np.isin(dd["d_year"][0], (1999, 2000, 2001))
    d_set = set(dd["d_date_sk"][0][d_ok].tolist())

    hd = tables["household_demographics"]
    bps = _sv(hd, "hd_buy_potential")
    h_ok = np.array([b in buy_potentials for b in bps])
    h_ok &= hd["hd_vehicle_count"][0] > 0
    with np.errstate(divide="ignore"):
        ratio = hd["hd_dep_count"][0] / np.maximum(hd["hd_vehicle_count"][0], 1)
    h_ok &= np.where(hd["hd_vehicle_count"][0] > 0, ratio > dep_vehicle_ratio, False)
    h_set = set(hd["hd_demo_sk"][0][h_ok].tolist())

    st = tables["store"]
    counties = _sv(st, "s_county")
    s_set = {
        int(sk) for i, sk in enumerate(st["s_store_sk"][0])
        if counties[i] in ("Williamson County", "Franklin Parish",
                           "Bronx County", "Orange County")
    }

    ss = tables["store_sales"]
    counts = {}
    d_sk = ss["ss_sold_date_sk"][0]; h_sk = ss["ss_hdemo_sk"][0]
    s_sk = ss["ss_store_sk"][0]; tick = ss["ss_ticket_number"][0]
    cust = ss["ss_customer_sk"][0]
    for i in range(d_sk.shape[0]):
        if int(d_sk[i]) in d_set and int(h_sk[i]) in h_set and int(s_sk[i]) in s_set:
            key = (int(tick[i]), int(cust[i]))
            counts[key] = counts.get(key, 0) + 1

    c = tables["customer"]
    sal = _sv(c, "c_salutation")
    fn_ = _sv(c, "c_first_name")
    ln_ = _sv(c, "c_last_name")
    pf = _sv(c, "c_preferred_cust_flag")
    cust_by_sk = {
        int(sk): (sal[i], fn_[i], ln_[i], pf[i])
        for i, sk in enumerate(c["c_customer_sk"][0])
    }
    out = {}
    for (tick_no, csk), n in counts.items():
        if not (cnt_lo <= n <= cnt_hi):
            continue
        info = cust_by_sk.get(csk)
        if info is None:
            continue
        out[(tick_no, csk)] = info + (n,)
    return out


def oracle_q34(tables):
    return _oracle_ticket_report(
        tables, dom_ranges=[(1, 3), (25, 28)],
        buy_potentials={">10000", "Unknown"}, cnt_lo=15, cnt_hi=20,
        dep_vehicle_ratio=1.2,
    )


def oracle_q73(tables):
    return _oracle_ticket_report(
        tables, dom_ranges=[(1, 2)],
        buy_potentials={">10000", "Unknown"}, cnt_lo=1, cnt_hi=5,
        dep_vehicle_ratio=1.0,
    )


def oracle_q19(tables):
    """{(brand_id, brand, manufact_id, manufact): ext_price} for
    out-of-zip sales in 1998-11 by manager-8 items."""
    dd = tables["date_dim"]
    d_set = set(
        dd["d_date_sk"][0][(dd["d_moy"][0] == 11) & (dd["d_year"][0] == 1998)].tolist()
    )
    it = tables["item"]
    i_ok = it["i_manager_id"][0] == 8
    brands = _sv(it, "i_brand")
    manufs = _sv(it, "i_manufact")
    item_by_sk = {
        int(sk): (int(it["i_brand_id"][0][i]), brands[i],
                  int(it["i_manufact_id"][0][i]), manufs[i])
        for i, sk in enumerate(it["i_item_sk"][0]) if i_ok[i]
    }
    c = tables["customer"]
    addr_by_cust = dict(zip(
        c["c_customer_sk"][0].tolist(), c["c_current_addr_sk"][0].tolist()
    ))
    ca = tables["customer_address"]
    zips = _sv(ca, "ca_zip")
    zip_by_addr = {int(sk): zips[i][:5] for i, sk in enumerate(ca["ca_address_sk"][0])}
    st = tables["store"]
    szips = _sv(st, "s_zip")
    zip_by_store = {int(sk): szips[i][:5] for i, sk in enumerate(st["s_store_sk"][0])}

    ss = tables["store_sales"]
    sums = {}
    d_sk = ss["ss_sold_date_sk"][0]; i_sk = ss["ss_item_sk"][0]
    c_sk = ss["ss_customer_sk"][0]; s_sk = ss["ss_store_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(d_sk.shape[0]):
        if int(d_sk[i]) not in d_set:
            continue
        itm = item_by_sk.get(int(i_sk[i]))
        if itm is None:
            continue
        a_sk = addr_by_cust.get(int(c_sk[i]))
        if a_sk is None:
            continue
        czip = zip_by_addr.get(int(a_sk))
        szip = zip_by_store.get(int(s_sk[i]))
        if czip is None or szip is None or czip == szip:
            continue
        sums[itm] = sums.get(itm, 0) + int(price[i])
    return sums


def _oracle_manufact_window(tables, group_col):
    """{(manufact_id, qoy_or_moy): (sum, avg_unscaled)} rows passing
    the |sum-avg|/avg > 0.1 filter (avg at scale+4 HALF_UP)."""
    it = tables["item"]
    cats = _sv(it, "i_category")
    clss = _sv(it, "i_class")
    a = {(c, k) for c in ("Books", "Children", "Electronics")
         for k in ("personal", "self-help", "reference")}
    b = {(c, k) for c in ("Women", "Music", "Men")
         for k in ("accessories", "classical", "fragrances")}
    keep = a | b
    manu_by_sk = {
        int(sk): int(it["i_manufact_id"][0][i])
        for i, sk in enumerate(it["i_item_sk"][0])
        if (cats[i], clss[i]) in keep
    }
    dd = tables["date_dim"]
    grp_by_sk = {
        int(sk): int(g)
        for sk, g, y in zip(dd["d_date_sk"][0], dd[group_col][0], dd["d_year"][0])
        if y in (1999, 2000)
    }
    st_set = set(tables["store"]["s_store_sk"][0].tolist())
    ss = tables["store_sales"]
    sums = {}
    i_sk = ss["ss_item_sk"][0]; d_sk = ss["ss_sold_date_sk"][0]
    s_sk = ss["ss_store_sk"][0]; price = ss["ss_sales_price"][0]
    for i in range(i_sk.shape[0]):
        m = manu_by_sk.get(int(i_sk[i]))
        if m is None:
            continue
        g = grp_by_sk.get(int(d_sk[i]))
        if g is None or int(s_sk[i]) not in st_set:
            continue
        sums[(m, g)] = sums.get((m, g), 0) + int(price[i])
    parts = {}
    for (m, g), sv in sums.items():
        parts.setdefault(m, []).append(sv)
    out = {}
    for (m, g), sv in sums.items():
        vals = parts[m]
        avg_unscaled = int(_round_half_up(np.array(
            [float(sum(vals)) * float(10**4) / len(vals)]
        ))[0])
        sum_f = float(sv) / 100.0
        avg_f = avg_unscaled / 1e6
        if avg_f > 0 and abs(sum_f - avg_f) / avg_f > 0.1:
            out[(m, g)] = (sv, avg_unscaled)
    return out


def oracle_q53(tables):
    return _oracle_manufact_window(tables, "d_qoy")


def oracle_q63(tables):
    return _oracle_manufact_window(tables, "d_moy")
