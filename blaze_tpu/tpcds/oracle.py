"""Independent numpy/python oracles for the TPC-DS query subset.

Same differential role as tpch/oracle.py: each query re-implemented
from the spec over the generated host tables, no engine code reused.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..tpch.datagen import HostTable
from ..tpch.oracle import _round_half_up, _s_eq, _sv


def _index_by(table: HostTable, key: str) -> Dict[int, int]:
    keys = table[key][0]
    return {int(k): i for i, k in enumerate(keys)}


def _brand_rollup(tables, *, year, moy, item_filter_col, item_filter_val, group_cols):
    """Shared star-join: date slice × item slice × store_sales, grouped
    sums of ss_ext_sales_price."""
    dd = tables["date_dim"]
    it = tables["item"]
    ss = tables["store_sales"]

    d_mask = dd["d_moy"][0] == moy
    if year is not None:
        d_mask &= dd["d_year"][0] == year
    d_sk = dd["d_date_sk"][0][d_mask]
    d_year_by_sk = dict(zip(d_sk.tolist(), dd["d_year"][0][d_mask].tolist()))

    i_mask = it[item_filter_col][0] == item_filter_val
    i_sk = it["i_item_sk"][0][i_mask]
    group_by_sk = {}
    gvals = []
    for gc in group_cols:
        if it[gc][1] is not None:  # string col
            gvals.append(np.array(_sv(it, gc)))
        else:
            gvals.append(it[gc][0])
    for idx in np.flatnonzero(i_mask):
        group_by_sk[int(it["i_item_sk"][0][idx])] = tuple(
            (gv[idx] if isinstance(gv[idx], str) else int(gv[idx])) for gv in gvals
        )

    sums: Dict[tuple, int] = {}
    date_sk = ss["ss_sold_date_sk"][0]
    item_sk = ss["ss_item_sk"][0]
    price = ss["ss_ext_sales_price"][0]
    for i in range(date_sk.shape[0]):
        dsk = int(date_sk[i])
        isk = int(item_sk[i])
        if dsk not in d_year_by_sk or isk not in group_by_sk:
            continue
        key = (d_year_by_sk[dsk],) + group_by_sk[isk]
        sums[key] = sums.get(key, 0) + int(price[i])
    return sums


def oracle_q3(tables):
    return _brand_rollup(
        tables, year=None, moy=11,
        item_filter_col="i_manufact_id", item_filter_val=128,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q52(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q55(tables):
    return _brand_rollup(
        tables, year=1999, moy=11,
        item_filter_col="i_manager_id", item_filter_val=28,
        group_cols=["i_brand_id", "i_brand"],
    )


def oracle_q42(tables):
    return _brand_rollup(
        tables, year=2000, moy=11,
        item_filter_col="i_manager_id", item_filter_val=1,
        group_cols=["i_category_id", "i_category"],
    )


def oracle_q7(tables):
    cd = tables["customer_demographics"]
    cd_ok = (
        _s_eq(cd, "cd_gender", "M")
        & _s_eq(cd, "cd_marital_status", "S")
        & _s_eq(cd, "cd_education_status", "College")
    )
    cd_set = set(cd["cd_demo_sk"][0][cd_ok].tolist())

    dd = tables["date_dim"]
    d_set = set(dd["d_date_sk"][0][dd["d_year"][0] == 2000].tolist())

    pr = tables["promotion"]
    p_ok = _s_eq(pr, "p_channel_email", "N") | _s_eq(pr, "p_channel_event", "N")
    p_set = set(pr["p_promo_sk"][0][p_ok].tolist())

    it = tables["item"]
    item_id_by_sk = dict(zip(it["i_item_sk"][0].tolist(), _sv(it, "i_item_id")))

    ss = tables["store_sales"]
    acc: Dict[str, list] = {}
    cols = [ss[c][0] for c in (
        "ss_cdemo_sk", "ss_sold_date_sk", "ss_promo_sk", "ss_item_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
    )]
    for i in range(cols[0].shape[0]):
        if int(cols[0][i]) not in cd_set:
            continue
        if int(cols[1][i]) not in d_set:
            continue
        if int(cols[2][i]) not in p_set:
            continue
        iid = item_id_by_sk.get(int(cols[3][i]))
        if iid is None:
            continue
        acc.setdefault(iid, []).append(tuple(int(c[i]) for c in cols[4:]))

    out = {}
    for iid, rows in acc.items():
        n = len(rows)

        def avg_dec(idx):
            # decimal avg: result scale +4, float64 HALF_UP (engine path)
            s = sum(r[idx] for r in rows)
            f = float(s) * float(10**4) / n
            return int(_round_half_up(np.array([f]))[0])

        avg_qty = float(sum(r[0] for r in rows)) / n  # int avg -> float64
        out[iid] = (avg_qty, avg_dec(1), avg_dec(2), avg_dec(3), n)
    return out


def oracle_q96(tables):
    td = tables["time_dim"]
    t_set = set(
        td["t_time_sk"][0][(td["t_hour"][0] == 20) & (td["t_minute"][0] >= 30)].tolist()
    )
    hd = tables["household_demographics"]
    h_set = set(hd["hd_demo_sk"][0][hd["hd_dep_count"][0] == 7].tolist())
    st = tables["store"]
    s_set = set(st["s_store_sk"][0][_s_eq(st, "s_store_name", "ese")].tolist())

    ss = tables["store_sales"]
    t_sk = ss["ss_sold_time_sk"][0]
    h_sk = ss["ss_hdemo_sk"][0]
    s_sk = ss["ss_store_sk"][0]
    cnt = 0
    for i in range(t_sk.shape[0]):
        if int(t_sk[i]) in t_set and int(h_sk[i]) in h_set and int(s_sk[i]) in s_set:
            cnt += 1
    return cnt
