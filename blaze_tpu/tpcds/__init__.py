"""TPC-DS support: star schemas, deterministic datagen, query plans,
and numpy oracles for differential validation.

≙ the reference's TPC-DS end-to-end matrix (SURVEY.md §4) — its CI
runs ~103 queries against vanilla-Spark answers; this package carries
the same differential strategy for the TPU engine, growing query by
query (tpch/ covers all 22 TPC-H; this covers the q3/q7 BASELINE
configs plus the classic reporting-join shapes).
"""

from .datagen import generate_all, generate_table
from .queries import QUERIES, build_query
from .schema import TPCDS_SCHEMAS

__all__ = [
    "QUERIES",
    "TPCDS_SCHEMAS",
    "build_query",
    "generate_all",
    "generate_table",
]
