"""TPC-DS data generation (star-schema subset, deterministic).

Shares the host-table format and helpers with the TPC-H generator
(tpch/datagen.py).  Foreign keys that TPC-DS leaves NULL are generated
as -1 here (no dimension row matches): identical behavior for the
inner-join query set, without per-column validity plumbing.

≙ the reference's dsdgen-produced datasets (tpcds/datagen wrapper,
tpcds-reusable.yml checks out a pregenerated 1 GB set).
"""

from __future__ import annotations

import zlib
from typing import Dict

import numpy as np

from ..tpch.datagen import (
    HostTable,
    _days,
    _encode_options,
    table_to_batches,  # noqa: F401  (re-export: tests build batches the same way)
)

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
EDUCATIONS = [
    "Primary", "Secondary", "College", "2 yr Degree",
    "4 yr Degree", "Advanced Degree", "Unknown",
]
MARITALS = ["M", "S", "D", "W", "U"]
GENDERS = ["M", "F"]
STORE_NAMES = ["ese", "ought", "able", "pri", "bar", "anti"]
STATES = ["TN", "SD", "AL", "GA", "OH"]
# incl. values OUTSIDE the q34/q73 filter set so the IN predicate
# actually filters rows
COUNTIES = ["Williamson County", "Franklin Parish", "Bronx County",
            "Orange County", "Salem County", "Kern County"]
BUY_POTENTIALS = ["1001-5000", "0-500", ">10000", "Unknown", "501-1000", "5001-10000"]
SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"]
FIRST_NAMES = ["James", "Mary", "John", "Linda", "Robert", "Susan", "David", "Karen"]
LAST_NAMES = ["Smith", "Jones", "Brown", "Davis", "Miller", "Wilson", "Moore", "Taylor"]
CLASSES = [
    "accessories", "classical", "fiction", "shirts", "birdal",
    "dresses", "football", "fragrances", "pants", "pop",
    "reference", "romance", "self-help", "wallpaper", "personal", "maternity",
]

REASON_DESCS = ["Package was damaged", "Stopped working", "Did not get it on time",
                "Not the product that was ordred", "Parts missing"]

CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville", "Riverside"]
UNITS = ["Each", "Dozen", "Case", "Pallet", "Gross", "Box"]
SIZES = ["small", "medium", "large", "extra large", "petite", "N/A"]
SHIP_MODE_TYPES = ["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"]
SHIP_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL"]
WAREHOUSE_NAMES = ["Conventional childr", "Important issues liv",
                   "Doors canno", "Bad cards must make", "Rooms cook"]
WEB_SITE_NAMES = ["site_0", "site_1", "site_2", "site_3"]

DATE_SK_BASE = 2450815  # arbitrary julian-like base, spec-style


def _n_customers(scale: float) -> int:
    return max(50, int(100000 * scale))


def _n_items(scale: float) -> int:
    """item row count — inventory/fact generators MUST use this same
    formula or their item_sk draws desync from the item table."""
    return max(60, int(18000 * scale))


def _n_cdemo() -> int:
    """customer_demographics row count — MUST match that generator's
    cross-product x reps."""
    return len(EDUCATIONS) * len(MARITALS) * len(GENDERS) * 4


def _n_promos(scale: float) -> int:
    return max(5, int(300 * scale))


def _n_addresses(scale: float) -> int:
    return max(25, _n_customers(scale) // 2)
D_FIRST = (1998, 1, 1)
D_LAST = (2002, 12, 31)


def _money(rng, n, lo, hi):
    """decimal(7,2) unscaled int64."""
    return rng.randint(int(lo * 100), int(hi * 100) + 1, n).astype(np.int64)


def _date_dim() -> HostTable:
    first = _days(*D_FIRST)
    last = _days(*D_LAST)
    days = np.arange(first, last + 1, dtype=np.int32)
    # civil calendar split (vectorized Hinnant)
    z = days.astype(np.int64) + 719468
    era = np.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return {
        "d_date_sk": ((days - first + DATE_SK_BASE).astype(np.int64), None),
        "d_date": (days, None),
        "d_year": (y.astype(np.int32), None),
        "d_moy": (m.astype(np.int32), None),
        "d_dom": (d.astype(np.int32), None),
        "d_qoy": (((m - 1) // 3 + 1).astype(np.int32), None),
        # 0 = Sunday (dsdgen convention); 1970-01-01 was a Thursday
        "d_dow": (((days.astype(np.int64) + 4) % 7).astype(np.int32), None),
        # monotone sequences for the year-over-year window families
        # (q2/q59 join week_seq±53; q67/q14 slice month_seq ranges);
        # anchored at the dataset's first day, spec-shaped not
        # spec-identical — oracles compute from the same columns
        "d_week_seq": (((days - first) // 7 + 1).astype(np.int32), None),
        "d_month_seq": (
            (((y - 1998) * 12 + m - 1) + 1176).astype(np.int32), None),
    }


def _time_dim() -> HostTable:
    mins = np.arange(1440, dtype=np.int64)
    hours = mins // 60
    # round-5 columns (deterministic, no rng): t_time in seconds since
    # midnight (q66 slices a BETWEEN range on it); dsdgen's meal-time
    # buckets (q71 filters breakfast/dinner)
    meal = np.where(
        (hours >= 6) & (hours < 9), "breakfast",
        np.where((hours >= 17) & (hours < 20), "dinner", ""),
    )
    return {
        "t_time_sk": (mins, None),
        "t_hour": (hours.astype(np.int32), None),
        "t_minute": ((mins % 60).astype(np.int32), None),
        "t_time": ((mins * 60).astype(np.int64), None),
        "t_meal_time": (*_encode_options([str(m) for m in meal], 16),),
    }


def generate_table(name: str, scale: float, seed: int = 20011129,
                   _base: Dict[str, "HostTable"] = None) -> HostTable:
    rng = np.random.RandomState((seed + zlib.crc32(name.encode())) % (2**31))
    base = _base or {}
    if name == "date_dim":
        return _date_dim()
    if name == "time_dim":
        return _time_dim()
    if name == "store":
        n = len(STORE_NAMES)
        data, lengths = _encode_options(STORE_NAMES, 16)
        st_data, st_len = _encode_options([STATES[i % len(STATES)] for i in range(n)], 8)
        co_data, co_len = _encode_options(["Unknown"] * n, 16)
        cty_data, cty_len = _encode_options([COUNTIES[i % len(COUNTIES)] for i in range(n)], 24)
        zip_data, zip_len = _encode_options([f"{35000 + 137 * i:05d}" for i in range(n)], 16)
        return {
            "s_store_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "s_store_name": (data, lengths),
            "s_state": (st_data, st_len),
            "s_company_name": (co_data, co_len),
            "s_county": (cty_data, cty_len),
            "s_zip": (zip_data, zip_len),
            # market 8 ≈ a third of stores so the q24 filter keeps rows
            "s_market_id": ((np.arange(n) % 3 * 2 + 6).astype(np.int32), None),
            "s_city": (*_encode_options([CITIES[i % len(CITIES)] for i in range(n)], 16),),
        }
    if name == "promotion":
        n = _n_promos(scale)
        yn = lambda: _encode_options([("Y" if v else "N") for v in rng.randint(0, 2, n)], 8)
        e_data, e_len = yn()
        v_data, v_len = yn()
        return {
            "p_promo_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "p_channel_email": (e_data, e_len),
            "p_channel_event": (v_data, v_len),
        }
    if name == "customer_demographics":
        # full cross product, spec-style smallest-cycle dimension
        combos = [
            (g, m, e)
            for e in EDUCATIONS
            for m in MARITALS
            for g in GENDERS
        ]
        reps = 4
        combos = combos * reps
        nc = len(combos)
        assert nc == _n_cdemo()
        g_data, g_len = _encode_options([c[0] for c in combos], 8)
        m_data, m_len = _encode_options([c[1] for c in combos], 8)
        e_data, e_len = _encode_options([c[2] for c in combos], 24)
        ratings = ["Low Risk", "Good", "High Risk", "Unknown"]
        cr_data, cr_len = _encode_options([ratings[i % 4] for i in range(nc)], 16)
        return {
            "cd_demo_sk": (np.arange(1, nc + 1, dtype=np.int64), None),
            "cd_gender": (g_data, g_len),
            "cd_marital_status": (m_data, m_len),
            "cd_education_status": (e_data, e_len),
            "cd_purchase_estimate": (((np.arange(nc) % 10 + 1) * 500).astype(np.int32), None),
            "cd_credit_rating": (cr_data, cr_len),
            "cd_dep_count": ((np.arange(nc) % 7).astype(np.int32), None),
            "cd_dep_employed_count": ((np.arange(nc) % 5).astype(np.int32), None),
            "cd_dep_college_count": ((np.arange(nc) % 4).astype(np.int32), None),
        }
    if name == "household_demographics":
        n = 720
        bp_data, bp_len = _encode_options(
            [BUY_POTENTIALS[i % len(BUY_POTENTIALS)] for i in range(n)], 16
        )
        return {
            "hd_demo_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "hd_dep_count": ((np.arange(n) % 10).astype(np.int32), None),
            "hd_buy_potential": (bp_data, bp_len),
            "hd_vehicle_count": (((np.arange(n) % 5) - 1).astype(np.int32), None),
            # round-5 column (deterministic): q84's income-band edge
            "hd_income_band_sk": ((np.arange(n) % 20 + 1).astype(np.int64), None),
        }
    if name == "customer":
        n = _n_customers(scale)
        sal, sal_len = _encode_options([SALUTATIONS[i % len(SALUTATIONS)] for i in range(n)], 8)
        fn_, fn_len = _encode_options([FIRST_NAMES[i % len(FIRST_NAMES)] for i in range(n)], 16)
        ln_, ln_len = _encode_options([LAST_NAMES[(i * 3) % len(LAST_NAMES)] for i in range(n)], 16)
        pf, pf_len = _encode_options([("Y" if i % 2 else "N") for i in range(n)], 8)
        n_addr = _n_addresses(scale)
        n_cd = _n_cdemo()
        return {
            "c_customer_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "c_current_addr_sk": (rng.randint(1, n_addr + 1, n).astype(np.int64), None),
            "c_current_cdemo_sk": (rng.randint(1, n_cd + 1, n).astype(np.int64), None),
            "c_salutation": (sal, sal_len),
            "c_first_name": (fn_, fn_len),
            "c_last_name": (ln_, ln_len),
            "c_preferred_cust_flag": (pf, pf_len),
            "c_customer_id": (*_encode_options([f"CUST{k:012d}" for k in range(1, n + 1)], 16),),
            "c_birth_year": ((1930 + np.arange(n) % 63).astype(np.int32), None),
            # round-5 column (new draw strictly after the existing
            # ones): q84's household-demographics edge
            "c_current_hdemo_sk": (rng.randint(1, 721, n).astype(np.int64), None),
        }
    if name == "customer_address":
        n = _n_addresses(scale)
        # ~10% of addresses share a store's 5-digit zip prefix so the
        # q19 "customer zip != store zip" predicate filters real rows
        zips = [
            (f"{35000 + 137 * (i % 6):05d}" if i % 10 == 0 else f"{60000 + 31 * i:05d}")
            for i in range(n)
        ]
        z_data, z_len = _encode_options([z[:5] + "-" + z[:4] for z in zips], 16)
        co_data, co_len = _encode_options(
            [COUNTIES[i % len(COUNTIES)] for i in range(n)], 24
        )
        st_data, st_len = _encode_options(
            [STATES[(i * 7) % len(STATES)] for i in range(n)], 8
        )
        # gmt offsets from the dsdgen domain; ~40% at -5 so the
        # q33/q56/q60 filter keeps a real subset (decimal(5,2) unscaled)
        gmt = np.array([(-500 if i % 5 < 2 else -600 - 100 * (i % 3)) for i in range(n)],
                       np.int64)
        return {
            "ca_address_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "ca_zip": (z_data, z_len),
            "ca_county": (co_data, co_len),
            "ca_state": (st_data, st_len),
            "ca_gmt_offset": (gmt, None),
            # ~1/6 of addresses share each store city so the q46/q68
            # "bought in another city" predicate splits rows both ways
            "ca_city": (*_encode_options([CITIES[(i * 5) % len(CITIES)] for i in range(n)], 16),),
            # round-5 column (deterministic): ~10% non-US so the q85
            # ca_country predicate filters real rows
            "ca_country": (*_encode_options(
                [("Canada" if i % 10 == 9 else "United States") for i in range(n)], 16),),
        }
    if name == "call_center":
        names = ["NY Metro", "Mid Atlantic", "North Midwest", "Pacific Northwest"]
        d, ln = _encode_options(names, 24)
        return {
            "cc_call_center_sk": (np.arange(1, len(names) + 1, dtype=np.int64), None),
            "cc_name": (d, ln),
            "cc_county": (*_encode_options(
                [COUNTIES[i % len(COUNTIES)] for i in range(len(names))], 24),),
        }
    if name == "reason":
        d, ln = _encode_options(REASON_DESCS, 40)
        return {
            "r_reason_sk": (np.arange(1, len(REASON_DESCS) + 1, dtype=np.int64), None),
            "r_reason_desc": (d, ln),
        }
    if name == "store_returns":
        # ~8% of store_sales lines come back; keys reference the SAME
        # deterministic store_sales draw (callers may pass it via
        # _base to avoid regenerating the largest fact table)
        ss = base.get("store_sales") or generate_table("store_sales", scale, seed)
        n_ss = ss["ss_item_sk"][0].shape[0]
        take = rng.rand(n_ss) < 0.08
        idx = np.flatnonzero(take)
        n = idx.shape[0]
        qty = ss["ss_quantity"][0][idx]
        ret_q = np.minimum(rng.randint(1, 101, n), qty).astype(np.int32)
        out = {
            "sr_item_sk": (ss["ss_item_sk"][0][idx], None),
            "sr_ticket_number": (ss["ss_ticket_number"][0][idx], None),
            "sr_reason_sk": (rng.randint(1, len(REASON_DESCS) + 1, n).astype(np.int64), None),
            "sr_return_quantity": (ret_q, None),
            "sr_return_amt": (_money(rng, n, 0, 300), None),
        }
        # round-4 columns: all NEW rng draws stay strictly AFTER the
        # original ones so the pre-existing columns are byte-identical
        # across rounds (oracle seeds/filters were tuned against them).
        # Return-side keys mirror the originating ticket line so
        # (item, ticket) joins recover the full provenance.
        sold = ss["ss_sold_date_sk"][0][idx]
        last_sk = _days(*D_LAST) - _days(*D_FIRST) + DATE_SK_BASE
        ret_date = np.where(
            sold < 0, np.int64(-1),
            np.minimum(sold + rng.randint(1, 91, n), last_sk),
        ).astype(np.int64)
        out.update({
            "sr_returned_date_sk": (ret_date, None),
            "sr_customer_sk": (ss["ss_customer_sk"][0][idx], None),
            "sr_store_sk": (ss["ss_store_sk"][0][idx], None),
            "sr_cdemo_sk": (ss["ss_cdemo_sk"][0][idx], None),
            "sr_net_loss": (_money(rng, n, 0, 500), None),
        })
        return out
    if name == "catalog_sales":
        n = max(150, int(1_440_000 * scale))
        n_date = _days(*D_LAST) - _days(*D_FIRST) + 1
        n_item = _n_items(scale)
        n_cust = _n_customers(scale)
        n_addr = _n_addresses(scale)
        date_sk = np.where(
            rng.rand(n) < 0.02, np.int64(-1),
            rng.randint(0, n_date, n) + DATE_SK_BASE,
        ).astype(np.int64)
        n_cd = _n_cdemo()
        n_promo = _n_promos(scale)
        out = {
            "cs_sold_date_sk": (date_sk, None),
            "cs_item_sk": (rng.randint(1, n_item + 1, n).astype(np.int64), None),
            "cs_bill_customer_sk": (rng.randint(1, n_cust + 1, n).astype(np.int64), None),
            "cs_ship_customer_sk": (rng.randint(1, n_cust + 1, n).astype(np.int64), None),
            "cs_bill_addr_sk": (rng.randint(1, n_addr + 1, n).astype(np.int64), None),
            "cs_bill_cdemo_sk": (rng.randint(1, n_cd + 1, n).astype(np.int64), None),
            "cs_promo_sk": (rng.randint(1, n_promo + 1, n).astype(np.int64), None),
            "cs_call_center_sk": (rng.randint(1, 5, n).astype(np.int64), None),
            "cs_quantity": (rng.randint(1, 101, n).astype(np.int32), None),
            "cs_list_price": (_money(rng, n, 1, 200), None),
            "cs_coupon_amt": (_money(rng, n, 0, 100), None),
            "cs_sales_price": (_money(rng, n, 0, 300), None),
            "cs_ext_sales_price": (_money(rng, n, 0, 2000), None),
            "cs_ext_discount_amt": (_money(rng, n, 0, 1000), None),
        }
        # round-4 columns (new draws strictly after the original ones;
        # see store_returns note).  Orders group 1-6 consecutive lines
        # (dsdgen's order model) — per-line warehouses/dates still vary
        # within an order, which the q16/q94 EXISTS shapes require.
        order = np.repeat(np.arange(1, n + 1), rng.randint(1, 7, n))[:n].astype(np.int64)
        ship_lag = rng.randint(2, 121, n)
        last_sk = _days(*D_LAST) - _days(*D_FIRST) + DATE_SK_BASE
        ship_date = np.where(
            date_sk < 0, np.int64(-1), np.minimum(date_sk + ship_lag, last_sk)
        ).astype(np.int64)
        out.update({
            "cs_order_number": (order, None),
            "cs_ship_date_sk": (ship_date, None),
            "cs_warehouse_sk": (rng.randint(1, len(WAREHOUSE_NAMES) + 1, n).astype(np.int64), None),
            "cs_ship_mode_sk": (rng.randint(1, len(SHIP_MODE_TYPES) + 1, n).astype(np.int64), None),
            "cs_ship_addr_sk": (rng.randint(1, n_addr + 1, n).astype(np.int64), None),
            "cs_bill_hdemo_sk": (rng.randint(1, 721, n).astype(np.int64), None),
            "cs_catalog_page_sk": (rng.randint(1, 21, n).astype(np.int64), None),
            "cs_net_profit": (_money(rng, n, -1000, 1500), None),
            "cs_ext_ship_cost": (_money(rng, n, 0, 500), None),
            "cs_wholesale_cost": (_money(rng, n, 1, 100), None),
            "cs_ext_list_price": (_money(rng, n, 1, 3000), None),
            "cs_net_paid": (_money(rng, n, 0, 2000), None),
        })
        # round-5 columns (new draws strictly after the round-4 ones;
        # q66 pivots on sold time + net incl. tax, q71 on sold time)
        out.update({
            "cs_sold_time_sk": (rng.randint(0, 1440, n).astype(np.int64), None),
            "cs_net_paid_inc_tax": (_money(rng, n, 0, 2200), None),
        })
        return out
    if name == "web_sales":
        n = max(100, int(720_000 * scale))
        n_date = _days(*D_LAST) - _days(*D_FIRST) + 1
        n_item = _n_items(scale)
        n_cust = _n_customers(scale)
        n_addr = _n_addresses(scale)
        date_sk = np.where(
            rng.rand(n) < 0.02, np.int64(-1),
            rng.randint(0, n_date, n) + DATE_SK_BASE,
        ).astype(np.int64)
        out = {
            "ws_sold_date_sk": (date_sk, None),
            "ws_item_sk": (rng.randint(1, n_item + 1, n).astype(np.int64), None),
            "ws_bill_customer_sk": (rng.randint(1, n_cust + 1, n).astype(np.int64), None),
            "ws_bill_addr_sk": (rng.randint(1, n_addr + 1, n).astype(np.int64), None),
            "ws_ext_sales_price": (_money(rng, n, 0, 2000), None),
            "ws_net_paid": (_money(rng, n, 0, 2000), None),
            "ws_ext_discount_amt": (_money(rng, n, 0, 1000), None),
        }
        # round-4 columns (new draws strictly after the original ones)
        order = np.repeat(np.arange(1, n + 1), rng.randint(1, 7, n))[:n].astype(np.int64)
        ship_lag = rng.randint(2, 121, n)
        last_sk = _days(*D_LAST) - _days(*D_FIRST) + DATE_SK_BASE
        ship_date = np.where(
            date_sk < 0, np.int64(-1), np.minimum(date_sk + ship_lag, last_sk)
        ).astype(np.int64)
        out.update({
            "ws_order_number": (order, None),
            "ws_ship_date_sk": (ship_date, None),
            "ws_warehouse_sk": (rng.randint(1, len(WAREHOUSE_NAMES) + 1, n).astype(np.int64), None),
            "ws_ship_mode_sk": (rng.randint(1, len(SHIP_MODE_TYPES) + 1, n).astype(np.int64), None),
            "ws_ship_addr_sk": (rng.randint(1, n_addr + 1, n).astype(np.int64), None),
            "ws_web_site_sk": (rng.randint(1, len(WEB_SITE_NAMES) + 1, n).astype(np.int64), None),
            "ws_web_page_sk": (rng.randint(1, 11, n).astype(np.int64), None),
            "ws_sold_time_sk": (rng.randint(0, 1440, n).astype(np.int64), None),
            "ws_quantity": (rng.randint(1, 101, n).astype(np.int32), None),
            "ws_list_price": (_money(rng, n, 1, 200), None),
            "ws_sales_price": (_money(rng, n, 0, 300), None),
            "ws_net_profit": (_money(rng, n, -1000, 1500), None),
            "ws_ext_ship_cost": (_money(rng, n, 0, 500), None),
            "ws_wholesale_cost": (_money(rng, n, 1, 100), None),
            "ws_ext_list_price": (_money(rng, n, 1, 3000), None),
            "ws_promo_sk": (rng.randint(1, _n_promos(scale) + 1, n).astype(np.int64), None),
        })
        return out
    if name == "item":
        n = _n_items(scale)
        sk = np.arange(1, n + 1, dtype=np.int64)
        ids = [f"ITEM{k:012d}" for k in range(1, n + 1)]
        id_data, id_len = _encode_options(ids, 16)
        brand_id = (rng.randint(1, 10, n) * 1000000 + rng.randint(1, 200, n)).astype(np.int32)
        brands = [f"brand#{b}" for b in brand_id]
        b_data, b_len = _encode_options(brands, 32)
        cat_id = rng.randint(1, len(CATEGORIES) + 1, n).astype(np.int32)
        c_data, c_len = _encode_options([CATEGORIES[c - 1] for c in cat_id], 16)
        class_id = rng.randint(1, len(CLASSES) + 1, n).astype(np.int32)
        cl_data, cl_len = _encode_options([CLASSES[c - 1] for c in class_id], 16)
        desc_data, desc_len = _encode_options([f"desc of item {k % 97}" for k in range(n)], 32)
        mfi = rng.randint(1, 200, n).astype(np.int32)
        mf_data, mf_len = _encode_options([f"manufact#{m}" for m in mfi], 24)
        colors = ["slate", "blanched", "burnished", "peach", "saddle",
                  "powder", "navy", "chiffon", "ivory", "plum"]
        col_data, col_len = _encode_options([colors[int(v)] for v in rng.randint(0, len(colors), n)], 16)
        return {
            "i_item_sk": (sk, None),
            "i_color": (col_data, col_len),
            "i_item_id": (id_data, id_len),
            "i_item_desc": (desc_data, desc_len),
            "i_brand_id": (brand_id, None),
            "i_brand": (b_data, b_len),
            "i_class_id": (class_id, None),
            "i_class": (cl_data, cl_len),
            "i_category_id": (cat_id, None),
            "i_category": (c_data, c_len),
            "i_manufact_id": (mfi, None),
            "i_manufact": (mf_data, mf_len),
            "i_manager_id": (rng.randint(1, 40, n).astype(np.int32), None),
            "i_current_price": (_money(rng, n, 1, 99), None),
            "i_units": (*_encode_options([UNITS[int(v)] for v in rng.randint(0, len(UNITS), n)], 8),),
            "i_size": (*_encode_options([SIZES[int(v)] for v in rng.randint(0, len(SIZES), n)], 16),),
            "i_wholesale_cost": (_money(rng, n, 1, 80), None),
        }
    if name == "store_sales":
        # dsdgen's basket model: a TICKET (1..25 lines, ~13 avg) shares
        # one date/time/store/customer/demographics draw; per-LINE
        # attributes (item, quantity, prices) vary within the basket.
        # Ticket-level HAVING queries (q34/q73) depend on this shape.
        n_target = max(200, int(2_880_000 * scale))
        n_tickets = max(2, n_target // 13)
        n_date = _days(*D_LAST) - _days(*D_FIRST) + 1
        n_item = _n_items(scale)
        n_cd = _n_cdemo()
        n_promo = _n_promos(scale)
        n_cust = _n_customers(scale)

        lines_per = rng.randint(1, 26, n_tickets)
        n = int(lines_per.sum())
        tidx = np.repeat(np.arange(n_tickets), lines_per)

        def ticket_fk(upper, null_frac=0.04):
            v = rng.randint(1, upper + 1, n_tickets).astype(np.int64)
            nulls = rng.rand(n_tickets) < null_frac
            return np.where(nulls, np.int64(-1), v)[tidx]

        t_date = np.where(
            rng.rand(n_tickets) < 0.02, np.int64(-1),
            rng.randint(0, n_date, n_tickets) + DATE_SK_BASE,
        ).astype(np.int64)[tidx]
        t_time = np.where(
            rng.rand(n_tickets) < 0.02, np.int64(-1),
            rng.randint(0, 1440, n_tickets),
        ).astype(np.int64)[tidx]
        return {
            "ss_sold_date_sk": (t_date, None),
            "ss_sold_time_sk": (t_time, None),
            "ss_item_sk": (rng.randint(1, n_item + 1, n).astype(np.int64), None),
            "ss_customer_sk": (ticket_fk(n_cust), None),
            "ss_cdemo_sk": (ticket_fk(n_cd), None),
            "ss_hdemo_sk": (ticket_fk(720), None),
            "ss_store_sk": (ticket_fk(len(STORE_NAMES)), None),
            "ss_promo_sk": (
                np.where(rng.rand(n) < 0.04, np.int64(-1),
                         rng.randint(1, n_promo + 1, n)).astype(np.int64), None),
            "ss_addr_sk": (ticket_fk(_n_addresses(scale)), None),
            "ss_ticket_number": ((tidx + 1).astype(np.int64), None),
            "ss_quantity": (rng.randint(1, 101, n).astype(np.int32), None),
            "ss_list_price": (_money(rng, n, 1, 200), None),
            "ss_sales_price": (_money(rng, n, 0, 200), None),
            "ss_ext_discount_amt": (_money(rng, n, 0, 1000), None),
            "ss_ext_sales_price": (_money(rng, n, 0, 2000), None),
            "ss_coupon_amt": (_money(rng, n, 0, 100), None),
            "ss_net_profit": (_money(rng, n, -1000, 1000), None),
            "ss_net_paid": (_money(rng, n, 0, 2000), None),
            "ss_wholesale_cost": (_money(rng, n, 1, 100), None),
            "ss_ext_list_price": (_money(rng, n, 1, 3000), None),
            "ss_ext_wholesale_cost": (_money(rng, n, 1, 5000), None),
        }
    if name == "warehouse":
        n = len(WAREHOUSE_NAMES)
        return {
            "w_warehouse_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "w_warehouse_name": (*_encode_options(WAREHOUSE_NAMES, 24),),
            "w_state": (*_encode_options([STATES[i % len(STATES)] for i in range(n)], 8),),
            "w_county": (*_encode_options([COUNTIES[i % len(COUNTIES)] for i in range(n)], 24),),
            # round-5 columns (deterministic, q66's pivot attributes)
            "w_warehouse_sq_ft": (((np.arange(n) + 1) * 73065).astype(np.int32), None),
            "w_city": (*_encode_options([CITIES[i % len(CITIES)] for i in range(n)], 16),),
            "w_country": (*_encode_options(["United States"] * n, 16),),
        }
    if name == "web_site":
        n = len(WEB_SITE_NAMES)
        return {
            "web_site_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "web_name": (*_encode_options(WEB_SITE_NAMES, 16),),
            "web_company_name": (*_encode_options(["pri", "ought", "able", "ese"], 16),),
        }
    if name == "ship_mode":
        n = len(SHIP_MODE_TYPES)
        return {
            "sm_ship_mode_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "sm_type": (*_encode_options(SHIP_MODE_TYPES, 16),),
            "sm_carrier": (*_encode_options(SHIP_CARRIERS, 16),),
        }
    if name == "catalog_page":
        n = 20
        return {
            "cp_catalog_page_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "cp_catalog_page_id": (*_encode_options([f"CPAG{k:08d}" for k in range(1, n + 1)], 16),),
        }
    if name == "income_band":
        # dsdgen's 20 fixed bands: [0..10000], [10001..20000], ...
        sk = np.arange(1, 21, dtype=np.int64)
        return {
            "ib_income_band_sk": (sk, None),
            "ib_lower_bound": (np.where(sk == 1, 0, (sk - 1) * 10000 + 1).astype(np.int32), None),
            "ib_upper_bound": ((sk * 10000).astype(np.int32), None),
        }
    if name == "web_page":
        n = 10
        return {
            "wp_web_page_sk": (np.arange(1, n + 1, dtype=np.int64), None),
            "wp_char_count": ((np.arange(n) * 800 + 400).astype(np.int32), None),
        }
    if name == "inventory":
        # weekly snapshots x item x warehouse, dsdgen-style full cross
        # (row count scales with the item dimension only)
        n_item = _n_items(scale)
        n_wh = len(WAREHOUSE_NAMES)
        first = _days(*D_FIRST)
        last = _days(*D_LAST)
        week_days = np.arange(first, last + 1, 7, dtype=np.int64) - first + DATE_SK_BASE
        dd, ii, ww = np.meshgrid(
            week_days, np.arange(1, n_item + 1, dtype=np.int64),
            np.arange(1, n_wh + 1, dtype=np.int64), indexing="ij",
        )
        n = dd.size
        return {
            "inv_date_sk": (dd.ravel(), None),
            "inv_item_sk": (ii.ravel(), None),
            "inv_warehouse_sk": (ww.ravel(), None),
            "inv_quantity_on_hand": (rng.randint(0, 1001, n).astype(np.int32), None),
        }
    if name == "catalog_returns":
        cs = base.get("catalog_sales") or generate_table("catalog_sales", scale, seed)
        n_cs = cs["cs_item_sk"][0].shape[0]
        take = rng.rand(n_cs) < 0.08
        idx = np.flatnonzero(take)
        n = idx.shape[0]
        ship = cs["cs_ship_date_sk"][0][idx]
        last_sk = _days(*D_LAST) - _days(*D_FIRST) + DATE_SK_BASE
        ret_date = np.where(
            ship < 0, np.int64(-1), np.minimum(ship + rng.randint(1, 61, n), last_sk)
        ).astype(np.int64)
        ret_q = np.minimum(rng.randint(1, 101, n), cs["cs_quantity"][0][idx]).astype(np.int32)
        return {
            "cr_item_sk": (cs["cs_item_sk"][0][idx], None),
            "cr_order_number": (cs["cs_order_number"][0][idx], None),
            "cr_returned_date_sk": (ret_date, None),
            "cr_return_quantity": (ret_q, None),
            "cr_return_amount": (_money(rng, n, 0, 300), None),
            "cr_net_loss": (_money(rng, n, 0, 500), None),
            "cr_catalog_page_sk": (cs["cs_catalog_page_sk"][0][idx], None),
            "cr_returning_customer_sk": (cs["cs_bill_customer_sk"][0][idx], None),
            "cr_call_center_sk": (cs["cs_call_center_sk"][0][idx], None),
            "cr_refunded_cash": (_money(rng, n, 0, 250), None),
        }
    if name == "web_returns":
        ws = base.get("web_sales") or generate_table("web_sales", scale, seed)
        n_ws = ws["ws_item_sk"][0].shape[0]
        take = rng.rand(n_ws) < 0.08
        idx = np.flatnonzero(take)
        n = idx.shape[0]
        ship = ws["ws_ship_date_sk"][0][idx]
        last_sk = _days(*D_LAST) - _days(*D_FIRST) + DATE_SK_BASE
        ret_date = np.where(
            ship < 0, np.int64(-1), np.minimum(ship + rng.randint(1, 61, n), last_sk)
        ).astype(np.int64)
        ret_q = np.minimum(rng.randint(1, 101, n), ws["ws_quantity"][0][idx]).astype(np.int32)
        return {
            "wr_item_sk": (ws["ws_item_sk"][0][idx], None),
            "wr_order_number": (ws["ws_order_number"][0][idx], None),
            "wr_returned_date_sk": (ret_date, None),
            "wr_return_quantity": (ret_q, None),
            "wr_return_amt": (_money(rng, n, 0, 300), None),
            "wr_net_loss": (_money(rng, n, 0, 500), None),
            "wr_web_page_sk": (ws["ws_web_page_sk"][0][idx], None),
            "wr_returning_customer_sk": (ws["ws_bill_customer_sk"][0][idx], None),
            "wr_refunded_cash": (_money(rng, n, 0, 250), None),
            # round-5 columns (new draws strictly after the round-4
            # ones): the q85 demographics/address/reason edges
            "wr_fee": (_money(rng, n, 0, 100), None),
            "wr_refunded_cdemo_sk": (rng.randint(1, _n_cdemo() + 1, n).astype(np.int64), None),
            "wr_returning_cdemo_sk": (rng.randint(1, _n_cdemo() + 1, n).astype(np.int64), None),
            "wr_refunded_addr_sk": (rng.randint(1, _n_addresses(scale) + 1, n).astype(np.int64), None),
            "wr_reason_sk": (rng.randint(1, len(REASON_DESCS) + 1, n).astype(np.int64), None),
        }
    raise KeyError(f"unknown tpcds table {name!r}")


def with_null_fks(table: HostTable, columns) -> HostTable:
    """Expose a table's -1 foreign-key sentinels as REAL nulls.

    The generator draws NULL foreign keys as -1 (module docstring):
    join-equivalent for the inner-join query set, but `fk IS NULL`,
    null-key grouping, and outer-join null-extension semantics differ.
    This view rewrites the named columns to (data, lengths, validity)
    with validity = (data != -1) — the SAME underlying draws, so every
    existing oracle stays byte-identical while null-semantics
    differentials get honest NULLs end-to-end."""
    out = dict(table)
    for c in columns:
        entry = table[c]
        data, lengths = entry[0], entry[1]
        out[c] = (data, lengths, data != np.int64(-1))
    return out


def generate_all(scale: float, seed: int = 20011129) -> Dict[str, HostTable]:
    from .schema import TPCDS_SCHEMAS

    out: Dict[str, HostTable] = {}
    for name in TPCDS_SCHEMAS:
        out[name] = generate_table(name, scale, seed, _base=out)
    return out
