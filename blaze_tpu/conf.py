"""Configuration knobs, mirroring the reference's single-source-of-truth
Spark conf pattern.

The reference exposes an enum of ``spark.blaze.*`` knobs on the JVM side
(``spark-extension/.../BlazeConf.java:22-76``) and mirrors each one into
native code with live JNI static calls
(``native-engine/blaze-jni-bridge/src/conf.rs:19-91``).  Here the conf
is a process-global key→value store that the JVM gateway (when embedded
under Spark) populates from the SparkConf over JNI, and that tests /
standalone runs populate directly.  Defaults match the reference where
the knob has a reference equivalent.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict

from .analysis.locks import make_lock

# innermost subsystem lock of the declared hierarchy (analysis/locks.py):
# every subsystem reads conf while holding its own locks, never vice versa
_lock = make_lock("conf.store")
_values: Dict[str, Any] = {}

# guarded-by declaration (analysis/guarded.py): the live conf store is
# read from every subsystem's threads and written by the gateway/tests
GUARDED_BY = {"_values": "conf.store"}
GUARDED_REFS = ("_values",)


class ConfEntry:
    """One typed knob.  ``.get()`` reads the live value (env var override
    ``BLAZE_<NAME>`` > programmatic set > default), like the reference's
    ``define_conf!`` macro reads SparkConf through a JNI static."""

    def __init__(self, key: str, default: Any, parse: Callable[[str], Any]):
        self.key = key
        self.default = default
        self._parse = parse
        self._env_key = (
            "BLAZE_" + key.replace("spark.blaze.", "").replace(".", "_").upper()
        )

    def get(self) -> Any:
        env_key = self._env_key
        if env_key in os.environ:
            return self._parse(os.environ[env_key])
        with _lock:
            return _values.get(self.key, self.default)

    def set(self, value: Any) -> None:
        with _lock:
            _values[self.key] = value


def _bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


# ≙ BlazeConf.java defaults: BATCH_SIZE 10000, MEMORY_FRACTION 0.6, etc.
BATCH_SIZE = ConfEntry("spark.blaze.batchSize", 8192, int)
MEMORY_FRACTION = ConfEntry("spark.blaze.memoryFraction", 0.6, float)
ENABLE_PARTIAL_AGG_SKIPPING = ConfEntry("spark.blaze.partialAggSkipping.enable", True, _bool)
PARTIAL_AGG_SKIPPING_RATIO = ConfEntry("spark.blaze.partialAggSkipping.ratio", 0.8, float)
PARTIAL_AGG_SKIPPING_MIN_ROWS = ConfEntry("spark.blaze.partialAggSkipping.minRows", 20000, int)
SPILL_COMPRESSION_CODEC = ConfEntry("spark.blaze.spill.compression.codec", "zlib", str)
IO_COMPRESSION_CODEC = ConfEntry("spark.io.compression.codec", "zlib", str)
IGNORE_CORRUPT_FILES = ConfEntry("spark.files.ignoreCorruptFiles", False, _bool)
PARQUET_FILTER_PUSHDOWN = ConfEntry("spark.blaze.parquet.enable.pageFiltering", True, _bool)
# TPU-only: hand-written pallas kernels for hot loops (kernels/); the
# pure-XLA path is always kept as fallback
PALLAS_ENABLE = ConfEntry("spark.blaze.tpu.pallas.enable", True, _bool)
# hash-join probe inner loop as a fused pallas lookup (counting
# searchsorted over the sorted build table): work is probes x table,
# so it only engages for small build sides — default OFF until TPU
# profiles justify it; tier-1 exercises it via interpret mode
PALLAS_JOIN_PROBE = ConfEntry("spark.blaze.tpu.pallas.joinProbe", False, _bool)
INPUT_BATCH_STATISTICS = ConfEntry("spark.blaze.inputBatchStatistics", False, _bool)
UDF_WRAPPER_NUM_THREADS = ConfEntry("spark.blaze.udfWrapperNumThreads", 1, int)
# pickled UDF/UDTF payloads in TaskDefinitions execute arbitrary code at
# deserialization (round-1 advisor finding): a gateway deployed across a
# trust boundary must run with this OFF and register generators by name
ALLOW_PICKLED_UDFS = ConfEntry("spark.blaze.udf.allowPickled", True, _bool)
SMJ_FALLBACK_ENABLE = ConfEntry("spark.blaze.smjfallback.enable", True, _bool)
# fixed per-group element budget for collect_list/collect_set results
# (the reference's lists are unbounded; the padded device layout is not —
# elements past the budget are SILENTLY DROPPED: raise this knob when a
# query's groups can exceed it)
COLLECT_MAX_ELEMS = ConfEntry("spark.blaze.collect.maxElems", 64, int)
SUGGESTED_BATCH_MEM_SIZE = ConfEntry("spark.blaze.suggested.batch.mem.size", 8 << 20, int)
TOKIO_NUM_WORKER_THREADS = ConfEntry("spark.blaze.tokio.num.worker.threads", 2, int)
# bounded producer queue depth between host staging and device compute
# (≙ rt.rs sync_channel(1) + tokio stream drive); 0 = synchronous
PIPELINE_DEPTH = ConfEntry("spark.blaze.pipeline.depth", 2, int)
RSS_FETCH_BARRIER_TIMEOUT = ConfEntry("spark.blaze.rss.fetchBarrierTimeout", 120.0, float)
# Double-buffered shuffle write: the map task hands each batch's
# pid-sorted device output to a host staging thread (device->host
# transfer + per-pid slicing + memmgr-tracked buffering) while the next
# batch's program is already dispatched.  Off = the synchronous path.
SHUFFLE_ASYNC_WRITE = ConfEntry("spark.blaze.shuffle.asyncWrite", True, _bool)
# Bounded handoff queue depth for the async shuffle writer (device
# outputs in flight to the host stager; producer blocks when full).
SHUFFLE_ASYNC_QUEUE_DEPTH = ConfEntry("spark.blaze.shuffle.asyncWrite.queueDepth", 2, int)

# Fault-tolerant stage execution (runtime/retry.py + scheduler loop).
# ≙ spark.task.maxFailures: total attempts per task, 1 = fail fast.
TASK_MAX_ATTEMPTS = ConfEntry("spark.blaze.task.maxAttempts", 4, int)
# first retry delay (seconds); doubles per attempt with deterministic
# jitter (retry.py RetryPolicy.backoff).  0 disables backoff sleeps.
TASK_RETRY_BACKOFF = ConfEntry("spark.blaze.task.retryBackoff", 0.1, float)
# per-task wall-clock budget (seconds), checked between output batches;
# 0 = unlimited.  A timed-out attempt is retried like any failure.
TASK_TIMEOUT = ConfEntry("spark.blaze.task.timeout", 0.0, float)
# fetch-failure recoveries (upstream map-stage regenerations) allowed
# per fetching task before the failure is terminal
STAGE_MAX_ATTEMPTS = ConfEntry("spark.blaze.stage.maxAttempts", 4, int)
# Concurrent tasks per non-result stage in the scheduler (1 = the
# strictly serial pre-speculation behavior, which keeps fault-injection
# hit ordering deterministic; speculation/wedge detection force the
# concurrent attempt runner regardless).
STAGE_TASK_CONCURRENCY = ConfEntry("spark.blaze.stage.taskConcurrency", 1, int)
# Per-QUERY wall-clock budget (ms), enforced by the query CancelScope
# (runtime/context.py): every cooperative checkpoint (scheduler drain,
# result-batch pull, attempt launch, the concurrent runner's poll
# loop) checks the deadline, and expiry cancels every live attempt and
# raises QueryDeadlineError carrying the stage/task frontier.  The
# per-TASK half of the clock is spark.blaze.task.timeout /
# spark.blaze.task.wedgeMs; this is the per-query half.  0 = unlimited.
QUERY_TIMEOUT_MS = ConfEntry("spark.blaze.query.timeoutMs", 0, int)
# Heartbeat-age wedge detection on the plain (non-speculative) retry
# path, in ms: a task whose monitor heartbeat age exceeds this is
# cancelled cooperatively and RETRIED like a timeout — covering the
# blind spot where the cooperative drain deadline only fires between
# driver-observed batches, so a task wedged inside its first batch
# would hang forever.  0 = off.  Must exceed
# spark.blaze.monitor.heartbeatMs or healthy tasks look wedged.
TASK_WEDGE_MS = ConfEntry("spark.blaze.task.wedgeMs", 0, int)

# Speculative execution (runtime/speculation.py, ≙ spark.speculation):
# once a quantile of a stage's tasks have finished, a task running
# longer than multiplier x their median runtime (or whose heartbeat age
# crosses wedgeMs) gets ONE backup attempt racing it through the
# attempt-id commit seams (atomic-rename shuffle commit / RSS
# close-abort); first completion wins, the loser is cancelled
# cooperatively and its progress/heartbeat state rolled back.
SPECULATION_ENABLE = ConfEntry("spark.blaze.speculation.enabled", False, _bool)
# backup launches when runtime > multiplier x median(completed sibling
# durations) — ≙ spark.speculation.multiplier
SPECULATION_MULTIPLIER = ConfEntry("spark.blaze.speculation.multiplier", 1.5, float)
# fraction of the stage's tasks that must have completed before
# duration-based speculation engages — ≙ spark.speculation.quantile
SPECULATION_QUANTILE = ConfEntry("spark.blaze.speculation.quantile", 0.75, float)
# minimum runtime (seconds) before a task may be speculated — keeps
# short tasks from ever paying the backup cost
SPECULATION_MIN_RUNTIME = ConfEntry("spark.blaze.speculation.minRuntime", 0.1, float)
# heartbeat-age wedge trigger for speculation, in ms: a running task
# whose last beat is older than this gets its backup immediately,
# without waiting for the duration quantile (0 = duration-only)
SPECULATION_WEDGE_MS = ConfEntry("spark.blaze.speculation.wedgeMs", 0, int)
# deterministic fault-injection schedule (runtime/faults.py grammar,
# e.g. "shuffle.fetch@2,task.compute@1@a0"); empty = no injection.
# Env override BLAZE_FAULTS_SPEC reaches worker subprocesses too.
FAULTS_SPEC = ConfEntry("spark.blaze.faults.spec", "", str)

# Elastic worker-host pool (runtime/hostpool.py): persistent
# worker.py --serve processes the scheduler binds map tasks to.
# Number of pooled workers; 0 = pool disabled, everything in-process.
POOL_WORKERS = ConfEntry("spark.blaze.pool.workers", 0, int)
# pooled-worker heartbeat interval (ms) on the serve protocol's stdout
# frame stream — the liveness signal hostpool.heartbeat_ages() reads
# (same age mechanism as spark.blaze.monitor.heartbeatMs)
POOL_HEARTBEAT_MS = ConfEntry("spark.blaze.pool.heartbeatMs", 50, int)
# heartbeat silence (ms) past which a READY pooled worker is declared
# lost and its map outputs invalidated for partial rerun.  Must exceed
# spark.blaze.pool.heartbeatMs by a healthy margin.
POOL_LIVENESS_TIMEOUT_MS = ConfEntry(
    "spark.blaze.pool.livenessTimeoutMs", 10000, int)
# worker-slot failures inside the decay window before the slot is
# BLACKLISTED (no respawn) — ≙ spark.blacklist.* node blacklisting
HOST_BLACKLIST_MAX_FAILURES = ConfEntry(
    "spark.blaze.host.blacklist.maxFailures", 2, int)
# sliding decay window (seconds) for blacklist failure counts; a
# blacklisted slot is re-admitted once its count decays below the
# threshold — ≙ spark.blacklist.timeout
HOST_BLACKLIST_DECAY_SEC = ConfEntry(
    "spark.blaze.host.blacklist.decaySec", 60.0, float)

# End-to-end data integrity (runtime/integrity.py): checksum algorithm
# stamped on every framed block that crosses a process or disk boundary
# (shuffle map outputs, spill frames, RSS pushes, broadcast blobs,
# worker result frames) and verified at every read boundary — a
# mismatch raises typed BlockCorruptionError and rides the existing
# recovery ladder (fetch-failure map rerun / task retry / quarantine).
# Values: "crc32" (zlib-backed, C speed — the default), "crc32c"
# (Castagnoli, byte-interoperable with hardware CRC32C, pure-python
# table), "xxh32" (the LZ4-frame hash), "off" (no stamping, no
# verification).  Checksums are host-side over already-staged bytes:
# no device syncs, so the warm dispatch budget is untouched.
IO_CHECKSUM = ConfEntry("spark.blaze.io.checksum", "crc32", str)
# Orphan sweep on startup: a LocalShuffleManager re-opened over an
# EXISTING root (a restarted driver / a worker joining a shared root)
# reclaims `.inprogress` staging temps and blaze_spill_ files older
# than this many seconds — debris of a crashed prior process that
# would otherwise leak the dead run's disk.  0 disables the sweep.
ORPHAN_SWEEP_AGE = ConfEntry("spark.blaze.shuffle.orphanSweepAgeSec", 1800, int)
# Disk-pressure ladder (runtime/diskmgr.py): ENOSPC/EIO during a spill
# or shuffle write first RECLAIMS reclaimable disk — stale
# `.inprogress` temps and orphaned spill files older than this many
# seconds in the registered shuffle roots and the spill temp dir —
# before retrying the write, falling back to host RAM (bounded by the
# memmgr quota), or raising typed retryable DiskExhaustedError.
DISK_RECLAIM_AGE = ConfEntry("spark.blaze.disk.reclaimAgeSec", 300, int)

# Graceful degradation under device memory pressure (runtime/oom.py):
# an XLA RESOURCE_EXHAUSTED caught at the dispatch choke point first
# sheds host-staging pressure (memmgr force-spill) and retries; a
# fused-stage program that still OOMs halves its batch and re-runs,
# recursively up to this many times, before falling back to the eager
# per-operator path — only then does the attempt fail (retryable).
OOM_MAX_DOWNSHIFTS = ConfEntry("spark.blaze.oom.maxDownshifts", 2, int)

# Query-level tracing + structured event log (runtime/trace.py).
# OFF (default) keeps the dispatch hot path on the pre-existing code
# path — no span allocation, no block-until-ready timing per kernel.
# ON: scheduler/task/operator lifecycle events + per-kernel
# device/dispatch/compile attribution append to a JSONL event log
# (≙ Spark's spark.eventLog.enabled + EventLoggingListener).
TRACE_ENABLE = ConfEntry("spark.blaze.trace.enabled", False, _bool)
# Event-log directory (≙ spark.eventLog.dir); empty = a blaze_eventlog
# dir under the system temp dir.  One JSONL file per traced query.
EVENT_LOG_DIR = ConfEntry("spark.blaze.eventLog.dir", "", str)
# Size cap per event-log file (bytes): a full file rolls over into a
# numbered segment (<path>.seg1, .seg2, ...) so long-running services
# never grow one unbounded JSONL (≙ spark.eventLog.rolling.maxFileSize).
# 0 = unbounded.  --report reads a rotated set transparently.
EVENT_LOG_MAX_BYTES = ConfEntry("spark.blaze.eventLog.maxBytes", 0, int)
# Kernel-attribution sampling: with tracing armed, block-until-ready
# time every Nth instrumented program instead of all of them (attributed
# device times are scaled back up by the sampling factor in --report),
# so attribution is cheap enough to leave on in production.  1 = time
# every program (the full-fidelity profile default).  Caveat: on a
# device that truly queues async work, the sampled program's drain also
# waits out the N-1 unsampled programs queued ahead of it, so the
# scaled device time is an UPPER BOUND, not an unbiased estimate (the
# report flags it '~').
TRACE_SAMPLE_RATE = ConfEntry("spark.blaze.trace.sampleRate", 1, int)

# OpenTelemetry export (runtime/otel.py): map each traced query's
# event log onto an OTLP/JSON span tree (query -> stage -> task ->
# kernel, one W3C trace id end to end) at query-span exit.  OFF
# (default) is a structural no-op exactly like trace.enabled: one bool
# read at span exit, no conversion, no file, no thread.  Requires
# tracing armed (the event log is the source).
OTEL_ENABLE = ConfEntry("spark.blaze.otel.enabled", False, _bool)
# File sink directory for the exported OTLP/JSON documents (one
# <query>-<pid>-spans.json per traced query); empty = a blaze_otel dir
# under the system temp dir.
OTEL_DIR = ConfEntry("spark.blaze.otel.dir", "", str)
# Best-effort OTLP/HTTP push target (e.g. an OpenTelemetry collector's
# http://host:4318/v1/traces): when set, exported span documents are
# also queued to a daemon push loop (blaze-otel-push, next to the
# statsd pusher) that POSTs them with a short timeout — a dead
# collector costs nothing and never blocks the workload.  Empty
# (default) = file sink only, no socket, no thread.
OTEL_ENDPOINT = ConfEntry("spark.blaze.otel.endpoint", "", str)
# Push-loop flush cadence (ms) for the OTLP HTTP exporter.
OTEL_FLUSH_MS = ConfEntry("spark.blaze.otel.flushMs", 1000, int)

# Multi-tenant query service (runtime/service.py): admission control,
# fair-share scheduling, per-pool quotas, backpressure, supervision.
# Queries RUNNING concurrently once admitted (each interleaves its
# stages through the one-device-lease fair-share gate below).
SERVICE_MAX_CONCURRENT = ConfEntry("spark.blaze.service.maxConcurrent", 2, int)
# Submissions waiting for a run slot beyond the running set; PAST this
# bound a submission is SHED with a typed retryable QueryRejectedError
# (HTTP 429 on the service endpoint) instead of accepted-and-wedged.
SERVICE_MAX_QUEUED = ConfEntry("spark.blaze.service.maxQueued", 16, int)
# A QUEUED submission still waiting after this long is shed with
# QueryRejectedError(reason="queue_timeout") — bounded queueing delay
# instead of unbounded head-of-line blocking.  0 = wait forever.
SERVICE_QUEUE_TIMEOUT_MS = ConfEntry("spark.blaze.service.queueTimeoutMs", 0, int)
# Supervisor wedge reaping: a RUNNING service query whose monitor
# heartbeat age exceeds this is cancelled (reason="wedged") — the
# query-level analogue of spark.blaze.task.wedgeMs, read from the live
# registry's heartbeat-age signal (needs the monitor armed).  0 = off.
SERVICE_WEDGE_MS = ConfEntry("spark.blaze.service.wedgeMs", 0, int)
# Bounded result handoff between a service query's worker (producer)
# and the submitter consuming QueryHandle.batches(): a slow consumer
# BLOCKS the producer (which releases its device-lease turn first)
# instead of ballooning host buffers — the exchange backpressure.
SERVICE_RESULT_QUEUE_DEPTH = ConfEntry("spark.blaze.service.resultQueueDepth", 8, int)
# Per-pool knobs ride the registered dynamic prefix
# spark.blaze.service.pool.<name>.weight (fair-share weight, default 1)
# and spark.blaze.service.pool.<name>.quota (host-staging bytes budget,
# 0/unset = unlimited) — read via get_conf, like spark.blaze.enable.*.

# Serving-scale cache hierarchy (runtime/querycache.py).  Level 1,
# the PLAN cache: literal leaves canonicalize into slots
# (exprs.compile.slotify_literals) so parameter-shifted variants of one
# query shape share one plan fingerprint and ONE compiled fused program
# — the slot values ride as traced kernel arguments.  Off: literals
# bake into kernel keys again (every shifted variant recompiles).
CACHE_PLAN_ENABLED = ConfEntry("spark.blaze.cache.plan.enabled", True, _bool)
# Level 2, the RESULT cache: the service memoizes final result batches
# keyed by (plan fingerprint, slot values, source version); a hit is
# served host-side WITHOUT taking a fair-share device-lease turn.  Any
# source append/rewrite changes the version and invalidates exactly
# the dependent entries.
CACHE_RESULT_ENABLED = ConfEntry("spark.blaze.cache.result.enabled", True, _bool)
# Byte budget for cached result batches (LRU evicts past it), tracked
# through the memmgr as an UNOWNED consumer — watermark pressure spills
# cold entries down the diskmgr ladder, never a quota neighbor's memory.
CACHE_RESULT_MAX_BYTES = ConfEntry("spark.blaze.cache.result.maxBytes", 64 << 20, int)
# Per-entry cap: a single query result larger than this is never
# admitted (one giant result must not evict the whole working set).
CACHE_RESULT_MAX_ENTRY_BYTES = ConfEntry(
    "spark.blaze.cache.result.maxEntryBytes", 8 << 20, int)

# Live query monitoring (runtime/monitor.py).  OFF (default): no HTTP
# server, no background thread, and the heartbeat path is a structural
# no-op exactly like spark.blaze.trace.enabled=false.  ON: an in-process
# registry tracks per-query -> per-stage live state and a background
# HTTP server exposes /metrics (Prometheus text exposition rendered
# from the scheduler MetricNode tree + dispatch counters) and /queries
# (JSON live state) — ≙ the reference's metrics plumbed into the LIVE
# Spark UI while the query runs, not only post-hoc (SURVEY).
MONITOR_ENABLE = ConfEntry("spark.blaze.monitor.enabled", False, _bool)
# Port for the monitor HTTP server; 0 = pick a free ephemeral port
# (the bound port is logged and available via monitor.server_port()).
MONITOR_PORT = ConfEntry("spark.blaze.monitor.port", 4048, int)
# Progress-heartbeat cadence (ms): the scheduler and run_task emit
# stage_progress / task_heartbeat events at most this often, into the
# event log (when tracing is armed) and the live registry (when the
# monitor is armed).  Smaller = fresher /queries, more events.
MONITOR_HEARTBEAT_MS = ConfEntry("spark.blaze.monitor.heartbeatMs", 1000, int)
# Historical retention beyond the in-memory last-64 ring: when set,
# every FINISHED query's registry summary is appended to a JSONL
# history file under this directory (size-capped rollover like the
# event log), and /queries?all=1 serves the merged history.  Empty =
# in-memory ring only (the pre-existing behavior).
MONITOR_HISTORY_DIR = ConfEntry("spark.blaze.monitor.historyDir", "", str)
# Size cap (bytes) per history file before it rolls into a numbered
# .segN segment (same rollover contract as spark.blaze.eventLog.maxBytes).
MONITOR_HISTORY_MAX_BYTES = ConfEntry("spark.blaze.monitor.historyMaxBytes", 4 << 20, int)
# Push exporter: "host:port" arms a best-effort statsd UDP push loop
# (gauge lines derived from the same rendering as /metrics, pushed
# every heartbeat interval) so ops without a Prometheus scraper still
# get the numbers.  Empty (default) = structural no-op: no socket, no
# thread.
MONITOR_STATSD = ConfEntry("spark.blaze.monitor.statsd", "", str)

# SLO layer (runtime/slo.py): per-pool latency/error objectives
# declared as dynamic conf keys
# (spark.blaze.slo.pool.<name>.latencyP99Ms / .errorRate /
# .targetWindowSec) evaluated as MULTI-WINDOW BURN RATES over the
# observed per-pool latency/error stream — the SRE-workbook alerting
# shape: fire only when BOTH the fast and the slow window burn the
# error budget faster than the threshold, resolve only after the burn
# stays below it for a hold count (flap suppression).  Disarmed
# (default) the whole layer is a structural no-op: one bool read per
# query end, no state, no thread.
SLO_ENABLE = ConfEntry("spark.blaze.slo.enabled", False, _bool)
# Minimum interval (ms) between burn-rate evaluations — observe() and
# the /slo + /metrics render paths drive evaluation opportunistically
# (no background thread); this throttles the work, not the data.
SLO_EVAL_INTERVAL_MS = ConfEntry("spark.blaze.slo.evalIntervalMs", 200, int)
# Burn-rate threshold: an alert FIRES when both windows consume error
# budget at >= this multiple of the sustainable rate (1.0 = exactly
# exhausting the budget over the target window).
SLO_FIRE_BURN_RATE = ConfEntry("spark.blaze.slo.fireBurnRate", 1.0, float)
# Consecutive below-threshold evaluations required before a firing
# alert RESOLVES — the flap suppressor.
SLO_RESOLVE_HOLD_EVALS = ConfEntry("spark.blaze.slo.resolveHoldEvals", 2, int)

# Incident debug bundles (runtime/bundle.py, `--debug-bundle <dir>` /
# POST /queries/<id>/bundle): conf keys whose NAME matches any of
# these comma-separated lowercase substrings have their VALUE redacted
# in the bundle's conf dump (secrets never leave the host in a
# forensics snapshot).
BUNDLE_REDACT = ConfEntry(
    "spark.blaze.bundle.redactPatterns",
    "password,secret,token,credential,key.material", str)

# Whole-stage program fusion (ops/fusion.py): collapse traceable
# operator chains / agg pre-filters / final-agg sorts into single XLA
# programs.  OFF runs every operator as its own dispatch — the
# correctness fallback the fused-vs-unfused differential tests pin.
FUSION_ENABLE = ConfEntry("spark.blaze.fusion.enabled", True, _bool)
# Grouped/scalar aggs fold the per-batch reduce AND the accumulator
# merge into ONE jitted update program over stacked state (agg.py) —
# the q01 dispatch collapse.  OFF = reduce + concat + merge as
# separate programs (the pending-list doubling path).
FUSED_AGG_UPDATE = ConfEntry("spark.blaze.tpu.fusedAggUpdate", True, _bool)
# Persistent XLA compilation cache directory (jax_compilation_cache_dir)
# — empty disables.  Pre-warm once per image with
# `python -m blaze_tpu --warmup` so the 15-22 min first q01 compile
# (round 5) is never paid inside a query.  Env: BLAZE_XLA_CACHEDIR.
XLA_CACHE_DIR = ConfEntry("spark.blaze.xla.cacheDir", "", str)

# TPU-specific knobs (no reference equivalent).
ON_DEVICE = ConfEntry("spark.blaze.tpu.onDevice", True, _bool)
# Grouped-agg segment reduces via segmented associative scans + cumsum
# differences + gathers (scatter-free).  Off = jax.ops.segment_* +
# jnp.nonzero (scatter-based — a cliff on XLA:TPU).
SEG_SCAN_REDUCE = ConfEntry("spark.blaze.tpu.segScanReduce", True, _bool)
# PARTIAL grouped aggs sort ONE u32 key hash instead of every 64-bit
# key word (boundaries still compare full words; hash-collision
# duplicate groups are re-merged downstream)
AGG_HASH_SORT_PARTIAL = ConfEntry("spark.blaze.tpu.aggHashSortPartial", True, _bool)
# In-process exchanges keep partition buffers device-resident (HBM)
# instead of round-tripping IPC files through the host — over a
# remote/tunneled chip every host sync costs a full RTT.  The file
# shuffle remains the cross-process / spill path (turn this off to
# force it, e.g. when a stage's output exceeds HBM).
EXCHANGE_IN_PROCESS = ConfEntry("spark.blaze.exchange.inProcess", True, _bool)
# AQE-style dynamic join selection in the stage scheduler (the
# reference inherits this from Spark AQE): off by default — the
# scheduler re-plans shuffle joins as broadcast joins when a side's
# materialized map output is under the threshold
ADAPTIVE_JOIN_ENABLE = ConfEntry("spark.blaze.enable.adaptiveJoin", False, _bool)
ADAPTIVE_BROADCAST_THRESHOLD = ConfEntry(
    "spark.blaze.adaptiveBroadcastThreshold", 10 << 20, int)
DEVICE_MEMORY_BUDGET = ConfEntry("spark.blaze.tpu.hbmBudget", 8 << 30, int)
HOST_SPILL_BUDGET = ConfEntry("spark.blaze.tpu.hostSpillBudget", 4 << 30, int)
MIN_CAPACITY = ConfEntry("spark.blaze.tpu.minBatchCapacity", 1024, int)

# Dispatch-driven batch autotuning (runtime/dispatch.py controller):
# while a trace kernel capture is active, the per-kernel device_ns /
# dispatch_ns split feeds a controller that GROWS the agg input
# coalescing bucket (powers of the step factor, bounded below/above)
# until the device share of warm kernel time crosses the target —
# the dispatch floor amortizes over more rows per program.  Memory
# pressure (an OOM-ladder rung firing) pushes the bucket back down
# and caps re-growth below the rows that exhausted the device.  OFF
# (default) the whole controller is a structural no-op: decisions are
# only made under the same capture scope that already pays
# block-until-ready timing, so the untraced hot path never sees it.
BATCH_AUTOTUNE = ConfEntry("spark.blaze.tpu.batchAutotune", False, _bool)
# Coalescing-bucket bounds (rows) and growth step for the controller.
# The floor doubles as the starting target; the ceiling bounds device
# residency of one coalesced bucket.
BATCH_AUTOTUNE_MIN_ROWS = ConfEntry(
    "spark.blaze.tpu.batchAutotune.minRows", 8192, int)
BATCH_AUTOTUNE_MAX_ROWS = ConfEntry(
    "spark.blaze.tpu.batchAutotune.maxRows", 262144, int)
BATCH_AUTOTUNE_STEP = ConfEntry("spark.blaze.tpu.batchAutotune.step", 4, int)
# Warm device share (device_ns / (device_ns + dispatch_ns)) the
# controller grows toward; past it the workload classifies
# majority-device and growth stops.
BATCH_AUTOTUNE_TARGET_SHARE = ConfEntry(
    "spark.blaze.tpu.batchAutotune.deviceShareTarget", 0.5, float)
# Timed-kernel observations aggregated per growth decision (smooths
# single-program jitter without starving convergence at test scale).
BATCH_AUTOTUNE_WINDOW = ConfEntry(
    "spark.blaze.tpu.batchAutotune.window", 4, int)
# Donate fused-shuffle-write input buffers to XLA (jax.jit
# donate_argnums): the consumed batch's device buffers are reused for
# the program's outputs instead of holding both alive.  Only
# engine-produced single-consumer batches (RecordBatch.consumable) are
# ever donated; scan/cache-owned batches never are.  A donating
# program that hits a REAL device OOM forfeits the in-place retry
# rungs (its inputs are already dead) and surfaces the retryable
# task-level error instead.
DONATE_BUFFERS = ConfEntry("spark.blaze.tpu.donateBuffers", False, _bool)

# Performance introspection (runtime/perf.py): EXPLAIN ANALYZE,
# per-kernel roofline/MFU attribution, and the perf-baseline gate.
# Bytes-moved / flops estimation at the dispatch choke point — armed it
# runs ONLY while a trace kernel capture is active (the same scope that
# pays block-until-ready timing); disarmed it is one module-global bool
# read per traced call, exactly the spark.blaze.trace.enabled contract,
# and the untraced hot path never sees it at all.
PERF_ESTIMATES = ConfEntry("spark.blaze.perf.estimates", True, _bool)
# Relative drift tolerance for `--perfcheck` against the golden
# baseline registry (runtime/perf_baselines.json): warm dispatches /
# programs outside baseline*(1±tolerance) fail the gate.  0 (the
# default) defers to the registry's own pinned ``tolerance`` field.
PERF_TOLERANCE = ConfEntry("spark.blaze.perf.tolerance", 0.0, float)
# Override path for the perf-baseline registry (empty = the packaged
# runtime/perf_baselines.json) — tests and `--perfcheck --update`
# round-trips point this at a scratch copy.
PERF_BASELINES = ConfEntry("spark.blaze.perf.baselines", "", str)
# Override path for the per-device-kind peak table (empty = the
# packaged runtime/device_peaks.json).
PERF_PEAKS = ConfEntry("spark.blaze.perf.peaks", "", str)
# bench.py stale-cache guard: a carried cached q01/q06 half whose
# ``measured_at`` stamp is older than this many days is DROPPED from
# the merge (re-measured) instead of silently re-emitted — BENCH_r05
# shipped a q01 number stamped six days stale.  0 = never expire.
BENCH_MAX_CACHE_AGE_DAYS = ConfEntry("spark.blaze.bench.maxCacheAgeDays", 3, int)

# Runtime statistics observatory (runtime/stats.py): cardinality
# estimates stamped at optimize_plan, per-partition exchange
# histograms, Q-error drift reporting, and partition-skew findings.
# Disarmed cost is one module-global bool read per hook (the
# trace.enabled() contract).
STATS_ENABLED = ConfEntry("spark.blaze.stats.enabled", True, _bool)
# Per-group-key NDV HyperLogLog sketches on agg output streams —
# separately gated: updating a sketch reads column values back to the
# host, which the counter-only stats path never does.
STATS_SKETCHES = ConfEntry("spark.blaze.stats.sketches", False, _bool)
# Persistent stats store keyed by the plan fingerprint digest,
# versioned by source versions exactly like the result cache: observed
# actuals written at query-span exit, consulted by the estimator on
# the next run so warm estimates converge on actuals.
STATS_STORE_ENABLED = ConfEntry("spark.blaze.stats.store.enabled", True, _bool)
# Store directory (empty = <tmpdir>/blaze-stats-<uid>).
STATS_STORE_DIR = ConfEntry("spark.blaze.stats.store.dir", "", str)
# A partition is a skew finding when its rows are at least skewRatio x
# the median partition AND at least skewMinRows absolute — the floor
# keeps toy exchanges from alerting on noise.
STATS_SKEW_RATIO = ConfEntry("spark.blaze.stats.skewRatio", 4.0, float)
STATS_SKEW_MIN_ROWS = ConfEntry("spark.blaze.stats.skewMinRows", 4096, int)

# Static analysis & verification (blaze_tpu/analysis/).
# Plan verifier: run the rule-based structural checker
# (analysis/plan_verify.py — schema edges, partitioning/ordering
# prerequisites, fusion invariants) over every physical plan after
# ops/fusion.optimize_plan and before execution.  Off by default on
# the production hot path; FORCED ON in tests (conftest) and --chaos.
VERIFY_PLAN = ConfEntry("spark.blaze.verify.plan", False, _bool)
# Runtime lock-order assertion (analysis/locks.py): while armed, every
# acquisition of a hierarchy lock asserts strictly inward order and
# raises LockOrderError on inversion — the would-be deadlock surfaces
# deterministically instead of as a rare hang.  Armed in --chaos and
# the monitor/fault test suites; disarmed cost is one bool read.
VERIFY_LOCKS = ConfEntry("spark.blaze.verify.locks", False, _bool)
# Eraser-style dynamic lockset checker (runtime/lockset.py): while
# armed, every instrumented guarded-state access records the thread's
# held lockset, and a per-(object, attribute) empty intersection after
# the state is seen from >=2 threads raises LocksetViolation — the
# data race the static guarded-by pass (analysis/guarded.py) cannot
# see through dynamic dispatch surfaces deterministically.  Armed in
# --chaos / --chaos-seeds and the concurrency suites; disarmed cost is
# one bool read per instrumented access.
VERIFY_LOCKSET = ConfEntry("spark.blaze.verify.lockset", False, _bool)
# Error-escape recorder + per-query resource ledger (runtime/errors.py
# + runtime/ledger.py): while armed, every AUDITED broad-except site
# records a FATAL-class control-flow error it absorbs (the escape
# survives the swallow — lockset.reported()-style gate), and every
# tracked resource (spill files, .inprogress shuffle temps, scoped
# resource registrations, device-lease turns) must be released by
# query end or the leak is recorded and fails the run.  Armed in
# --chaos / --chaos-seeds and the faults/lifecycle/service suites;
# disarmed cost is one bool read per hook.
VERIFY_ERRORS = ConfEntry("spark.blaze.verify.errors", False, _bool)

# Per-operator enable flags, ≙ BlazeConverters.scala:82-120
# (spark.blaze.enable.scan / .project / .filter / ...).
_OP_FLAGS: Dict[str, ConfEntry] = {}


def op_enabled(name: str) -> bool:
    entry = _OP_FLAGS.get(name)
    if entry is None:
        entry = ConfEntry(f"spark.blaze.enable.{name}", True, _bool)
        _OP_FLAGS[name] = entry
    return entry.get()


CONF_NAMES_PATH = os.path.join(
    os.path.dirname(__file__), "runtime", "conf_names.json")


def load_conf_names() -> Dict[str, Any]:
    """The golden conf-name registry (runtime/conf_names.json,
    mirroring metric_names.json): every ``spark.blaze.*`` key this
    engine reads, plus the dynamic per-operator prefix.  Conf KEYS are
    API — deployment configs and docs reference them by string, so a
    silent rename strands every existing setting.  The drift is gated
    both ways by analysis/lint.py (``conf.*`` rules) in tier-1."""
    import json

    with open(CONF_NAMES_PATH) as f:
        return json.load(f)


def registered_conf_keys() -> set:
    """Flat set of every registered conf key."""
    return set(load_conf_names().get("keys", []))


def declared_entries() -> Dict[str, "ConfEntry"]:
    """Every module-level ConfEntry declared here, by key (the live
    half the registry mirrors; op_enabled's dynamic family is covered
    by the registry's ``dynamic_prefixes``)."""
    import sys

    mod = sys.modules[__name__]
    return {
        v.key: v for v in vars(mod).values() if isinstance(v, ConfEntry)
    }


def set_conf(key: str, value: Any) -> None:
    """Entry point for the gateway / tests to inject Spark conf values."""
    with _lock:
        _values[key] = value


def all_values() -> Dict[str, Any]:
    """Every explicitly-set conf value (static AND dynamic keys) — the
    debug bundle's conf dump source: declared entries cover defaults,
    but only this store knows the dynamic key families (per-pool SLO
    objectives, op toggles) an incident was running with."""
    with _lock:
        return dict(_values)


def get_conf(key: str, default: Any = None) -> Any:
    with _lock:
        return _values.get(key, default)
