"""ctypes binding to the C++ native runtime (libblaze_tpu_native.so).

≙ the reference's in-process .so boundary (libblaze.so loaded by
BlazeCallNativeWrapper.loadLibBlaze:187-208).  Pure-python fallbacks
exist for every entry point, so the engine degrades gracefully when the
library isn't built (the reference's "JNI bridge stubbed by absence"
test trick, SURVEY.md §4); `available()` reports which path is live.

Build:  cmake -S native -B native/build -G Ninja && cmake --build native/build
"""

from __future__ import annotations

import ctypes as C
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType, TypeKind

_KIND_MAP = {
    TypeKind.BOOL: 0,
    TypeKind.INT8: 1,
    TypeKind.INT16: 2,
    TypeKind.INT32: 3,
    TypeKind.INT64: 4,
    TypeKind.FLOAT32: 5,
    TypeKind.FLOAT64: 6,
    TypeKind.DATE32: 3,
    TypeKind.TIMESTAMP: 4,
    TypeKind.DECIMAL: 4,
    TypeKind.STRING: 7,
    TypeKind.BINARY: 7,
}


class _BtCol(C.Structure):
    _fields_ = [
        ("kind", C.c_int32),
        ("data", C.c_void_p),
        ("validity", C.c_void_p),
        ("lengths", C.c_void_p),
        ("width", C.c_int32),
    ]


class ArrowSchema(C.Structure):
    pass


class ArrowArray(C.Structure):
    pass


ArrowSchema._fields_ = [
    ("format", C.c_char_p),
    ("name", C.c_char_p),
    ("metadata", C.c_char_p),
    ("flags", C.c_int64),
    ("n_children", C.c_int64),
    ("children", C.POINTER(C.POINTER(ArrowSchema))),
    ("dictionary", C.POINTER(ArrowSchema)),
    ("release", C.c_void_p),
    ("private_data", C.c_void_p),
]
ArrowArray._fields_ = [
    ("length", C.c_int64),
    ("null_count", C.c_int64),
    ("offset", C.c_int64),
    ("n_buffers", C.c_int64),
    ("n_children", C.c_int64),
    ("buffers", C.POINTER(C.c_void_p)),
    ("children", C.POINTER(C.POINTER(ArrowArray))),
    ("dictionary", C.POINTER(ArrowArray)),
    ("release", C.c_void_p),
    ("private_data", C.c_void_p),
]

_lib = None


def _find_lib() -> Optional[str]:
    env = os.environ.get("BLAZE_TPU_NATIVE_LIB")
    if env and os.path.exists(env):
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (
        os.path.join(here, "native", "build", "libblaze_tpu_native.so"),
        os.path.join(here, "libblaze_tpu_native.so"),
    ):
        if os.path.exists(cand):
            return cand
    return None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = _find_lib()
    if path is None:
        return None
    lib = C.CDLL(path)
    lib.bt_murmur3.argtypes = [C.POINTER(_BtCol), C.c_int32, C.c_int64, C.c_int32, C.c_void_p]
    lib.bt_xxhash64.argtypes = [C.POINTER(_BtCol), C.c_int32, C.c_int64, C.c_int64, C.c_void_p]
    lib.bt_pmod.argtypes = [C.c_void_p, C.c_int64, C.c_int32, C.c_void_p]
    lib.bt_serialized_size.argtypes = [C.POINTER(_BtCol), C.c_int32, C.c_int64]
    lib.bt_serialized_size.restype = C.c_int64
    lib.bt_serialize_batch.argtypes = [C.POINTER(_BtCol), C.c_int32, C.c_int64, C.c_void_p, C.c_int64]
    lib.bt_serialize_batch.restype = C.c_int64
    lib.bt_max_frame_size.argtypes = [C.c_int64]
    lib.bt_max_frame_size.restype = C.c_int64
    lib.bt_compress_frame.argtypes = [C.c_void_p, C.c_int64, C.c_void_p, C.c_int64, C.c_int32]
    lib.bt_compress_frame.restype = C.c_int64
    lib.bt_decompress_frame.argtypes = [C.c_void_p, C.c_int64, C.c_void_p, C.c_int64]
    lib.bt_decompress_frame.restype = C.c_int64
    lib.bt_loser_tree_merge.argtypes = [
        C.POINTER(C.c_void_p), C.c_void_p, C.c_int32, C.c_void_p, C.c_void_p, C.c_int64,
    ]
    lib.bt_loser_tree_merge.restype = C.c_int64
    lib.bt_arrow_export_primitive.argtypes = [
        C.POINTER(_BtCol), C.c_int64, C.POINTER(ArrowSchema), C.POINTER(ArrowArray),
    ]
    lib.bt_arrow_export_primitive.restype = C.c_int32
    lib.bt_arrow_import_primitive.argtypes = [
        C.POINTER(ArrowSchema), C.POINTER(ArrowArray), C.c_void_p, C.c_void_p, C.c_int64,
    ]
    lib.bt_arrow_import_primitive.restype = C.c_int32
    lib.bt_arrow_export_string.argtypes = [
        C.POINTER(_BtCol), C.c_int64, C.POINTER(ArrowSchema), C.POINTER(ArrowArray),
    ]
    lib.bt_arrow_export_string.restype = C.c_int32
    lib.bt_arrow_import_string.argtypes = [
        C.POINTER(ArrowSchema), C.POINTER(ArrowArray), C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_int64, C.c_int32,
    ]
    lib.bt_arrow_import_string.restype = C.c_int32
    lib.bt_version.restype = C.c_char_p
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def version() -> Optional[str]:
    lib = _load()
    return lib.bt_version().decode() if lib else None


def _np_ptr(a: np.ndarray) -> C.c_void_p:
    return C.c_void_p(a.ctypes.data)


def _make_cols(cols, num_rows: int) -> Tuple[C.Array, List[np.ndarray]]:
    """Build bt_col descriptors for host Columns (keeps buffer refs
    alive via the returned list)."""
    keep: List[np.ndarray] = []
    arr = (_BtCol * len(cols))()
    for i, c in enumerate(cols):
        data = np.ascontiguousarray(np.asarray(c.data)[:num_rows])
        validity = np.ascontiguousarray(np.asarray(c.validity)[:num_rows].astype(np.uint8))
        keep += [data, validity]
        arr[i].kind = _KIND_MAP[c.dtype.kind]
        arr[i].data = data.ctypes.data
        arr[i].validity = validity.ctypes.data
        if c.lengths is not None:
            lengths = np.ascontiguousarray(np.asarray(c.lengths)[:num_rows].astype(np.int32))
            keep.append(lengths)
            arr[i].lengths = lengths.ctypes.data
            arr[i].width = data.shape[1]
        else:
            arr[i].lengths = None
            arr[i].width = 0
    return arr, keep


def murmur3_host(cols, num_rows: int, seed: int = 42) -> np.ndarray:
    lib = _load()
    assert lib is not None
    arr, keep = _make_cols(cols, num_rows)
    out = np.empty(num_rows, np.int32)
    lib.bt_murmur3(arr, len(cols), num_rows, seed, _np_ptr(out))
    return out


def xxhash64_host(cols, num_rows: int, seed: int = 42) -> np.ndarray:
    lib = _load()
    assert lib is not None
    arr, keep = _make_cols(cols, num_rows)
    out = np.empty(num_rows, np.int64)
    lib.bt_xxhash64(arr, len(cols), num_rows, seed, _np_ptr(out))
    return out


def serialize_batch_native(batch) -> Optional[bytes]:
    """Native serialization of a host RecordBatch; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    b = batch.to_host()
    arr, keep = _make_cols(b.columns, b.num_rows)
    size = lib.bt_serialized_size(arr, len(b.columns), b.num_rows)
    out = np.empty(size, np.uint8)
    written = lib.bt_serialize_batch(arr, len(b.columns), b.num_rows, _np_ptr(out), size)
    if written < 0:
        return None
    return out[:written].tobytes()


def compress_frame_native(payload: bytes, use_zlib: bool = True) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    cap = lib.bt_max_frame_size(len(payload))
    out = np.empty(cap, np.uint8)
    n = lib.bt_compress_frame(payload, len(payload), _np_ptr(out), cap, 1 if use_zlib else 0)
    if n < 0:
        return None
    return out[:n].tobytes()


def decompress_frame_native(frame: bytes, expected_max: int) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(max(expected_max, 1), np.uint8)
    n = lib.bt_decompress_frame(frame, len(frame), _np_ptr(out), out.size)
    if n < 0:
        return None
    return out[:n].tobytes()


def loser_tree_merge(run_keys: Sequence[np.ndarray]) -> Tuple[np.ndarray, np.ndarray]:
    """Merge k ascending uint64 runs; returns (run_idx, offset) arrays
    in globally sorted order."""
    lib = _load()
    assert lib is not None
    k = len(run_keys)
    runs = [np.ascontiguousarray(r, dtype=np.uint64) for r in run_keys]
    ptrs = (C.c_void_p * k)(*[r.ctypes.data for r in runs])
    lens = np.array([len(r) for r in runs], np.int64)
    total = int(lens.sum())
    out_run = np.empty(total, np.uint32)
    out_off = np.empty(total, np.uint32)
    n = lib.bt_loser_tree_merge(ptrs, _np_ptr(lens), k, _np_ptr(out_run), _np_ptr(out_off), total)
    assert n == total, (n, total)
    return out_run, out_off


def arrow_roundtrip(col, num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """Export a primitive host Column through the Arrow C ABI and
    import it back (FFI data-plane self test)."""
    lib = _load()
    assert lib is not None
    arr, keep = _make_cols([col], num_rows)
    schema = ArrowSchema()
    array = ArrowArray()
    rc = lib.bt_arrow_export_primitive(C.byref(arr[0]), num_rows, C.byref(schema), C.byref(array))
    assert rc == 0
    data = np.asarray(col.data)[:num_rows]
    out_data = np.empty_like(np.ascontiguousarray(data))
    out_valid = np.empty(num_rows, np.uint8)
    rc = lib.bt_arrow_import_primitive(
        C.byref(schema), C.byref(array), _np_ptr(out_data), _np_ptr(out_valid), num_rows
    )
    assert rc == 0
    # release through the Arrow callback contract
    rel = C.CFUNCTYPE(None, C.POINTER(ArrowArray))(array.release)
    rel(C.byref(array))
    return out_data, out_valid.astype(bool)
