"""protobuf -> ExecNode/Expr trees + task runner.

≙ reference blaze-serde/src/from_proto.rs:125-1283 (recursive
ExecutionPlan builder) plus the task entry half of blaze/src/exec.rs
(decode TaskDefinition -> build plan -> run).
"""

from __future__ import annotations

import logging
import pickle
import re
from typing import List, Optional

from ..exprs.ir import (
    Alias, BinOp, Case, Cast, Col, Expr, GetIndexedField, GetMapValue,
    GetStructField, InList, IsNotNull, IsNull, Like, Lit, NamedStruct, Not,
    ScalarFunc, SparkUdfWrapper,
)
from ..schema import DataType, Field, Schema, TypeKind
from . import plan_pb2 as pb

_log = logging.getLogger("blaze_tpu.task")


def dtype_from_proto(t: pb.DataTypeProto) -> DataType:
    kind = TypeKind(t.kind)
    if kind == TypeKind.DECIMAL:
        return DataType.decimal(t.precision, t.scale)
    if kind in (TypeKind.STRING, TypeKind.BINARY):
        return DataType(kind, string_width=t.string_width or 64)
    if kind == TypeKind.ARRAY:
        return DataType.array(dtype_from_proto(t.elem), t.max_elems)
    if kind == TypeKind.MAP:
        return DataType.map(dtype_from_proto(t.key), dtype_from_proto(t.value), t.max_elems)
    if kind == TypeKind.STRUCT:
        return DataType.struct(
            [Field(f.name, dtype_from_proto(f.dtype), f.nullable) for f in t.struct_fields]
        )
    return DataType(kind)


def schema_from_proto(s: pb.SchemaProto) -> Schema:
    return Schema(
        [Field(f.name, dtype_from_proto(f.dtype), f.nullable) for f in s.fields]
    )


def _lit_from_proto(l: pb.LiteralValue) -> Lit:
    t = dtype_from_proto(l.dtype)
    if l.is_null:
        return Lit(None, t)
    kind = l.WhichOneof("value")
    if kind == "bool_value":
        return Lit(l.bool_value, t)
    if kind == "float_value":
        return Lit(l.float_value, t)
    if kind == "bytes_value":
        v = l.bytes_value
        return Lit(v.decode("utf-8") if t.kind == TypeKind.STRING else v, t)
    # int_value: decimals arrive unscaled; Lit stores logical values, so
    # wrap through a raw-int constructor
    if t.is_decimal:
        lit = Lit(0, t)
        lit.value = _RawUnscaled(l.int_value)
        return lit
    return Lit(l.int_value, t)


class _RawUnscaled(int):
    """Marker: the literal int is ALREADY the unscaled decimal value."""


# teach the lowering about _RawUnscaled without touching its fast path
def _patch_lit_lowering():
    from ..exprs import compile as C

    orig = C._lit_column

    def lit_column(value, dtype, n):
        if isinstance(value, _RawUnscaled) and dtype.is_decimal:
            import jax.numpy as jnp

            return C.Column(dtype, jnp.full(n, int(value), jnp.int64), jnp.ones(n, jnp.bool_))
        return orig(value, dtype, n)

    if orig.__name__ != "lit_column":
        C._lit_column = lit_column


_patch_lit_lowering()


def expr_from_proto(n: pb.ExprNode) -> Expr:
    kind = n.WhichOneof("expr")
    if kind == "column":
        return Col(n.column)
    if kind == "literal":
        return _lit_from_proto(n.literal)
    if kind == "alias":
        return Alias(expr_from_proto(n.alias.child), n.alias.name)
    if kind == "binary":
        return BinOp(n.binary.op, expr_from_proto(n.binary.left), expr_from_proto(n.binary.right))
    if kind == "not":
        return Not(expr_from_proto(getattr(n, "not")))
    if kind == "is_null":
        return IsNull(expr_from_proto(n.is_null))
    if kind == "is_not_null":
        return IsNotNull(expr_from_proto(n.is_not_null))
    if kind == "cast":
        return Cast(expr_from_proto(n.cast.child), dtype_from_proto(n.cast.to))
    if kind == "case":
        branches = [
            (expr_from_proto(b.condition), expr_from_proto(b.value)) for b in n.case.branches
        ]
        else_ = expr_from_proto(n.case.else_expr) if n.case.has_else else None
        return Case(branches, else_)
    if kind == "in_list":
        return InList(
            expr_from_proto(n.in_list.child),
            [expr_from_proto(v) for v in n.in_list.values],
            n.in_list.negated,
        )
    if kind == "like":
        return Like(expr_from_proto(n.like.child), n.like.pattern, n.like.negated)
    if kind == "scalar_func":
        return ScalarFunc(n.scalar_func.name, [expr_from_proto(a) for a in n.scalar_func.args])
    if kind == "get_indexed_field":
        return GetIndexedField(expr_from_proto(n.get_indexed_field.child), n.get_indexed_field.index)
    if kind == "get_map_value":
        key = _lit_from_proto(n.get_map_value.key).value
        return GetMapValue(expr_from_proto(n.get_map_value.child), key)
    if kind == "get_struct_field":
        return GetStructField(expr_from_proto(n.get_struct_field.child), n.get_struct_field.name)
    if kind == "named_struct":
        return NamedStruct(
            list(n.named_struct.names), [expr_from_proto(e) for e in n.named_struct.exprs]
        )
    if kind == "spark_udf_wrapper":
        w = n.spark_udf_wrapper
        return SparkUdfWrapper(
            bytes(w.serialized),
            [expr_from_proto(a) for a in w.args],
            dtype_from_proto(w.dtype),
            w.expr_string,
        )
    raise NotImplementedError(f"from_proto expr {kind}")


def _partitioning_from_proto(p: pb.PartitioningProto):
    from ..parallel.shuffle import (
        HashPartitioning, RangePartitioning, RoundRobinPartitioning,
        SinglePartitioning,
    )

    if p.kind == pb.PartitioningProto.HASH:
        return HashPartitioning([expr_from_proto(e) for e in p.exprs], p.num_partitions)
    if p.kind == pb.PartitioningProto.ROUND_ROBIN:
        return RoundRobinPartitioning(p.num_partitions)
    if p.kind == pb.PartitioningProto.RANGE:
        import numpy as np

        from ..ops import SortField

        fields = [
            SortField(expr_from_proto(f.expr), f.ascending, f.nulls_first)
            for f in p.sort_fields
        ]
        nw = int(p.num_boundary_words)
        flat = np.array(list(p.boundary_words), np.uint64)
        per = len(flat) // nw if nw else 0
        boundaries = tuple(flat[i * per:(i + 1) * per] for i in range(nw))
        return RangePartitioning(fields, p.num_partitions, boundaries=boundaries)
    return SinglePartitioning(p.num_partitions)


def plan_from_proto(n: pb.PhysicalPlanNode):
    from ..ops import (
        AggExec, AggFunction, AggMode, CoalesceBatchesExec, DebugExec,
        EmptyPartitionsExec, ExpandExec, FilterExec, GenerateExec, GroupingExpr,
        LimitExec, MemoryScanExec, ProjectExec, RenameColumnsExec, SortExec,
        SortField, UnionExec, WindowExec, WindowFunction,
    )
    from ..ops.joins import BroadcastJoinExec, HashJoinExec, JoinType, SortMergeJoinExec
    from ..parallel.broadcast import IpcWriterExec
    from ..parallel.shuffle import IpcReaderExec, ShuffleWriterExec
    from ..runtime.context import RESOURCES

    kind = n.WhichOneof("node")
    if kind == "memory_scan":
        rid = n.memory_scan.resource_id
        parts = RESOURCES.get(rid)
        scan = MemoryScanExec(parts, schema_from_proto(n.memory_scan.schema))
        # re-adopt the ORIGINAL table's source identity from the rid
        # (serde/to_proto.py encodes s<source_id>e<epoch>): a rebuilt
        # scan is the SAME data source, not a fresh one — without this
        # every task of a stage would mint its own source id, split
        # the stage's plan fingerprint per task, and scatter the stats
        # store's actuals across per-task entries
        m = re.match(r"memscan_s(\d+)e(\d+)_", rid)
        if m:
            scan.source_id = int(m.group(1))
            scan.epoch = int(m.group(2))
        return scan
    if kind in ("parquet_scan", "orc_scan"):
        s = n.parquet_scan if kind == "parquet_scan" else n.orc_scan
        pred = None
        for e in s.predicate:
            sub = expr_from_proto(e)
            pred = sub if pred is None else (pred & sub)
        groups = [g.split(";") if g else [] for g in s.file_groups]
        if kind == "parquet_scan":
            from ..ops import ParquetScanExec

            return ParquetScanExec(groups, schema_from_proto(s.schema), pred)
        from ..ops.orc_scan import OrcScanExec

        return OrcScanExec(groups, schema_from_proto(s.schema), pred)
    if kind == "project":
        p = n.project
        return ProjectExec(plan_from_proto(p.input), [expr_from_proto(e) for e in p.exprs], list(p.names))
    if kind == "filter":
        project = None
        if n.filter.project_exprs:
            project = (
                [expr_from_proto(e) for e in n.filter.project_exprs],
                list(n.filter.project_names),
            )
        return FilterExec(
            plan_from_proto(n.filter.input), expr_from_proto(n.filter.predicate), project
        )
    if kind == "agg":
        a = n.agg
        return AggExec(
            plan_from_proto(a.input),
            AggMode(a.mode),
            [GroupingExpr(expr_from_proto(g.expr), g.name) for g in a.groupings],
            [
                AggFunction(f.fn, expr_from_proto(f.expr) if f.has_expr else None, f.name)
                for f in a.aggs
            ],
            supports_partial_skipping=a.supports_partial_skipping,
        )
    if kind == "sort":
        s = n.sort
        return SortExec(
            plan_from_proto(s.input),
            [SortField(expr_from_proto(f.expr), f.ascending, f.nulls_first) for f in s.fields],
            fetch=s.fetch if s.has_fetch else None,
        )
    if kind == "limit":
        return LimitExec(plan_from_proto(n.limit.input), n.limit.limit)
    if kind == "union":
        return UnionExec([plan_from_proto(c) for c in n.union.inputs])
    if kind == "rename_columns":
        return RenameColumnsExec(plan_from_proto(n.rename_columns.input), list(n.rename_columns.names))
    if kind == "empty_partitions":
        return EmptyPartitionsExec(
            schema_from_proto(n.empty_partitions.schema), n.empty_partitions.num_partitions
        )
    if kind == "debug":
        return DebugExec(plan_from_proto(n.debug.input), n.debug.tag, n.debug.verbose)
    if kind == "coalesce_batches":
        return CoalesceBatchesExec(
            plan_from_proto(n.coalesce_batches.input), n.coalesce_batches.target_rows
        )
    if kind == "shuffle_writer":
        w = n.shuffle_writer
        return ShuffleWriterExec(
            plan_from_proto(w.input), _partitioning_from_proto(w.partitioning),
            w.output_data_file, w.output_index_file,
        )
    if kind == "ipc_reader":
        r = n.ipc_reader
        return IpcReaderExec(schema_from_proto(r.schema), r.ipc_provider_resource_id, r.num_partitions)
    if kind == "ipc_writer":
        return IpcWriterExec(plan_from_proto(n.ipc_writer.input), n.ipc_writer.ipc_consumer_resource_id)
    if kind in ("broadcast_join", "hash_join"):
        j = n.broadcast_join if kind == "broadcast_join" else n.hash_join
        cls = BroadcastJoinExec if kind == "broadcast_join" else HashJoinExec
        extra = {}
        if kind == "broadcast_join":
            if j.build_data_schema.fields:
                extra["build_data_schema"] = schema_from_proto(j.build_data_schema)
            if j.cached_build_id:
                extra["cached_build_id"] = j.cached_build_id
        return cls(
            plan_from_proto(j.build), plan_from_proto(j.probe),
            [expr_from_proto(e) for e in j.build_keys],
            [expr_from_proto(e) for e in j.probe_keys],
            JoinType[pb.JoinTypeProto.Name(j.join_type)],
            j.build_is_left,
            **extra,
        )
    if kind == "broadcast_join_build_hash_map":
        from ..ops.joins import BroadcastJoinBuildHashMapExec

        b = n.broadcast_join_build_hash_map
        return BroadcastJoinBuildHashMapExec(
            plan_from_proto(b.input), [expr_from_proto(e) for e in b.keys]
        )
    if kind == "sort_merge_join":
        j = n.sort_merge_join
        return SortMergeJoinExec(
            plan_from_proto(j.left), plan_from_proto(j.right),
            [expr_from_proto(e) for e in j.left_keys],
            [expr_from_proto(e) for e in j.right_keys],
            JoinType[pb.JoinTypeProto.Name(j.join_type)],
            nulls_first=not j.nulls_last,
        )
    if kind == "window":
        w = n.window
        return WindowExec(
            plan_from_proto(w.input),
            [
                WindowFunction(
                    f.kind, f.name,
                    expr_from_proto(f.expr) if f.has_expr else None,
                    f.whole_partition,
                    # lead/lag: 0 is a legal offset (current row);
                    # other kinds never read it (default 1)
                    offset=f.offset if f.kind in ("lead", "lag") else (f.offset or 1),
                    rows_frame=(
                        (None if f.frame_preceding < 0 else f.frame_preceding,
                         None if f.frame_following < 0 else f.frame_following)
                        if f.has_rows_frame else None
                    ),
                    ignore_nulls=f.ignore_nulls,
                    range_frame=(
                        (None if f.range_preceding < 0 else f.range_preceding,
                         None if f.range_following < 0 else f.range_following)
                        if f.has_range_frame else None
                    ),
                )
                for f in w.functions
            ],
            [expr_from_proto(e) for e in w.partition_by],
            [SortField(expr_from_proto(f.expr), f.ascending, f.nulls_first) for f in w.order_by],
        )
    if kind == "expand":
        e = n.expand
        return ExpandExec(
            plan_from_proto(e.input),
            [[expr_from_proto(x) for x in p.exprs] for p in e.projections],
            list(e.names),
        )
    if kind == "generate":
        g = n.generate
        if g.native_kind:
            from ..ops.generate import NativeGenerator

            gen = NativeGenerator(g.native_kind, expr_from_proto(g.native_expr))
        else:
            from .. import conf

            if not bool(conf.ALLOW_PICKLED_UDFS.get()):
                raise PermissionError(
                    "pickled generator payload rejected: "
                    "spark.blaze.udf.allowPickled is false"
                )
            gen = pickle.loads(g.generator_payload)
        return GenerateExec(
            plan_from_proto(g.input),
            gen,
            [expr_from_proto(e) for e in g.input_exprs],
            [Field(f.name, dtype_from_proto(f.dtype), f.nullable) for f in g.gen_fields],
            g.outer,
            g.keep_input,
        )
    if kind == "object_agg":
        from .. import conf
        from ..ops.agg import GroupingExpr
        from ..ops.object_agg import ObjectAggExec

        o = n.object_agg
        if not bool(conf.ALLOW_PICKLED_UDFS.get()):
            raise PermissionError(
                "pickled UDAF payload rejected: set spark.blaze.udf.allowPickled"
            )
        return ObjectAggExec(
            plan_from_proto(o.input),
            AggMode(o.mode),
            [GroupingExpr(expr_from_proto(g.expr), g.name) for g in o.groupings],
            pickle.loads(o.udafs_payload),
        )
    if kind == "bloom_filter_agg":
        from ..ops.bloom_agg import BloomFilterAggExec

        b = n.bloom_filter_agg
        return BloomFilterAggExec(
            plan_from_proto(b.input),
            expr_from_proto(b.expr) if b.has_expr else None,
            b.name, AggMode(b.mode), b.expected_items, b.num_bits or None,
        )
    raise NotImplementedError(f"from_proto node {kind}")


def run_task(task_def_bytes: bytes, task_attempt_id: int = 0,
             resources=None, cancel_event=None, on_beat=None):
    """Decode a TaskDefinition and drive its plan for its partition —
    the python mirror of the gateway's callNative entry
    (≙ blaze/src/exec.rs:46-142).  ``task_attempt_id`` threads the
    scheduler's attempt counter into the TaskContext (and the fault
    injector), so retried attempts are distinguishable at every site.

    Speculation/wedge plumbing (runtime/speculation.py): ``resources``
    swaps in a per-attempt ScopedResources view so concurrent attempts
    of one task never steal each other's one-shot registrations,
    ``cancel_event`` lets the driver cancel a losing attempt
    cooperatively, and ``on_beat`` is a liveness callback fired at the
    heartbeat cadence from inside the plan drive — the wedge detector's
    clock, armed even when tracing and the monitor are off."""
    from ..runtime import faults
    from ..runtime.context import TaskContext

    td = pb.TaskDefinition()
    td.ParseFromString(task_def_bytes)
    from ..ops.fusion import optimize_plan

    faults.hit("task.compute", attempt=task_attempt_id, detail=td.task_id)
    plan = optimize_plan(plan_from_proto(td.plan))
    if _log.isEnabledFor(logging.DEBUG):
        # ≙ the reference's native plan display at task start
        # (blaze/src/exec.rs:101-106)
        _log.debug("task %s partition %d plan:\n%s",
                   td.task_id, td.partition, plan.tree_string())
    ctx = TaskContext(
        td.partition, max(plan.num_partitions(), td.partition + 1),
        stage_id=td.stage_id, task_attempt_id=task_attempt_id,
        resources=resources, cancel_event=cancel_event,
    )
    stream = plan.execute(td.partition, ctx)
    from ..runtime import monitor, trace

    if not trace.enabled() and not monitor.enabled() and on_beat is None:
        return stream
    return _instrumented_task_stream(stream, plan, td, task_attempt_id,
                                     on_beat=on_beat)


def _instrumented_task_stream(stream, plan, td, attempt: int, on_beat=None):
    """Observability-armed task drive.  With tracing armed, a kernel
    capture attributes every XLA program issued while this attempt runs
    to its operator label, and on completion the attempt emits its
    kernel split (``task_kernels``) plus the plan-annotated metrics
    tree (``task_plan`` — the executed plan instance's per-node
    MetricsSet, the per-attempt analogue of the MetricNode walk the JVM
    gateway does).  With tracing OR the live monitor armed, the stream
    additionally heartbeats: at most once per
    ``spark.blaze.monitor.heartbeatMs`` a ``task_heartbeat`` event
    (event log) / registry beat (/queries) carries rows-so-far plus an
    incremental snapshot of the plan root's MetricsSet, so a slow task
    is visibly alive mid-flight.  Monitor-only arming deliberately
    skips the kernel capture — that would flip the block-until-ready
    timing path and serialize the device just to watch progress."""
    import contextlib as _contextlib
    import time as _time

    from ..runtime import monitor, trace

    traced = trace.enabled()
    mon = monitor.enabled()
    t0 = _time.perf_counter_ns()
    rows = 0
    batches = 0

    def _tree_metrics(node, out, max_rows):
        for k, v in node.metrics.snapshot().items():
            if isinstance(v, int):
                out[k] = out.get(k, 0) + v
                if k == "output_rows":
                    max_rows = max(max_rows, v)
        for c in node.children:
            max_rows = _tree_metrics(c, out, max_rows)
        return max_rows

    def beat() -> None:
        # incremental MetricsSet snapshot SUMMED over the plan tree
        # (per-operator rows/timers so far) — output_rows there counts
        # every operator boundary, so the chain-depth-independent live
        # row count is progress_rows: the widest single node's rows
        if on_beat is not None:
            on_beat()
        if not traced and not mon:
            return  # wedge-clock-only arming: no snapshot walk owed
        metrics: dict = {}
        progress_rows = _tree_metrics(plan, metrics, 0)
        now = _time.perf_counter_ns()
        # the PR 3 kernel-sink split for this attempt so far — where
        # the task's wall is going (device compute vs dispatch
        # overhead), live in /queries and the heartbeat event; only
        # tracing arms the capture, so monitor-only runs report 0/0
        # rather than paying the block-until-ready path
        device_ns = dispatch_ns = 0
        ksnap = None
        if traced and kc:
            ksnap = trace.snapshot_kernels(kc)
            split = trace.sum_kernels(ksnap)
            device_ns = split["device_time_ns"]
            dispatch_ns = split["dispatch_overhead_ns"]
        if traced:
            trace.emit(
                "task_heartbeat", task_id=td.task_id, stage_id=td.stage_id,
                partition=td.partition, attempt=attempt, rows=rows,
                batches=batches, elapsed_ns=now - t0,
                progress_rows=progress_rows, metrics=metrics,
                device_ns=device_ns, dispatch_ns=dispatch_ns,
            )
        if mon:
            monitor.task_beat(td.stage_id, td.partition, attempt,
                              rows=rows, batches=batches, metrics=metrics,
                              progress_rows=progress_rows,
                              task_id=td.task_id,
                              device_ns=device_ns, dispatch_ns=dispatch_ns,
                              # per-label sink snapshot: the live flame
                              # profile's source (/queries/<id>/profile)
                              kernels=ksnap)

    kc_scope = trace.kernel_capture() if traced else _contextlib.nullcontext({})
    # the beat fires from monitor.tick() — called per operator output
    # batch inside the plan drive (ops/base._count_output), so a map
    # task that yields nothing to the driver still heartbeats — and
    # from the driver-side loop below for result streams.  The beat
    # state is active ONLY while the plan drive runs (inside next()),
    # never across a yield: an abandoned half-consumed stream must not
    # leave a stale callback cross-attributing this task's beats into
    # the next query on the consumer's thread.
    beat_state = monitor.new_task_beat(beat)
    with kc_scope as kc:
        try:
            it = iter(stream)
            while True:
                prev = monitor.activate_beat(beat_state)
                try:
                    b = next(it)
                except StopIteration:
                    break
                finally:
                    monitor.deactivate_beat(prev)
                rows += b.num_rows
                batches += 1
                beat_state.tick()
                yield b
            if mon:
                # FINAL beat, interval-ungated: a task faster than the
                # heartbeat period would otherwise never land its rows
                # or kernel split in the registry at all (a failed
                # attempt's entry is discarded by the scheduler's
                # rollback hook right after this unwinds, so only the
                # completed drive beats here)
                beat()
        finally:
            if traced:
                trace.emit(
                    "task_kernels", task_id=td.task_id, stage_id=td.stage_id,
                    partition=td.partition, attempt=attempt,
                    wall_ns=_time.perf_counter_ns() - t0, kernels=kc,
                    **trace.sum_kernels(kc),
                )
                trace.emit(
                    "task_plan", task_id=td.task_id, stage_id=td.stage_id,
                    partition=td.partition, attempt=attempt,
                    plan=trace.plan_tree(plan),
                )
