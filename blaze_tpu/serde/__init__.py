"""Plan serde: the protobuf contract between the (JVM) planner and the
native TPU engine — ≙ reference crate blaze-serde.

Regenerate plan_pb2.py with:  protoc --python_out=. blaze_tpu/serde/plan.proto
"""

from .to_proto import expr_to_proto, plan_to_proto, task_definition
from .from_proto import expr_from_proto, plan_from_proto, run_task

__all__ = [
    "expr_to_proto", "plan_to_proto", "task_definition",
    "expr_from_proto", "plan_from_proto", "run_task",
]
