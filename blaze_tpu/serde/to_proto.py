"""ExecNode/Expr trees -> protobuf.

≙ the JVM side of the reference's serde (NativeConverters.scala
convertExpr/convertDataType + the per-plan-node proto builders in
spark-extension/.../blaze/plan/*.scala).  In-process this is used by
tests (roundtrip) and by the standalone scheduler when shipping task
plans to worker processes.
"""

from __future__ import annotations

import datetime
import pickle
from typing import Optional

from ..exprs.ir import (
    Alias, BinOp, Case, Cast, Col, Expr, GetIndexedField, GetMapValue,
    GetStructField, InList, IsNotNull, IsNull, Like, Lit, NamedStruct, Not,
    ScalarFunc, SparkUdfWrapper,
)
from ..schema import DataType, Field, Schema, TypeKind
from . import plan_pb2 as pb


import contextvars
import itertools

# itertools.count.__next__ is atomic under the GIL, so concurrent
# serializations (exchange map threads, parallel task-def building)
# never mint the same resource id
_memscan_rids = itertools.count()

# When set (scheduler retry path), every resource id staged during
# serialization is appended here so a failed attempt can discard its
# one-shot resources instead of leaking them in the process-global map.
STAGED_RIDS: contextvars.ContextVar = contextvars.ContextVar(
    "blaze_staged_rids", default=None
)


def dtype_to_proto(t: DataType) -> pb.DataTypeProto:
    out = pb.DataTypeProto(
        kind=t.kind.value, precision=t.precision, scale=t.scale,
        string_width=t.string_width, max_elems=t.max_elems,
    )
    if t.elem is not None:
        out.elem.CopyFrom(dtype_to_proto(t.elem))
    if t.key is not None:
        out.key.CopyFrom(dtype_to_proto(t.key))
    if t.value is not None:
        out.value.CopyFrom(dtype_to_proto(t.value))
    if t.struct_fields is not None:
        for f in t.struct_fields:
            out.struct_fields.append(
                pb.FieldProto(name=f.name, dtype=dtype_to_proto(f.dtype), nullable=f.nullable)
            )
    return out


def schema_to_proto(s: Schema) -> pb.SchemaProto:
    return pb.SchemaProto(
        fields=[
            pb.FieldProto(name=f.name, dtype=dtype_to_proto(f.dtype), nullable=f.nullable)
            for f in s.fields
        ]
    )


def _lit_to_proto(e: Lit) -> pb.LiteralValue:
    from ..exprs.compile import infer_lit_dtype

    t = infer_lit_dtype(e.value, e.dtype)
    out = pb.LiteralValue(dtype=dtype_to_proto(t))
    v = e.value
    if v is None:
        out.is_null = True
    elif t.kind == TypeKind.BOOL:
        out.bool_value = bool(v)
    elif t.is_string:
        out.bytes_value = v.encode("utf-8") if isinstance(v, str) else bytes(v)
    elif t.is_float:
        out.float_value = float(v)
    elif t.is_decimal:
        from .from_proto import _RawUnscaled

        if isinstance(v, _RawUnscaled):
            # already the unscaled representation (a scalar-subquery
            # result round-tripping back out) — scaling it again would
            # inflate the literal 10^scale-fold
            out.int_value = int(v)
        elif isinstance(v, str):
            from decimal import Decimal

            out.int_value = int(Decimal(v).scaleb(t.scale).to_integral_value())
        elif isinstance(v, float):
            out.int_value = int(round(v * 10**t.scale))
        else:
            out.int_value = int(v) * 10**t.scale
    elif t.kind == TypeKind.DATE32:
        if isinstance(v, str):
            v = datetime.date.fromisoformat(v)
        if isinstance(v, datetime.date):
            v = (v - datetime.date(1970, 1, 1)).days
        out.int_value = int(v)
    else:
        out.int_value = int(v)
    return out


def expr_to_proto(e: Expr) -> pb.ExprNode:
    n = pb.ExprNode()
    if isinstance(e, Col):
        n.column = e.name
    elif isinstance(e, Lit):
        n.literal.CopyFrom(_lit_to_proto(e))
    elif isinstance(e, Alias):
        n.alias.child.CopyFrom(expr_to_proto(e.child))
        n.alias.name = e.name
    elif isinstance(e, BinOp):
        n.binary.op = e.op
        n.binary.left.CopyFrom(expr_to_proto(e.left))
        n.binary.right.CopyFrom(expr_to_proto(e.right))
    elif isinstance(e, Not):
        getattr(n, "not").CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, IsNull):
        n.is_null.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, IsNotNull):
        n.is_not_null.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, Cast):
        n.cast.child.CopyFrom(expr_to_proto(e.child))
        n.cast.to.CopyFrom(dtype_to_proto(e.to))
    elif isinstance(e, Case):
        for c, v in e.branches:
            b = n.case.branches.add()
            b.condition.CopyFrom(expr_to_proto(c))
            b.value.CopyFrom(expr_to_proto(v))
        if e.else_ is not None:
            n.case.has_else = True
            n.case.else_expr.CopyFrom(expr_to_proto(e.else_))
    elif isinstance(e, InList):
        n.in_list.child.CopyFrom(expr_to_proto(e.child))
        for v in e.values:
            n.in_list.values.add().CopyFrom(expr_to_proto(v))
        n.in_list.negated = e.negated
    elif isinstance(e, Like):
        n.like.child.CopyFrom(expr_to_proto(e.child))
        n.like.pattern = e.pattern
        n.like.negated = e.negated
    elif isinstance(e, ScalarFunc):
        n.scalar_func.name = e.name
        for a in e.args:
            n.scalar_func.args.add().CopyFrom(expr_to_proto(a))
    elif isinstance(e, GetIndexedField):
        n.get_indexed_field.child.CopyFrom(expr_to_proto(e.child))
        n.get_indexed_field.index = e.index
    elif isinstance(e, GetMapValue):
        n.get_map_value.child.CopyFrom(expr_to_proto(e.child))
        n.get_map_value.key.CopyFrom(_lit_to_proto(Lit(e.key)))
    elif isinstance(e, GetStructField):
        n.get_struct_field.child.CopyFrom(expr_to_proto(e.child))
        n.get_struct_field.name = e.name
    elif isinstance(e, NamedStruct):
        n.named_struct.names.extend(e.names)
        for a in e.exprs:
            n.named_struct.exprs.add().CopyFrom(expr_to_proto(a))
    elif isinstance(e, SparkUdfWrapper):
        n.spark_udf_wrapper.serialized = e.serialized
        n.spark_udf_wrapper.dtype.CopyFrom(dtype_to_proto(e.dtype))
        for a in e.args:
            n.spark_udf_wrapper.args.add().CopyFrom(expr_to_proto(a))
        n.spark_udf_wrapper.expr_string = e.expr_string
    else:
        raise NotImplementedError(f"to_proto for {type(e).__name__}")
    return n


def _partitioning_to_proto(p) -> pb.PartitioningProto:
    from ..parallel.shuffle import (
        HashPartitioning, RangePartitioning, RoundRobinPartitioning,
    )

    out = pb.PartitioningProto(num_partitions=p.num_partitions)
    if isinstance(p, HashPartitioning):
        out.kind = pb.PartitioningProto.HASH
        for e in p.exprs:
            out.exprs.add().CopyFrom(expr_to_proto(e))
    elif isinstance(p, RoundRobinPartitioning):
        out.kind = pb.PartitioningProto.ROUND_ROBIN
    elif isinstance(p, RangePartitioning):
        if p.boundaries is None:
            # boundaries come from the scheduler's driver-side sampling
            # pass (≙ Spark's RangePartitioner sample job); a map task
            # cannot compute global boundaries alone
            raise NotImplementedError(
                "range partitioning crosses the serde boundary only "
                "with precomputed boundaries (scheduler boundary pass)"
            )
        out.kind = pb.PartitioningProto.RANGE
        for f in p.fields:
            fp = out.sort_fields.add()
            fp.expr.CopyFrom(expr_to_proto(f.expr))
            fp.ascending = f.ascending
            fp.nulls_first = f.nulls_first
        out.num_boundary_words = len(p.boundaries)
        import numpy as _np

        for w in p.boundaries:
            out.boundary_words.extend(int(v) for v in _np.asarray(w, _np.uint64))
    else:
        out.kind = pb.PartitioningProto.SINGLE
    return out


def plan_to_proto(node) -> pb.PhysicalPlanNode:
    from ..ops import (
        AggExec, CoalesceBatchesExec, DebugExec, EmptyPartitionsExec, ExpandExec,
        FilterExec, GenerateExec, LimitExec, MemoryScanExec, OrcScanExec,
        ParquetScanExec, ProjectExec, RenameColumnsExec, SortExec, UnionExec,
        WindowExec,
    )
    from ..ops.joins import (
        BroadcastJoinBuildHashMapExec,
        BroadcastJoinExec,
        HashJoinExec,
        SortMergeJoinExec,
    )
    from ..parallel.broadcast import IpcWriterExec
    from ..parallel.shuffle import IpcReaderExec, ShuffleWriterExec
    from ..runtime.context import RESOURCES

    out = pb.PhysicalPlanNode()
    if isinstance(node, MemoryScanExec):
        # stage partitions under a resources-map id so the decoded plan
        # finds them (≙ FFIReader export).  The id must be unique PER
        # SERIALIZATION: resources pop on read, and one plan node is
        # serialized once per task (N tasks = N gets).  A serialized
        # plan that is never executed strands its entry until process
        # exit — callers (scheduler) serialize exactly what they run.
        # the s<source_id>e<epoch> segment carries the table's data
        # identity (querycache source versioning) across the serde
        # boundary: every task rebuild of this scan re-adopts the
        # ORIGINAL source id + epoch (serde/from_proto.py parses it
        # back), so all tasks of a stage share one plan fingerprint
        # and the stats store folds their actuals into one entry
        rid = (f"memscan_s{node.source_id}e{node.epoch}"
               f"_{id(node)}_{next(_memscan_rids)}")
        RESOURCES.put(rid, node._partitions)
        staged = STAGED_RIDS.get()
        if staged is not None:
            staged.append(rid)
        out.memory_scan.resource_id = rid
        out.memory_scan.schema.CopyFrom(schema_to_proto(node.schema))
        out.memory_scan.num_partitions = node.num_partitions()
    elif isinstance(node, (ParquetScanExec, OrcScanExec)):
        sub = out.parquet_scan if isinstance(node, ParquetScanExec) else out.orc_scan
        sub.schema.CopyFrom(schema_to_proto(node.schema))
        for g in node.file_groups:
            sub.file_groups.append(";".join(g))
        if node.predicate is not None:
            sub.predicate.add().CopyFrom(expr_to_proto(node.predicate))
    elif isinstance(node, ProjectExec):
        out.project.input.CopyFrom(plan_to_proto(node.children[0]))
        for e in node.exprs:
            out.project.exprs.add().CopyFrom(expr_to_proto(e))
        out.project.names.extend(node.names)
    elif isinstance(node, FilterExec):
        out.filter.input.CopyFrom(plan_to_proto(node.children[0]))
        out.filter.predicate.CopyFrom(expr_to_proto(node.predicate))
        if node.project is not None:
            proj_exprs, proj_names = node.project
            for e in proj_exprs:
                out.filter.project_exprs.add().CopyFrom(expr_to_proto(e))
            out.filter.project_names.extend(proj_names)
    elif isinstance(node, AggExec):
        out.agg.input.CopyFrom(plan_to_proto(node.children[0]))
        out.agg.mode = node.mode.value
        for g in node.groupings:
            ge = out.agg.groupings.add()
            ge.expr.CopyFrom(expr_to_proto(g.expr))
            ge.name = g.name
        for a in node.aggs:
            ap = out.agg.aggs.add()
            ap.fn = a.fn
            ap.name = a.name
            if a.expr is not None:
                ap.has_expr = True
                ap.expr.CopyFrom(expr_to_proto(a.expr))
        out.agg.supports_partial_skipping = node.supports_partial_skipping
    elif isinstance(node, SortExec):
        out.sort.input.CopyFrom(plan_to_proto(node.children[0]))
        for f in node.fields:
            fp = out.sort.fields.add()
            fp.expr.CopyFrom(expr_to_proto(f.expr))
            fp.ascending = f.ascending
            fp.nulls_first = f.nulls_first
        if node.fetch is not None:
            out.sort.has_fetch = True
            out.sort.fetch = node.fetch
    elif isinstance(node, LimitExec):
        out.limit.input.CopyFrom(plan_to_proto(node.children[0]))
        out.limit.limit = node.limit
    elif isinstance(node, UnionExec):
        for c in node.children:
            out.union.inputs.add().CopyFrom(plan_to_proto(c))
    elif isinstance(node, RenameColumnsExec):
        out.rename_columns.input.CopyFrom(plan_to_proto(node.children[0]))
        out.rename_columns.names.extend(node.schema.names)
    elif isinstance(node, EmptyPartitionsExec):
        out.empty_partitions.schema.CopyFrom(schema_to_proto(node.schema))
        out.empty_partitions.num_partitions = node.num_partitions()
    elif isinstance(node, DebugExec):
        out.debug.input.CopyFrom(plan_to_proto(node.children[0]))
        out.debug.tag = node.tag
        out.debug.verbose = node.verbose
    elif isinstance(node, CoalesceBatchesExec):
        out.coalesce_batches.input.CopyFrom(plan_to_proto(node.children[0]))
        out.coalesce_batches.target_rows = node.target_rows
    elif isinstance(node, ShuffleWriterExec):
        out.shuffle_writer.input.CopyFrom(plan_to_proto(node.children[0]))
        out.shuffle_writer.partitioning.CopyFrom(_partitioning_to_proto(node.partitioning))
        out.shuffle_writer.output_data_file = node.data_path
        out.shuffle_writer.output_index_file = node.index_path
    elif isinstance(node, IpcReaderExec):
        out.ipc_reader.schema.CopyFrom(schema_to_proto(node.schema))
        out.ipc_reader.ipc_provider_resource_id = node.resource_id
        out.ipc_reader.num_partitions = node.num_partitions()
    elif isinstance(node, IpcWriterExec):
        out.ipc_writer.input.CopyFrom(plan_to_proto(node.children[0]))
        out.ipc_writer.ipc_consumer_resource_id = node.resource_id
    elif isinstance(node, (BroadcastJoinExec, HashJoinExec)):
        dst = out.broadcast_join if isinstance(node, BroadcastJoinExec) else out.hash_join
        dst.build.CopyFrom(plan_to_proto(node.children[0]))
        dst.probe.CopyFrom(plan_to_proto(node.children[1]))
        for e in node.build_keys:
            dst.build_keys.add().CopyFrom(expr_to_proto(e))
        for e in node.probe_keys:
            dst.probe_keys.add().CopyFrom(expr_to_proto(e))
        dst.join_type = pb.JoinTypeProto.Value(node.join_type.name)
        dst.build_is_left = node.build_is_left
        if isinstance(node, BroadcastJoinExec):
            dst.build_data_schema.CopyFrom(schema_to_proto(node.build_data_schema))
            if node.cached_build_id:
                dst.cached_build_id = node.cached_build_id
    elif isinstance(node, BroadcastJoinBuildHashMapExec):
        out.broadcast_join_build_hash_map.input.CopyFrom(plan_to_proto(node.children[0]))
        for e in node.keys:
            out.broadcast_join_build_hash_map.keys.add().CopyFrom(expr_to_proto(e))
    elif type(node).__name__ == "ObjectAggExec":
        out.object_agg.input.CopyFrom(plan_to_proto(node.children[0]))
        out.object_agg.mode = node.mode.value
        for g in node.groupings:
            ne = out.object_agg.groupings.add()
            ne.expr.CopyFrom(expr_to_proto(g.expr))
            ne.name = g.name
        out.object_agg.udafs_payload = pickle.dumps(node.udafs)
    elif type(node).__name__ == "BloomFilterAggExec":
        out.bloom_filter_agg.input.CopyFrom(plan_to_proto(node.children[0]))
        if node.expr is not None:
            out.bloom_filter_agg.has_expr = True
            out.bloom_filter_agg.expr.CopyFrom(expr_to_proto(node.expr))
        out.bloom_filter_agg.name = node.agg_name
        out.bloom_filter_agg.mode = node.mode.value
        out.bloom_filter_agg.expected_items = node.expected_items
        out.bloom_filter_agg.num_bits = node.num_bits
    elif isinstance(node, SortMergeJoinExec):
        out.sort_merge_join.left.CopyFrom(plan_to_proto(node.children[0]))
        out.sort_merge_join.right.CopyFrom(plan_to_proto(node.children[1]))
        for e in node.left_keys:
            out.sort_merge_join.left_keys.add().CopyFrom(expr_to_proto(e))
        for e in node.right_keys:
            out.sort_merge_join.right_keys.add().CopyFrom(expr_to_proto(e))
        out.sort_merge_join.join_type = pb.JoinTypeProto.Value(node.join_type.name)
        out.sort_merge_join.nulls_last = not node.nulls_first
    elif isinstance(node, WindowExec):
        out.window.input.CopyFrom(plan_to_proto(node.children[0]))
        for f in node.functions:
            fp = out.window.functions.add()
            fp.kind = f.kind
            fp.name = f.name
            if f.expr is not None:
                fp.has_expr = True
                fp.expr.CopyFrom(expr_to_proto(f.expr))
            fp.whole_partition = f.whole_partition
            fp.offset = f.offset
            fp.ignore_nulls = f.ignore_nulls
            if f.rows_frame is not None:
                fp.has_rows_frame = True
                p_, q_ = f.rows_frame
                fp.frame_preceding = -1 if p_ is None else p_
                fp.frame_following = -1 if q_ is None else q_
            if f.range_frame is not None:
                fp.has_range_frame = True
                x_, y_ = f.range_frame
                fp.range_preceding = -1 if x_ is None else x_
                fp.range_following = -1 if y_ is None else y_
        for e in node.partition_by:
            out.window.partition_by.add().CopyFrom(expr_to_proto(e))
        for f in node.order_by:
            fp = out.window.order_by.add()
            fp.expr.CopyFrom(expr_to_proto(f.expr))
            fp.ascending = f.ascending
            fp.nulls_first = f.nulls_first
    elif isinstance(node, ExpandExec):
        out.expand.input.CopyFrom(plan_to_proto(node.children[0]))
        for proj in node._projects:
            ep = out.expand.projections.add()
            for e in proj.exprs:
                ep.exprs.add().CopyFrom(expr_to_proto(e))
        out.expand.names.extend(node.schema.names)
    elif isinstance(node, GenerateExec):
        from ..ops.generate import NativeGenerator

        out.generate.input.CopyFrom(plan_to_proto(node.children[0]))
        if isinstance(node.generator, NativeGenerator):
            out.generate.native_kind = node.generator.kind
            out.generate.native_expr.CopyFrom(expr_to_proto(node.generator.expr))
        else:
            out.generate.generator_payload = pickle.dumps(node.generator)
        for e in node.input_exprs:
            out.generate.input_exprs.add().CopyFrom(expr_to_proto(e))
        for f in node.gen_fields:
            out.generate.gen_fields.add().CopyFrom(
                pb.FieldProto(name=f.name, dtype=dtype_to_proto(f.dtype), nullable=f.nullable)
            )
        out.generate.outer = node.outer
        out.generate.keep_input = node.keep_input
    else:
        raise NotImplementedError(f"to_proto for {type(node).__name__}")
    return out


def task_definition(plan, task_id: str, stage_id: int, partition: int) -> bytes:
    td = pb.TaskDefinition(
        task_id=task_id, stage_id=stage_id, partition=partition,
        plan=plan_to_proto(plan),
    )
    return td.SerializeToString()
