"""Command-line runner: execute TPC-H / TPC-DS queries through the
engine from a shell.

≙ the reference's benchmark tooling (``dev/run-tpcds-test`` +
``tpcds/benchmark-runner`` — spark-submit launchers around the same
query set, ``tpcds/README.md:1-52``), sized for this engine: datagen
at the requested scale, plan build, execution either in-process or
through the stage scheduler (every task crossing TaskDefinition
protobuf bytes + shuffle files), wall-clock per query, and an optional
row-count/total printout.

Usage:
    python -m blaze_tpu tpch q6 q1 --scale 0.05
    python -m blaze_tpu tpcds q36 --scale 0.002 --parts 4 --scheduler
    python -m blaze_tpu tpch all --scale 0.01
"""

from __future__ import annotations

import argparse
import sys
import time


def _run_suite(suite: str, names, scale: float, n_parts: int,
               scheduler: bool) -> int:
    if suite == "tpch":
        from .tpch import TPCH_SCHEMAS as SCHEMAS
        from .tpch import build_query
        from .tpch.datagen import generate_all, table_to_batches
        from .tpch.queries import QUERIES
    else:
        from .tpcds import TPCDS_SCHEMAS as SCHEMAS
        from .tpcds import build_query, generate_all
        from .tpcds.queries import QUERIES
        from .tpch.datagen import table_to_batches

    if names == ["all"]:
        names = sorted(QUERIES)
    unknown = [n for n in names if n not in QUERIES]
    if unknown:
        print(f"unknown {suite} queries: {', '.join(unknown)} "
              f"(available: {', '.join(sorted(QUERIES))})", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    data = generate_all(scale)
    from .ops import MemoryScanExec

    scans = {
        name: MemoryScanExec(
            table_to_batches(data[name], SCHEMAS[name], n_parts, batch_rows=65536),
            SCHEMAS[name],
        )
        for name in SCHEMAS
    }
    print(f"# datagen scale={scale}: {time.perf_counter() - t0:.2f}s")

    from .runtime.context import TaskContext

    failed = []
    for name in names:
        t0 = time.perf_counter()
        try:
            plan = build_query(name, scans, n_parts)
            rows = 0
            if scheduler:
                from .runtime.scheduler import run_stages, split_stages

                stages, manager = split_stages(plan)
                for b in run_stages(stages, manager):
                    rows += b.num_rows
            else:
                for p in range(plan.num_partitions()):
                    for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
                        rows += b.num_rows
            dt = time.perf_counter() - t0
            print(f"{suite} {name}: {rows} rows in {dt:.2f}s"
                  + (" [scheduler]" if scheduler else ""))
        except Exception as e:  # noqa: BLE001 — report per query, keep going
            failed.append(name)
            print(f"{suite} {name}: FAILED {type(e).__name__}: {e}",
                  file=sys.stderr)
    if failed:
        print(f"# {len(failed)} failed: {', '.join(failed)}", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m blaze_tpu",
        description="Run TPC-H / TPC-DS queries through the engine.",
    )
    ap.add_argument("suite", choices=["tpch", "tpcds"])
    ap.add_argument("queries", nargs="+",
                    help="query names (q1, q6, ...) or 'all'")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="datagen scale factor (default 0.01)")
    ap.add_argument("--parts", type=int, default=2,
                    help="partitions per table (default 2)")
    ap.add_argument("--scheduler", action="store_true",
                    help="run through the stage scheduler (TaskDefinition "
                         "bytes + shuffle files) instead of in-process")
    args = ap.parse_args(argv)
    return _run_suite(args.suite, args.queries, args.scale, args.parts,
                      args.scheduler)


if __name__ == "__main__":
    sys.exit(main())
